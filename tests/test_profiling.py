"""Continuous profiling plane (ISSUE 20): the per-step host-overhead
decomposition recorder, the on-demand ``/profilez`` capture window, the
SLO-triggered capture, and the stitched fleet timeline.

The acceptance gates pinned here:

* ring accounting: every step's phase seconds sum EXACTLY to its wall
  time (the lap/cursor model attributes each elapsed nanosecond to one
  phase), the ring stays bounded, and the three surfaces — engine
  statusz, ``mxtpu_step_phase_seconds`` metrics, flight dumps — agree;
* ``POST /profilez``: happy path produces a real device-trace artifact,
  a concurrent second POST gets a clean 409 (never a breaker-tripping
  500), back-to-back windows are rate-limited (429 + retry_after_s),
  and stopping the replica mid-window ends the capture cleanly;
* an SLO fast-burn alert triggers a short capture on the offending
  replica and the flight dump carries the capture id;
* ``tools/timeline_report.py`` stitches router hops, replica trace
  lines and step rings into a well-formed Chrome trace with zero
  unresolved hops under ``--check``;
* inertness: ``MXTPU_STEP_PROFILE=0`` installs the NOOP recorder and
  tokens are byte-identical either way.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import mxnet_tpu as mx
from mxnet_tpu import profiler as profiler_mod
from mxnet_tpu import telemetry
from mxnet_tpu.fleet import (FaultInjector, FleetCollector, ReplicaServer,
                             Router, SLOEvaluator, parse_slo_spec)
from mxnet_tpu.telemetry import profiling

VOCAB = 53


@pytest.fixture(scope="module")
def model():
    """Tiny gpt2-style net + params (the test_serve recipe)."""
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _prompts(n, seed=7, lo=6, hi=22):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (rng.randint(lo, hi),)).astype(np.int32)
            for _ in range(n)]


def _run(eng, prompt, max_new=4):
    req = eng.submit(prompt, max_new_tokens=max_new)
    while not req.done:
        eng.step()
    return req


def _get(url, path, timeout=10):
    with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _post(url, path, payload, timeout=30):
    import urllib.error

    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.reset()


def _wait_capture(url, cap_id, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        meta = _get(url, f"/profilez/{cap_id}")
        if meta.get("state") in ("done", "failed"):
            return meta
        time.sleep(0.05)
    return meta


# -- ring accounting (pure unit: fake clock) ----------------------------------
def test_step_profiler_phases_sum_to_wall():
    clock = {"now": 100.0}

    def tick():
        return clock["now"]

    sp = profiling.StepProfiler(clock=tick, ring=4)
    laps = [("schedule", 0.010), ("prefill_dispatch", 0.002),
            ("device_wait", 0.050), ("host_sync", 0.001),
            ("decode_dispatch", 0.004), ("device_wait", 0.030)]
    for step in range(6):
        sp.begin(step)
        for phase, dt in laps:
            clock["now"] += dt
            sp.lap(phase)
        clock["now"] += 0.003           # residual -> callbacks
        sp.commit(emitted=2, prefills=1, decodes=1)
    # ring bounded at 4; totals keep counting all 6 steps
    entries = sp.recent()
    assert len(entries) == 4
    assert [e["step"] for e in entries] == [2, 3, 4, 5]
    wall = 0.1
    for e in entries:
        assert e["wall_s"] == pytest.approx(wall, abs=1e-12)
        # the accounting identity: phases sum EXACTLY to the wall
        assert sum(e["phases"].values()) == pytest.approx(
            e["wall_s"], rel=1e-12)
        # repeated laps into one phase accumulate (two device waits)
        assert e["phases"]["device_wait"] == pytest.approx(0.08)
        assert e["phases"]["callbacks"] == pytest.approx(0.003)
        assert e["emitted"] == 2
    st = sp.statusz()
    assert st["enabled"] is True and st["steps"] == 6
    assert st["wall_s"] == pytest.approx(6 * wall)
    assert sum(st["totals_s"].values()) == pytest.approx(st["wall_s"])
    fr = st["fractions"]
    assert set(fr) == set(profiling.PHASES)
    assert sum(fr.values()) == pytest.approx(1.0)
    assert fr["device_wait"] == pytest.approx(0.8)
    # the stitching anchor rides along
    assert set(st["clock_anchor"]) == {"perf", "epoch"}
    assert sp.summary()["steps"] == 6


def test_step_profiler_env_knobs(monkeypatch):
    monkeypatch.setenv(profiling.ENV_ENABLE, "0")
    assert profiling.make_step_profiler() is profiling.NOOP_STEP_PROFILER
    noop = profiling.make_step_profiler()
    noop.begin(1)
    noop.lap("schedule")
    noop.commit()
    assert noop.recent() == [] and noop.summary() is None
    assert noop.statusz() == {"enabled": False}
    monkeypatch.setenv(profiling.ENV_ENABLE, "1")
    monkeypatch.setenv(profiling.ENV_RING, "7")
    live = profiling.make_step_profiler()
    assert live.enabled and live._ring.maxlen == 7


# -- engine integration: three-view agreement ---------------------------------
def test_statusz_metrics_flight_three_views_agree(model, tel,
                                                  monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    eng = _engine(model)
    try:
        for p in _prompts(6, seed=11):
            r = _run(eng, p)
            assert r.status == "finished"
        sz = eng.statusz()["step_profile"]
        assert sz["enabled"] and sz["steps"] > 0
        # view 1 vs view 2: statusz totals == the metrics histogram
        snap = telemetry.registry().snapshot()
        fam = snap["mxtpu_step_phase_seconds"]
        by_phase = {s["labels"]["phase"]: s for s in fam["samples"]}
        for phase, total in sz["totals_s"].items():
            if total == 0.0 and phase not in by_phase:
                continue          # a phase that never ran observes nothing
            assert by_phase[phase]["sum"] == pytest.approx(total)
        # "callbacks" is swept on every commit -> count == steps
        assert by_phase["callbacks"]["count"] == sz["steps"]
        assert sum(sz["totals_s"].values()) == pytest.approx(
            sz["wall_s"])
        # view 3: the flight dump embeds the same ring tail via the
        # statusz snapshot
        path = telemetry.flight.dump_now("profiling_three_view")
        payload = json.loads(open(path).read())
        sections = [v for v in payload["statusz"].values()
                    if isinstance(v, dict) and "step_profile" in v]
        assert sections, list(payload["statusz"])
        emb = sections[0]["step_profile"]
        assert emb["steps"] >= sz["steps"]
        assert emb["recent"], "flight dump carries no ring entries"
        last = emb["recent"][-1]
        assert sum(last["phases"].values()) == pytest.approx(
            last["wall_s"])
    finally:
        eng.shutdown()


def test_disabled_recorder_is_inert_and_tokens_identical(model,
                                                         monkeypatch):
    p = _prompts(1, seed=5)[0]
    monkeypatch.setenv(profiling.ENV_ENABLE, "0")
    off = _engine(model)
    try:
        assert off._sprof is profiling.NOOP_STEP_PROFILER
        assert off.statusz()["step_profile"] == {"enabled": False}
        toks_off = _run(off, p, max_new=6).tokens
    finally:
        off.shutdown()
    monkeypatch.delenv(profiling.ENV_ENABLE)
    on = _engine(model)
    try:
        assert on._sprof.enabled      # default ON
        toks_on = _run(on, p, max_new=6).tokens
        assert on.statusz()["step_profile"]["steps"] > 0
    finally:
        on.shutdown()
    assert toks_on == toks_off


# -- profiler.py concurrency guard --------------------------------------------
def test_profiler_double_start_raises_profiler_active(tmp_path):
    profiler_mod.start(str(tmp_path / "a"))
    try:
        assert profiler_mod.active_logdir() == str(tmp_path / "a")
        with pytest.raises(profiler_mod.ProfilerActive):
            profiler_mod.start(str(tmp_path / "b"))
        # ProfilerActive subclasses RuntimeError (old callers' except
        # clauses keep working) but is distinguishable for the 409 map
        assert issubclass(profiler_mod.ProfilerActive, RuntimeError)
    finally:
        profiler_mod.stop()
    assert profiler_mod.active_logdir() is None
    # released: a fresh window starts fine
    profiler_mod.start(str(tmp_path / "c"))
    profiler_mod.stop()


# -- POST /profilez ------------------------------------------------------------
def test_profilez_capture_conflict_and_rate_limit(model, fleet_cleanup,
                                                  monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_PROFILEZ_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("MXTPU_PROFILEZ_INTERVAL_S", "30")
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    st, cap = _post(rep.url, "/profilez",
                    {"duration_s": 0.4, "reason": "unit"})
    assert st == 200, cap
    assert cap["state"] == "running" and cap["replica"] == rep.replica_id
    assert cap["started_epoch"] > 0
    # concurrent window -> clean 409, never a RuntimeError→500
    st2, body2 = _post(rep.url, "/profilez", {"duration_s": 0.2})
    assert st2 == 409 and body2["error"] == "capture_in_progress"
    assert body2["id"] == cap["id"]
    # serving continues during the window
    gst, gen = _post(rep.url, "/generate",
                     {"prompt": [1, 2, 3, 4], "max_new_tokens": 4})
    assert gst == 200 and gen["tokens"]
    meta = _wait_capture(rep.url, cap["id"])
    assert meta["state"] == "done", meta
    assert meta["trace_file"] and os.path.exists(meta["trace_file"])
    # the raw artifact serves back over the id
    with urllib.request.urlopen(
            f"{rep.url}/profilez/{cap['id']}/trace", timeout=10) as resp:
        blob = resp.read()
        assert resp.headers["Content-Type"] == "application/gzip"
    assert blob[:2] == b"\x1f\x8b" and len(blob) > 100
    # back-to-back window -> rate limited with a retry hint
    st3, body3 = _post(rep.url, "/profilez", {"duration_s": 0.2})
    assert st3 == 429 and body3["error"] == "rate_limited"
    assert 0 < body3["retry_after_s"] <= 30
    # bad duration -> 400, unknown id -> 404
    assert _post(rep.url, "/profilez", {"duration_s": -1})[0] == 400
    assert _post(rep.url, "/profilez", {"duration_s": "x"})[0] == 400
    try:
        _get(rep.url, "/profilez/nope")
        assert False, "unknown capture id answered 200"
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert json.loads(e.read())["error"] == "unknown_capture"


def test_profilez_duration_clamp_and_stop_during_capture(
        model, fleet_cleanup, monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_PROFILEZ_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("MXTPU_PROFILEZ_MAX_S", "8")
    monkeypatch.setenv("MXTPU_PROFILEZ_INTERVAL_S", "0")
    rep = ReplicaServer(_engine(model)).start()
    st, cap = _post(rep.url, "/profilez", {"duration_s": 9999})
    assert st == 200 and cap["duration_s"] == 8.0   # clamped
    # stopping the replica mid-window ends the capture cleanly (early
    # out on the stop event) and releases the process-global profiler
    t0 = time.monotonic()
    rep.stop()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and profiler_mod.active_logdir() is not None:
        time.sleep(0.05)
    assert profiler_mod.active_logdir() is None
    assert time.monotonic() - t0 < 8.0, \
        "stop waited out the full capture window"
    # the entry leaves "running" (kept artifact or clean fail); the
    # finisher flips state just after releasing the profiler, so poll
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and rep._captures[cap["id"]]["state"] == "running":
        time.sleep(0.05)
    assert rep._captures[cap["id"]]["state"] in ("done", "failed")


def test_capture_fleet_concurrent_windows_and_annotation(
        model, fleet_cleanup, monkeypatch, tmp_path):
    """``capture_fleet`` opens one window per replica concurrently.
    In-process replicas share ONE process-global jax profiler, so
    exactly one window wins and the others refuse cleanly (409 ->
    None) — the annotation records who accepted."""
    monkeypatch.setenv("MXTPU_PROFILEZ_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("MXTPU_PROFILEZ_INTERVAL_S", "0")
    reps = [ReplicaServer(_engine(model), replica_id=f"cf-{i}").start()
            for i in range(2)]
    for r in reps:
        fleet_cleanup.append(r)
    col = FleetCollector(urls=[r.url for r in reps], interval_s=0)
    fleet_cleanup.append(col)
    col.scrape()                    # views need names before filtering
    results = col.capture_fleet(duration_s=0.3, reason="unit_fleet")
    assert set(results) == {"cf-0", "cf-1"}
    accepted = [n for n, p in results.items() if p]
    assert len(accepted) == 1, results
    ann = [a for a in col.annotations() if a["kind"] == "fleet_capture"]
    assert ann and ann[-1]["reason"] == "unit_fleet"
    caps = {c["replica"]: c for c in ann[-1]["captures"]}
    assert caps[accepted[0]]["accepted"] is True
    assert sum(c["accepted"] for c in caps.values()) == 1
    # role filter: no replica advertises "prefill" here -> no targets
    assert col.capture_fleet(duration_s=0.2, roles=("prefill",)) == {}
    # the winning window still finishes
    winner = [r for r in reps if r.replica_id == accepted[0]][0]
    meta = _wait_capture(winner.url, results[accepted[0]]["id"])
    assert meta["state"] in ("done", "failed")


# -- SLO fast-burn -> automatic capture + flight dump -------------------------
def test_slo_burn_triggers_capture_and_dump_carries_id(
        model, fleet_cleanup, monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("MXTPU_PROFILEZ_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("MXTPU_PROFILEZ_INTERVAL_S", "0")
    monkeypatch.setenv("MXTPU_PROFILEZ_BURN_S", "0.3")
    col = FleetCollector(urls=[], interval_s=0, port=0)
    fleet_cleanup.append(col)
    col.start()
    monkeypatch.setenv("MXTPU_TRACE_PUSH_URL", col.url + "/trace")
    slow = ReplicaServer(
        _engine(model), replica_id="slow-profilee",
        fault_injector=FaultInjector(
            ";".join(f"delay@{k}:0.4" for k in range(1, 9))))
    fleet_cleanup.append(slow.start())
    col.add_replica(slow.url)
    router = Router([slow.url], scrape_interval_s=0, retries=4,
                    backoff_s=0.01, backoff_max_s=0.05)
    fleet_cleanup.append(router)
    router.scrape()
    ev = SLOEvaluator(parse_slo_spec("total_p90_ms=150"), col,
                      fast_s=120.0, slow_s=240.0, fast_burn=2.0,
                      slow_burn=1.0, min_requests=5,
                      dump_interval_s=0.0)
    assert ev.capture_on_burn and ev.capture_s == 0.3
    col.slo = ev
    for i, p in enumerate(_prompts(8, seed=29)):
        res = router.generate(p.tolist(), max_new_tokens=4,
                              request_id=f"burn-{i}")
        assert res.tokens
        col.scrape()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and len(col.trace_records()) < 10:
        time.sleep(0.05)
    col.scrape()
    assert ev.statusz()["objectives"][0]["firing"], ev.statusz()
    # the alert captured the offender and chained the id into the dump
    dump_ann = [a for a in col.annotations()
                if a["kind"] == "slo_flight_dump"]
    assert dump_ann, col.annotations()
    # dump_interval_s=0 re-dumps every evaluation: later entries
    # legitimately degrade (409 while the first window runs, per-
    # reason dump rate limit) — the FIRST firing carries the real
    # capture id and dump path
    entry = dump_ann[0]["dumps"][0]
    assert entry["replica"] == "slow-profilee"
    assert entry["path"], entry
    cap_id = entry["capture_id"]
    assert cap_id, entry
    meta = _wait_capture(slow.url, cap_id)
    assert meta["state"] in ("done", "failed")
    assert meta["reason"].startswith("slo_burn_total_p90_ms")
    # the on-disk flight dump carries the same capture id
    dumps = list((tmp_path / "flight").glob("flight-*slo_burn*.json"))
    assert dumps
    payload = json.loads(dumps[0].read_text())
    assert payload["extra"]["capture_id"] == cap_id


# -- the stitched fleet timeline ----------------------------------------------
def test_timeline_report_stitches_fleet_run(model, fleet_cleanup,
                                            monkeypatch, tmp_path):
    import timeline_report

    trace_file = tmp_path / "trace.jsonl"
    monkeypatch.setenv("MXTPU_REQUEST_TRACE", str(trace_file))
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    router = Router([rep.url], scrape_interval_s=0)
    fleet_cleanup.append(router)
    router.scrape()
    for i in range(4):
        res = router.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                              request_id=f"tl-{i}")
        assert res.tokens
    # both line kinds must have flushed (router + engine per request)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        lines = [json.loads(ln) for ln
                 in trace_file.read_text().splitlines()] \
            if trace_file.exists() else []
        if len(lines) >= 8:
            break
        time.sleep(0.05)
    assert len(lines) >= 8, len(lines)
    statusz_file = tmp_path / "statusz.json"
    statusz_file.write_text(json.dumps(
        _get(rep.url, "/statusz.json")))
    out = tmp_path / "TIMELINE.json"
    summary_file = tmp_path / "summary.json"
    rc = timeline_report.main([
        "--trace", str(trace_file), "--statusz", str(statusz_file),
        "--out", str(out), "--json", str(summary_file), "--check"])
    assert rc == 0
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    assert evs and all("name" in e and "ph" in e for e in evs)
    assert all(e.get("dur", 0) >= 0 for e in evs if e["ph"] == "X")
    summary = json.loads(summary_file.read_text())["summary"]
    assert summary["requests"] == 4
    assert summary["router_hops"] == 4
    assert summary["unresolved_hops"] == []
    assert summary["steps"] > 0
    # fleet lines carry clock anchors: nothing floats unanchored
    assert summary["unanchored"] == 0
    # request events land under both the router and the replica pids
    req_pids = {e["pid"] for e in evs if e.get("cat") == "request"}
    assert len(req_pids) == 2
    # and a router-only trace id is what --check must catch
    orphan = tmp_path / "orphan.jsonl"
    orphan.write_text(json.dumps({
        "trace_id": "lost-req", "rid": 1, "status": "finished",
        "source": "router", "replica": "router",
        "events": [{"ev": "pick", "t": 0.0},
                   {"ev": "finished", "t": 0.1}]}) + "\n")
    rc = timeline_report.main([
        "--trace", str(orphan), "--out",
        str(tmp_path / "bad.json"), "--check"])
    assert rc == 1
