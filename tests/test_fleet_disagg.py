"""Disaggregated prefill/decode serving (mxnet_tpu/fleet, ISSUE 13).

Role-split replicas with content-keyed KV-block handoff over the wire:
``BlockManager.export_blocks``/``import_blocks`` unit semantics (chain
verification, dedup, truncation-degrades), the replica role surface
(``/generate`` on a prefill replica answers a handoff envelope,
``/handoff`` on a decode replica ingests it into the host tier), the
router's prefill→decode orchestration (role-aware least-loaded pick,
``/handoff_probe`` dedup, deadline/trace propagation), and the chaos
matrix — handoff drop, handoff delay past the router timeout,
decode-replica kill mid-handoff with supervisor respawn — every arm
byte-identical to a role="both" fleet.  Composition gates: handoff +
int8 KV + tp=2 + prefix sharing.

Everything is CPU-deterministic and in-process (the test_fleet.py
recipe: real HTTP replicas over real engines, no subprocesses); the
measured A/B contract lives in test_bench_contract-style slow tier
against ``tools/fleet_bench.py --disagg``.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.fleet import (DEAD, FaultInjector, ReplicaServer, Router,
                             Supervisor)
from mxnet_tpu.serve import BlockManager, HostKVPool

VOCAB = 53
POOL = 1 << 22


@pytest.fixture(scope="module")
def model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _prompts(n, seed=7, lo=10, hi=20, shared_prefix=0):
    rng = np.random.RandomState(seed)
    out = []
    prefix = rng.randint(0, VOCAB, (shared_prefix,)) if shared_prefix \
        else None
    for _ in range(n):
        p = rng.randint(0, VOCAB,
                        (rng.randint(lo, hi),)).astype(np.int32)
        if prefix is not None:
            p[:shared_prefix] = prefix
        out.append(p)
    return out


def _reference_tokens(model, prompts, max_new, **kw):
    eng = _engine(model, **kw)
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run()
    assert all(r.status == "finished" for r in reqs)
    out = [list(r.tokens) for r in reqs]
    eng.shutdown()
    return out


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


def _disagg_fleet(model, fleet_cleanup, n_decode=2, router_kw=None,
                  decode_kw=None, prefill_kw=None, decode_rep_kw=None):
    """1 prefill + ``n_decode`` decode replicas + a scraped router."""
    pre = ReplicaServer(_engine(model, **(prefill_kw or {})),
                        replica_id="pre", role="prefill").start()
    fleet_cleanup.append(pre)
    eng_kw = dict(host_kv_bytes=POOL)
    eng_kw.update(decode_kw or {})
    decs = []
    for i in range(n_decode):
        rep_kw = (decode_rep_kw or {}).get(i, {})
        rep = ReplicaServer(_engine(model, **eng_kw),
                            replica_id=f"dec{i}", role="decode",
                            **rep_kw).start()
        fleet_cleanup.append(rep)
        decs.append(rep)
    kw = dict(scrape_interval_s=0, timeout_s=30, retries=4,
              backoff_s=0.01, backoff_max_s=0.05)
    kw.update(router_kw or {})
    router = Router([pre.url] + [d.url for d in decs], **kw)
    fleet_cleanup.append(router)
    router.scrape()
    return pre, decs, router


# -- export/import units ------------------------------------------------------
def _fake_fetch(store):
    """Offload source over a dict: block id -> deterministic arrays."""
    def fetch(blk):
        return store.setdefault(
            blk, (np.full(8, float(blk), np.float32),
                  np.full(8, float(blk) + 0.5, np.float32)))
    return fetch


def test_export_import_roundtrip_and_chain_verification():
    m = BlockManager(num_blocks=9, block_size=4,
                     host_pool=HostKVPool(4096, block_tokens=4))
    m.set_offload_source(_fake_fetch({}))
    ids = list(range(30, 42))                     # 3 full blocks
    m.allocate("a", 12, token_ids=ids)
    m.note_tokens("a", ids)
    recs = m.export_blocks("a", ids)
    assert len(recs) == 3
    assert recs[0][1] is None                     # root has no parent
    assert recs[1][1] == recs[0][0]               # chain links
    assert recs[0][2] == ids[:4]
    # a finished request's blocks still export (parked published)
    m.free("a", retain=True)
    assert [r[0] for r in m.export_blocks("a", ids)] == \
        [r[0] for r in recs]

    # import into a second manager: all park in its host pool and the
    # next allocate walks them as cached tokens
    m2 = BlockManager(num_blocks=9, block_size=4,
                      host_pool=HostKVPool(4096, block_tokens=4))
    assert m2.import_blocks(recs) == (3, 0, 0)
    assert sorted(m2.has_blocks([r[0] for r in recs])) == \
        sorted(r[0] for r in recs)
    _, cached = m2.allocate("b", 13, token_ids=ids + [99])
    assert cached == 12                           # the whole chain hit
    # re-import of the same chain is a pure dedup
    m3_imported = m2.import_blocks(recs)
    assert m3_imported == (0, 3, 0)


def test_import_rejects_corrupt_and_out_of_chain_records():
    m = BlockManager(num_blocks=9, block_size=4,
                     host_pool=HostKVPool(4096, block_tokens=4))
    m.set_offload_source(_fake_fetch({}))
    ids = list(range(50, 62))
    m.allocate("a", 12, token_ids=ids)
    m.note_tokens("a", ids)
    recs = m.export_blocks("a", ids)

    tgt = BlockManager(num_blocks=9, block_size=4,
                       host_pool=HostKVPool(4096, block_tokens=4))
    # corrupt the middle record's tokens: its key no longer verifies,
    # so the chain stops after record 0 (the tail is unreachable)
    bad = [recs[0],
           (recs[1][0], recs[1][1], [1, 2, 3, 4], recs[1][3]),
           recs[2]]
    assert tgt.import_blocks(bad) == (1, 0, 2)
    assert len(tgt.has_blocks([r[0] for r in recs])) == 1
    # out-of-chain-order records never import
    tgt2 = BlockManager(num_blocks=9, block_size=4,
                        host_pool=HostKVPool(4096, block_tokens=4))
    assert tgt2.import_blocks(recs[1:]) == (0, 0, 2)
    # a record with bytes skipped (dedup probe) that is NOT actually
    # cached here breaks the chain instead of importing a hole
    tgt3 = BlockManager(num_blocks=9, block_size=4,
                        host_pool=HostKVPool(4096, block_tokens=4))
    skipped = [(recs[0][0], None, recs[0][2], None)] + recs[1:]
    assert skipped[0][3] is None
    assert tgt3.import_blocks(skipped) == (0, 0, 3)
    # without a host pool nothing imports (and nothing crashes)
    plain = BlockManager(num_blocks=9, block_size=4)
    assert plain.import_blocks(recs) == (0, 0, 3)


def test_pool_peek_leaves_entry_parked():
    p = HostKVPool(4096, block_tokens=4)
    arrs = (np.full(4, 7.0, np.float32),)
    p.put(b"k", None, arrs)
    got = p.peek(b"k")
    assert got is not None and got[0][0] == 7.0
    assert p.has(b"k") and p.restores == 0        # still parked
    assert p.peek(b"missing") is None


# -- role surface -------------------------------------------------------------
def test_role_validation_and_health_signal(model):
    with pytest.raises(ValueError, match="role"):
        ReplicaServer(_engine(model), role="weird")
    # decode role demands the host tier (records land in it)
    eng = _engine(model)
    with pytest.raises(ValueError, match="host-RAM KV tier"):
        ReplicaServer(eng, role="decode")
    eng.shutdown()
    # default role is "both" and the new fields ride /healthz
    eng = _engine(model)
    rep = ReplicaServer(eng, replica_id="r0")
    assert rep.role == "both"
    h = rep._health()
    assert h["role"] == "both" and h["waiting_handoffs"] == 0
    s = rep._replica_state()
    assert s["role"] == "both"
    assert s["handoff"]["received"] == 0
    eng.shutdown()


def test_wrong_role_is_retriable_503(model, fleet_cleanup):
    pre = ReplicaServer(_engine(model), replica_id="p",
                        role="prefill").start()
    dec = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="d", role="decode").start()
    fleet_cleanup.extend([pre, dec])
    prompt = _prompts(1)[0].tolist()

    def post(url, path, payload):
        req = urllib.request.Request(
            f"{url}{path}", data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    code, out = post(dec.url, "/generate",
                     {"prompt": prompt, "max_new_tokens": 4})
    assert code == 503 and out["error"] == "wrong_role"
    assert out["retriable"] is True
    code, out = post(pre.url, "/handoff",
                     {"prompt": prompt, "max_new_tokens": 4,
                      "records": []})
    assert code == 503 and out["error"] == "wrong_role"
    # a prefill replica rejects requests whose FULL length could never
    # be served (it only submits prompt+1 itself)
    code, out = post(pre.url, "/generate",
                     {"prompt": [1] * 40, "max_new_tokens": 30})
    assert code == 400 and out["error"] == "exceeds_max_len"


# -- disaggregated fleet ------------------------------------------------------
def test_disagg_fleet_token_identity_and_dedup(model, fleet_cleanup):
    """The acceptance core: a 1-prefill + 2-decode fleet serves
    byte-identically to an uncontended engine, transferred spans count
    as cached tokens on the decode side, and shared prefixes dedup on
    the wire (the radix key IS the transfer dedup)."""
    prompts = _prompts(5, seed=11, shared_prefix=8)
    refs = _reference_tokens(model, prompts, 8)
    pre, decs, router = _disagg_fleet(model, fleet_cleanup)
    for i, p in enumerate(prompts):
        res = router.generate(p.tolist(), max_new_tokens=8,
                              request_id=f"dg-{i}")
        assert res.tokens == refs[i], f"request {i} diverged"
        assert [h.get("hop") for h in res.hops] == [None, "handoff"]
    pstate = pre._replica_state()
    assert pstate["handoff"]["exported"] == len(prompts)
    assert pstate["handoff"]["bytes_exported"] > 0
    received = imported = deduped = 0
    restored = 0
    for d in decs:
        h = d._replica_state()["handoff"]
        received += h["received"]
        imported += h["blocks_imported"]
        deduped += h["blocks_deduped"]
        restored += d.engine.stats().host_kv_restored_tokens
    assert received == len(prompts)
    assert imported > 0
    # the shared 8-token prefix (2 blocks) dedups once a decode
    # replica has seen it — with 5 prompts over 2 replicas at least
    # one repeat lands somewhere
    assert deduped > 0
    assert restored > 0          # imported chains really restored


def test_disagg_identity_int8_kv_tp2_prefix_sharing(model,
                                                    fleet_cleanup):
    """Composition gate: handoff x int8 KV blocks x tp=2 x shared
    prefixes — byte-identical to a role='both' engine with the same
    formulation (identity is per-formulation, as everywhere)."""
    kw = dict(kv_dtype="int8", tp=2)
    prompts = _prompts(3, seed=13, shared_prefix=8)
    refs = _reference_tokens(model, prompts, 6, **kw)
    pre, decs, router = _disagg_fleet(
        model, fleet_cleanup, n_decode=1,
        prefill_kw=kw, decode_kw=kw)
    for i, p in enumerate(prompts):
        res = router.generate(p.tolist(), max_new_tokens=6,
                              request_id=f"q-{i}")
        assert res.tokens == refs[i], f"request {i} diverged"
    h = decs[0]._replica_state()["handoff"]
    assert h["received"] == 3 and h["blocks_imported"] > 0
    # int8 wire records carry the scale slots: 2 extra arrays
    assert len(decs[0].engine.host_block_spec()) == 4


def test_handoff_idempotency_by_request_id(model, fleet_cleanup):
    prompts = _prompts(1, seed=17)
    [ref] = _reference_tokens(model, prompts, 6)
    pre, (dec,), router = _disagg_fleet(model, fleet_cleanup,
                                        n_decode=1)
    r1 = router.generate(prompts[0].tolist(), max_new_tokens=6,
                         request_id="same-id")
    r2 = router.generate(prompts[0].tolist(), max_new_tokens=6,
                         request_id="same-id")
    assert r1.tokens == r2.tokens == ref
    # at-most-once execution per replica: the decode replica served
    # the id once, the retry came from its done-cache
    assert dec.engine.stats().completed == 1
    assert pre.engine.stats().completed == 1


# -- chaos matrix -------------------------------------------------------------
def test_handoff_drop_degrades_to_recompute(model, fleet_cleanup):
    """MXTPU_FAULT_HANDOFF_DROP: the KV records never arrive — the
    decode replica recomputes from the prompt, tokens byte-identical,
    zero imports, drops counted."""
    prompts = _prompts(3, seed=19)
    refs = _reference_tokens(model, prompts, 8)
    pre, decs, router = _disagg_fleet(
        model, fleet_cleanup, n_decode=2,
        decode_rep_kw={0: dict(handoff_drop=100),
                       1: dict(handoff_drop=100)})
    for i, p in enumerate(prompts):
        res = router.generate(p.tolist(), max_new_tokens=8,
                              request_id=f"dr-{i}")
        assert res.tokens == refs[i], f"request {i} diverged"
    h0 = decs[0]._replica_state()["handoff"]
    h1 = decs[1]._replica_state()["handoff"]
    assert h0["blocks_imported"] + h1["blocks_imported"] == 0
    assert h0["drops"] + h1["drops"] == 3


def test_handoff_delay_times_out_and_rehandoffs_on_sibling(
        model, fleet_cleanup):
    """MXTPU_FAULT_HANDOFF_DELAY past the router's per-hop timeout:
    the handoff hop times out and the router re-sends the payload it
    still holds to the sibling decode replica."""
    prompts = _prompts(2, seed=23)
    refs = _reference_tokens(model, prompts, 6)
    pre, decs, router = _disagg_fleet(
        model, fleet_cleanup, n_decode=2,
        router_kw=dict(timeout_s=1.0),
        decode_rep_kw={0: dict(handoff_delay_s=5.0)})
    saw_timeout = False
    for i, p in enumerate(prompts):
        res = router.generate(p.tolist(), max_new_tokens=6,
                              request_id=f"dl-{i}")
        assert res.tokens == refs[i], f"request {i} diverged"
        saw_timeout = saw_timeout or any(
            h["status"] == "timeout" and h.get("hop") == "handoff"
            for h in res.hops)
    assert saw_timeout, "no handoff ever hit the slow replica"


def test_handoff_payload_corruption_detected(model):
    """Same-length byte corruption (valid keys, valid record sizes —
    the arm the chain hash alone cannot catch) fails the payload
    digest at decode, so wrong K/V can never park under a valid
    content key; the receiver degrades to recompute."""
    import base64

    src_eng = _engine(model)
    pre = ReplicaServer(src_eng, replica_id="src", role="prefill")
    prompt = _prompts(1, seed=43, lo=12, hi=13)[0]
    req = src_eng.submit(prompt, max_new_tokens=1)
    src_eng.run()
    records, nbytes = pre._encode_records(
        src_eng.blocks.export_blocks(req.rid, prompt))
    assert records and nbytes > 0
    dst_eng = _engine(model, host_kv_bytes=POOL)
    dst = ReplicaServer(dst_eng, replica_id="dst", role="decode")
    parsed, _ = dst._decode_records(records)      # clean decode works
    assert parsed[0][3] is not None
    raw = bytearray(base64.b64decode(records[0]["k"]))
    raw[0] ^= 0xFF                                # same length, wrong bytes
    records[0]["k"] = base64.b64encode(bytes(raw)).decode()
    with pytest.raises(ValueError, match="digest"):
        dst._decode_records(records)
    src_eng.shutdown()
    dst_eng.shutdown()


class _InProcHandle:
    def __init__(self, replica):
        self.replica = replica
        self.url = replica.url

    def poll(self):
        return None if self.replica.state != DEAD else 1

    def terminate(self, grace_s=None):
        self.replica.stop()


def test_decode_kill_mid_handoff_rehandoff_and_respawn(
        model, fleet_cleanup):
    """Chaos gate: a decode replica dies mid-handoff (kill fault on
    its first /handoff arrival).  The router re-handoffs to the
    sibling — tokens identical — and the supervisor respawns the dead
    slot, after which it serves handoffs again."""
    prompts = _prompts(4, seed=29)
    refs = _reference_tokens(model, prompts, 8)
    pre = ReplicaServer(_engine(model), replica_id="pre",
                        role="prefill").start()
    fleet_cleanup.append(pre)
    router = Router([pre.url], scrape_interval_s=0, timeout_s=30,
                    retries=4, backoff_s=0.01, backoff_max_s=0.05)
    fleet_cleanup.append(router)
    spawned = []

    def spawn(slot):
        injector = (FaultInjector("kill@1")
                    if slot == 0 and not spawned else None)
        rep = ReplicaServer(
            _engine(model, host_kv_bytes=POOL),
            replica_id=f"dec{slot}-{len(spawned)}", role="decode",
            fault_injector=injector).start()
        fleet_cleanup.append(rep)
        spawned.append(rep)
        return _InProcHandle(rep)

    sup = Supervisor(spawn, 2, router=router, restart_backoff_s=0.0)
    sup.start()
    router.scrape()
    doomed = spawned[0]
    results = [router.generate(p.tolist(), max_new_tokens=8,
                               request_id=f"k-{i}")
               for i, p in enumerate(prompts)]
    for i, res in enumerate(results):
        assert res.tokens == refs[i], f"request {i} diverged"
    assert doomed.state == DEAD, "kill fault never fired"
    assert any(len([h for h in r.hops if h.get("hop") == "handoff"]) > 1
               for r in results), "no re-handoff happened"
    # supervisor respawns the dead slot; its replacement serves
    assert sup.check() == [0]
    router.scrape()
    replacement = spawned[-1]
    assert replacement is not doomed
    res = router.generate(prompts[0].tolist(), max_new_tokens=8,
                          request_id="after-respawn")
    assert res.tokens == refs[0]
    sup.stop()


def test_no_decode_replica_exhausts_cleanly(model, fleet_cleanup):
    """A role-split fleet whose every decode replica is gone fails the
    handoff with NoReplicaAvailable after the retry budget — never a
    hang, never a wrong answer."""
    from mxnet_tpu.fleet import NoReplicaAvailable

    pre, (dec,), router = _disagg_fleet(model, fleet_cleanup,
                                        n_decode=1)
    dec.hard_stop()
    router.scrape()
    with pytest.raises(NoReplicaAvailable, match="handoff"):
        router.generate(_prompts(1)[0].tolist(), max_new_tokens=4,
                        request_id="nd-1")


def test_handoff_deadline_propagates_end_to_end(model, fleet_cleanup):
    """deadline_s spans BOTH hops: a decode side that can only reject
    (draining) exhausts the one budget with PermanentError instead of
    getting a fresh window per re-handoff."""
    from mxnet_tpu.fleet import PermanentError

    pre, (dec,), router = _disagg_fleet(
        model, fleet_cleanup, n_decode=1,
        router_kw=dict(retries=10, backoff_s=0.05, backoff_max_s=0.05))
    dec.drain()
    with pytest.raises(PermanentError, match="exhausted"):
        router.generate(_prompts(1)[0].tolist(), max_new_tokens=4,
                        deadline_s=0.3, request_id="ddl-1")


# -- traces + load signal -----------------------------------------------------
def test_trace_stitches_across_roles(model, tmp_path, monkeypatch,
                                     fleet_cleanup):
    """One trace id spans the prefill hop, the decode hop AND (since
    PR 14) the router's own hop-event line — trace_report --stitch
    sees a single multi-hop request."""
    monkeypatch.setenv("MXTPU_REQUEST_TRACE",
                       str(tmp_path / "trace.jsonl"))
    prompts = _prompts(1, seed=31)
    pre, (dec,), router = _disagg_fleet(model, fleet_cleanup,
                                        n_decode=1)
    res = router.generate(prompts[0].tolist(), max_new_tokens=6,
                          request_id="tr-1", trace_id="disagg-tr-1")
    assert res.trace_id == "disagg-tr-1"
    # both replicas' engines share the process-wide trace file here;
    # stop them so the lines flush
    for rep in (pre, dec):
        rep.stop()
    lines = [json.loads(l) for l in
             (tmp_path / "trace.jsonl").read_text().splitlines()
             if l.strip()]
    hops = [l for l in lines if l.get("trace_id") == "disagg-tr-1"]
    # one line per role plus the router's hop-event line (it writes
    # under the same MXTPU_REQUEST_TRACE opt-in, same trace id)
    assert len(hops) == 3
    assert all(h["status"] == "finished" for h in hops)
    router_lines = [h for h in hops if h.get("replica") == "router"]
    assert len(router_lines) == 1
    router_evs = [e["ev"] for e in router_lines[0]["events"]]
    # the stitched view shows router time: pick + generate hop +
    # the handoff move to the decode replica
    assert "pick" in router_evs and "hop" in router_evs
    assert "handoff" in router_evs
    # the decode hop's admit event is marked as a handoff ingest with
    # the transferred span counted as cached tokens
    admits = [e for h in hops for e in h["events"]
              if e["ev"] == "admitted"]
    handoff_admits = [e for e in admits if e.get("handoff")]
    assert len(handoff_admits) == 1
    assert handoff_admits[0]["cached_tokens"] > 0
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_report

    traces = []
    for h in hops:
        traces.append((h, {}, h["status"], None, True))
    s = trace_report.stitch(traces)
    assert s["requests"] == 1 and s["max_hops"] == 3
    assert s["unresolved"] == []


def test_waiting_handoffs_load_signal(model, fleet_cleanup):
    """waiting_handoffs counts accepted-but-not-admitted ingests in
    /healthz and the router's load score reads it."""
    eng = _engine(model, host_kv_bytes=POOL)
    rep = ReplicaServer(eng, replica_id="wh", role="decode")
    assert rep.waiting_handoffs == 0
    # a queued handoff request (scheduler component of the signal)
    req = eng.submit(_prompts(1)[0], max_new_tokens=2, handoff=True)
    assert eng.scheduler.waiting_handoffs() == 1
    assert rep.waiting_handoffs == 1
    assert rep._health()["waiting_handoffs"] == 1
    eng.run()
    assert req.status == "finished"
    assert rep.waiting_handoffs == 0
    eng.shutdown()
    # the router folds it into the load score
    score_idle = Router._load_score(
        {"max_batch": 4, "queue_depth": 0, "running": 0,
         "kv_utilization": 0.0})
    score_busy = Router._load_score(
        {"max_batch": 4, "queue_depth": 0, "running": 0,
         "waiting_handoffs": 2, "kv_utilization": 0.0})
    assert score_busy > score_idle


def test_role_unset_is_inert_schema(model, fleet_cleanup):
    """MXTPU_FLEET_ROLE unset: role 'both', /generate serves tokens
    directly (no handoff envelope), and the /healthz payload is the
    pre-disaggregation one plus only the new optional fields."""
    assert "MXTPU_FLEET_ROLE" not in os.environ
    rep = ReplicaServer(_engine(model), replica_id="inert").start()
    fleet_cleanup.append(rep)
    router = Router([rep.url], scrape_interval_s=0, timeout_s=30,
                    retries=2)
    router.scrape()
    prompts = _prompts(1, seed=37)
    [ref] = _reference_tokens(model, prompts, 6)
    res = router.generate(prompts[0].tolist(), max_new_tokens=6,
                          request_id="in-1")
    assert res.tokens == ref
    assert [h.get("hop") for h in res.hops] == [None]   # single hop
    with urllib.request.urlopen(f"{rep.url}/healthz",
                                timeout=10) as resp:
        hz = json.loads(resp.read())
    legacy = {"status", "state", "in_flight", "queue_depth", "running",
              "host_kv_utilization"}
    assert legacy <= set(hz)
    # the documented additive fields: the disaggregation role/load
    # signals plus the (size-bounded) routable-cache advertisement
    assert set(hz) - legacy == {"role", "waiting_handoffs",
                                "kv_summary"}
    # the advertisement stays bounded: bloom bitmap of m/8 bytes plus
    # at most top_k truncated-hex keys, whatever the cache holds
    ks = hz["kv_summary"]
    assert ks["bloom"]["m"] // 8 >= len(ks["bloom"]["bits"]) * 3 // 4 - 3
    assert len(ks["top"]) <= 32


# -- process-fleet A/B contract (slow tier) -----------------------------------
@pytest.mark.slow
def test_disagg_bench_contract():
    """The DISAGG_BENCH.json stage contract: complete:true (both arms
    availability 1.0, byte-identical tokens, handoffs flowed) and the
    decode-stall improvement the disaggregation exists for."""
    out = "/tmp/disagg_bench_contract.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--disagg", "--json", out],
        capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    with open(out) as f:
        rec = json.load(f)
    assert rec["complete"] is True
    assert rec["tokens_identical"] is True
    assert rec["disagg"]["availability"] == 1.0
    assert rec["interleaved"]["availability"] == 1.0
    assert rec["handoff_bytes"] > 0
    assert rec["handoff_dedup_blocks"] > 0
    # timing-based: assert the direction with margin (the bench_watch
    # stage holds the >= 3x line for the committed artifact)
    assert rec["stall_improvement"] >= 2
