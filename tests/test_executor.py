"""Executor bind / grad_req semantics (rebuild of test_executor.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def _setup():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a * b + a
    return out


def test_bind_forward():
    out = _setup()
    ashape = (3, 4)
    a_arr = mx.nd.array(np.random.rand(*ashape))
    b_arr = mx.nd.array(np.random.rand(*ashape))
    exe = out.bind(mx.cpu(), args={"a": a_arr, "b": b_arr})
    res = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(res, a_arr.asnumpy() * b_arr.asnumpy()
                               + a_arr.asnumpy(), rtol=1e-6)


def test_backward_write_req():
    out = _setup()
    a_arr = mx.nd.array(np.random.rand(2, 2))
    b_arr = mx.nd.array(np.random.rand(2, 2))
    ga = mx.nd.zeros((2, 2))
    gb = mx.nd.zeros((2, 2))
    exe = out.bind(mx.cpu(), args=[a_arr, b_arr], args_grad=[ga, gb],
                   grad_req="write")
    exe.forward(is_train=True)
    head = mx.nd.ones((2, 2))
    exe.backward([head])
    np.testing.assert_allclose(ga.asnumpy(), b_arr.asnumpy() + 1, rtol=1e-6)
    np.testing.assert_allclose(gb.asnumpy(), a_arr.asnumpy(), rtol=1e-6)


def test_backward_add_req():
    out = _setup()
    a_arr = mx.nd.array(np.random.rand(2, 2))
    b_arr = mx.nd.array(np.random.rand(2, 2))
    ga = mx.nd.ones((2, 2))
    gb = mx.nd.ones((2, 2))
    exe = out.bind(mx.cpu(), args=[a_arr, b_arr], args_grad=[ga, gb],
                   grad_req="add")
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ga.asnumpy(), 1 + b_arr.asnumpy() + 1, rtol=1e-6)
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ga.asnumpy(), 1 + 2 * (b_arr.asnumpy() + 1),
                               rtol=1e-6)


def test_null_grad_req():
    out = _setup()
    exe = out.simple_bind(mx.cpu(), grad_req="null", a=(2, 2), b=(2, 2))
    exe.forward(is_train=True)
    exe.backward()  # no-op
    assert exe.grad_dict == {}


def test_grad_req_dict():
    out = _setup()
    exe = out.simple_bind(mx.cpu(), grad_req={"a": "write", "b": "null"},
                          a=(2, 2), b=(2, 2))
    assert "a" in exe.grad_dict and "b" not in exe.grad_dict


def test_forward_kwargs_assign():
    out = _setup()
    exe = out.simple_bind(mx.cpu(), a=(2, 2), b=(2, 2))
    res = exe.forward(a=np.ones((2, 2)), b=np.full((2, 2), 3.0))[0]
    np.testing.assert_allclose(res.asnumpy(), np.full((2, 2), 4.0))


def test_reshape():
    out = _setup()
    exe = out.simple_bind(mx.cpu(), a=(2, 2), b=(2, 2))
    # growing an array requires allow_up_sizing (reference reshape contract)
    exe2 = exe.reshape(a=(4, 2), b=(4, 2), allow_up_sizing=True)
    res = exe2.forward(a=np.ones((4, 2)), b=np.ones((4, 2)))[0]
    assert res.shape == (4, 2)


def test_executor_loss_default_head_grad():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(fc, label, name="sm")
    exe = out.simple_bind(mx.cpu(), data=(4, 5), label=(4,))
    exe.arg_dict["data"][:] = np.random.randn(4, 5)
    exe.arg_dict["fc_weight"][:] = np.random.randn(3, 5) * 0.1
    exe.arg_dict["label"][:] = [0, 1, 2, 0]
    exe.forward(is_train=True)
    exe.backward()  # loss head: no explicit out_grads needed
    g = exe.grad_dict["fc_weight"].asnumpy()
    assert np.abs(g).sum() > 0


def test_monitor_callback():
    out = _setup()
    exe = out.simple_bind(mx.cpu(), a=(2, 2), b=(2, 2))
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward()
    assert any("_output" in s for s in seen)


def test_mirror_attr_runs():
    # force_mirroring (gradient checkpointing) produces identical grads
    data = mx.sym.Variable("data")
    with mx.AttrScope(force_mirroring="1"):
        act = mx.sym.Activation(data, act_type="tanh")
    out = mx.sym.MakeLoss(mx.sym.sum(act * act))
    exe = out.simple_bind(mx.cpu(), data=(3, 3))
    x = np.random.RandomState(0).randn(3, 3) * 0.5
    exe.arg_dict["data"][:] = x
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    expected = 2 * np.tanh(x) * (1 - np.tanh(x) ** 2)
    np.testing.assert_allclose(g, expected, rtol=1e-5, atol=1e-6)


def test_backward_do_mirror_env(monkeypatch):
    # MXNET_BACKWARD_DO_MIRROR=1 rematerializes activations; gradients
    # must be identical to the unmirrored run
    import numpy as np

    def build():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        net = mx.sym.Activation(net, act_type="tanh")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    x = rng.standard_normal((4, 8)).astype(np.float32)
    y = rng.randint(0, 4, 4).astype(np.float32)

    grads = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", flag)
        exe = build().simple_bind(mx.cpu(), grad_req="write",
                                  data=(4, 8), softmax_label=(4,))
        rng2 = np.random.RandomState(7)
        for name, arr in exe.arg_dict.items():
            if name not in ("data", "softmax_label"):
                arr[:] = rng2.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
        exe.forward(is_train=True, data=x, softmax_label=y)
        exe.backward()
        grads[flag] = exe.grad_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(grads["0"], grads["1"], rtol=1e-5, atol=1e-6)


def test_profiler_api_smoke(tmp_path):
    from mxnet_tpu import profiler

    @profiler.annotate("square")
    def f(v):
        return v * v

    with profiler.trace(str(tmp_path / "prof")):
        with profiler.scope("region"):
            f(np.ones(4))
    mem = profiler.device_memory()
    assert isinstance(mem, dict) and len(mem) >= 1


def test_reshape_partial_shaping_and_up_sizing_flags():
    """Reference executor.py reshape contract: un-named arrays may only
    change shape under partial_shaping=True; growth requires
    allow_up_sizing=True."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(8, 6), softmax_label=(8,))

    # batch-only change: weights keep shape; smaller batch is fine
    ex2 = ex.reshape(data=(4, 6), softmax_label=(4,))
    assert ex2.arg_dict["data"].shape == (4, 6)
    # weight buffers are carried over, not re-allocated
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]

    # growing the batch requires allow_up_sizing
    with pytest.raises(Exception):
        ex.reshape(data=(16, 6), softmax_label=(16,))
    ex3 = ex.reshape(data=(16, 6), softmax_label=(16,),
                     allow_up_sizing=True)
    assert ex3.arg_dict["data"].shape == (16, 6)

    # feature-dim change reshapes fc_weight (not named in kwargs):
    # rejected without partial_shaping
    with pytest.raises(Exception):
        ex.reshape(data=(8, 3), softmax_label=(8,))
    ex4 = ex.reshape(data=(8, 3), softmax_label=(8,),
                     partial_shaping=True)
    assert ex4.arg_dict["fc_weight"].shape == (4, 3)


def test_reshape_preserves_buffer_prefix():
    """Same-or-smaller reshape carries the old buffer's leading elements
    (reference reuses the allocation; content must survive)."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(8, 6), softmax_label=(8,))
    w = np.arange(24, dtype=np.float32).reshape(4, 6)
    ex.arg_dict["fc_weight"][:] = w
    ex2 = ex.reshape(data=(8, 3), softmax_label=(8,), partial_shaping=True)
    np.testing.assert_allclose(ex2.arg_dict["fc_weight"].asnumpy(),
                               w.reshape(-1)[:12].reshape(4, 3))


# -- partial_forward (stepwise execution) -----------------------------------
# reference: GraphExecutor::PartialForward, graph_executor.cc:994-1001


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def test_partial_forward_prefix_equality():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(5, 7), softmax_label=(5,))
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        arr[:] = rng.uniform(-1, 1, arr.shape)
    full = exe.forward()[0].asnumpy()

    step = 0
    steps_seen = 0
    while True:
        left = exe.partial_forward(is_train=False, step=step)
        steps_seen += 1
        if left == 0:
            break
        step += 1
    assert steps_seen == exe.num_forward_nodes
    np.testing.assert_allclose(exe.outputs[0].asnumpy(), full, rtol=1e-6)


def test_partial_forward_out_of_order_raises():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(3, 7), softmax_label=(3,))
    with pytest.raises(mx.MXNetError, match="increasing order"):
        exe.partial_forward(step=2)


def test_partial_forward_past_end_returns_zero():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(3, 7), softmax_label=(3,))
    assert exe.partial_forward(step=exe.num_forward_nodes + 5) == 0


def test_partial_forward_monitor_callback():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(4, 7), softmax_label=(4,))
    rng = np.random.RandomState(1)
    for name, arr in exe.arg_dict.items():
        arr[:] = rng.uniform(-1, 1, arr.shape)
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    step = 0
    while exe.partial_forward(step=step) != 0:
        step += 1
    assert any("fc1" in n for n in seen)
    assert any("softmax" in n for n in seen)


def test_partial_forward_then_backward():
    """Train-mode stepwise run then backward() — grads must match the
    fused forward(is_train=True)+backward() path."""
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(6, 7), softmax_label=(6,))
    rng = np.random.RandomState(2)
    for name, arr in exe.arg_dict.items():
        if name == "softmax_label":
            arr[:] = rng.randint(0, 4, arr.shape).astype(np.float32)
        else:
            arr[:] = rng.uniform(-1, 1, arr.shape)

    step = 0
    while exe.partial_forward(is_train=True, step=step) != 0:
        step += 1
    exe.backward()
    got = {k: v.asnumpy().copy() for k, v in exe.grad_dict.items()}

    exe.forward(is_train=True)
    exe.backward()
    want = {k: v.asnumpy() for k, v in exe.grad_dict.items()}
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)


def test_partial_forward_batchnorm_aux_commit():
    """Completing a train-mode stepwise run commits aux (moving stats)
    exactly like forward(is_train=True)."""
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data, name="bn")
    exe = net.simple_bind(mx.cpu(), data=(8, 3))
    exe2 = net.simple_bind(mx.cpu(), data=(8, 3))
    rng = np.random.RandomState(3)
    x = rng.uniform(-2, 2, (8, 3)).astype(np.float32)
    for e in (exe, exe2):
        e.arg_dict["data"][:] = x
        e.arg_dict["bn_gamma"][:] = 1
        e.arg_dict["bn_beta"][:] = 0

    step = 0
    while exe.partial_forward(is_train=True, step=step) != 0:
        step += 1
    exe2.forward(is_train=True)
    for k in exe.aux_dict:
        np.testing.assert_allclose(exe.aux_dict[k].asnumpy(),
                                   exe2.aux_dict[k].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                               exe2.outputs[0].asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_monitor_train_backward_grads_match():
    """Monitor installed during TRAINING: backward() must produce the
    same gradients as the unmonitored fused path (the reference Monitor
    is a training-loop tool)."""
    net = _mlp()

    def make():
        exe = net.simple_bind(mx.cpu(), data=(6, 7), softmax_label=(6,))
        rng = np.random.RandomState(4)
        for name, arr in exe.arg_dict.items():
            if name == "softmax_label":
                arr[:] = rng.randint(0, 4, arr.shape).astype(np.float32)
            else:
                arr[:] = rng.uniform(-1, 1, arr.shape)
        return exe

    plain = make()
    plain.forward(is_train=True)
    plain.backward()
    want = {k: v.asnumpy() for k, v in plain.grad_dict.items()}

    mon = make()
    seen = []
    mon.set_monitor_callback(lambda name, arr: seen.append(name))
    mon.forward(is_train=True)
    mon.backward()
    assert seen  # stats actually collected
    got = {k: v.asnumpy() for k, v in mon.grad_dict.items()}
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)
