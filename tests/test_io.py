"""IO iterators + RecordIO (rebuild of test_io.py / test_recordio.py)."""

import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io import (CSVIter, DataBatch, MNISTIter, NDArrayIter,
                          PrefetchingIter, ResizeIter)


def test_ndarray_iter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    labels = np.arange(25).astype(np.float32)
    it = NDArrayIter(data, labels, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5])
    np.testing.assert_allclose(batches[2].label[0].asnumpy(), labels[10:15])
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad():
    data = np.arange(23 * 2).reshape(23, 2).astype(np.float32)
    it = NDArrayIter(data, np.zeros(23), batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    # padded rows wrap to the beginning
    np.testing.assert_allclose(batches[-1].data[0].asnumpy()[-2:], data[:2])


def test_ndarray_iter_discard():
    data = np.zeros((23, 2), np.float32)
    it = NDArrayIter(data, np.zeros(23), batch_size=5,
                     last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarray_iter_dict_data():
    it = NDArrayIter({"a": np.zeros((10, 2)), "b": np.zeros((10, 3))},
                     np.zeros(10), batch_size=5)
    assert sorted(d[0] for d in it.provide_data) == ["a", "b"]


def test_resize_iter():
    data = np.zeros((20, 2), np.float32)
    it = ResizeIter(NDArrayIter(data, np.zeros(20), batch_size=5), size=7)
    assert len(list(it)) == 7
    it.reset()
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(40).reshape(20, 2).astype(np.float32)
    base = NDArrayIter(data, np.zeros(20), batch_size=5)
    it = PrefetchingIter(base)
    total = 0
    for epoch in range(3):
        got = []
        for batch in it:
            got.append(batch.data[0].asnumpy())
            total += 1
        it.reset()
        np.testing.assert_allclose(got[0], data[:5])
    assert total == 12


def test_prefetching_iter_capacity_env():
    """MXTPU_PREFETCH_CAPACITY sets the queue depth when the ctor
    doesn't; an explicit capacity argument always wins; the live queue
    depth is exported as a telemetry gauge."""
    import os

    from mxnet_tpu import telemetry

    data = np.zeros((20, 2), np.float32)
    os.environ["MXTPU_PREFETCH_CAPACITY"] = "5"
    try:
        it = PrefetchingIter(NDArrayIter(data, np.zeros(20), batch_size=5))
        assert it.capacity == 5
        assert it._queue.maxsize == 5
        it2 = PrefetchingIter(NDArrayIter(data, np.zeros(20), batch_size=5),
                              capacity=3)
        assert it2.capacity == 3
    finally:
        os.environ.pop("MXTPU_PREFETCH_CAPACITY", None)

    telemetry.enable()
    telemetry.reset()
    try:
        it3 = PrefetchingIter(NDArrayIter(data, np.zeros(20), batch_size=5))
        for _ in it3:
            pass
        snap = telemetry.registry().snapshot()
        sample = snap["mxtpu_io_prefetch_depth"]["samples"][0]
        assert sample["labels"]["iterator"] == "PrefetchingIter"
        assert 0 <= sample["value"] <= it3.capacity
    finally:
        telemetry.disable()
        telemetry.reset()


def test_csv_iter(tmp_path):
    data = np.random.rand(10, 4).astype(np.float32)
    labels = np.arange(10).astype(np.float32)
    dcsv = str(tmp_path / "d.csv")
    lcsv = str(tmp_path / "l.csv")
    np.savetxt(dcsv, data, delimiter=",")
    np.savetxt(lcsv, labels, delimiter=",")
    it = CSVIter(data_csv=dcsv, data_shape=(4,), label_csv=lcsv, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5],
                               rtol=1e-5)


def _write_idx(path, arr):
    """Write MNIST idx format."""
    with open(path, "wb") as f:
        dtype_code = {np.uint8: 8}[arr.dtype.type]
        f.write(struct.pack(">i", (dtype_code << 8) + arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">i", d))
        f.write(arr.tobytes())


def test_mnist_iter(tmp_path):
    images = (np.random.rand(50, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, 50).astype(np.uint8)
    img_path = str(tmp_path / "img.idx")
    lab_path = str(tmp_path / "lab.idx")
    _write_idx(img_path, images)
    _write_idx(lab_path, labels)
    it = MNISTIter(image=img_path, label=lab_path, batch_size=10,
                   shuffle=False)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (10, 1, 28, 28)
    np.testing.assert_allclose(batches[0].data[0].asnumpy()[0, 0],
                               images[0] / 255.0, rtol=1e-5)
    # flat + sharded
    it2 = MNISTIter(image=img_path, label=lab_path, batch_size=5, flat=True,
                    shuffle=False, part_index=1, num_parts=2)
    b = next(iter(it2))
    assert b.data[0].shape == (5, 784)
    np.testing.assert_allclose(b.data[0].asnumpy()[0],
                               images[1].ravel() / 255.0, rtol=1e-5)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    payloads = [b"x" * n for n in (1, 5, 125, 1000)]
    for p in payloads:
        writer.write(p)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for expected in payloads:
        assert reader.read() == expected
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    rec = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(10):
        writer.write_idx(i, f"record{i}".encode())
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert reader.read_idx(7) == b"record7"
    assert reader.read_idx(2) == b"record2"
    assert reader.keys == list(range(10))


def test_irheader_pack_unpack():
    hdr = recordio.IRHeader(0, 3.0, 42, 0)
    packed = recordio.pack(hdr, b"payload")
    hdr2, payload = recordio.unpack(packed)
    assert hdr2.label == 3.0
    assert hdr2.id == 42
    assert payload == b"payload"
    # multi-label
    hdr = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    packed = recordio.pack(hdr, b"data")
    hdr2, payload = recordio.unpack(packed)
    np.testing.assert_allclose(hdr2.label, [1, 2, 3])
    assert payload == b"data"


def test_layout_mapper():
    """Name-driven layout decisions (reference io.py:24-85)."""
    m = mx.io.DefaultLayoutMapper()
    assert m.get_layout_string("data") == "NCHW"
    assert m.get_batch_axis("data") == 0
    assert m.get_layout_string("seq:__layout_TNC__") == "TNC"
    assert m.get_batch_axis("seq:__layout_TNC__") == 1
    m2 = mx.io.DefaultLayoutMapper(default_layout="TNC")
    assert m2.get_batch_axis("anything") == 1


def test_mxdataiter_by_name(tmp_path):
    """MXDataIter factory resolves registered iterators by name
    (reference io.py:521) from the same registry as the C ABI."""
    import numpy as np

    reg = mx.io.iter_registry()
    for name in ("MNISTIter", "CSVIter", "NDArrayIter", "ImageRecordIter"):
        assert name in reg, reg
    X = np.arange(24, dtype=np.float32).reshape(6, 4)
    it = mx.io.MXDataIter("NDArrayIter", data=X, batch_size=2)
    batches = list(it)
    assert len(batches) == 3
    with pytest.raises(mx.base.MXNetError):
        mx.io.MXDataIter("NoSuchIter")


def test_log_validation_metrics_callback(caplog):
    """LogValidationMetricsCallback logs each metric at epoch end
    (reference callback.py:127-136)."""
    import logging

    from mxnet_tpu.callback import BatchEndParam

    m = mx.metric.Accuracy()
    m.update([mx.nd.array([0, 1])], [mx.nd.array([[0.9, 0.1], [0.2, 0.8]])])
    param = BatchEndParam(epoch=3, nbatch=0, eval_metric=m, locals=None)
    with caplog.at_level(logging.INFO):
        mx.callback.LogValidationMetricsCallback()(param)
    assert any("Validation-accuracy" in r.message for r in caplog.records)
