"""NDArray vs numpy semantics (rebuild of tests/python/unittest/test_ndarray.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_ndarray_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert (a.asnumpy() == 0).all()
    b = mx.nd.ones((2, 2), dtype="float64")
    assert b.dtype == np.float64
    c = mx.nd.full((2,), 7.0)
    assert (c.asnumpy() == 7).all()
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.dtype == np.float32
    assert d.asnumpy().tolist() == [[1, 2], [3, 4]]


def test_ndarray_elementwise():
    rng = np.random.RandomState(0)
    for _ in range(3):
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.rand(4, 5).astype(np.float32) + 0.5
        a, b = mx.nd.array(x), mx.nd.array(y)
        np.testing.assert_allclose((a + b).asnumpy(), x + y, rtol=1e-6)
        np.testing.assert_allclose((a - b).asnumpy(), x - y, rtol=1e-6)
        np.testing.assert_allclose((a * b).asnumpy(), x * y, rtol=1e-6)
        np.testing.assert_allclose((a / b).asnumpy(), x / y, rtol=1e-5)
        np.testing.assert_allclose((a + 2).asnumpy(), x + 2, rtol=1e-6)
        np.testing.assert_allclose((2 - a).asnumpy(), 2 - x, rtol=1e-6)
        np.testing.assert_allclose((a / 2).asnumpy(), x / 2, rtol=1e-6)
        np.testing.assert_allclose((2 / b).asnumpy(), 2 / y, rtol=1e-5)
        np.testing.assert_allclose((-a).asnumpy(), -x, rtol=1e-6)
        np.testing.assert_allclose(mx.nd.sqrt(b).asnumpy(), np.sqrt(y), rtol=1e-6)
        np.testing.assert_allclose(mx.nd.square(a).asnumpy(), x * x, rtol=1e-6)
        np.testing.assert_allclose(mx.nd.exp(a).asnumpy(), np.exp(x), rtol=1e-5)


def test_ndarray_inplace():
    a = mx.nd.ones((2, 3))
    a += 2
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 3), 3.0))
    a *= 2
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 3), 6.0))
    a -= 1
    a /= 5
    np.testing.assert_allclose(a.asnumpy(), np.ones((2, 3)))


def test_ndarray_setitem():
    a = mx.nd.zeros((3, 4))
    a[:] = 2.5
    assert (a.asnumpy() == 2.5).all()
    a[1] = 1.0
    expected = np.full((3, 4), 2.5)
    expected[1] = 1.0
    np.testing.assert_allclose(a.asnumpy(), expected)
    a[0:2] = 0.0
    expected[0:2] = 0.0
    np.testing.assert_allclose(a.asnumpy(), expected)
    a[:] = np.arange(12).reshape(3, 4)
    np.testing.assert_allclose(a.asnumpy(), np.arange(12).reshape(3, 4))


def test_ndarray_slicing():
    x = np.arange(24).reshape(4, 6).astype(np.float32)
    a = mx.nd.array(x)
    np.testing.assert_allclose(a[1].asnumpy(), x[1])
    np.testing.assert_allclose(a[1:3].asnumpy(), x[1:3])
    np.testing.assert_allclose(a[:, 2].asnumpy(), x[:, 2])
    assert a[2, 3].asscalar() == x[2, 3]


def test_ndarray_reshape_transpose():
    x = np.arange(12).reshape(3, 4).astype(np.float32)
    a = mx.nd.array(x)
    np.testing.assert_allclose(a.reshape((4, 3)).asnumpy(), x.reshape(4, 3))
    np.testing.assert_allclose(a.T.asnumpy(), x.T)
    np.testing.assert_allclose(
        mx.nd.transpose(a, axes=(1, 0)).asnumpy(), x.T)


def test_ndarray_dot():
    x = np.random.rand(3, 4).astype(np.float32)
    y = np.random.rand(4, 5).astype(np.float32)
    out = mx.nd.dot(mx.nd.array(x), mx.nd.array(y))
    np.testing.assert_allclose(out.asnumpy(), x.dot(y), rtol=1e-5)


def test_ndarray_reduce():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    a = mx.nd.array(x)
    np.testing.assert_allclose(mx.nd.sum(a).asnumpy(),
                               [x.sum()], rtol=1e-5)
    np.testing.assert_allclose(mx.nd.sum(a, axis=(1,)).asnumpy(),
                               x.sum(axis=1), rtol=1e-5)
    np.testing.assert_allclose(mx.nd.max(a, axis=(0, 2)).asnumpy(),
                               x.max(axis=(0, 2)), rtol=1e-5)


def test_ndarray_copy():
    a = mx.nd.array(np.random.rand(3, 3))
    b = a.copy()
    b[:] = 0
    assert not (a.asnumpy() == 0).all()
    c = mx.nd.zeros((3, 3))
    a.copyto(c)
    np.testing.assert_allclose(a.asnumpy(), c.asnumpy())


def test_ndarray_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu(1))
    assert a.context == mx.cpu(1)
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0)
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    with pytest.raises(mx.MXNetError):
        _ = a + mx.nd.ones((2, 2), ctx=mx.cpu(0))


def test_ndarray_saveload(tmp_path):
    fname = str(tmp_path / "nd.npz")
    data = [mx.nd.array(np.random.rand(3, 3)) for _ in range(3)]
    mx.nd.save(fname, data)
    loaded = mx.nd.load(fname)
    assert len(loaded) == 3
    for a, b in zip(data, loaded):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    dmap = {"w": data[0], "b": data[1]}
    mx.nd.save(fname, dmap)
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"w", "b"}
    np.testing.assert_allclose(loaded["w"].asnumpy(), data[0].asnumpy())


def test_ndarray_bf16_saveload(tmp_path):
    fname = str(tmp_path / "bf.npz")
    a = mx.nd.array(np.random.rand(4, 4), dtype="bfloat16")
    mx.nd.save(fname, {"a": a})
    out = mx.nd.load(fname)["a"]
    assert out.dtype == mx.base.np_dtype("bfloat16")
    np.testing.assert_allclose(out.astype("float32").asnumpy(),
                               a.astype("float32").asnumpy())


def test_onehot_encode():
    idx = mx.nd.array([1, 0, 2])
    out = mx.nd.zeros((3, 3))
    mx.nd.onehot_encode(idx, out)
    np.testing.assert_allclose(out.asnumpy(),
                               np.eye(3)[[1, 0, 2]])


def test_choose_fill_element_0index():
    lhs = mx.nd.array([[1., 2., 3.], [4., 5., 6.], [7., 8., 9.]])
    rhs = mx.nd.array([2, 0, 1])
    picked = mx.nd.choose_element_0index(lhs, rhs)
    np.testing.assert_allclose(picked.asnumpy(), [3., 4., 8.])
    vals = mx.nd.array([-1., -2., -3.])
    expect = np.array([[1., 2., -1.], [-2., 5., 6.], [7., -3., 9.]])
    filled = mx.nd.fill_element_0index(lhs, vals, rhs)
    np.testing.assert_allclose(filled.asnumpy(), expect)
    # default call leaves lhs untouched; out=lhs is the in-place form
    np.testing.assert_allclose(lhs.asnumpy()[0], [1., 2., 3.])
    mx.nd.fill_element_0index(lhs, vals, rhs, out=lhs)
    np.testing.assert_allclose(lhs.asnumpy(), expect)


def test_ndarray_comparison():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([2.0, 2.0, 2.0])
    np.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose((a <= 2).asnumpy(), [1, 1, 0])


def test_clip_and_sample():
    a = mx.nd.array(np.linspace(-5, 5, 11))
    np.testing.assert_allclose(mx.nd.clip(a, a_min=-2, a_max=2).asnumpy(),
                               np.clip(np.linspace(-5, 5, 11), -2, 2))
    mx.random.seed(42)
    u = mx.random.uniform(0, 1, shape=(1000,))
    assert 0.4 < float(u.asnumpy().mean()) < 0.6
    n = mx.random.normal(0, 1, shape=(1000,))
    assert abs(float(n.asnumpy().mean())) < 0.15


def test_broadcast_to_method():
    a = mx.nd.array([[1.0], [2.0]])
    b = a.broadcast_to((2, 3))
    np.testing.assert_allclose(b.asnumpy(), [[1, 1, 1], [2, 2, 2]])
    c = mx.nd.array([5.0]).broadcast_to((4, 2))
    assert c.shape == (4, 2)
    with pytest.raises(ValueError):
        a.broadcast_to((3, 3))


def test_ndarray_pickle():
    import pickle
    a = mx.nd.array(np.random.RandomState(0).rand(3, 4))
    b = pickle.loads(pickle.dumps(a))
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
