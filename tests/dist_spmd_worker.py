"""Worker for the 2-process jax.distributed SPMD dryrun (the DCN path).

Each process contributes 4 virtual CPU devices to ONE global 8-device
``dp`` mesh; the jitted training step therefore spans processes — data
parallelism over the process boundary rides the same XLA collectives
that cross DCN on a multi-host pod (SURVEY.md §5 "distributed
communication backend": in-program collectives replace the reference's
ps-lite transport, kvstore_dist.h:181-226).

Asserts, per rank:
 1. DistKVStore.init broadcast: rank 0's values win everywhere
    (the reference PS contract, kvstore_dist_server.h DataHandle).
 2. NUMERICAL PARITY: two sharded global training steps produce exactly
    the params of a single-device dense run of the same global batch —
    the N-CPU-contexts equality trick extended across processes.

Launched by tests/test_dist.py via tools/launch.py -n 2.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.kvstore import _maybe_init_distributed


def _net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main():
    _maybe_init_distributed()
    rank = jax.process_index()
    n_procs = int(os.environ["MXTPU_NUM_PROCS"])
    assert jax.process_count() == n_procs
    assert len(jax.devices()) == 4 * n_procs, len(jax.devices())
    assert len(jax.local_devices()) == 4

    # -- 1. DistKVStore init broadcast across the process boundary ------
    kv = mx.kv.create("dist_sync")
    assert kv.rank == rank and kv.num_workers == n_procs
    kv.init(7, mx.nd.ones((3, 3)) * (rank + 1) * 10)  # ranks disagree
    got = mx.nd.zeros((3, 3))
    kv.pull(7, got)
    np.testing.assert_array_equal(got.asnumpy(), 10.0)  # rank 0 won
    kv.barrier()

    # -- 2. process-spanning training step with numerical parity --------
    # MXTPU_SPMD_MESH=dp (default): pure data parallel over all devices.
    # MXTPU_SPMD_MESH=dp_tp: dp spans the PROCESS boundary (DCN axis),
    # tp spans each process's local devices (ICI axis) with megatron
    # column/row FC shards — the canonical multi-host mesh layout
    # (slow axis outermost), crossing processes on the dp collectives
    # and staying intra-process for the tp ones.
    lr = 0.1
    mesh_kind = os.environ.get("MXTPU_SPMD_MESH", "dp")
    from jax.sharding import PartitionSpec as P

    if mesh_kind == "dp_tp":
        dp, tp = n_procs, 4
        mesh = mx.parallel.make_mesh({"dp": dp, "tp": tp},
                                     devices=jax.devices())
        param_specs = {"fc1_weight": P("tp", None),   # column-parallel
                       "fc1_bias": P("tp"),
                       "fc2_weight": P(None, "tp")}   # row-parallel
    else:
        dp, tp = 4 * n_procs, 1
        mesh = mx.parallel.make_mesh({"dp": dp}, devices=jax.devices())
        param_specs = None
    batch, d_in = 2 * dp, 10
    mx.random.seed(0)
    trainer = mx.parallel.ShardedTrainer(
        _net(), {"data": (batch, d_in), "softmax_label": (batch,)},
        mesh=mesh, batch_axis="dp", param_specs=param_specs,
        optimizer="sgd", optimizer_params={"learning_rate": lr,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())

    # dense single-LOCAL-device reference with identical params + key
    ref_mesh = mx.parallel.make_mesh({"dp": 1},
                                     devices=jax.local_devices()[:1])
    mx.random.seed(0)
    ref = mx.parallel.ShardedTrainer(
        _net(), {"data": (batch, d_in), "softmax_label": (batch,)},
        mesh=ref_mesh, batch_axis="dp",
        optimizer="sgd", optimizer_params={"learning_rate": lr,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    p0 = trainer.get_params()
    ref.set_params(p0)
    key_np = np.asarray(jax.device_get(trainer._key))
    ref._key = jax.device_put(key_np, ref._replicated)

    rng = np.random.RandomState(42)  # same global batch on every rank
    feed = {"data": rng.standard_normal((batch, d_in)).astype(np.float32),
            "softmax_label": rng.randint(0, 4, batch).astype(np.float32)}
    for _ in range(2):  # second step covers momentum-state parity
        jax.block_until_ready(trainer.step(feed))
        jax.block_until_ready(ref.step(feed))
    p_global = trainer.get_params()
    p_ref = ref.get_params()
    for k in p0:
        np.testing.assert_allclose(p_global[k], p_ref[k],
                                   atol=5e-6, rtol=1e-5)
        assert not np.allclose(p0[k], p_global[k])  # training moved

    # every rank must also hold IDENTICAL global params (replica sync)
    import hashlib

    digest = hashlib.sha1()
    for k in sorted(p_global):
        digest.update(np.ascontiguousarray(p_global[k]).tobytes())
    print(f"RANK_{rank}_SPMD_DIGEST {digest.hexdigest()}")
    print(f"RANK_{rank}_SPMD_PARITY_OK")


if __name__ == "__main__":
    main()
