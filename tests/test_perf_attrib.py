"""Performance-attribution plane (telemetry/perf_attrib.py + serve
wiring) — the PR-17 acceptance surface on CPU:

  * cost table: every serve program family (prefill, chunk, decode,
    draft, draft_chunk, verify, restore) appears in the statusz perf
    section with nonzero flops after warmup — on the fresh-trace path,
    the warm-AOT restart path AND the process-local step-cache-hit
    path (a warm engine must not report an empty perf section)
  * inertness: MXTPU_PERF_ATTRIB / MXTPU_PERF_ATTRIB_SAMPLE in any
    combination leave greedy tokens byte-identical and the AOT
    fingerprint (_spec_digest) unchanged; sampling off records zero
    timings
  * three-view agreement: statusz per-program sampled counts == the
    mxtpu_serve_program_seconds{kind,bucket} histogram counts in the
    registry == the rows tools/metrics_report.py renders
  * satellites: ServeMonitor perf tail appears only once a sample
    exists (plain lines byte-identical), metrics_report numeric-aware
    label ordering, fleet replica/collector/fleet_report MFU-goodput
    plumbing, tools/perf_report.py breakdown rendering
"""

import json
import logging
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.serve import engine as engine_mod
from mxnet_tpu.telemetry import perf_attrib

VOCAB = 53
SEQ = 64


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.disable()
    telemetry.reset()


def _params(net, seed=3):
    arg_shapes, _, _ = net.infer_shape(data=(1, SEQ),
                                       softmax_label=(1, SEQ))
    rng = np.random.RandomState(seed)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return params


@pytest.fixture(scope="module")
def model():
    net = mx.models.gpt(VOCAB, SEQ, num_layers=2, d_model=32,
                        num_heads=4)
    return net, _params(net)


@pytest.fixture(scope="module")
def draft_model():
    net = mx.models.gpt(VOCAB, SEQ, num_layers=1, d_model=16,
                        num_heads=2)
    return net, _params(net, seed=5)


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 32)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _prompts(n=3, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (4 + 2 * i,)).astype(np.int32)
            for i in range(n)]


def _serve(eng, prompts, tokens=6):
    reqs = [eng.submit(p, max_new_tokens=tokens) for p in prompts]
    eng.run()
    assert all(r.status == "finished" for r in reqs)
    return [tuple(r.tokens) for r in reqs]


ALL_FAMILIES = {"prefill", "chunk", "decode", "draft", "draft_chunk",
                "verify", "restore"}


# -- tentpole: cost table covers every family --------------------------------
def test_cost_table_all_families_nonzero_flops(model, draft_model):
    """Acceptance gate: after warmup every program family this config
    can dispatch appears in the statusz perf cost table with nonzero
    flops — no traffic required (the offline pre-bake default)."""
    dnet, dparams = draft_model
    eng = _engine(model, spec_k=2, draft_params=dparams,
                  draft_symbol=dnet, host_kv_bytes=1 << 24)
    try:
        assert eng.warmup() > 0
        perf = eng.statusz()["perf"]
        assert perf is not None and perf["enabled"]
        rows = perf["programs"]
        assert {r["kind"] for r in rows} == ALL_FAMILIES
        for r in rows:
            assert r["flops"] and r["flops"] > 0, r
            assert r["source"] in ("cost_analysis", "analytic"), r
            # warmup resolves programs without dispatching or timing
            assert r["sampled"] == 0 and r["mean_s"] is None, r
        # no sample yet -> goodput columns empty, summary sampled == 0
        assert perf["sampled_steps"] == 0
        assert eng.perf_summary()["sampled"] == 0
    finally:
        eng.shutdown()


def test_cost_table_warm_aot_and_cache_hit_paths(model, tmp_path):
    """The cost table fills on ALL THREE resolve paths: fresh trace,
    warm-AOT artifact load after a simulated restart, and a twin
    engine riding the process-local step cache — a warm engine must
    not report an empty perf section."""
    aot_dir = str(tmp_path / "aot")
    prompts = _prompts()

    cold = _engine(model, aot_dir=aot_dir)
    toks = _serve(cold, prompts)
    fresh = {(r["kind"], r["bucket"]): r["flops"]
             for r in cold.statusz()["perf"]["programs"]}
    cold.shutdown()
    assert fresh and all(f and f > 0 for f in fresh.values())

    engine_mod._STEP_CACHE.clear()                # simulated restart
    warm = _engine(model, aot_dir=aot_dir)
    assert _serve(warm, prompts) == toks
    warmed = {(r["kind"], r["bucket"]): r["flops"]
              for r in warm.statusz()["perf"]["programs"]}
    assert set(warmed) == set(fresh)
    for key, f in warmed.items():
        assert f and f > 0, (key, f)

    # twin engine: every program resolves via the step-cache hit path
    twin = _engine(model, aot_dir=aot_dir)
    assert _serve(twin, prompts) == toks
    twinned = {(r["kind"], r["bucket"]) for r in
               twin.statusz()["perf"]["programs"]}
    assert twinned == set(fresh)
    warm.shutdown()
    twin.shutdown()


# -- inertness: knobs never touch tokens or fingerprints ---------------------
def test_sampling_and_kill_switch_inert(model, monkeypatch):
    """Greedy tokens and the AOT fingerprint are byte-identical across
    MXTPU_PERF_ATTRIB / MXTPU_PERF_ATTRIB_SAMPLE in any combination
    (the PR 10/11 inertness rule)."""
    prompts = _prompts()

    base = _engine(model)
    toks = _serve(base, prompts)
    digest = base._spec_digest
    perf = base.statusz()["perf"]
    assert perf["sampled_steps"] == 0 and perf["tokens"] > 0
    assert all(r["sampled"] == 0 for r in perf["programs"])
    base.shutdown()

    monkeypatch.setenv(perf_attrib.ENV_SAMPLE, "1")
    sampled = _engine(model)
    assert _serve(sampled, prompts) == toks
    assert sampled._spec_digest == digest
    perf = sampled.statusz()["perf"]
    assert perf["sampled_steps"] > 0 and perf["sampled_tokens"] > 0
    assert perf["device_seconds"] > 0
    timed = [r for r in perf["programs"] if r["sampled"]]
    assert timed and all(r["mean_s"] > 0 for r in timed)
    # shares partition the sampled step budget
    assert sum(r["share"] for r in timed) == pytest.approx(1.0)
    assert sampled.perf_summary()["sampled"] > 0
    sampled.shutdown()

    monkeypatch.setenv(perf_attrib.ENV_ENABLE, "0")
    off = _engine(model)
    assert _serve(off, prompts) == toks
    assert off._spec_digest == digest
    assert off.statusz()["perf"] is None
    assert off.perf_summary() is None
    off.shutdown()


# -- three-view agreement ----------------------------------------------------
def test_three_view_agreement(model, tel, monkeypatch):
    """statusz per-program sampled counts == the registry's
    mxtpu_serve_program_seconds{kind,bucket} histogram counts == the
    per-label rows metrics_report renders."""
    import metrics_report

    monkeypatch.setenv(perf_attrib.ENV_SAMPLE, "1")
    eng = _engine(model)
    try:
        _serve(eng, _prompts())
        perf = eng.statusz()["perf"]
        by_label = {(r["kind"], str(r["bucket"])): r["sampled"]
                    for r in perf["programs"] if r["sampled"]}
        assert by_label

        snap = telemetry.snapshot()["metrics"]
        fam = snap["mxtpu_serve_program_seconds"]
        assert fam["kind"] == "histogram"
        hist = {(s["labels"]["kind"], s["labels"]["bucket"]): s["count"]
                for s in fam["samples"]}
        assert hist == by_label

        out = metrics_report.report(snap, "mxtpu_serve_program_seconds")
        rows = [l for l in out.splitlines()
                if l.startswith("mxtpu_serve_program_seconds")]
        assert len(rows) == len(by_label)
        for kind, bucket in by_label:
            assert any(f"bucket={bucket},kind={kind}" in l
                       for l in rows)
    finally:
        eng.shutdown()


def test_metrics_report_numeric_label_order():
    """{kind,bucket} rows render grouped with buckets in numeric order
    (lexical sorting would put 16 before 2)."""
    import metrics_report

    def sample(bucket):
        return {"labels": {"kind": "decode", "bucket": bucket},
                "count": 1, "sum": 0.001, "buckets": [["+Inf", 1]]}

    fake = {"m": {"kind": "histogram", "help": "",
                  "label_names": ["kind", "bucket"],
                  "samples": [sample("16"), sample("2"), sample("4")]}}
    out = metrics_report.report(fake)
    assert (out.index("bucket=2,") < out.index("bucket=4,")
            < out.index("bucket=16,"))


# -- satellite: ServeMonitor perf tail ---------------------------------------
def test_monitor_perf_tail_only_after_sample(model, monkeypatch, caplog):
    logger = logging.getLogger("mxtpu.test.perfmon")
    prompts = _prompts()

    def line_for(eng):
        caplog.clear()
        with caplog.at_level(logging.INFO, logger=logger.name):
            mx.monitor.ServeMonitor(eng, interval=1e9,
                                    logger=logger).log_now()
        return caplog.messages[-1]

    import re

    def normalize(line):
        # wall-clock latency fields honestly differ run to run; the
        # byte-identity contract is about the FORMAT, not the timings
        return re.sub(r"(ttft_ms|tok/s)=[0-9.]+", r"\1=X", line)

    plain = _engine(model)
    _serve(plain, prompts)
    unsampled_line = line_for(plain)
    assert "mfu=" not in unsampled_line
    plain.shutdown()

    # the kill switch produces the SAME line (byte-identical plain
    # format, not merely "no perf tail")
    monkeypatch.setenv(perf_attrib.ENV_ENABLE, "0")
    killed = _engine(model)
    _serve(killed, prompts)
    assert normalize(line_for(killed)) == normalize(unsampled_line)
    killed.shutdown()
    monkeypatch.delenv(perf_attrib.ENV_ENABLE)

    monkeypatch.setenv(perf_attrib.ENV_SAMPLE, "1")
    sampled = _engine(model)
    _serve(sampled, prompts)
    tail = line_for(sampled)
    assert "mfu=" in tail and "tok_flops=" in tail
    sampled.shutdown()


# -- satellite: fleet plumbing ----------------------------------------------
def test_replica_state_carries_perf(model, monkeypatch):
    from mxnet_tpu.fleet.replica import ReplicaServer

    monkeypatch.setenv(perf_attrib.ENV_SAMPLE, "1")
    eng = _engine(model)
    try:
        _serve(eng, _prompts())
        srv = ReplicaServer(eng)
        state = srv._replica_state()
        assert state["perf"]["sampled"] > 0
        assert state["perf"]["achieved_tflops"] > 0
    finally:
        eng.shutdown()


def test_collector_role_mfu_goodput_aggregates():
    """Role aggregates: MFU averages over fresh replicas, achieved
    TFLOP/s sums to the role's delivered compute rate; replicas
    without a perf section (older builds, MXTPU_PERF_ATTRIB=0) are
    skipped, not zero-counted."""
    from mxnet_tpu.fleet.collector import FleetCollector

    col = FleetCollector(urls=["http://a:1", "http://b:1",
                               "http://c:1"], interval_s=0)
    try:
        perfs = [{"sampled": 5, "mfu": 0.2, "achieved_tflops": 1.0,
                  "tok_flops": 2e6, "cost_per_1k_tokens_s": 0.1},
                 {"sampled": 9, "mfu": 0.4, "achieved_tflops": 3.0,
                  "tok_flops": 2e6, "cost_per_1k_tokens_s": 0.3},
                 None]          # a replica predating the perf plane
        for view, perf in zip(col.views(), perfs):
            sec = {"replica": view.url, "role": "decode",
                   "state": "serving", "queue_depth": 0, "running": 0,
                   "stats": {"tokens_generated": 10, "completed": 1,
                             "rejected": 0},
                   "perf": perf}
            view.ring.append(FleetCollector._flatten_replica(sec),
                             now=col.clock())
            view.role = "decode"
            view.last_success_t = col.clock()

        view = col.fleet_view()
        agg = view["roles"]["decode"]
        assert agg["mfu_mean"] == pytest.approx(0.3)
        assert agg["achieved_tflops"] == pytest.approx(4.0)
        rows = {r["url"]: r for r in view["replicas"]}
        assert rows["http://a:1"]["perf_mfu"] == pytest.approx(0.2)
        assert rows["http://b:1"]["perf_sampled"] == 9
        assert "perf_mfu" not in rows["http://c:1"]

        import fleet_report

        text = fleet_report.render(view)
        assert "MFU%" in text and "TFLOPS" in text
        role_line = [l for l in text.splitlines()
                     if l.startswith("decode")][0]
        assert "30.0" in role_line and "4.00" in role_line
    finally:
        col.stop()


# -- satellite: tools/perf_report.py ----------------------------------------
def test_perf_report_renders_breakdown(model, monkeypatch, tmp_path,
                                       capsys):
    import perf_report

    monkeypatch.setenv(perf_attrib.ENV_SAMPLE, "1")
    eng = _engine(model)
    try:
        _serve(eng, _prompts())
        doc = {"engine": eng.statusz()}
    finally:
        eng.shutdown()
    path = tmp_path / "statusz.json"
    path.write_text(json.dumps(doc, default=str))

    assert perf_report.main(["--file", str(path)]) == 0
    out = capsys.readouterr().out
    assert "goodput:" in out
    assert "decode" in out and "prefill" in out
    assert "cost_analysis" in out

    # an attribution-off snapshot is a clean nonzero exit, not a crash
    path2 = tmp_path / "empty.json"
    path2.write_text(json.dumps({"engine": {"perf": None}}))
    assert perf_report.main(["--file", str(path2)]) == 1
