"""accnn low-rank factorization tool (port of tools/accnn: acc_conv
vertical/horizontal SVD split, acc_fc two-FC split, DP rank selection)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import accnn  # noqa: E402

rng = np.random.RandomState(3)


def _forward(sym, params, data, label_shape=None):
    shapes = {"data": data.shape}
    exe = sym.simple_bind(mx.cpu(), grad_req="null", **shapes)
    exe.arg_dict["data"][:] = data
    for k, v in params.items():
        if k in exe.arg_dict and k != "data":
            exe.arg_dict[k][:] = v.asnumpy() if hasattr(v, "asnumpy") else v
    return exe.forward(is_train=False)[0].asnumpy()


def _small_model(data_shape=(1, 3, 8, 8)):
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=4, pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=6, pad=(1, 1),
                             name="conv2")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc1")
    arg_shapes, _, _ = net.infer_shape(data=data_shape)
    arg_params = {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name != "data":
            arg_params[name] = mx.nd.array(
                rng.randn(*shape).astype(np.float32) * 0.3)
    return mx.model.FeedForward(symbol=net, arg_params=arg_params,
                                aux_params={}), arg_params


def test_conv_vh_full_rank_is_exact():
    model, params = _small_model()
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    ref = _forward(model.symbol, params, x)
    # conv1 weight viewed as (C*y, N*x) = (9, 12): full rank 9 -> exact
    new = accnn.conv_vh_decomposition(model, "conv1", K=9,
                                      data_shape=(1, 3, 8, 8))
    out = _forward(new.symbol, new.arg_params, x)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    # replaced layer's weights are gone, factor weights present
    assert "conv1_weight" not in new.arg_params
    assert "conv1_v_weight" in new.arg_params
    assert "conv1_h_weight" in new.arg_params


def test_conv_vh_low_rank_approximates():
    model, params = _small_model()
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    ref = _forward(model.symbol, params, x)
    errs = []
    for K in (2, 6, 9):
        new = accnn.conv_vh_decomposition(model, "conv1", K=K,
                                          data_shape=(1, 3, 8, 8))
        out = _forward(new.symbol, new.arg_params, x)
        errs.append(np.abs(out - ref).max())
    assert errs[2] < 1e-3
    assert errs[0] >= errs[1] >= errs[2]  # error shrinks with rank


def test_fc_decomposition_full_rank_exact():
    model, params = _small_model()
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    ref = _forward(model.symbol, params, x)
    new = accnn.fc_decomposition(model, "fc1", K=10,
                                 data_shape=(1, 3, 8, 8))
    out = _forward(new.symbol, new.arg_params, x)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    assert "fc1_red_weight" in new.arg_params
    assert "fc1_rec_bias" in new.arg_params


def test_rank_selection_respects_budget():
    model, _ = _small_model()
    sel = accnn.get_ranksel(model, ratio=2.0, data_shape=(1, 3, 8, 8))
    assert set(sel) == {"conv1", "conv2"}
    assert all(1 <= k for k in sel.values())
    # total factorized cost under original/ratio
    conf = json.loads(model.symbol.tojson())
    nodes = accnn.topsort(conf["nodes"])
    internals = model.symbol.get_internals()
    _, oshapes, _ = internals.infer_shape(data=(1, 3, 8, 8))
    out_shape = dict(zip(internals.list_outputs(), oshapes))
    total = used = 0
    for node in nodes:
        if node["op"] != "Convolution":
            continue
        data = [nodes[j[0]] for j in node["inputs"]
                if not nodes[j[0]]["name"].startswith(node["name"] + "_")][0]
        ishape = ((3, 8, 8) if accnn.is_input(data)
                  else tuple(out_shape[data["name"] + "_output"][1:]))
        per_rank, orig = accnn._conv_complexity(ishape, node)
        total += orig
        used += sel[node["name"]] * per_rank
    assert used <= total / 2.0


@pytest.mark.slow
def test_compress_end_to_end_and_cli(tmp_path):
    model, params = _small_model()
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    ref = _forward(model.symbol, params, x)
    new = accnn.compress(model, ratio=1.5, data_shape=(1, 3, 8, 8))
    out = _forward(new.symbol, new.arg_params, x)
    assert out.shape == ref.shape
    assert np.isfinite(out).all()

    # CLI round-trip through checkpoints
    prefix = str(tmp_path / "m")
    model.save(prefix, 1)
    out_prefix = str(tmp_path / "m-acc")
    env = dict(os.environ, MXTPU_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(accnn.__file__),
                                      "accnn.py"),
         "-m", prefix, "--load-epoch", "1", "--save-model", out_prefix,
         "--ratio", "1.5", "--data-shape", "1,3,8,8"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    loaded = mx.model.FeedForward.load(out_prefix, 1)
    out2 = _forward(loaded.symbol, loaded.arg_params, x)
    np.testing.assert_allclose(out2, out, rtol=1e-4, atol=1e-5)
