"""Fleet observability tests (ISSUE 14): the time-series ring, the
FleetCollector (scrape isolation, role aggregation, trace push), the
SLO burn-rate layer, and the satellite instrumentation (router hops,
ServeStats percentiles, supervisor lifecycle annotations).

The three acceptance gates:

* three-view agreement: fleet ``/fleetz`` aggregates == the sum of
  per-replica ``/statusz.json`` ground truth == the collector's
  registry series, for queue depth, tokens and reject counts;
* the burn-rate alert FIRES under injected kill/delay chaos (with the
  flight dump produced on the offending replica) and stays silent on
  a clean run;
* everything is inert when unconfigured: no ring, no pusher thread,
  no router trace, no statusz section.

Everything tier-1 here is CPU-deterministic and in-process (real
``ReplicaServer`` HTTP servers over real engines, real collector HTTP
endpoint); the subprocess A/B lives in the slow-tier bench contract.
"""

import json
import os
import socket
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.fleet import (FaultInjector, FleetCollector, Objective,
                             ReplicaServer, Router, SLOEvaluator,
                             Supervisor, parse_slo_spec)
from mxnet_tpu.serve.stats import Reservoir, StatsRecorder
from mxnet_tpu.telemetry import timeseries
from mxnet_tpu.telemetry.metrics import Registry
from mxnet_tpu.telemetry.request_trace import RequestTracer

VOCAB = 53


@pytest.fixture(scope="module")
def model():
    """Tiny gpt2-style net + params (the test_serve recipe)."""
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _prompts(n, seed=7, lo=6, hi=22):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (rng.randint(lo, hi),)).astype(np.int32)
            for _ in range(n)]


def _get(url, path, timeout=10):
    with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _post(url, path, payload, timeout=30):
    import urllib.error

    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.reset()


# -- time-series ring ---------------------------------------------------------
def test_timeseries_ring_rates_quantiles_and_eviction():
    clock = {"now": 0.0}
    ring = timeseries.TimeSeriesRing(capacity=4,
                                     clock=lambda: clock["now"])
    for i in range(6):
        clock["now"] = float(i)
        ring.append({"tok_total": 10.0 * i, "queue": i % 3,
                     "skipme": "text"})
    # capacity 4: samples at t=2..5 survive, t=0/1 evicted
    assert len(ring) == 4 and ring.taken == 6
    assert ring.series("tok_total")[0][0] == 2.0
    assert ring.latest("tok_total") == 50.0
    assert ring.latest("skipme") is None          # non-numeric dropped
    # counter rate over the whole retained window: 30 tokens / 3s
    assert ring.rate("tok_total", window_s=10) == pytest.approx(10.0)
    assert ring.delta("tok_total", window_s=10) == pytest.approx(30.0)
    # narrower window: only t in [3.5, 5] -> points at 4, 5
    assert ring.rate("tok_total", window_s=1.5) == pytest.approx(10.0)
    assert ring.quantile_over("queue", 10, 1.0) == 2.0
    assert ring.quantile_over("queue", 10, 0.0) == 0.0
    assert ring.rate("missing", 10) is None
    assert ring.quantile_over("missing", 10, 0.5) is None
    assert ring.span_s() == pytest.approx(3.0)

    # counter RESET (process restart): the fresh life's level counts,
    # never a negative step
    ring2 = timeseries.TimeSeriesRing(capacity=8,
                                      clock=lambda: clock["now"])
    for t, v in [(0, 100.0), (1, 110.0), (2, 5.0), (3, 15.0)]:
        clock["now"] = float(t)
        ring2.append({"c": v})
    # increases: +10, reset->5 (counts 5), +10 => 25 over 3s
    assert ring2.delta("c", 10) == pytest.approx(25.0)


def test_flatten_registry_and_prometheus_parse_agree():
    reg = Registry()
    reg.counter("t_total", "x").inc(7)
    reg.gauge("g", "x", ("role",)).labels(role="decode").set(2.5)
    h = reg.histogram("lat_seconds", "x")
    h.observe(0.1)
    h.observe(0.2)
    flat = timeseries.flatten_registry(reg)
    assert flat["t_total"] == 7.0
    assert flat["g{role=decode}"] == 2.5
    assert flat["lat_seconds_count"] == 2.0
    assert flat["lat_seconds_sum"] == pytest.approx(0.3)
    # the prometheus text round-trip lands on the same keys/values
    parsed = timeseries.parse_prometheus_text(
        telemetry.to_prometheus_text(reg))
    assert parsed["t_total"] == 7.0
    assert parsed["g{role=decode}"] == 2.5
    assert parsed["lat_seconds_count"] == 2.0
    assert "lat_seconds_bucket{le=0.25}" not in parsed  # buckets dropped


def test_global_ring_inert_by_default_and_configurable():
    # inert: no env -> no ring object, sample() is a cheap no-op, and
    # /statusz carries no timeseries section
    timeseries.configure(0)
    assert timeseries.ring() is None
    assert timeseries.sample() is False
    assert "timeseries" not in telemetry.statusz.snapshot()
    try:
        ring = timeseries.configure(32, interval_s=0.0)
        assert timeseries.ring() is ring
        assert timeseries.sample() is True
        snap = telemetry.statusz.snapshot()
        assert snap["timeseries"]["capacity"] == 32
        assert snap["timeseries"]["samples"] >= 1
    finally:
        timeseries.configure(0)
    assert "timeseries" not in telemetry.statusz.snapshot()


def test_serve_monitor_samples_the_ring(model):
    eng = _engine(model)
    try:
        timeseries.configure(16, interval_s=0.0)
        mon = mx.monitor.ServeMonitor(eng, interval=1)
        req = eng.submit(_prompts(1)[0], max_new_tokens=3)
        while not req.done:
            eng.step()
            mon.tic()
        assert len(timeseries.ring()) >= 1
    finally:
        timeseries.configure(0)
        eng.shutdown()


# -- ServeStats percentiles / TPOT -------------------------------------------
def test_reservoir_bounded_with_exact_aggregates():
    res = Reservoir(capacity=64)
    for i in range(1000):
        res.add(float(i))
    assert res.count == 1000 and res.max == 999.0
    assert res.mean == pytest.approx(499.5)
    assert len(res._sample) == 64                 # bounded
    # a uniform estimate: the median of 0..999 is ~500
    assert 250 <= res.percentile(0.5) <= 750
    small = Reservoir(capacity=64)
    for v in [1.0, 2.0, 3.0, 4.0]:
        small.add(v)
    assert small.percentile(0.5) == 3.0           # exact under capacity
    assert small.percentile(0.99) == 4.0
    assert Reservoir().percentile(0.5) is None


def test_stats_recorder_ttft_tpot_percentiles():
    clock = {"now": 0.0}
    rec = StatsRecorder(clock=lambda: clock["now"])
    for ms in (10, 20, 30, 40, 1000):
        rec.on_first_token(ms / 1e3)

    class _Req:
        first_token_t = 0.0

    # 4 single-token gaps of 50ms, then one 3-token step 150ms later
    # (=> three 50ms per-token observations)
    r = _Req()
    for k in range(1, 5):
        clock["now"] = 0.05 * k
        rec.on_tokens(r, 1)
    clock["now"] = 0.05 * 4 + 0.15
    rec.on_tokens(r, 3)

    class _Sched:
        max_batch = 4
        queue_depth = 0
        running = ()
        rejections = 0
        preemptions = 0
        reject_reasons = {}

        @staticmethod
        def tenant_stats():
            return {}

    class _Blocks:
        blocks_in_use = 0
        total_blocks = 8
        evictions = 0

        @staticmethod
        def utilization():
            return 0.0

        @staticmethod
        def prefix_stats():
            return {"hits": 0, "misses": 0, "hit_rate": None,
                    "tokens_saved": 0, "evictions": 0,
                    "discarded_tokens": 0, "host_hits": 0,
                    "host_restored_tokens": 0}

        @staticmethod
        def host_stats():
            return None

    s = rec.snapshot(_Sched, _Blocks)
    assert s.ttft_ms_p50 == 30.0
    assert s.ttft_ms_p99 == 1000.0 and s.ttft_ms_max == 1000.0
    assert s.ttft_ms_mean == pytest.approx(220.0)
    # all 7 per-token gaps are exactly 50ms
    assert s.tpot_ms_p50 == pytest.approx(50.0)
    assert s.tpot_ms_p99 == pytest.approx(50.0)
    assert s.tpot_ms_mean == pytest.approx(50.0)


def test_engine_feeds_tpot_and_statusz_stats_section(model,
                                                    fleet_cleanup):
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    code, _ = _post(rep.url, "/generate",
                    {"prompt": [1, 2, 3, 4], "max_new_tokens": 6})
    assert code == 200
    s = rep.engine.stats()
    assert s.tpot_ms_p50 is not None and s.tpot_ms_p50 >= 0
    assert s.ttft_ms_p99 is not None
    sec = _get(rep.url, "/statusz.json")["replica"]
    st = sec["stats"]
    assert st["tokens_generated"] == s.tokens_generated == 6
    assert st["completed"] == 1 and st["rejected"] == 0
    assert st["ttft_ms_p99"] == s.ttft_ms_p99
    assert st["tenants"] == {"default": 1}


# -- SLO grammar + burn math --------------------------------------------------
def test_slo_spec_grammar():
    objs = parse_slo_spec(
        "ttft_p99_ms=500;availability=0.999;tpot_p90_ms=80;"
        "total_p99_9_ms=2000")
    assert [o.key for o in objs] == ["ttft_p99_ms", "availability",
                                    "tpot_p90_ms", "total_p99_9_ms"]
    assert objs[0].budget == pytest.approx(0.01)
    assert objs[1].budget == pytest.approx(0.001)
    assert objs[2].budget == pytest.approx(0.10)
    assert objs[3].budget == pytest.approx(0.001)
    assert parse_slo_spec("") == [] and parse_slo_spec(None) == []
    for bad in ("ttft_p99_ms", "bogus=1", "availability=1.5",
                "ttft_p0_ms=5", "ttft_p99_ms=zzz",
                "availability=0.9;availability=0.99"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)
    # bad-event semantics
    lat = parse_slo_spec("ttft_p99_ms=100")[0]
    assert lat.is_bad({"status": "finished", "ttft_s": 0.2}) is True
    assert lat.is_bad({"status": "finished", "ttft_s": 0.05}) is False
    assert lat.is_bad({"status": "rejected", "ttft_s": None}) is None
    avail = parse_slo_spec("availability=0.99")[0]
    assert avail.is_bad({"status": "rejected"}) is True
    assert avail.is_bad({"status": "finished"}) is False


def test_request_grouping_and_judging():
    """One client request = one SLO unit, however many lines it
    pushed: router line is the availability truth, latency takes the
    worst observed value, and multi-line requests never dilute the
    bad fraction."""
    from mxnet_tpu.fleet.slo import group_requests, request_failed

    engine_ok = {"trace_id": "a", "status": "finished", "source":
                 "serve", "ttft_s": 0.01, "total_s": 0.02}
    router_slow = {"trace_id": "a", "status": "finished",
                   "source": "router", "ttft_s": None, "total_s": 0.9}
    prefill_ok = {"trace_id": "b", "status": "finished",
                  "source": "serve", "ttft_s": 0.01, "total_s": 0.02}
    router_dead = {"trace_id": "b", "status": "cancelled",
                   "source": "router", "ttft_s": None, "total_s": None}
    solo = {"trace_id": None, "status": "rejected", "source": "serve",
            "ttft_s": None, "total_s": None}
    groups = group_requests([engine_ok, router_slow, prefill_ok,
                             router_dead, solo])
    assert len(groups) == 3
    # availability: the router saw request b fail even though the
    # prefill replica's own line finished (its local 1-token request)
    assert request_failed([prefill_ok, router_dead]) is True
    assert request_failed([engine_ok, router_slow]) is False
    assert request_failed([solo]) is True
    assert request_failed([{"trace_id": "x", "status": "preempted",
                            "source": "serve"}]) is None
    # latency: worst line wins — the request is slow end-to-end even
    # though the engine's own line was fast
    total = parse_slo_spec("total_p99_ms=100")[0]
    assert total.judge([engine_ok, router_slow]) is True
    assert total.judge([engine_ok]) is False
    assert total.judge([router_dead]) is None
    ttft = parse_slo_spec("ttft_p99_ms=100")[0]
    # the router line has no TTFT; the engine line's counts
    assert ttft.judge([engine_ok, router_slow]) is False
    # burn math counts GROUPS: 10 requests with 3 lines each, all
    # failed, must read bad_fraction 1.0 — not 1/3
    clock = {"now": 100.0}
    col = _FakeCollector(lambda: clock["now"])
    for i in range(10):
        for src, status in (("serve", "finished"),
                            ("serve", "finished"),
                            ("router", "cancelled")):
            col.records.append({"t": 99.0, "trace_id": f"req{i}",
                                "status": status, "source": src,
                                "ttft_s": None, "total_s": None,
                                "replica": "r0"})
    ev = SLOEvaluator(parse_slo_spec("availability=0.9"), col,
                      fast_s=10, slow_s=10, fast_burn=1, slow_burn=1,
                      min_requests=5, clock=lambda: clock["now"])
    out = ev.evaluate()
    assert out[0]["total_fast"] == 10 and out[0]["bad_fast"] == 10
    assert out[0]["firing"]


class _FakeCollector:
    """Duck-typed collector for burn-math units: canned records plus
    call recording for annotations and flight dumps."""

    def __init__(self, clock):
        self.records = []
        self.clock = clock
        self.annotations = []
        self.dump_calls = []
        self.urls = {}

    def trace_records(self, window_s, now=None):
        now = self.clock() if now is None else now
        return [r for r in self.records if r["t"] >= now - window_s]

    def annotate(self, kind, **fields):
        self.annotations.append(dict(fields, kind=kind))

    def url_for_replica(self, name):
        return self.urls.get(name)

    def request_flight_dump(self, url, reason):
        self.dump_calls.append((url, reason))
        return f"{url}/dump.json"


_rec_ids = iter(range(10 ** 9))


def _rec(t, status="finished", ttft=0.01, replica="r0"):
    # unique trace id per record: each synthetic line is its own
    # client request (the burn math groups lines by trace id)
    return {"t": t, "status": status, "ttft_s": ttft, "tpot_s": 0.01,
            "total_s": 0.1, "replica": replica,
            "trace_id": f"t{next(_rec_ids)}"}


def test_burn_rate_multi_window_fake_clock():
    """The SRE-workbook shape under a fake clock: a fresh burst fires
    only once the slow window ALSO burns; records aging out of the
    fast window resolve the alert; min_requests gates noise."""
    clock = {"now": 1000.0}
    col = _FakeCollector(lambda: clock["now"])
    col.urls["bad-rep"] = "http://x"
    ev = SLOEvaluator(parse_slo_spec("ttft_p99_ms=100"), col,
                      fast_s=10.0, slow_s=100.0, fast_burn=5.0,
                      slow_burn=2.0, min_requests=5,
                      dump_interval_s=30.0,
                      clock=lambda: clock["now"])
    # a long clean history fills the slow window with good requests
    # (enough volume that an 8-request burst cannot burn the SLOW
    # window: 8/608 bad < slow_burn * budget)
    for i in range(600):
        col.records.append(_rec(900.0 + (i % 90), ttft=0.01))
    out = ev.evaluate()
    assert not out[0]["firing"] and out[0]["burn_fast"] == 0.0

    # burst of terrible TTFTs in the fast window: fast burns hard but
    # the slow window still holds 90 good requests -> burn_slow low
    for i in range(8):
        col.records.append(_rec(995.0 + i * 0.5, ttft=0.5,
                                replica="bad-rep"))
    out = ev.evaluate()
    assert out[0]["burn_fast"] >= 5.0
    assert not out[0]["firing"]            # slow window not burning yet

    # sustained: age the clean history out of the slow window
    clock["now"] = 1080.0
    for i in range(10):
        col.records.append(_rec(1070.0 + i, ttft=0.5,
                                replica="bad-rep"))
    out = ev.evaluate()
    assert out[0]["firing"]
    assert any(a["kind"] == "slo_alert" and a["state"] == "firing"
               for a in col.annotations)
    # the flight dump went to the offending replica, once (rate limit)
    assert col.dump_calls == [("http://x", "slo_burn_ttft_p99_ms")]
    ev.evaluate()
    assert len(col.dump_calls) == 1        # inside dump_interval_s
    # the registry-direct burning counter moved (no MXTPU_TELEMETRY)
    snap = telemetry.registry().snapshot().get("mxtpu_slo_burning")
    assert snap and any(s["labels"]["objective"] == "ttft_p99_ms"
                        and s["value"] >= 2 for s in snap["samples"])

    # recovery: bad records age out of the fast window
    clock["now"] = 1200.0
    for i in range(10):
        col.records.append(_rec(1195.0 + i * 0.4, ttft=0.01))
    out = ev.evaluate()
    assert not out[0]["firing"]
    assert any(a["kind"] == "slo_alert" and a["state"] == "resolved"
               for a in col.annotations)

    # min_requests: 3 terrible requests alone are noise, not an alert
    col2 = _FakeCollector(lambda: clock["now"])
    ev2 = SLOEvaluator(parse_slo_spec("availability=0.9"), col2,
                       fast_s=10, slow_s=10, fast_burn=1, slow_burn=1,
                       min_requests=5, clock=lambda: clock["now"])
    for i in range(3):
        col2.records.append(_rec(1199.0, status="rejected"))
    assert not ev2.evaluate()[0]["firing"]


# -- collector: scrape, aggregate, isolate ------------------------------------
def test_collector_three_view_agreement(model, fleet_cleanup, tel):
    """Acceptance gate: fleet /fleetz aggregates == sum of per-replica
    /statusz.json ground truth == the collector's registry series,
    for queue depth, tokens and reject counts."""
    reps = [ReplicaServer(_engine(model)).start() for _ in range(2)]
    fleet_cleanup.extend(reps)
    router = Router([r.url for r in reps], scrape_interval_s=0)
    fleet_cleanup.append(router)
    router.scrape()
    for i, p in enumerate(_prompts(6, seed=11)):
        router.generate(p.tolist(), max_new_tokens=5,
                        request_id=f"tv-{i}")
    # two engine-level rejections (too long for the model: 400s)
    for r in reps:
        code, body = _post(r.url, "/generate",
                           {"prompt": [1] * 30, "max_new_tokens": 60})
        assert code == 400 and body["error"] == "exceeds_max_len"

    col = FleetCollector(urls=[r.url for r in reps], interval_s=0)
    fleet_cleanup.append(col)
    assert col.scrape() == {"replicas": 2, "ok": 2, "failed": 0}
    view = col.fleet_view()

    # ground truth: every replica's own statusz
    truth = {"tokens_generated": 0, "completed": 0, "rejected": 0,
             "queue_depth": 0}
    for r in reps:
        sec = _get(r.url, "/statusz.json")["replica"]
        truth["queue_depth"] += sec["queue_depth"]
        for k in ("tokens_generated", "completed", "rejected"):
            truth[k] += sec["stats"][k]
    assert truth["tokens_generated"] == 30 and truth["rejected"] == 2

    # view 2: the fleet aggregate
    assert view["totals"]["stale"] == 0
    for k, want in truth.items():
        assert view["totals"][k] == want, k
    assert view["roles"]["both"]["tokens_generated"] == \
        truth["tokens_generated"]
    assert view["roles"]["both"]["tenant_goodput"] == {"default": 6}

    # view 3: the collector's registry series
    snap = telemetry.registry().snapshot()
    for field in ("tokens_generated", "rejected", "queue_depth",
                  "completed"):
        fam = snap[f"mxtpu_fleet_agg_{field}"]
        total = sum(s["value"] for s in fam["samples"])
        assert total == truth[field], field


def test_collector_scrape_failure_isolation(model, fleet_cleanup):
    """A dead replica and a black-holed replica each degrade only
    their OWN series: failures counted, staleness marked, the live
    sibling keeps collecting samples."""
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    # a socket that accepts connections but never answers (hung
    # replica) and a closed port (killed replica)
    hung = socket.socket()
    hung.bind(("127.0.0.1", 0))
    hung.listen(1)
    hung_url = f"http://127.0.0.1:{hung.getsockname()[1]}"
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    dead.close()
    try:
        col = FleetCollector(
            urls=[rep.url, hung_url, f"http://127.0.0.1:{dead_port}"],
            interval_s=0, timeout_s=0.5)
        fleet_cleanup.append(col)
        for _ in range(2):
            out = col.scrape()
        assert out == {"replicas": 3, "ok": 1, "failed": 2}
        rows = {r["url"]: r for r in col.fleet_view()["replicas"]}
        live = rows[rep.url.rstrip("/")]
        assert not live["stale"] and live["samples"] == 2
        assert live["total_failures"] == 0
        for url, row in rows.items():
            if url == rep.url.rstrip("/"):
                continue
            assert row["stale"] and row["total_failures"] == 2
            assert row["consecutive_failures"] == 2
        # stale replicas are listed but never summed
        assert col.fleet_view()["totals"]["stale"] == 2
    finally:
        hung.close()


def test_collector_stale_replica_ages_out_of_totals(model,
                                                    fleet_cleanup):
    """A replica that stops answering keeps its last values OUT of the
    fleet totals once stale (fake clock drives staleness)."""
    clock = {"now": 0.0}
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    col = FleetCollector(urls=[rep.url], interval_s=0, stale_after=3.0,
                         clock=lambda: clock["now"])
    fleet_cleanup.append(col)
    col.scrape()
    assert col.fleet_view()["totals"]["replicas"] == 1
    assert col.fleet_view()["totals"]["stale"] == 0
    clock["now"] = 10.0          # > stale_after * max(interval, 1)
    view = col.fleet_view()
    assert view["totals"]["stale"] == 1
    assert view["replicas"][0]["stale"] is True


# -- trace push + live stitching ---------------------------------------------
def test_trace_push_and_live_cross_stitch(model, fleet_cleanup,
                                          monkeypatch, tmp_path):
    col = FleetCollector(urls=[], interval_s=0, port=0)
    fleet_cleanup.append(col)
    col.start()
    monkeypatch.setenv("MXTPU_REQUEST_TRACE",
                       str(tmp_path / "trace.jsonl"))
    monkeypatch.setenv("MXTPU_TRACE_PUSH_URL", col.url + "/trace")
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    col.add_replica(rep.url)
    router = Router([rep.url], scrape_interval_s=0)
    fleet_cleanup.append(router)
    router.scrape()
    res = router.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                          request_id="push-1")
    # both lines (engine + router) ship asynchronously
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        recs = col.trace_records()
        if len(recs) >= 2:
            break
        time.sleep(0.05)
    recs = col.trace_records()
    assert len(recs) == 2
    by_replica = {r["replica"] for r in recs}
    # the engine line carries the replica id; the router line
    # attributes its terminal to the SERVING replica too
    assert by_replica == {rep.replica_id}
    # live stitch: one trace id across both lines
    assert {r["trace_id"] for r in recs} == {res.trace_id}
    engine_line = [r for r in recs if r["ttft_s"] is not None]
    assert len(engine_line) == 1          # router lines have no ttft
    assert engine_line[0]["status"] == "finished"
    view = col.fleet_view()
    assert view["traces"]["received"] == 2
    assert view["traces"]["window_availability"] == 1.0
    # the local JSONL file still got both lines (push is additive)
    lines = [json.loads(ln) for ln in
             (tmp_path / "trace.jsonl").read_text().splitlines()]
    assert {ln["replica"] for ln in lines} == {rep.replica_id, "router"}
    router_line = [ln for ln in lines if ln["replica"] == "router"][0]
    evs = [e["ev"] for e in router_line["events"]]
    assert "pick" in evs and "hop" in evs and evs[-1] == "finished"


# -- the burn-alert E2E under chaos ------------------------------------------
def test_burn_alert_fires_under_kill_delay_chaos(model, fleet_cleanup,
                                                 monkeypatch, tmp_path):
    """Acceptance gate: delay+kill chaos on one replica pushes the
    total-latency objective's burn over BOTH windows -> the alert
    fires, annotates the timeline, and the flight dump lands via the
    offender's /flight_dump; the clean evaluator stays silent."""
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path / "flight"))
    col = FleetCollector(urls=[], interval_s=0, port=0)
    fleet_cleanup.append(col)
    col.start()
    monkeypatch.setenv("MXTPU_TRACE_PUSH_URL", col.url + "/trace")
    slow = ReplicaServer(
        _engine(model), replica_id="slow-replica",
        fault_injector=FaultInjector(
            ";".join(f"delay@{k}:0.4" for k in range(1, 7))))
    dying = ReplicaServer(_engine(model), replica_id="dying-replica",
                          fault_injector=FaultInjector("kill@2"))
    good = ReplicaServer(_engine(model), replica_id="good-replica")
    for r in (slow, dying, good):
        fleet_cleanup.append(r.start())
        col.add_replica(r.url)
    router = Router([slow.url, dying.url, good.url],
                    scrape_interval_s=0, retries=6, breaker_fails=20,
                    backoff_s=0.01, backoff_max_s=0.05)
    fleet_cleanup.append(router)
    router.scrape()
    ev = SLOEvaluator(parse_slo_spec("total_p90_ms=150"), col,
                      fast_s=120.0, slow_s=240.0, fast_burn=2.0,
                      slow_burn=1.0, min_requests=5,
                      dump_interval_s=0.0)
    col.slo = ev
    clean = SLOEvaluator(parse_slo_spec("total_p90_ms=60000;"
                                        "availability=0.5"), col,
                         fast_s=120.0, slow_s=240.0, fast_burn=2.0,
                         slow_burn=1.0, min_requests=5)
    # sequential load round-robins across the three replicas: the slow
    # one delays every arrival 400ms, the dying one is hard-killed
    # mid-stream on its second — every request still completes
    for i, p in enumerate(_prompts(12, seed=23)):
        res = router.generate(p.tolist(), max_new_tokens=4,
                              request_id=f"chaos-{i}")
        assert res.tokens                   # chaos stays client-invisible
        router.scrape()                     # track the kill
        col.scrape()                        # scrape + evaluate as live
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and len(col.trace_records()) < 20:
        time.sleep(0.05)
    col.scrape()                            # final evaluate
    state = ev.statusz()["objectives"][0]
    assert state["firing"], ev.statusz()
    assert any(a["kind"] == "slo_alert" and a["state"] == "firing"
               for a in col.annotations())
    # the flight dump landed on disk via the offender's /flight_dump
    # (the in-process recorder is shared, so exactly one file exists —
    # the per-reason rate limit suppressed later offenders)
    dumps = list((tmp_path / "flight").glob(
        "flight-*slo_burn_total_p90_ms*.json"))
    assert dumps, list((tmp_path / "flight").glob("*"))
    payload = json.loads(dumps[0].read_text())
    assert payload["reason"].startswith("slo_burn_total_p90_ms")
    assert payload["extra"]["requested_by"] == "fleet"
    # offender attribution: the worst offender is the delaying replica
    # (it slowed every one of its arrivals; the killed one's retried
    # request was served fast by a sibling)
    assert payload["extra"]["replica"] == "slow-replica"
    # the killed replica only degraded its OWN series
    rows = {r["replica"]: r for r in col.fleet_view()["replicas"]}
    assert rows["good-replica"]["total_failures"] == 0
    assert rows["slow-replica"]["total_failures"] == 0
    assert rows["dying-replica"]["total_failures"] >= 1
    assert rows["dying-replica"]["stale"] or \
        rows["dying-replica"]["consecutive_failures"] >= 1
    # and the same records leave a lenient evaluator silent
    assert not any(o["firing"] for o in clean.evaluate())
    assert clean.statusz()["objectives"][0]["fired_total"] == 0


# -- satellite: router hop instrumentation ------------------------------------
def test_router_hop_histogram_and_breaker_gauge(model, fleet_cleanup,
                                                tel):
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    dead.close()
    router = Router([f"http://127.0.0.1:{dead_port}", rep.url],
                    scrape_interval_s=0, retries=4, backoff_s=0.01,
                    backoff_max_s=0.02, timeout_s=5)
    fleet_cleanup.append(router)
    res = router.generate([1, 2, 3], max_new_tokens=3,
                          request_id="hop-1")
    assert res.tokens
    snap = telemetry.registry().snapshot()
    fam = snap["mxtpu_fleet_router_hop_seconds"]
    by_outcome = {s["labels"]["outcome"]: s["count"]
                  for s in fam["samples"]}
    assert by_outcome.get("ok", 0) >= 1         # the serving hop
    assert by_outcome.get("retry", 0) >= 1      # the dead-replica hop
    gauge = snap["mxtpu_fleet_breaker_state"]
    states = {s["labels"]["replica"]: s["value"]
              for s in gauge["samples"]}
    # never-scraped routers label by URL; closed after the success
    assert states[rep.url] == 0.0


def test_router_trace_inert_without_env(model, fleet_cleanup):
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    router = Router([rep.url], scrape_interval_s=0)
    fleet_cleanup.append(router)
    assert not router._trace.enabled
    assert router._trace_begin(4, 4, None, "tid") is None
    res = router.generate([1, 2, 3], max_new_tokens=3)
    assert res.tokens
    # engine tracer grew no pusher either (MXTPU_TRACE_PUSH_URL unset)
    assert rep.engine._rtrace._pusher is None


# -- satellite: supervisor lifecycle events -----------------------------------
class _InProcHandle:
    def __init__(self, replica):
        self.replica = replica
        self.url = replica.url

    def poll(self):
        return None if self.replica.state != "dead" else 1

    def terminate(self, grace_s=None):
        self.replica.stop()


def test_supervisor_lifecycle_annotations_and_reasons(model,
                                                      fleet_cleanup,
                                                      tel):
    col = FleetCollector(urls=[], interval_s=0)
    fleet_cleanup.append(col)
    spawned = []

    def spawn(slot):
        rep = ReplicaServer(_engine(model),
                            replica_id=f"s{slot}-{len(spawned)}").start()
        fleet_cleanup.append(rep)
        spawned.append(rep)
        return _InProcHandle(rep)

    sup = Supervisor(spawn, 1, restart_backoff_s=0.0, collector=col,
                     drain_timeout_s=10)
    sup.start()
    spawned[-1].hard_stop()                     # crash
    assert sup.check() == [0]
    sup.drain_and_restart(0)                    # rolling
    sup.stop()
    kinds = [a["kind"] for a in col.annotations()]
    assert "replica_crash_restart" in kinds
    assert "replica_respawn" in kinds
    assert kinds.count("rolling_restart_slot") >= 3   # 3 phases
    phases = [a["phase"] for a in col.annotations()
              if a["kind"] == "rolling_restart_slot"]
    assert phases == ["drain", "terminate", "respawned"]
    snap = telemetry.registry().snapshot()
    reasons = {s["labels"]["reason"]: s["value"]
               for s in snap["mxtpu_fleet_restarts_total"]["samples"]}
    assert reasons == {"crash": 1, "rolling": 1}


# -- replica endpoints --------------------------------------------------------
def test_replica_metrics_endpoint(model, fleet_cleanup, tel):
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    code, _ = _post(rep.url, "/generate",
                    {"prompt": [5, 6, 7], "max_new_tokens": 4})
    assert code == 200
    with urllib.request.urlopen(rep.url + "/metrics",
                                timeout=10) as resp:
        assert "version=0.0.4" in resp.headers["Content-Type"]
        text = resp.read().decode()
    parsed = timeseries.parse_prometheus_text(text)
    assert parsed.get("mxtpu_serve_tokens_generated_total", 0) >= 4


def test_replica_flight_dump_route_rate_limited(model, fleet_cleanup,
                                                monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    code, body = _post(rep.url, "/flight_dump", {"reason": "op_asked"})
    assert code == 200 and body["path"]
    assert os.path.exists(body["path"])
    assert "op_asked" in body["path"]
    # second request within the recorder's per-reason window: suppressed
    code, body2 = _post(rep.url, "/flight_dump", {"reason": "op_asked"})
    assert code == 200 and body2["path"] is None


# -- fleet_report rendering ---------------------------------------------------
def test_fleet_report_renders_live_view(model, fleet_cleanup):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_report import render

    rep = ReplicaServer(_engine(model)).start()
    fleet_cleanup.append(rep)
    _post(rep.url, "/generate", {"prompt": [1, 2, 3],
                                 "max_new_tokens": 3})
    col = FleetCollector(urls=[rep.url], interval_s=0,
                         slo_spec="availability=0.99")
    fleet_cleanup.append(col)
    col.scrape()
    col.annotate("rolling_restart", phase="start", slots=1)
    text = render(col.fleet_view())
    assert "both" in text and rep.replica_id in text
    # the availability objective renders, state ok (only the column
    # header mentions FIRING)
    assert "availability" in text and text.count("FIRING") == 1
    assert "rolling_restart" in text
    # the whole view survives a JSON round trip (the --file mode)
    json.loads(json.dumps(col.fleet_view(), default=str))


# -- env knob documentation pin ----------------------------------------------
def test_obs_env_knobs_documented():
    doc = open(os.path.join(REPO, "docs", "env_vars.md")).read()
    for var in ("MXTPU_TIMESERIES", "MXTPU_TIMESERIES_INTERVAL",
                "MXTPU_TRACE_PUSH_URL", "MXTPU_FLEET_COLLECT_INTERVAL",
                "MXTPU_FLEET_COLLECT_PORT", "MXTPU_SLO_SPEC",
                "MXTPU_SLO_FAST_WINDOW", "MXTPU_SLO_SLOW_WINDOW",
                "MXTPU_SLO_FAST_BURN", "MXTPU_SLO_SLOW_BURN",
                "MXTPU_SLO_MIN_REQUESTS"):
        assert var in doc, var


# -- the subprocess A/B contract (slow tier) ----------------------------------
@pytest.mark.slow
def test_fleet_obs_bench_contract(tmp_path):
    """tools/fleet_bench.py --obs stamps complete:true with the clean
    arm silent, the chaos arm firing, and overhead within noise."""
    import subprocess

    out = tmp_path / "obs.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--obs", "--obs-requests", "10", "--json", str(out)],
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(out.read_text().splitlines()[-1])
    assert payload["complete"] is True
    assert payload["alert_fired_clean"] is False
    assert payload["alert_fired_chaos"] is True
    assert payload["chaos_flight_dumps"] > 0
    assert payload["overhead_ratio"] >= 0.75
