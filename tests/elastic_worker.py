"""Worker for the elastic-recovery test: rank 1 crashes partway through
its first life (before pushing), the launcher respawns it with
MXTPU_IS_RECOVERY set, and the restarted worker rejoins — re-init is a
server-side no-op and startup barriers are skipped (reference ps-lite
is_recovery: servers keep state, restarted nodes skip the barrier) —
then training completes exactly.

Launched by test_ps.py via tools/launch.py -n 2 -s 1 --max-restarts 1.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import mxnet_tpu as mx


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    marker = os.environ["ELASTIC_MARKER"] + f".rank{rank}"
    first_life = not os.path.exists(marker)
    if first_life:
        with open(marker, "w") as f:
            f.write("seen")

    kv = mx.kv.create("dist_async")
    shape = (3, 2)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, rescale_grad=1.0))
    kv.init("w", mx.nd.zeros(shape))

    if rank == 1 and first_life:
        # crash before contributing; the launcher must respawn us
        os._exit(3)

    # server-side SGD: w -= grad per push; both contributions -> -3 exactly
    kv.push("w", mx.nd.ones(shape) * (rank + 1))

    expect = -3.0
    out = mx.nd.zeros(shape)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        kv.pull("w", out=out)
        if abs(float(out.asnumpy()[0, 0]) - expect) < 1e-6:
            print(f"RANK_{rank}_ELASTIC_OK", flush=True)
            return
        time.sleep(0.1)
    raise AssertionError(
        f"rank {rank}: never saw {expect}, last {out.asnumpy()[0, 0]}")


if __name__ == "__main__":
    main()
