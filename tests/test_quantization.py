"""Post-training int8 quantization (contrib/quantization.py +
ops/quantized.py — beyond the 2016 reference; the contrib/quantize.py
capability of later MXNet, rebuilt TPU-native)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.quantization import quantize_model, quantize_weight


def test_quantize_weight_roundtrip():
    rng = np.random.RandomState(0)
    w = rng.randn(8, 32).astype(np.float32)
    wq, scale = quantize_weight(w)
    assert wq.dtype == np.int8 and scale.shape == (8,)
    deq = wq.astype(np.float32) * scale[:, None]
    # per-channel symmetric int8: max error is half a quantization step
    step = scale[:, None]
    assert np.all(np.abs(deq - w) <= step * 0.5 + 1e-7)
    # zero rows quantize cleanly (scale falls back to 1)
    wq0, s0 = quantize_weight(np.zeros((2, 4), np.float32))
    assert np.all(wq0 == 0) and np.all(s0 == 1.0)


def _trained_mlp():
    rng = np.random.RandomState(1)
    X = rng.randn(256, 20).astype(np.float32)
    y = (X[:, :4].sum(1) > 0).astype(np.float32)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=32,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, 64), num_epoch=10, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier())
    args, aux = mod.get_params()
    probs = mod.predict(mx.io.NDArrayIter(X, None, 64)).asnumpy()
    return net, args, aux, X, y, probs


def _run_quantized(qsym, qargs, X):
    exe = qsym.simple_bind(mx.cpu(), grad_req="null", data=X.shape,
                           softmax_label=(X.shape[0],))
    for k, v in qargs.items():
        if k in exe.arg_dict:
            exe.arg_dict[k][:] = v
    exe.arg_dict["data"][:] = X
    return exe, exe.forward(is_train=False)[0].asnumpy()


def test_weight_only_fc_close_to_float():
    net, args, aux, X, y, probs_f = _trained_mlp()
    qsym, qargs, _ = quantize_model(net, args, aux)
    # weights really stored int8; scale vectors appear
    assert qargs["fc1_weight"].dtype == np.int8
    assert qargs["fc1_wscale"].shape == (32,)
    assert "wscale" in " ".join(qsym.list_arguments())
    exe, probs_q = _run_quantized(qsym, qargs, X)
    # int8 weight noise is tiny for a 2-layer MLP
    assert np.abs(probs_q - probs_f).max() < 0.05
    assert (probs_q.argmax(1) == probs_f.argmax(1)).mean() > 0.98


def test_calibrated_int8_fc():
    net, args, aux, X, y, probs_f = _trained_mlp()
    qsym, qargs, _ = quantize_model(net, args, aux,
                                    calib_data=[X[:64], X[64:128]])
    # act_scale baked into the graph
    import json

    conf = json.loads(qsym.tojson())
    scales = [float(n["param"]["act_scale"]) for n in conf["nodes"]
              if n["op"] == "QuantizedFullyConnected"]
    assert len(scales) == 2 and all(s > 0 for s in scales)
    exe, probs_q = _run_quantized(qsym, qargs, X)
    acc_f = (probs_f.argmax(1) == y).mean()
    acc_q = (probs_q.argmax(1) == y).mean()
    assert acc_q >= acc_f - 0.03, (acc_f, acc_q)


def test_quantized_conv_net():
    rng = np.random.RandomState(2)
    X = rng.randn(64, 1, 8, 8).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, 32), num_epoch=3,
            initializer=mx.initializer.Xavier())
    args, aux = mod.get_params()
    probs_f = mod.predict(mx.io.NDArrayIter(X, None, 32)).asnumpy()

    for calib in (None, [X[:32]]):
        qsym, qargs, _ = quantize_model(net, args, aux, calib_data=calib)
        assert qargs["conv1_weight"].dtype == np.int8
        exe, probs_q = _run_quantized(qsym, qargs, X)
        assert (probs_q.argmax(1) == probs_f.argmax(1)).mean() > 0.95, \
            ("calib" if calib else "weight-only")


def test_exclude_and_ineligible_pass_through():
    import json

    data = mx.sym.Variable("data")
    # grouped conv: structurally ineligible
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                             num_group=2, pad=(1, 1), name="gconv")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=3,
                                name="fc_keep")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc_q")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = {n: s for n, s in zip(
        net.list_arguments(),
        net.infer_shape(data=(2, 4, 6, 6))[0])}
    rng = np.random.RandomState(3)
    args = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32))
            for n in shapes if n not in ("data", "softmax_label")}
    qsym, qargs, _ = quantize_model(net, args, exclude=("fc_keep",))
    ops = {n["name"]: n["op"] for n in json.loads(qsym.tojson())["nodes"]}
    assert ops["gconv"] == "Convolution"          # ineligible: grouped
    assert ops["fc_keep"] == "FullyConnected"     # excluded by name
    assert ops["fc_q"] == "QuantizedFullyConnected"
    assert qargs["fc_keep_weight"].dtype == np.float32
    assert qargs["fc_q_weight"].dtype == np.int8


def test_quantized_checkpoint_roundtrip(tmp_path):
    """int8 params survive the standard two-artifact checkpoint."""
    net, args, aux, X, y, _ = _trained_mlp()
    qsym, qargs, qaux = quantize_model(net, args, aux)
    prefix = str(tmp_path / "quant")
    qsym.save(prefix + "-symbol.json")
    mx.nd.save(prefix + "-0000.params",
               {"arg:" + k: v for k, v in qargs.items()})
    sym2 = mx.sym.load(prefix + "-symbol.json")
    loaded = mx.nd.load(prefix + "-0000.params")
    args2 = {k[4:]: v for k, v in loaded.items()}
    assert args2["fc1_weight"].dtype == np.int8
    _, p1 = _run_quantized(qsym, qargs, X)
    _, p2 = _run_quantized(sym2, args2, X)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_quantized_conv_nhwc_and_ragged_calibration():
    """NHWC layout (weights stay OIHW like the float op) and a ragged
    final calibration batch both work."""
    rng = np.random.RandomState(4)
    X = rng.randn(48, 8, 8, 2).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3,), num_filter=4, pad=(1, 1),
                             layout="NHWC", name="cq")  # 1-tuple kernel
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fq")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = dict(zip(net.list_arguments(),
                      net.infer_shape(data=(48, 8, 8, 2))[0]))
    args = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32) * 0.3)
            for n in shapes if n not in ("data", "softmax_label")}

    exe_f = net.simple_bind(mx.cpu(), grad_req="null", data=(48, 8, 8, 2),
                            softmax_label=(48,))
    for k, v in args.items():
        exe_f.arg_dict[k][:] = v
    exe_f.arg_dict["data"][:] = X
    probs_f = exe_f.forward(is_train=False)[0].asnumpy()

    for calib in (None, [X[:32], X[32:48]]):   # ragged second batch
        qsym, qargs, _ = quantize_model(net, args, calib_data=calib)
        assert qargs["cq_weight"].dtype == np.int8
        # quantization is shape-preserving: OIHW in both layouts
        assert tuple(qargs["cq_weight"].shape) == tuple(args["cq_weight"].shape)
        exe, probs_q = _run_quantized(qsym, qargs, X)
        assert (probs_q.argmax(1) == probs_f.argmax(1)).mean() > 0.93, \
            ("calib" if calib else "weight-only")


def test_multi_output_source_and_string_exclude():
    """Calibration taps resolve multi-output sources by output index,
    and a bare-string exclude= means one name, not its characters."""
    data = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(data, num_outputs=2, axis=1, name="slice")
    net = mx.sym.FullyConnected(parts[1], num_hidden=3, name="fcm")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(5)
    shapes = dict(zip(net.list_arguments(),
                      net.infer_shape(data=(8, 4))[0]))
    args = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32))
            for n in shapes if n not in ("data", "softmax_label")}
    X = rng.randn(8, 4).astype(np.float32)
    qsym, qargs, _ = quantize_model(net, args, calib_data=[X])
    assert qargs["fcm_weight"].dtype == np.int8
    exe, probs = _run_quantized(qsym, qargs, X)
    assert probs.shape == (8, 3)

    # string exclude: the named layer must NOT be quantized
    q2, qa2, _ = quantize_model(net, args, exclude="fcm")
    assert qa2["fcm_weight"].dtype == np.float32


def test_quantized_predict_api():
    """The predict-only deployment surface consumes quantized
    artifacts unchanged (symbol JSON + int8 param blob)."""
    net, args, aux, X, y, probs_f = _trained_mlp()
    qsym, qargs, _ = quantize_model(net, args, aux)
    pred = mx.predict.create(qsym.tojson(),
                             {"arg:" + k: v for k, v in qargs.items()},
                             {"data": X.shape})
    out = np.asarray(pred.forward(data=X)[0])
    assert (out.argmax(1) == probs_f.argmax(1)).mean() > 0.98


def test_tap_resolves_ambiguous_output_names():
    """Calibration taps index internals POSITIONALLY: an RNN's
    'rnn_state' output collides with its 'rnn_state' initial-state
    variable, which a name lookup would mis-resolve; weight-only mode
    must not touch tap resolution at all."""
    data = mx.sym.Variable("data")
    rnn = mx.sym.RNN(data, state_size=8, num_layers=1, mode="lstm",
                     state_outputs=True, name="rnn")
    net = mx.sym.FullyConnected(rnn[1], num_hidden=3, name="fcs")
    net = mx.sym.SoftmaxOutput(mx.sym.Reshape(net, shape=(-1, 3)),
                               name="softmax")
    shapes = dict(zip(net.list_arguments(),
                      net.infer_shape(data=(5, 2, 4))[0]))
    rng = np.random.RandomState(6)
    args = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32))
            for n in shapes if n not in ("data", "softmax_label")}
    X = rng.randn(5, 2, 4).astype(np.float32)
    for calib in (None, [X]):
        qsym, qargs, _ = quantize_model(net, args, calib_data=calib)
        assert qargs["fcs_weight"].dtype == np.int8


def test_quantize_cli_tool(tmp_path):
    """tools/quantize.py round-trips a trained checkpoint to an int8
    pair loadable through the standard loaders."""
    import subprocess
    import sys as _sys

    net, args_p, aux_p, X, y, probs_f = _trained_mlp()
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 3, net, args_p, aux_p)
    out = str(tmp_path / "m_int8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "quantize.py"),
         "--prefix", prefix, "--epoch", "3", "--out", out],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "MXTPU_PLATFORMS": "cpu"})
    assert r.returncode == 0, (r.stdout + "\n" + r.stderr)[-2000:]
    assert "quantized 2 layers" in r.stdout
    sym2, args2, aux2 = mx.model.load_checkpoint(out, 0)
    assert args2["fc1_weight"].dtype == np.int8
    _, probs_q = _run_quantized(sym2, args2, X)
    assert (probs_q.argmax(1) == probs_f.argmax(1)).mean() > 0.98


def test_quantize_cli_calibrated_rec(tmp_path):
    """The --calib-rec path: a RecordIO dataset drives activation
    calibration with training-matched preprocessing, and act_scale
    lands in the output symbol."""
    import json
    import subprocess
    import sys as _sys

    from mxnet_tpu import recordio

    pytest.importorskip("cv2")
    rng = np.random.RandomState(7)
    rec_path = str(tmp_path / "calib.rec")
    writer = recordio.MXRecordIO(rec_path, "w")
    for i in range(8):
        img = rng.randint(0, 255, (12, 12, 3), dtype=np.uint8)
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i % 2), i, 0), img, quality=95))
    writer.close()

    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=4, pad=(1, 1), name="c1")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=2,
                                name="f1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = dict(zip(net.list_arguments(),
                      net.infer_shape(data=(4, 3, 12, 12))[0]))
    args_p = {n: mx.nd.array(
        np.random.RandomState(8).randn(*shapes[n]).astype(np.float32) * 0.1)
        for n in shapes if n not in ("data", "softmax_label")}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 1, net, args_p, {})

    out = str(tmp_path / "m_int8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "quantize.py"),
         "--prefix", prefix, "--epoch", "1", "--out", out,
         "--calib-rec", rec_path, "--batch-size", "4",
         "--data-shape", "3,12,12", "--scale", str(1.0 / 255)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "MXTPU_PLATFORMS": "cpu"})
    assert r.returncode == 0, (r.stdout + "\n" + r.stderr)[-2000:]
    conf = json.loads(open(out + "-symbol.json").read())
    scales = [float(n["param"]["act_scale"]) for n in conf["nodes"]
              if n["op"].startswith("Quantized")]
    assert scales and all(s > 0 for s in scales), scales
    # preprocessing applied: calibrated input scale reflects /255 pixels
    first = min(scales)
    assert first < 1.0, scales  # raw 0-255 calibration would be >> 1
