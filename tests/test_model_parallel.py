"""Model parallelism via ctx_group/group2ctx (rebuild of
tests/python/unittest/test_model_parallel.py): a graph split across two
CPU contexts must produce outputs and gradients identical to a
single-context bind."""

import numpy as np

import mxnet_tpu as mx


def _net():
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        act1 = mx.sym.Activation(fc1, act_type="relu", name="act1")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=8, name="fc2")
        act2 = mx.sym.Activation(fc2, act_type="relu", name="act2")
        fc3 = mx.sym.FullyConnected(act2, num_hidden=4, name="fc3")
        out = mx.sym.SoftmaxOutput(fc3, name="softmax")
    return out


def test_chain_multi_context_matches_single():
    net = _net()
    shape = (8, 10)
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=shape)
    values = {name: rng.randn(*s).astype(np.float32) * 0.5
              for name, s in zip(net.list_arguments(), arg_shapes)}
    values["softmax_label"] = rng.randint(0, 4, 8).astype(np.float32)

    def run(group2ctx):
        exe = net.simple_bind(mx.cpu(0), group2ctx=group2ctx,
                              grad_req="write", data=shape)
        for k, v in values.items():
            exe.arg_dict[k][:] = v
        outs = [o.asnumpy() for o in exe.forward(is_train=True)]
        exe.backward()
        grads = {k: g.asnumpy() for k, g in exe.grad_dict.items()}
        return outs, grads

    outs1, grads1 = run(None)
    outs2, grads2 = run({"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    for o1, o2 in zip(outs1, outs2):
        np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-6)
    assert set(grads1) == set(grads2)
    for k in grads1:
        np.testing.assert_allclose(grads1[k], grads2[k], rtol=1e-4,
                                   atol=1e-6, err_msg=k)


def test_array_placement_follows_groups():
    net = _net()
    exe = net.simple_bind(mx.cpu(0),
                          group2ctx={"dev1": mx.cpu(2), "dev2": mx.cpu(3)},
                          data=(8, 10))
    assert exe.arg_dict["fc1_weight"].context == mx.cpu(2)
    assert exe.arg_dict["fc3_weight"].context == mx.cpu(3)
    assert exe.arg_dict["data"].context == mx.cpu(2)


def test_multi_ctx_training_converges():
    np.random.seed(11)
    net = _net()
    rng = np.random.RandomState(1)
    X = rng.randn(128, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    exe = net.simple_bind(mx.cpu(0),
                          group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
                          grad_req="write", data=(32, 10))
    ini = mx.initializer.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            ini(name, arr)
    opt = mx.optimizer.SGD(learning_rate=0.3, momentum=0.9,
                           rescale_grad=1.0 / 32)
    updater = mx.optimizer.get_updater(opt)
    for step in range(40):
        b = (step * 32) % 96
        exe.arg_dict["data"][:] = X[b:b + 32]
        exe.arg_dict["softmax_label"][:] = y[b:b + 32]
        exe.forward(is_train=True)
        exe.backward()
        for i, name in enumerate(exe.arg_names):
            if name in ("data", "softmax_label"):
                continue
            updater(i, exe.grad_dict[name], exe.arg_dict[name])
    exe.arg_dict["data"][:] = X[:32]
    exe.arg_dict["softmax_label"][:] = y[:32]
    pred = exe.forward(is_train=False)[0].asnumpy().argmax(axis=1)
    assert (pred == y[:32]).mean() > 0.9


def test_mixed_device_bind_arrays():
    """bind() with arrays pre-placed on different contexts partitions the
    graph accordingly (reference model-parallel-lstm custom bind)."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.MakeLoss(mx.sym.sum((a * 2) * b))
    a_arr = mx.nd.array(np.ones((3, 3)), ctx=mx.cpu(0))
    b_arr = mx.nd.array(np.full((3, 3), 2.0), ctx=mx.cpu(1))
    ga = mx.nd.zeros((3, 3), ctx=mx.cpu(0))
    gb = mx.nd.zeros((3, 3), ctx=mx.cpu(1))
    exe = out.bind(mx.cpu(0), args={"a": a_arr, "b": b_arr},
                   args_grad={"a": ga, "b": gb})
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(ga.asnumpy(), np.full((3, 3), 4.0), rtol=1e-6)
    np.testing.assert_allclose(gb.asnumpy(), np.full((3, 3), 2.0), rtol=1e-6)
    assert ga.context == mx.cpu(0) and gb.context == mx.cpu(1)


def test_partial_forward_multi_context():
    """Stepwise execution honors ctx_group placement and matches the
    fused multi-context forward."""
    net = _net()
    shape = (4, 10)
    rng = np.random.RandomState(7)
    arg_shapes, _, _ = net.infer_shape(data=shape)
    values = {name: rng.randn(*s).astype(np.float32) * 0.5
              for name, s in zip(net.list_arguments(), arg_shapes)}
    values["softmax_label"] = rng.randint(0, 4, 4).astype(np.float32)

    group2ctx = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    exe = net.simple_bind(mx.cpu(0), group2ctx=group2ctx, data=shape)
    for k, v in values.items():
        exe.arg_dict[k][:] = v
    full = exe.forward()[0].asnumpy()

    step = 0
    while exe.partial_forward(step=step) != 0:
        step += 1
    np.testing.assert_allclose(exe.outputs[0].asnumpy(), full,
                               rtol=1e-5, atol=1e-6)
