"""Training convergence gates (rebuild of tests/python/train/test_mlp.py /
test_conv.py, on synthetic data — no dataset downloads in CI)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io import NDArrayIter


def _synthetic_images(n=512, c=10, seed=0):
    """Separable image-like task: class-dependent bar pattern + noise."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 1, 28, 28), np.float32)
    y = rng.randint(0, c, n)
    for i in range(n):
        X[i, 0, y[i] * 2:y[i] * 2 + 3, 5:20] = 1.0
    X += rng.randn(*X.shape).astype(np.float32) * 0.1
    return X, y.astype(np.float32)


def test_mlp_convergence():
    X, y = _synthetic_images(512)
    Xf = X.reshape(512, -1)
    train = NDArrayIter(Xf[:384], y[:384], batch_size=64, shuffle=True)
    val = NDArrayIter(Xf[384:], y[384:], batch_size=64)
    model = mx.FeedForward(mx.models.mlp(), ctx=mx.cpu(), num_epoch=6,
                           learning_rate=0.2, momentum=0.9,
                           initializer=mx.initializer.Xavier())
    model.fit(train, eval_data=val)
    acc = model.score(val)
    assert acc > 0.95, f"mlp accuracy {acc} below gate"


def test_lenet_convergence():
    X, y = _synthetic_images(512)
    train = NDArrayIter(X[:384], y[:384], batch_size=64, shuffle=True)
    val = NDArrayIter(X[384:], y[384:], batch_size=64)
    model = mx.FeedForward(mx.models.lenet(), ctx=mx.cpu(), num_epoch=3,
                           learning_rate=0.1, momentum=0.9,
                           initializer=mx.initializer.Xavier())
    model.fit(train, eval_data=val)
    acc = model.score(val)
    assert acc > 0.95, f"lenet accuracy {acc} below gate"


def test_bf16_training():
    """bfloat16 data path (the TPU-native half type; rebuild of
    tests/python/train/test_dtype.py's fp16 intent)."""
    X, y = _synthetic_images(256)
    Xf = X.reshape(256, -1)
    train = NDArrayIter(Xf, y, batch_size=64, shuffle=True)
    data = mx.sym.Variable("data")
    net = mx.sym.Cast(data, dtype="bfloat16")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Cast(net, dtype="float32")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, num_epoch=6,
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), kvstore=None)
    acc = mod.score(train, "acc")[0][1]
    assert acc > 0.9, f"bf16 accuracy {acc} below gate"


def test_checkpoint_resume(tmp_path):
    """Train, checkpoint, resume, continue — loss keeps improving
    (checkpoint/resume contract, SURVEY.md §5)."""
    X, y = _synthetic_images(256)
    Xf = X.reshape(256, -1)
    train = NDArrayIter(Xf, y, batch_size=64, shuffle=True)
    prefix = str(tmp_path / "ckpt")
    model = mx.FeedForward(mx.models.mlp(), ctx=mx.cpu(), num_epoch=2,
                           learning_rate=0.2, momentum=0.9,
                           initializer=mx.initializer.Xavier())
    model.fit(train, epoch_end_callback=mx.callback.do_checkpoint(prefix))
    acc1 = model.score(train)
    model2 = mx.FeedForward.load(prefix, 2, ctx=mx.cpu(), num_epoch=4,
                                 learning_rate=0.2, momentum=0.9)
    acc_loaded = model2.score(train)
    assert abs(acc_loaded - acc1) < 0.05
    model2.fit(train)
    acc2 = model2.score(train)
    assert acc2 >= acc1 - 0.05


def test_async_checkpoint_matches_sync(tmp_path):
    """async_save writes the same artifact (atomically) as the sync
    path, pinned to the state at call time — later param mutations must
    not leak into the file."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.model import (load_checkpoint, save_checkpoint,
                                 wait_checkpoints)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4),
        name="softmax")
    rng = np.random.RandomState(0)
    args = {"fullyconnected0_weight": mx.nd.array(rng.randn(4, 6)),
            "fullyconnected0_bias": mx.nd.array(rng.randn(4))}
    aux = {}

    sync_prefix = str(tmp_path / "sync")
    async_prefix = str(tmp_path / "async")
    save_checkpoint(sync_prefix, 3, net, args, aux)
    save_checkpoint(async_prefix, 3, net, args, aux, async_save=True)
    # mutate AFTER the async call returns: snapshot semantics
    args["fullyconnected0_bias"][:] = 999.0
    wait_checkpoints()

    _, a_sync, _ = load_checkpoint(sync_prefix, 3)
    _, a_async, _ = load_checkpoint(async_prefix, 3)
    for k in a_sync:
        np.testing.assert_allclose(a_async[k].asnumpy(),
                                   a_sync[k].asnumpy())
    assert not np.allclose(a_async["fullyconnected0_bias"].asnumpy(), 999.0)
    # no torn temp files left behind
    import os

    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_do_checkpoint_async_callback(tmp_path):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.model import load_checkpoint, wait_checkpoints

    rng = np.random.RandomState(0)
    X = rng.rand(64, 8).astype(np.float32)
    y = (X.sum(axis=1) > 4).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    prefix = str(tmp_path / "cb")
    model = mx.FeedForward(net, num_epoch=3, learning_rate=0.05,
                           numpy_batch_size=16)
    model.fit(X=mx.io.NDArrayIter(X, y, batch_size=16),
              epoch_end_callback=mx.callback.do_checkpoint(
                  prefix, async_save=True))
    wait_checkpoints()
    sym2, args2, aux2 = load_checkpoint(prefix, 3)
    assert any(k.endswith("_weight") for k in args2)


def test_async_checkpoint_failure_surfaces(tmp_path):
    """A failed background write must raise from wait_checkpoints(), not
    silently report success over a missing artifact."""
    import numpy as np
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.model import save_checkpoint, wait_checkpoints

    net = mx.sym.Variable("data")
    args = {"w": np.zeros(3, np.float32)}
    prefix = str(tmp_path / "nodir" / "m")  # parent doesn't exist
    with pytest.raises((MXNetError, OSError, FileNotFoundError)):
        try:
            save_checkpoint(prefix, 1, None, args, {}, async_save=True)
        finally:
            wait_checkpoints()


def test_stage_async_write_failure_leaves_no_tmp_orphan(tmp_path):
    """A writer that produced its temp file and THEN died must not
    leave the ``.tmp.*`` behind (a crash-looping writer would otherwise
    fill the checkpoint volume with torn temps)."""
    import os

    import pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.model import stage_async_write, wait_checkpoints

    target = str(tmp_path / "ckpt.params")

    def writer(tmp):
        with open(tmp, "w") as f:
            f.write("half a checkpoint")
        raise RuntimeError("disk full")

    stage_async_write(target, writer)
    with pytest.raises(MXNetError, match="disk full"):
        wait_checkpoints()
    assert not os.path.exists(target)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_async_checkpoint_numpy_args_pinned(tmp_path):
    """Plain-numpy params must be deep-copied at call time."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.model import (load_checkpoint, save_checkpoint,
                                 wait_checkpoints)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    w = np.ones((2, 4), np.float32)
    prefix = str(tmp_path / "np")
    save_checkpoint(prefix, 1, net, {"w": w}, {}, async_save=True)
    w[:] = -5.0  # caller mutates in place after the call returns
    wait_checkpoints()
    _, a, _ = load_checkpoint(prefix, 1)
    np.testing.assert_allclose(a["w"].asnumpy(), 1.0)
