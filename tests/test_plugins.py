"""Plugin-parity features: CTC loss (warpctc) and the torch bridge
(plugin/torch)."""

import numpy as np
import pytest

import mxnet_tpu as mx


# -- CTC loss ---------------------------------------------------------------
def _np_ctc_ref(log_probs, labels, blank=0):
    """Brute-force CTC via dynamic programming in prob space (small T)."""
    T, C = log_probs.shape
    probs = np.exp(log_probs)
    z = [blank]
    for l in labels:
        z += [l, blank]
    S = len(z)
    alpha = np.zeros((T, S))
    alpha[0, 0] = probs[0, blank]
    if S > 1:
        alpha[0, 1] = probs[0, z[1]]
    for t in range(1, T):
        for s in range(S):
            a = alpha[t - 1, s]
            if s >= 1:
                a += alpha[t - 1, s - 1]
            if s >= 2 and z[s] != blank and z[s] != z[s - 2]:
                a += alpha[t - 1, s - 2]
            alpha[t, s] = a * probs[t, z[s]]
    p = alpha[T - 1, S - 1] + (alpha[T - 1, S - 2] if S > 1 else 0)
    return -np.log(max(p, 1e-300))


def _run_ctc(data, label, **kw):
    d = mx.sym.Variable("data")
    l = mx.sym.Variable("label")
    loss = mx.sym.CTCLoss(d, l, **kw)
    exe = loss.bind(mx.cpu(), {"data": mx.nd.array(data),
                               "label": mx.nd.array(label)})
    exe.forward(is_train=False)
    return exe.outputs[0].asnumpy()


def test_ctc_loss_matches_bruteforce():
    rng = np.random.RandomState(0)
    T, N, C = 6, 2, 5
    data = rng.standard_normal((T, N, C)).astype(np.float32)
    label = np.array([[1, 2, -1], [3, 3, 4]], np.float32)
    out = _run_ctc(data, label)
    log_probs = data - np.log(np.exp(data).sum(-1, keepdims=True))
    for n in range(N):
        labs = [int(x) for x in label[n] if x >= 0]
        want = _np_ctc_ref(log_probs[:, n], labs)
        assert out[n] == pytest.approx(want, rel=1e-4), (n, out[n], want)


def test_ctc_loss_variable_lengths():
    rng = np.random.RandomState(1)
    T, N, C = 8, 2, 4
    data = rng.standard_normal((T, N, C)).astype(np.float32)
    label = np.array([[1, 2], [2, 0]], np.float32)
    dlen = np.array([5, 8], np.float32)
    llen = np.array([2, 1], np.float32)
    d = mx.sym.Variable("data")
    l = mx.sym.Variable("label")
    loss = mx.sym.CTCLoss(d, l, mx.sym.Variable("dl"), mx.sym.Variable("ll"),
                          use_data_lengths=True, use_label_lengths=True)
    exe = loss.bind(mx.cpu(), {"data": mx.nd.array(data),
                               "label": mx.nd.array(label),
                               "dl": mx.nd.array(dlen),
                               "ll": mx.nd.array(llen)})
    exe.forward(is_train=False)
    out = exe.outputs[0].asnumpy()
    log_probs = data - np.log(np.exp(data).sum(-1, keepdims=True))
    want0 = _np_ctc_ref(log_probs[:5, 0], [1, 2])
    want1 = _np_ctc_ref(log_probs[:, 1], [2])
    assert out[0] == pytest.approx(want0, rel=1e-4)
    assert out[1] == pytest.approx(want1, rel=1e-4)


def test_ctc_loss_gradient_descends():
    # training with the CTC gradient must reduce the loss
    rng = np.random.RandomState(2)
    T, N, C = 6, 3, 5
    data = rng.standard_normal((T, N, C)).astype(np.float32) * 0.1
    label = np.array([[1, 2, -1], [3, -1, -1], [4, 1, 2]], np.float32)
    d = mx.sym.Variable("data")
    loss = mx.sym.CTCLoss(d, mx.sym.Variable("label"))
    exe = loss.simple_bind(mx.cpu(), grad_req="write",
                           data=(T, N, C), label=(N, 3))
    exe.arg_dict["data"][:] = data
    exe.arg_dict["label"][:] = label
    losses = []
    for _ in range(12):
        exe.forward(is_train=True)
        losses.append(float(exe.outputs[0].asnumpy().sum()))
        exe.backward()
        g = exe.grad_dict["data"].asnumpy()
        exe.arg_dict["data"][:] = exe.arg_dict["data"].asnumpy() - 0.5 * g
    assert losses[-1] < losses[0] * 0.8, losses


# -- torch bridge -----------------------------------------------------------
torch = pytest.importorskip("torch")


def test_torch_module_forward_matches_torch():
    tmod = torch.nn.Linear(8, 4)
    bridge = mx.torch_bridge.TorchModule(tmod, name="tlin")
    data = mx.sym.Variable("data")
    out_sym = bridge(data)
    x = np.random.RandomState(0).standard_normal((3, 8)).astype(np.float32)

    args = {"data": mx.nd.array(x)}
    for k, v in bridge.init_values().items():
        args[k] = mx.nd.array(v)
    exe = out_sym.bind(mx.cpu(), args)
    exe.forward(is_train=False)
    got = exe.outputs[0].asnumpy()
    want = tmod(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_torch_module_gradients():
    tmod = torch.nn.Linear(6, 2)
    bridge = mx.torch_bridge.TorchModule(tmod, name="tg")
    out_sym = mx.sym.MakeLoss(bridge(mx.sym.Variable("data")) ** 2)
    x = np.random.RandomState(1).standard_normal((4, 6)).astype(np.float32)
    exe = out_sym.simple_bind(mx.cpu(), grad_req="write", data=(4, 6))
    exe.arg_dict["data"][:] = x
    for k, v in bridge.init_values().items():
        exe.arg_dict[k][:] = v
    exe.forward(is_train=True)
    exe.backward()
    # torch reference gradient
    xt = torch.from_numpy(x)
    xt.requires_grad_(True)
    for p in tmod.parameters():
        p.grad = None
    (tmod(xt) ** 2).sum().backward()
    got = exe.grad_dict["data"].asnumpy()
    np.testing.assert_allclose(got, xt.grad.numpy(), rtol=1e-4, atol=1e-5)
    w_grad = exe.grad_dict["tg_param_0"].asnumpy()
    np.testing.assert_allclose(
        w_grad, list(tmod.parameters())[0].grad.numpy(), rtol=1e-4, atol=1e-5)


def test_torch_criterion():
    crit = mx.torch_bridge.TorchCriterion(torch.nn.MSELoss(), name="tmse")
    pred = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    loss_sym = crit(pred, label)
    x = np.random.RandomState(2).standard_normal((5, 3)).astype(np.float32)
    y = np.random.RandomState(3).standard_normal((5, 3)).astype(np.float32)
    exe = loss_sym.bind(mx.cpu(), {"data": mx.nd.array(x),
                                   "label": mx.nd.array(y)},
                        args_grad={"data": mx.nd.zeros((5, 3)),
                                   "label": mx.nd.zeros((5, 3))})
    exe.forward(is_train=True)
    want = float(((x - y) ** 2).mean())
    np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                               np.full(5, want), rtol=1e-5)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    np.testing.assert_allclose(g, 2 * (x - y) / x.size, rtol=1e-4, atol=1e-6)


def test_torch_function_eager():
    x = mx.nd.array(np.array([[1.0, 4.0], [9.0, 16.0]], np.float32))
    out = mx.torch_bridge.torch_function(torch.sqrt, x)
    np.testing.assert_allclose(out.asnumpy(), [[1, 2], [3, 4]], rtol=1e-6)


@pytest.mark.slow
def test_notebook_callbacks():
    """Notebook metric loggers (reference python/mxnet/notebook/callback.py
    surface: PandasLogger frames + live-curve history)."""
    import matplotlib
    matplotlib.use("Agg")

    from mxnet_tpu.notebook.callback import LiveLearningCurve, PandasLogger

    X = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    logger = PandasLogger(frequent=1)
    curve = LiveLearningCurve(frequent=1)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, 16), num_epoch=2,
            optimizer_params={"learning_rate": 0.5},
            batch_end_callback=[logger, curve])
    assert len(logger.train) > 0
    df = logger.train_df
    cols = list(df.columns) if hasattr(df, "columns") else list(df[0].keys())
    assert "accuracy" in cols and "epoch" in cols
    assert len(curve.train) > 0


def test_profiler_trace_and_summarize(tmp_path):
    """profiler.start/stop + summarize aggregates per-op time from the
    captured XLA trace."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import profiler

    logdir = str(tmp_path / "prof")
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    f(x).block_until_ready()      # compile outside the trace
    profiler.start(logdir)
    with profiler.scope("bench-step"):
        for _ in range(3):
            f(x).block_until_ready()
    profiler.stop()
    rows = profiler.summarize(logdir, top=10, device_only=False)
    assert rows and all(len(r) == 3 for r in rows)
    assert any(ms > 0 for _, ms, _ in rows)
