"""Driver-contract regression gate for bench.py.

The driver runs ``python bench.py`` at the end of every round and
records its one JSON line; a crash (e.g. an internal trainer-API
signature change) silently downgrades the round's official perf record
to a CPU fallback or an error line.  These tests run both benchmark
modes in CPU smoke mode and assert the contract fields, so the break
is caught in CI instead of on round-end hardware.  (SURVEY.md §4 lists
"no perf regression gates" among the reference's testing gaps to
improve on.)
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = dict(os.environ)
    env.update(extra_env)
    # BENCH_CHILD skips the watchdog wrapper; BENCH_FORCE_CPU pins the
    # backend so the test never touches (or waits for) the TPU tunnel
    env["BENCH_CHILD"] = "1"
    env["BENCH_FORCE_CPU"] = "1"
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in output: {r.stdout!r}"
    return json.loads(lines[-1])


def _check_contract(rec, metric, unit):
    assert rec["metric"] == metric
    assert rec["unit"] == unit
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    assert rec["platform"] == "cpu"
    # MFU accounting fields (VERDICT round-1 weak #2).  model_tflops is
    # rounded to 2 decimals and can legitimately be 0.0 on a very slow
    # CI box, so assert presence only; the 3-decimal per-sample FLOPs
    # field is a deterministic analytic count and must be positive.
    assert rec["fwd_gflops_per_sample"] > 0
    assert "model_tflops_per_sec" in rec


@pytest.mark.slow
def test_resnet_bench_contract():
    rec = _run_bench({})
    _check_contract(rec, "resnet50_train_throughput", "images/sec/chip")


@pytest.mark.slow
def test_gpt_bench_contract():
    rec = _run_bench({"BENCH_MODEL": "gpt"})
    _check_contract(rec, "gpt_train_throughput", "tokens/sec/chip")


@pytest.mark.slow
def test_cifar_bench_contract():
    rec = _run_bench({"BENCH_MODEL": "cifar"})
    _check_contract(rec, "cifar_inception_bn_small_train_throughput",
                    "images/sec/chip")


@pytest.mark.slow
def test_xla_cost_analysis_cross_check():
    """XLA's own cost model and the analytic FLOP counter must agree to
    ~15% on the resnet step (guards count_flops against drift)."""
    rec = _run_bench({})
    # CPU cost_analysis is always available: absence of the fields means
    # the lowering plumbing drifted (exactly what this gate exists for)
    assert "xla_step_gflops" in rec, rec
    ratio = rec["xla_step_gflops"] / rec["analytic_step_gflops"]
    assert 0.85 < ratio < 1.3, rec


def test_adopt_sweep_winner(tmp_path, monkeypatch):
    """bench.py defaults to the sweep's measured best config; explicit
    env always wins; CPU-fallback records are never adopted."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    # full isolation: the function writes os.environ via setdefault,
    # which monkeypatch's per-key records would NOT restore — swap the
    # whole mapping for a plain dict copy instead (auto-restored)
    env = dict(os.environ)
    monkeypatch.setattr(os, "environ", env)
    for k in ("BENCH_BATCH", "BENCH_LAYOUT", "BENCH_STEM"):
        env.pop(k, None)

    sweep = tmp_path / "sweep.json"
    sweep.write_text(json.dumps({"best_resnet50": {
        "platform": "tpu",
        "config": {"BENCH_BATCH": "64", "BENCH_LAYOUT": "NHWC",
                   "BENCH_STEM": "s2d"}}}))
    env["BENCH_SWEEP_PATH"] = str(sweep)
    bench._adopt_sweep_winner()
    assert env["BENCH_BATCH"] == "64"

    env["BENCH_BATCH"] = "512"
    bench._adopt_sweep_winner()
    assert env["BENCH_BATCH"] == "512"  # explicit wins

    sweep.write_text(json.dumps({"best_resnet50": {
        "platform": "cpu", "config": {"BENCH_BATCH": "8"}}}))
    env.pop("BENCH_BATCH")
    bench._adopt_sweep_winner()
    assert "BENCH_BATCH" not in env  # cpu record ignored
