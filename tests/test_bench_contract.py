"""Driver-contract regression gate for bench.py.

The driver runs ``python bench.py`` at the end of every round and
records its one JSON line; a crash (e.g. an internal trainer-API
signature change) silently downgrades the round's official perf record
to a CPU fallback or an error line.  These tests run both benchmark
modes in CPU smoke mode and assert the contract fields, so the break
is caught in CI instead of on round-end hardware.  (SURVEY.md §4 lists
"no perf regression gates" among the reference's testing gaps to
improve on.)
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = dict(os.environ)
    env.update(extra_env)
    # BENCH_CHILD skips the watchdog wrapper; BENCH_FORCE_CPU pins the
    # backend so the test never touches (or waits for) the TPU tunnel
    env["BENCH_CHILD"] = "1"
    env["BENCH_FORCE_CPU"] = "1"
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in output: {r.stdout!r}"
    return json.loads(lines[-1])


def _check_contract(rec, metric, unit):
    assert rec["metric"] == metric
    assert rec["unit"] == unit
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    assert rec["platform"] == "cpu"
    # MFU accounting fields (VERDICT round-1 weak #2).  model_tflops is
    # rounded to 2 decimals and can legitimately be 0.0 on a very slow
    # CI box, so assert presence only; the 3-decimal per-sample FLOPs
    # field is a deterministic analytic count and must be positive.
    assert rec["fwd_gflops_per_sample"] > 0
    assert "model_tflops_per_sec" in rec


@pytest.mark.slow
def test_resnet_bench_contract():
    rec = _run_bench({})
    _check_contract(rec, "resnet50_train_throughput", "images/sec/chip")


@pytest.mark.slow
def test_gpt_bench_contract():
    rec = _run_bench({"BENCH_MODEL": "gpt"})
    _check_contract(rec, "gpt_train_throughput", "tokens/sec/chip")


@pytest.mark.slow
def test_cifar_bench_contract():
    rec = _run_bench({"BENCH_MODEL": "cifar"})
    _check_contract(rec, "cifar_inception_bn_small_train_throughput",
                    "images/sec/chip")


@pytest.mark.slow
def test_xla_cost_analysis_cross_check():
    """XLA's own cost model and the analytic FLOP counter must agree to
    ~15% on the resnet step (guards count_flops against drift)."""
    rec = _run_bench({})
    # CPU cost_analysis is always available: absence of the fields means
    # the lowering plumbing drifted (exactly what this gate exists for)
    assert "xla_step_gflops" in rec, rec
    ratio = rec["xla_step_gflops"] / rec["analytic_step_gflops"]
    assert 0.85 < ratio < 1.3, rec


def test_adopt_sweep_winner(tmp_path, monkeypatch):
    """bench.py defaults to the sweep's measured best config; explicit
    env always wins; CPU-fallback records are never adopted."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    # full isolation: the function writes os.environ via setdefault,
    # which monkeypatch's per-key records would NOT restore — swap the
    # whole mapping for a plain dict copy instead (auto-restored)
    env = dict(os.environ)
    monkeypatch.setattr(os, "environ", env)
    for k in ("BENCH_BATCH", "BENCH_LAYOUT", "BENCH_STEM"):
        env.pop(k, None)

    sweep = tmp_path / "sweep.json"
    sweep.write_text(json.dumps({"best_resnet50": {
        "platform": "tpu",
        "config": {"BENCH_BATCH": "64", "BENCH_LAYOUT": "NHWC",
                   "BENCH_STEM": "s2d"}}}))
    env["BENCH_SWEEP_PATH"] = str(sweep)
    bench._adopt_sweep_winner()
    assert env["BENCH_BATCH"] == "64"

    env["BENCH_BATCH"] = "512"
    bench._adopt_sweep_winner()
    assert env["BENCH_BATCH"] == "512"  # explicit wins

    sweep.write_text(json.dumps({"best_resnet50": {
        "platform": "cpu", "config": {"BENCH_BATCH": "8"}}}))
    env.pop("BENCH_BATCH")
    bench._adopt_sweep_winner()
    assert "BENCH_BATCH" not in env  # cpu record ignored


@pytest.mark.slow
def test_promotion_of_prior_tpu_record():
    """Tunnel-down fallback (BENCH_PROMOTE_PRIOR) promotes the prior
    real-TPU capture to the PRIMARY line — platform:tpu, stale-stamped,
    CPU smoke demoted to provenance (VERDICT r4 item 3).  Requires the
    committed BENCH_TPU_LATEST.json artifact."""
    if not os.path.exists(os.path.join(REPO, "BENCH_TPU_LATEST.json")):
        pytest.skip("no committed TPU record to promote")
    env = dict(os.environ)
    env.update({"BENCH_CHILD": "1", "BENCH_FORCE_CPU": "1",
                "BENCH_PROMOTE_PRIOR": "1"})
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads([l for l in r.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert rec["platform"] == "tpu"
    assert rec["stale"] is True
    assert rec["value"] > 100           # a real chip number, not smoke
    assert rec["source"] == "BENCH_TPU_LATEST.json"
    assert "measured_at" in rec
    assert rec["fallback_this_run"]["platform"] == "cpu"


@pytest.mark.slow
def test_longcontext_bench_contract():
    """tools/longcontext_bench.py (VERDICT r4 item 8) emits its JSON
    payload: flash/dense tokens-per-sec + peak-HBM points and the ring
    scaling lane, on the CPU smoke shapes."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "longcontext_bench.py"),
         "--seqs", "256", "--heads", "2", "--head-dim", "32",
         "--ring-seq", "256", "--ring-widths", "1,2"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    pt = payload["points"][0]
    assert pt["flash_tokens_per_sec"] > 0 and pt["dense_tokens_per_sec"] > 0
    assert pt["flash_peak_hbm_gb"] > 0
    ring = payload["ring"]["points"]
    assert [p["sp"] for p in ring] == [1, 2]
    assert all(p["tokens_per_sec"] > 0 for p in ring)


@pytest.mark.slow
def test_decode_bench_contract():
    """tools/decode_bench.py emits decode tokens/sec points for both the
    gpt2-style and llama-style KV-cache decoders on CPU smoke shapes."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "decode_bench.py"),
         "--platform", "cpu", "--layers", "2", "--d-model", "64",
         "--heads", "4", "--vocab", "97", "--prompt", "8",
         "--t1", "4", "--t2", "24", "--batches", "1,2"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert {pt["config"] for pt in payload["points"]}         == {"gpt2", "llama-style/kv1"}
    assert {pt["batch"] for pt in payload["points"]} == {1, 2}
    for pt in payload["points"]:
        assert pt.get("decode_tok_per_sec", 0) > 0             or "decode_error" in pt, pt


@pytest.mark.slow
def test_serve_bench_contract():
    """tools/serve_bench.py (the SERVE_BENCH.json bench_watch stage)
    emits the serving record on CPU smoke shapes: last line is the
    payload with aggregate tokens/sec, mean TTFT, preemption count,
    the serial-decode speedup, zero silent drops, and complete:true
    (the bench_io contract the watchdog trusts)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--layers", "2", "--d-model", "64",
         "--heads", "4", "--vocab", "211", "--requests", "12",
         "--concurrency", "4", "--prompt-lens", "8,16,24",
         "--max-new", "8"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    assert payload["tokens_per_sec"] > 0
    assert payload["ttft_ms_mean"] > 0
    assert payload["preemptions"] >= 0
    assert payload["completed"] == 12
    assert payload["dropped_without_rejection"] == 0
    assert payload["speedup_vs_serial"] > 0
    modes = {pt["mode"] for pt in payload["points"]}
    assert modes == {"continuous/closed", "serial/closed"}
    # every serving record carries the telemetry snapshot field (the
    # registry is empty-disabled unless MXTPU_TELEMETRY=1 was exported)
    assert "telemetry" in payload
    assert payload["telemetry"]["enabled"] in (True, False)


@pytest.mark.slow
def test_prefix_bench_contract():
    """tools/serve_bench.py --workload prefix (the PREFIX_BENCH.json
    bench_watch stage) emits both prefix-cache acceptance records on
    CPU smoke shapes: the shared-prefix A/B with hit rate > 0.8, a
    >= 2x prefill-compute reduction and byte-identical tokens, and the
    mixed-length A/B with the chunked decode-stall p99 beating the
    whole-prompt one — the exact invariants the serve_prefix watchdog
    gate trusts."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--workload", "prefix",
         "--layers", "2", "--d-model", "64", "--heads", "4",
         "--vocab", "211", "--prefixes", "2", "--continuations", "6",
         "--prefix-len", "32", "--suffix-len", "8", "--max-new", "8",
         "--long-prompt", "1024", "--prefill-chunk", "128"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    modes = {pt["mode"] for pt in payload["points"]}
    assert modes == {"shared-prefix", "mixed-len"}
    # the acceptance bars the serve_prefix stage gates on
    assert payload["tokens_identical"] is True
    assert payload["prefix_hit_rate"] > 0.8
    assert payload["prefill_compute_ratio"] >= 2
    assert payload["prefill_tokens_saved"] > 0
    assert payload["stall_improved"] is True
    assert (payload["decode_stall_p99_ms_chunked"]
            < payload["decode_stall_p99_ms_whole"])
    sp = next(pt for pt in payload["points"]
              if pt["mode"] == "shared-prefix")
    assert sp["completed_on"] == sp["completed_off"] == sp["requests"]
    assert sp["prefix_misses"] == 2         # one cold prefill per prefix
    assert "telemetry" in payload


@pytest.mark.slow
def test_sampling_bench_contract():
    """tools/serve_bench.py --workload sampling (the SAMPLING_BENCH.json
    bench_watch stage) on the default CPU smoke shapes (the tiny
    2-layer shapes other contracts use make dispatches too cheap for
    spec to win): a mixed-sampling-config batch with ZERO fresh traces
    and greedy rows byte-identical to a greedy-only engine,
    rejection-sampled spec >= 1.25x plain sampling at temperature>0,
    and the spec-on/off token distributions statistically
    indistinguishable — the invariants the serve_sampling watchdog
    gate trusts."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--workload", "sampling",
         "--max-new", "64", "--spec-k", "6",
         "--agreement-samples", "128"],
        capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    # the acceptance bars the serve_sampling stage gates on
    assert payload["retraces"] == 0
    assert payload["greedy_rows_identical"] is True
    assert payload["logprobs_ok"] is True
    assert payload["sampling_spec_speedup"] >= 1.25
    assert 0 < payload["accept_rate_stochastic"] < 1
    assert abs(payload["agreement_z"]) < 5
    assert "telemetry" in payload


@pytest.mark.slow
def test_offload_bench_contract():
    """tools/serve_bench.py --workload offload (the OFFLOAD_BENCH.json
    bench_watch stage) on CPU smoke shapes: with the HBM prefix LRU
    sized to thrash, the host tier recovers the hit rate to >= 0.8 of
    the unconstrained-HBM run, cuts prefill compute >= 2x vs
    offload-off, and every arm (cold, off, on, int8-KV, tp=2) emits
    byte-identical tokens — the invariants the serve_offload watchdog
    gate trusts."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    # a pre-set host device count (this repo's conftest pins 8; dev
    # shells sometimes pin 1) would make serve_bench skip forcing its
    # own — drop it so the tp=2 arm always runs
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--workload", "offload",
         "--layers", "2", "--d-model", "64", "--heads", "4",
         "--kv-heads", "2", "--vocab", "211", "--offload-prefixes", "6",
         "--continuations", "4", "--prefix-len", "48",
         "--suffix-len", "8", "--max-new", "8"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    # the acceptance bars the serve_offload stage gates on
    assert payload["tokens_identical"] is True
    assert payload["hit_rate_recovery"] >= 0.8
    assert payload["prefill_compute_ratio"] >= 2
    assert payload["host_restores"] > 0
    rec = payload["points"][0]
    assert rec["identity"]["int8_on_vs_off"] is True
    assert rec["tp2"] is not None, "tp=2 arm was skipped (no 2nd device)"
    assert rec["identity"]["tp2_on_vs_cold"] is True
    # the off arm really thrashed (discarding is what motivates the
    # tier) and the on arm really parked instead
    assert payload["discarded_tokens_off"] > 0
    assert rec["discarded_tokens_on"] == 0
    assert rec["hit_rate_off"] < rec["hit_rate_on"]
    assert "telemetry" in payload


@pytest.mark.slow
def test_perf_attrib_bench_contract():
    """tools/serve_bench.py --workload perf-attrib (the
    PERF_ATTRIB_BENCH.json bench_watch stage) on CPU smoke shapes:
    device-timing sampling on vs off emits byte-identical tokens with
    unchanged AOT fingerprints, records sampled dispatches and a
    populated nonzero-flops cost table, and the off arm records zero
    timings — the invariants the serve_perf watchdog gate trusts."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--workload", "perf-attrib",
         "--layers", "2", "--d-model", "64", "--heads", "4",
         "--vocab", "211", "--requests", "12", "--concurrency", "4",
         "--prompt-lens", "8,16,24", "--max-new", "8"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    # the acceptance bars the serve_perf stage gates on
    assert payload["tokens_identical"] is True
    assert payload["fingerprint_identical"] is True
    assert payload["cost_flops_nonzero"] is True
    assert payload["sampled_dispatches"] > 0
    assert "decode" in payload["cost_table_kinds"]
    assert "prefill" in payload["cost_table_kinds"]
    rec = payload["points"][0]
    assert rec["off_sampled_steps"] == 0    # sampling-off is inert
    assert rec["sampled_steps"] > 0
    assert rec["cost_errors"] == 0
    assert "telemetry" in payload


@pytest.mark.slow
def test_lora_bench_contract():
    """tools/serve_bench.py --workload lora (the LORA_BENCH.json
    bench_watch stage) on CPU smoke shapes: one multiplexed engine
    serves base + 3 LoRA adapters with zero fresh traces on the
    rotated second pass and token-identical output against per-tenant
    merged-weights engines — the invariants the serve_lora watchdog
    gate trusts."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--backend", "cpu", "--workload", "lora",
         "--layers", "2", "--d-model", "32", "--heads", "4",
         "--vocab", "128", "--requests", "8", "--concurrency", "4",
         "--max-new", "8", "--prompt-lens", "8,12,16",
         "--block-size", "4"],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    # the acceptance bars the serve_lora stage gates on
    assert payload["fresh_traces_second_pass"] == 0
    assert payload["agreement_vs_merged"] >= 0.98
    assert payload["tokens_identical"] is True
    assert payload["lora_adapters"] == 3
    assert payload["mux_overhead_ratio"] > 0
    rec = payload["points"][0]
    assert rec["completed_off"] == 8
    assert rec["completed_mux"] == 8
    assert rec["adapter_slots_used"] == 3
    assert rec["adapter_loads"] >= 3
    assert "telemetry" in payload


@pytest.mark.slow
def test_train_bench_contract(tmp_path):
    """tools/train_bench.py (the TRAIN_BENCH.json bench_watch stage)
    emits the training-path comparison on a CPU smoke config: both
    modes measured, per-batch dispatch counts showing the O(1)-vs-
    O(num_params) contrast, and complete:true stamped before the final
    record."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    out = str(tmp_path / "train_bench.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "train_bench.py"),
         "--backend", "cpu", "--layers", "4", "--hidden", "32",
         "--batches", "8", "--epochs", "2", "--json", out],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    assert payload["fused_steps_per_sec"] > 0
    assert payload["unfused_steps_per_sec"] > 0
    assert payload["speedup"] > 0
    # the dispatch contract: fused <= 3 per batch, per-param pays
    # 1 (fwd_bwd) + num_params
    assert payload["fused_dispatches_per_batch"] <= 3
    assert (payload["unfused_dispatches_per_batch"]
            >= payload["num_params"] + 1)
    assert {pt["mode"] for pt in payload["points"]} == {"fused", "per_param"}
    assert "telemetry" in payload
    # the --json artifact matches the printed record
    disk = json.loads(open(out).read())
    assert disk["complete"] is True
    assert disk["fused_steps_per_sec"] == payload["fused_steps_per_sec"]


@pytest.mark.slow
def test_startup_bench_contract(tmp_path):
    """tools/startup_bench.py (the STARTUP_BENCH.json bench_watch
    stage) emits the cold-vs-warm restart record on CPU smoke shapes:
    warm engine-ready-time at most half of cold (the ISSUE acceptance
    bar), ZERO fresh traces on the warm start, token parity between the
    two runs, and complete:true stamped before the final record."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no tunnel for a CPU smoke
    # a surrounding compile-cache/AOT config must not leak into the
    # bench's own cold/warm dirs
    for k in ("MXTPU_COMPILE_CACHE", "MXTPU_AOT_DIR",
              "MXTPU_WARMUP_MANIFEST"):
        env.pop(k, None)
    out = str(tmp_path / "startup_bench.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "startup_bench.py"),
         "--backend", "cpu", "--json", out],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
    assert payload["platform"] == "cpu"
    assert payload["complete"] is True      # stamped BEFORE the print
    assert payload["cold_ready_s"] > 0 and payload["warm_ready_s"] > 0
    assert payload["warm_ready_s"] <= 0.5 * payload["cold_ready_s"], \
        "warm start did not skip enough compilation"
    assert payload["warm_fresh_traces"] == 0
    assert payload["warm_artifact_loads"] > 0
    assert payload["token_parity"] is True
    assert {pt["mode"] for pt in payload["points"]} == {"cold", "warm"}
    cold, warm = payload["points"]
    # the warm child's compiles were all persistent-cache disk hits
    assert warm["cache_misses"] == 0
    assert warm["cache_hits"] > 0
    assert cold["fresh_traces"] == cold["warmup_programs"]
    disk = json.loads(open(out).read())
    assert disk["complete"] is True
    assert disk["warm_ready_s"] == payload["warm_ready_s"]


@pytest.mark.slow
def test_watchdog_rejects_stale_promoted_record(tmp_path):
    """bench_watch.run_bench must NOT persist bench.py's stale-promoted
    prior record as a fresh capture (that would launder an old number as
    new and retire the stage): platform:tpu + stale:true is rejected."""
    if not os.path.exists(os.path.join(REPO, "BENCH_TPU_LATEST.json")):
        pytest.skip("no committed TPU record to promote")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_watch

    out = tmp_path / "captured.json"
    ok = bench_watch.run_bench(
        {"BENCH_FORCE_CPU": "1", "BENCH_PROMOTE_PRIOR": "1"},
        str(out), "stale-test", timeout=580)
    assert ok is False
    assert not out.exists()
