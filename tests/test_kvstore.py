"""KVStore aggregation correctness (rebuild of
tests/python/unittest/test_kvstore.py + the nightly exact-sum test)."""

import numpy as np
import pytest

import mxnet_tpu as mx

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _init_kv(kind="local"):
    kv = mx.kv.create(kind)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def _check_diff_to_scalar(arr, num):
    np.testing.assert_allclose(arr.asnumpy(), num * np.ones(SHAPE), rtol=1e-5)


@pytest.mark.parametrize("kind", ["local", "local_allreduce_cpu", "device"])
def test_single_kv_pair(kind):
    kv = _init_kv(kind)
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    _check_diff_to_scalar(val, 1)


@pytest.mark.parametrize("kind", ["local", "device"])
def test_aggregator(kind):
    """Aggregation over 4 'devices' (reference test_kvstore.py
    test_aggregator, using repeated values in place of GPUs)."""
    kv = _init_kv(kind)
    num_devs = 4
    devs = [mx.cpu(i % 2) for i in range(num_devs)]
    vals = [mx.nd.ones(SHAPE, ctx=d) for d in devs]
    kv.push(3, vals)
    outs = [mx.nd.empty(SHAPE, ctx=d) for d in devs]
    kv.pull(3, out=outs)
    for out in outs:
        _check_diff_to_scalar(out, num_devs)
    # list key push
    list_vals = [[mx.nd.ones(SHAPE, ctx=d) * 2 for d in devs]] * len(KEYS)
    kv.push(KEYS, list_vals)
    list_outs = [[mx.nd.empty(SHAPE, ctx=d) for d in devs]] * len(KEYS)
    kv.pull(KEYS, out=list_outs)
    for outs in list_outs:
        for out in outs:
            _check_diff_to_scalar(out, 2 * num_devs)


def test_updater():
    kv = _init_kv()

    def updater(key, recv, stored):
        stored += recv * 2

    kv._set_updater(updater)
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    _check_diff_to_scalar(val, 2)
    num_devs = 3
    vals = [mx.nd.ones(SHAPE, ctx=mx.cpu(i % 2)) for i in range(num_devs)]
    kv.push(3, vals)
    kv.pull(3, out=val)
    _check_diff_to_scalar(val, 2 + 2 * num_devs)


def test_set_optimizer_sgd():
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    grad = mx.nd.ones(SHAPE)
    kv.push(3, grad)
    w = mx.nd.empty(SHAPE)
    kv.pull(3, out=w)
    _check_diff_to_scalar(w, -0.1)


def test_deterministic_sum():
    """Exact deterministic reduction (rebuild of
    tests/nightly/dist_sync_kvstore.py exactness assertion)."""
    kv = _init_kv()
    rng = np.random.RandomState(0)
    data = [rng.randn(*SHAPE).astype(np.float32) for _ in range(4)]
    expected = np.zeros(SHAPE, np.float64)
    stored = np.zeros(SHAPE, np.float32)
    for it in range(10):
        vals = [mx.nd.array(d) for d in data]
        kv.push(3, vals)
        out = mx.nd.empty(SHAPE)
        kv.pull(3, out=out)
        stored = sum(data, start=np.zeros(SHAPE, np.float32))
        expected = expected * 0 + stored  # assign semantics (no updater)
        np.testing.assert_allclose(out.asnumpy(), expected.astype(np.float32),
                                   rtol=1e-6)


def test_dist_kvstore_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.init(0, mx.nd.zeros(SHAPE))
    kv.push(0, mx.nd.ones(SHAPE))
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    _check_diff_to_scalar(out, 1)
    kv.barrier()


def test_optimizer_state_roundtrip(tmp_path):
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(3, mx.nd.ones(SHAPE))
    fname = str(tmp_path / "states.bin")
    kv.save_optimizer_states(fname)
    kv.load_optimizer_states(fname)


def test_kvstore_server_module_wrapper():
    """mx.kvstore_server.KVStoreServer runs a PS shard with the
    reference entry shape (kvstore_server.py:11-57)."""
    import threading
    import time

    from mxnet_tpu.kvstore_server import KVStoreServer
    from mxnet_tpu.ps import PSClient

    srv = KVStoreServer(num_workers=1)
    t = threading.Thread(target=srv.run, daemon=True)
    t.start()
    addr = None
    for _ in range(100):
        try:
            addr = srv.address
            break
        except RuntimeError:
            time.sleep(0.05)
    assert addr is not None
    client = PSClient(addr)
    client.request("init", 3, np.arange(4, dtype=np.float32), True)
    got = np.asarray(client.request("pull", 3))
    np.testing.assert_array_equal(got, np.arange(4, dtype=np.float32))
    client.request("command", "stop", b"")
    client.close()
    t.join(timeout=5)
    assert not t.is_alive()
