"""Test configuration: run everything on the CPU backend with 8 virtual
XLA host devices, so multi-device paths (multi-context executors, model
parallelism, KVStore reduction, mesh sharding) are exercised without TPU
hardware — the rebuild of the reference's N-CPU-contexts testing trick
(tests/python/unittest/test_model_parallel.py, SURVEY.md §4.3)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# MXTPU_TEST_PLATFORM=default lifts the CPU pin so a chip window can run
# the convergence tier on real TPU (tools/bench_watch.py train_tier
# stage); any other value pins that platform explicitly.
_test_platform = os.environ.get("MXTPU_TEST_PLATFORM", "cpu")

import jax

if _test_platform != "default":
    os.environ.setdefault("JAX_PLATFORMS", _test_platform)
    # The env var alone can be overridden by accelerator plugins (axon);
    # the config update is authoritative.
    jax.config.update("jax_platforms", _test_platform)
else:
    # an on-chip tier must not silently fall back to CPU and report a
    # "tpu" pass (jax auto-falls-back when the tunnel drops mid-init)
    assert any(d.platform == "tpu" for d in jax.devices()), \
        "MXTPU_TEST_PLATFORM=default requires a reachable TPU"


# -- fast/slow tiers ---------------------------------------------------------
# Default `pytest tests/` is the fast tier (< 5 min, the reference's
# unittest bucket).  `--runslow` / RUN_SLOW=1 adds the example smokes and
# multi-process dist tests (the nightly bucket, tests/nightly/test_all.sh
# analog).
import pytest


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="run tests marked slow (nightly tier)")


def pytest_collection_modifyitems(config, items):
    run_slow = os.environ.get("RUN_SLOW", "").lower() not in ("", "0", "false")
    if config.getoption("--runslow") or run_slow:
        return
    skip_slow = pytest.mark.skip(reason="slow tier: use --runslow or RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
