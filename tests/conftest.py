"""Test configuration: run everything on the CPU backend with 8 virtual
XLA host devices, so multi-device paths (multi-context executors, model
parallelism, KVStore reduction, mesh sharding) are exercised without TPU
hardware — the rebuild of the reference's N-CPU-contexts testing trick
(tests/python/unittest/test_model_parallel.py, SURVEY.md §4.3)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

# The env var alone can be overridden by accelerator plugins (axon);
# the config update is authoritative.
jax.config.update("jax_platforms", "cpu")
