"""Quantized serving tests: weight-only int8, int8 KV blocks, and the
Pallas paged-attention decode kernel.

Three layers of guarantees, all CPU-deterministic:

- kernel: ``ops/pallas_paged_attention.py`` (run through the Pallas
  interpreter off-TPU) matches the jnp ``paged_attention`` oracle at
  f32-accumulation tolerance across GQA / window / padded-table /
  null-block / empty-row cases, fp and int8-quantized.
- inertness: ``quantize``/``kv_dtype`` off is byte-for-byte today's
  engine — same program-cache keys, same AOT fingerprints, same
  tokens (the PR-10 rule every optional serve subsystem follows).
- composition: int8 KV blocks stay token-stable across cold vs
  resumed paths (preemption-by-recomputation, chunked prefill,
  prefix-cache reuse, speculative decoding's verify program) — the
  per-slot quantization makes cache contents write-order-independent,
  which is exactly what these tests pin.
"""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.attention import paged_attention
from mxnet_tpu.ops.pallas_paged_attention import paged_attention_kernel


# -- kernel-level parity ------------------------------------------------------
def _paged_case(rng, B=3, Hq=8, Hkv=2, Dh=32, bs=4, nb=16, W=6,
                ctx=(9, 0, 21)):
    """A padded-table case: per-row context lengths (0 = dead slot),
    live blocks drawn without replacement, padding rows left at the
    null block (id 0)."""
    q = jnp.asarray(rng.randn(B, Hq, Dh).astype(np.float32))
    kc = jnp.asarray(rng.randn(nb, bs, Hkv, Dh).astype(np.float32))
    vc = jnp.asarray(rng.randn(nb, bs, Hkv, Dh).astype(np.float32))
    bt = np.zeros((B, W), np.int32)
    ctx = np.asarray(ctx, np.int32)
    for b in range(B):
        nblk = -(-int(ctx[b]) // bs)
        bt[b, :nblk] = rng.choice(np.arange(1, nb), nblk, replace=False)
    return q, kc, vc, jnp.asarray(bt), jnp.asarray(ctx)


@pytest.mark.parametrize("hq,hkv,window", [
    (8, 2, 0),       # grouped-query, full attention
    (8, 2, 5),       # grouped-query, sliding window
    (4, 4, 0),       # MHA
    (4, 1, 3),       # multi-query + window
])
def test_pallas_paged_matches_jnp(hq, hkv, window):
    rng = np.random.RandomState(0)
    q, kc, vc, bt, ctx = _paged_case(rng, Hq=hq, Hkv=hkv)
    ref = paged_attention(q, kc, vc, bt, ctx, window=window, impl="jnp")
    out = paged_attention(q, kc, vc, bt, ctx, window=window,
                          impl="pallas")
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6


def test_pallas_paged_matches_jnp_quantized():
    rng = np.random.RandomState(1)
    q, kc, vc, bt, ctx = _paged_case(rng)
    nb, bs, hkv, _ = kc.shape
    ksc = jnp.asarray(rng.rand(nb, bs, hkv).astype(np.float32) * 0.02
                      + 0.005)
    vsc = jnp.asarray(rng.rand(nb, bs, hkv).astype(np.float32) * 0.02
                      + 0.005)
    kq = jnp.clip(jnp.round(kc / ksc[..., None]), -127, 127).astype(
        jnp.int8)
    vq = jnp.clip(jnp.round(vc / vsc[..., None]), -127, 127).astype(
        jnp.int8)
    ref = paged_attention(q, kq, vq, bt, ctx, k_scale=ksc, v_scale=vsc,
                          impl="jnp")
    out = paged_attention(q, kq, vq, bt, ctx, k_scale=ksc, v_scale=vsc,
                          impl="pallas")
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6


def test_paged_attention_empty_row_returns_zeros():
    """Regression: a row with context_lens == 0 used to softmax a
    fully -inf score row into NaN, poisoning MXTPU_NUMERIC_WATCH for
    the whole bucketed batch.  Both impls must return zeros for the
    dead slot and leave live rows untouched."""
    rng = np.random.RandomState(2)
    q, kc, vc, bt, ctx = _paged_case(rng, ctx=(9, 0, 21))
    for impl in ("jnp", "pallas"):
        out = paged_attention(q, kc, vc, bt, ctx, impl=impl)
        assert bool(jnp.isfinite(out).all()), impl
        assert float(jnp.max(jnp.abs(out[1]))) == 0.0, impl
    # live rows match a run where the dead slot never existed
    sel = np.array([0, 2])
    live = paged_attention(q[sel], kc, vc, bt[sel], ctx[sel], impl="jnp")
    full = paged_attention(q, kc, vc, bt, ctx, impl="jnp")
    assert np.array_equal(np.asarray(full)[sel], np.asarray(live))


def test_paged_attention_validation_and_env_override(monkeypatch):
    rng = np.random.RandomState(3)
    q, kc, vc, bt, ctx = _paged_case(rng)
    with pytest.raises(ValueError, match="impl"):
        paged_attention(q, kc, vc, bt, ctx, impl="mosaic")
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        paged_attention(q, kc, vc, bt, ctx,
                        k_scale=jnp.zeros(kc.shape[:-1]))
    with pytest.raises(ValueError, match="window"):
        paged_attention(q, kc, vc, bt, ctx, window=-1)
    # the env override picks the kernel even off-TPU (interpret mode)
    ref = paged_attention(q, kc, vc, bt, ctx)            # auto -> jnp
    monkeypatch.setenv("MXTPU_PAGED_ATTENTION", "pallas")
    out = paged_attention(q, kc, vc, bt, ctx)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6
    monkeypatch.setenv("MXTPU_PAGED_ATTENTION", "bogus")
    with pytest.raises(ValueError, match="impl"):
        paged_attention(q, kc, vc, bt, ctx)


def test_pallas_paged_kernel_direct_rejects_mismatched_scales():
    rng = np.random.RandomState(4)
    q, kc, vc, bt, ctx = _paged_case(rng)
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        paged_attention_kernel(q, kc, vc, bt, ctx,
                               k_scale=jnp.zeros(kc.shape[:-1]))


# -- engine fixtures (same recipe as test_serve) ------------------------------
VOCAB = 53


@pytest.fixture(scope="module")
def model():
    """Llama-style variant (rmsnorm/swiglu/rope/GQA + tied head) so the
    quantized paths cover grouped-query attention and the tied-head
    exclusion."""
    S = 128
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4,
                        norm="rmsnorm", mlp="swiglu", pos_embed="rope",
                        tie_embeddings=True, kv_heads=2)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 80)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 96)
    return mx.serve.Engine(params, symbol=net, **kw)


def _run(eng, prompts, max_new=12):
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run()
    assert all(r.status == "finished" for r in reqs)
    return [r.tokens for r in reqs]


def _prompts(n, seed=7, lo=6, hi=22):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (rng.randint(lo, hi),)).astype(np.int32)
            for _ in range(n)]


# -- inertness (the PR-10 rule) ----------------------------------------------
def test_quant_off_is_byte_for_byte_inert(model):
    """quantize=None / kv_dtype=None IS today's engine: same program
    keys, same AOT fingerprints, same tokens."""
    plain = _engine(model)
    off = _engine(model, quantize=None, kv_dtype=None)
    assert off._spec_key() == plain._spec_key()
    assert off._aot_base_fp() == plain._aot_base_fp()
    assert off.statusz()["quant"] is None
    t1 = _run(plain, _prompts(3))
    t2 = _run(off, _prompts(3))
    assert t1 == t2
    plain.shutdown()
    off.shutdown()


def test_quant_modes_key_programs_and_fingerprints(model):
    """Each quant mode is a DIFFERENT compiled program and artifact:
    a quantized engine's programs must never be served to an
    unquantized twin (the params pytree itself differs)."""
    engines = {
        "off": _engine(model),
        "wq": _engine(model, quantize="int8"),
        "kv": _engine(model, kv_dtype="int8"),
        "both": _engine(model, quantize="int8", kv_dtype="int8"),
    }
    keys = {n: e._spec_key() for n, e in engines.items()}
    fps = {n: e._aot_base_fp() for n, e in engines.items()}
    assert len(set(map(str, keys.values()))) == 4
    assert len({str(sorted(fp.items())) for fp in fps.values()}) == 4
    for e in engines.values():
        e.shutdown()


def test_quant_env_defaults_and_validation(model, monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_QUANT", "int8")
    monkeypatch.setenv("MXTPU_SERVE_KV_DTYPE", "int8")
    eng = _engine(model)
    assert eng.quantize == "int8"
    assert str(eng._cache_k.dtype) == "int8"
    eng.shutdown()
    monkeypatch.setenv("MXTPU_SERVE_QUANT", "")
    monkeypatch.setenv("MXTPU_SERVE_KV_DTYPE", "")
    eng = _engine(model)
    assert eng.quantize is None and not eng._kv_quant
    eng.shutdown()
    with pytest.raises(ValueError, match="quantize"):
        _engine(model, quantize="fp8")
    with pytest.raises(ValueError, match="kv_dtype"):
        _engine(model, kv_dtype="int4")


# -- weight-only int8 ---------------------------------------------------------
def test_weight_only_serving_and_statusz(model):
    eng = _engine(model, quantize="int8")
    # every matmul projection carries int8 weights + a f32 scale; the
    # tied LM head (the embedding matrix) stays fp
    n_scales = sum(1 for k in eng.params if k.endswith("_wscale"))
    assert n_scales == 2 * 7          # 2 layers x (q,k,v,proj,gate,up,down)
    assert str(eng.params["gpt_l0_q_weight"].dtype) == "int8"
    assert str(eng.params["gpt_tok_embed_weight"].dtype) == "float32"
    toks = _run(eng, _prompts(3))
    st = eng.statusz()
    assert st["quant"]["weights"] == "int8"
    assert st["quant"]["quantized_weights"] == n_scales
    eng.shutdown()
    # deterministic: a second weight-only engine emits the same tokens
    eng2 = _engine(model, quantize="int8")
    assert _run(eng2, _prompts(3)) == toks
    eng2.shutdown()


def test_weight_only_agreement_with_fp(model):
    """Weight-only int8 is lossy but close: on this checkpoint the
    greedy streams must agree on the vast majority of positions (the
    bench gates >= 0.99 on its confident workload; random tiny-model
    logits are near-tie, so this in-tree floor is looser)."""
    fp = _run(_engine(model), _prompts(4), max_new=16)
    q8 = _run(_engine(model, quantize="int8"), _prompts(4), max_new=16)
    total = sum(len(t) for t in fp)
    agree = sum(a == b for t1, t2 in zip(fp, q8) for a, b in zip(t1, t2))
    assert agree / total >= 0.7, (agree, total)


# -- int8 KV blocks -----------------------------------------------------------
def test_kv_int8_bytes_drop_and_statusz(model):
    fp = _engine(model)
    q8 = _engine(model, kv_dtype="int8")
    a, b = fp.kv_cache_stats(), q8.kv_cache_stats()
    assert a["dtype"] == "float32" and b["dtype"] == "int8"
    # the acceptance bar: per-chip KV bytes (cache + scales) drop >=1.9x
    on_bytes = b["bytes_per_device"] + b["scale_bytes_per_device"]
    assert a["bytes_per_device"] / on_bytes >= 1.9
    st = q8.statusz()
    assert st["kv_cache"]["scale_bytes_total"] == 2 * int(
        q8._scale_k.nbytes)
    assert st["quant"]["kv_dtype"] == "int8"
    fp.shutdown()
    q8.shutdown()


def test_kv_int8_preemption_resume_token_stable(model):
    """Cold vs resumed must emit the SAME tokens under int8 KV (they
    differ from fp — that is expected and allowed): per-slot quant
    makes the recomputed cache bit-identical to the original."""
    prompts = _prompts(2, seed=11, lo=18, hi=26)
    ref = _run(_engine(model, kv_dtype="int8"), prompts, max_new=24)
    # starved cache: the second request forces preemption + resume
    eng = _engine(model, kv_dtype="int8", num_blocks=18, max_batch=2)
    got = _run(eng, prompts, max_new=24)
    assert eng.scheduler.preemptions > 0
    assert got == ref
    eng.shutdown()


def test_kv_int8_chunked_prefill_equals_whole(model):
    rng = np.random.RandomState(13)
    long_p = rng.randint(0, VOCAB, (60,)).astype(np.int32)
    whole = _engine(model, kv_dtype="int8", prefill_chunk=0,
                    prefix_cache=False)
    t1 = _run(whole, [long_p], max_new=16)
    whole.shutdown()
    chunked = _engine(model, kv_dtype="int8", prefill_chunk=16,
                      prefix_cache=False)
    t2 = _run(chunked, [long_p], max_new=16)
    chunked.shutdown()
    assert t1 == t2


def test_kv_int8_prefix_cache_shared_blocks_resurrect(model):
    """Shared int8 blocks come back WITH their scales: a prefix-cache
    hit (including a parked refcount-0 resurrection) serves the same
    tokens the cold path would."""
    rng = np.random.RandomState(17)
    prefix = rng.randint(0, VOCAB, (40,)).astype(np.int32)
    tails = [rng.randint(0, VOCAB, (6,)).astype(np.int32)
             for _ in range(2)]
    prompts = [np.concatenate([prefix, t]) for t in tails]
    cold = _engine(model, kv_dtype="int8", prefix_cache=False)
    ref = [_run(cold, [p], max_new=12)[0] for p in prompts]
    cold.shutdown()
    eng = _engine(model, kv_dtype="int8", prefix_cache=True)
    # sequential submits: the second reuses (resurrects) the first's
    # published chain — its blocks were freed (refcount 0, parked)
    got = [_run(eng, [p], max_new=12)[0] for p in prompts]
    st = eng.stats()
    assert st.prefix_hits > 0
    assert got == ref
    eng.shutdown()


def test_kv_int8_spec_decode_token_identity(model):
    """The verify program quantizes/dequantizes through the same
    tables as plain decode, so greedy speculative decoding stays
    byte-identical to spec-off under int8 KV."""
    net, params = model
    draft = {k: v for k, v in params.items()
             if not k.startswith("gpt_l1_")}
    prompts = _prompts(3, seed=19)
    plain = _run(_engine(model, kv_dtype="int8"), prompts, max_new=16)
    spec = _run(_engine(model, kv_dtype="int8", spec_k=3,
                        draft_params=draft, draft_num_heads=4,
                        draft_window=0), prompts, max_new=16)
    assert spec == plain


def test_quant_tp2_token_identity(model):
    """Sharded quantized serving: int8 weights shard like their fp
    parents, scale vectors replicate, the KV scale arrays head-shard
    with the cache — tokens identical to tp=1."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    prompts = _prompts(2, seed=23)
    t1 = _run(_engine(model, quantize="int8", kv_dtype="int8"), prompts)
    t2 = _run(_engine(model, quantize="int8", kv_dtype="int8", tp=2),
              prompts)
    assert t1 == t2


def test_quant_aot_warm_restart_token_parity(model, tmp_path):
    """Quantized programs export/reload like every other family; a
    warm restart serves identical tokens from the artifacts."""
    import mxnet_tpu.serve.engine as engine_mod

    d = str(tmp_path / "aot")
    prompts = _prompts(2, seed=29)
    e1 = _engine(model, quantize="int8", kv_dtype="int8", aot_dir=d)
    t1 = _run(e1, prompts)
    manifest = e1.manifest()
    e1.shutdown()
    stale = [k for k in engine_mod._STEP_CACHE]
    for k in stale:
        del engine_mod._STEP_CACHE[k]
    e2 = _engine(model, quantize="int8", kv_dtype="int8", aot_dir=d)
    assert e2.warmup(manifest) > 0
    t2 = _run(e2, prompts)
    e2.shutdown()
    assert t1 == t2
