"""Amalgamation deploy artifact (VERDICT r4 item 6): the single-file C
runtime (amalgamation/mxtpu_predict.c) runs the exported .mxa artifact
with NO Python tree, no libmxtpu, no jax — gcc + libm only — and its
outputs match the Python predictor within float tolerance.

Reference parity: amalgamation/ (predict-only single-file build,
c_predict_api.cc:1-305 consumed from one compiled object on
mobile/JS); here the artifact additionally carries StableHLO for the
jax-side loader (predict.load_exported), one export serving both."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _random_params(sym, input_shapes, seed):
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    rng = np.random.RandomState(seed)
    args, aux = {}, {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name in input_shapes or name.endswith("_label"):
            continue          # labels are free inputs, not parameters
        args[name] = mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.3)
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        # variance-like aux must be positive
        val = (rng.rand(*shp).astype(np.float32) + 0.5
               if name.endswith("var")
               else rng.randn(*shp).astype(np.float32) * 0.1)
        aux[name] = mx.nd.array(val)
    return args, aux


def _compile_consumer(tmp_path):
    exe = str(tmp_path / "amalgamation_consumer")
    # ONLY the amalgamation pair + libm: no -lmxtpu, no Python includes
    subprocess.run(
        ["gcc", "-std=c99", "-O2", "-I" + os.path.join(REPO, "amalgamation"),
         os.path.join(REPO, "tests", "cpp", "amalgamation_consumer.c"),
         os.path.join(REPO, "amalgamation", "mxtpu_predict.c"),
         "-lm", "-o", exe],
        check=True, capture_output=True)
    return exe


def _roundtrip(tmp_path, sym, input_shape, seed, batch=None):
    """Export with random params, run the C runtime, return (c_out,
    python_out)."""
    args, aux = _random_params(sym, {"data": input_shape}, seed)
    art = str(tmp_path / f"model{seed}.mxa")
    mx.predict.export_model(art, sym, args, aux, {"data": input_shape})

    run_shape = ((batch,) + input_shape[1:]) if batch else input_shape
    rng = np.random.RandomState(seed + 1)
    x = rng.randn(*run_shape).astype(np.float32)

    in_npy = str(tmp_path / f"in{seed}.npy")
    out_npy = str(tmp_path / f"out{seed}.npy")
    np.save(in_npy, x)
    exe = _compile_consumer(tmp_path)
    env = {k: v for k, v in os.environ.items()}
    env.pop("PYTHONPATH", None)     # prove: no Python tree needed
    r = subprocess.run([exe, art, in_npy, out_npy],
                       capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "AMALGAMATION_OK" in r.stdout
    c_out = np.load(out_npy)

    blob = {f"arg:{k}": v for k, v in args.items()}
    blob.update({f"aux:{k}": v for k, v in aux.items()})
    pred = mx.predict.create(sym.tojson(), blob, {"data": run_shape})
    pred.forward(data=x)
    py_out = pred.get_output(0)
    return c_out, py_out


def test_lenet_bn_artifact_matches_python(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=6, name="c1")
    net = mx.sym.BatchNorm(net, fix_gamma=False, eps=2e-5, name="bn1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=16, name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="sigmoid")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    c_out, py_out = _roundtrip(tmp_path, net, (2, 1, 28, 28), seed=3)
    assert c_out.shape == py_out.shape
    np.testing.assert_allclose(c_out, py_out, atol=1e-5, rtol=1e-4)


def test_resnet_block_artifact_matches_python(tmp_path):
    """Residual topology: conv+bn trunk with an elementwise shortcut and
    global average pooling — the ResNet op family end to end."""
    data = mx.sym.Variable("data")
    trunk = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                               num_filter=8, no_bias=True, name="c1")
    trunk = mx.sym.BatchNorm(trunk, fix_gamma=False, name="bn1")
    trunk = mx.sym.Activation(trunk, act_type="relu")
    trunk = mx.sym.Convolution(trunk, kernel=(3, 3), pad=(1, 1),
                               num_filter=8, no_bias=True, name="c2")
    short = mx.sym.Convolution(data, kernel=(1, 1), num_filter=8,
                               no_bias=True, name="sc")
    net = trunk + short
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    c_out, py_out = _roundtrip(tmp_path, net, (2, 3, 16, 16), seed=5)
    np.testing.assert_allclose(c_out, py_out, atol=1e-5, rtol=1e-4)


def test_artifact_batch_flexibility(tmp_path):
    """The C runtime re-infers shapes from the fed batch: export at
    batch 1, run at batch 4 (deploy-time batching)."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    c_out, py_out = _roundtrip(tmp_path, net, (1, 12), seed=7, batch=4)
    assert c_out.shape == (4, 4)
    np.testing.assert_allclose(c_out, py_out, atol=1e-5, rtol=1e-4)


def test_one_command_export_cli(tmp_path):
    """tools/export_model.py: checkpoint prefix -> .mxa in one command;
    the SAME artifact then loads through the jax-side ExportedPredictor
    (two consumers, one export)."""
    net = mx.models.mlp(num_classes=5)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 20))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"         # the test tier's pinned backend
    env["MXTPU_PLATFORMS"] = "cpu"       # authoritative (config.update)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel probe in a CPU export
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "export_model.py"),
         "--prefix", prefix, "--epoch", "0", "--data-shape", "2,20"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    art = prefix + ".mxa"
    assert os.path.exists(art)

    # jax-side consumer of the same artifact
    pred = mx.predict.load_exported(art)
    x = np.random.RandomState(0).randn(2, 20).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    assert out.shape == (2, 5)

    # C-side consumer of the same artifact
    exe = _compile_consumer(tmp_path)
    in_npy, out_npy = str(tmp_path / "x.npy"), str(tmp_path / "y.npy")
    np.save(in_npy, x)
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    r = subprocess.run([exe, art, in_npy, out_npy], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    np.testing.assert_allclose(np.load(out_npy), np.asarray(out),
                               atol=1e-5, rtol=1e-4)


def test_unsupported_op_fails_loudly(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.SwapAxis(data, dim1=0, dim2=1)
    art = str(tmp_path / "bad.mxa")
    mx.predict.export_model(art, net, {}, {}, {"data": (2, 3)})
    exe = _compile_consumer(tmp_path)
    in_npy = str(tmp_path / "x.npy")
    np.save(in_npy, np.zeros((2, 3), np.float32))
    r = subprocess.run([exe, art, in_npy, str(tmp_path / "y.npy")],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode != 0
    assert "unsupported op" in (r.stdout + r.stderr)


@pytest.mark.slow
def test_corrupt_artifact_never_crashes(tmp_path):
    """Byte-level robustness: random truncations and single-byte
    corruptions of a valid artifact must produce clean errors (rc=1),
    never signals — the parser-hardening contract, fuzz-style."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(11)
    args = {"fc_weight": mx.nd.array(rng.randn(3, 4).astype(np.float32)),
            "fc_bias": mx.nd.array(rng.randn(3).astype(np.float32))}
    art = str(tmp_path / "m.mxa")
    mx.predict.export_model(art, net, args, {}, {"data": (1, 4)})
    blob = bytearray(open(art, "rb").read())
    exe = _compile_consumer(tmp_path)
    in_npy = str(tmp_path / "x.npy")
    np.save(in_npy, np.zeros((1, 4), np.float32))

    def run(payload):
        bad = str(tmp_path / "bad.mxa")
        open(bad, "wb").write(bytes(payload))
        # bytes mode: corrupt entry names can echo into stderr as
        # non-UTF-8 via the runtime's error messages
        r = subprocess.run([exe, bad, in_npy, str(tmp_path / "y.npy")],
                           capture_output=True, timeout=60)
        # clean outcome only: success or a clean error exit — a signal
        # (negative returncode) means the parsers read out of bounds
        assert r.returncode in (0, 1), (
            r.returncode, r.stderr[-300:].decode("utf-8", "replace"))

    for cut in (0, 10, 22, len(blob) // 4, len(blob) // 2, len(blob) - 3):
        run(blob[:cut])                       # truncations
    for _ in range(60):                       # single-byte corruptions
        mutated = bytearray(blob)
        pos = rng.randint(0, len(mutated))
        mutated[pos] = rng.randint(0, 256)
        run(mutated)
