"""Operator correctness: forward values + numeric gradient checks
(rebuild of tests/python/unittest/test_operator.py using the ported
check_numeric_gradient / check_symbolic_forward from test_utils)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (check_numeric_gradient,
                                  check_symbolic_forward, reldiff)

rng = np.random.RandomState(7)


def test_elemwise_forward_backward():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    for sym, fn in [(a + b, np.add), (a * b, np.multiply),
                    (a - b, np.subtract)]:
        x = rng.randn(3, 4)
        y = rng.randn(3, 4)
        check_symbolic_forward(sym, {"a": x, "b": y}, [fn(x, y)])
        check_numeric_gradient(sym, {"a": x, "b": y})


def test_unary_ops_grad():
    x = rng.rand(3, 4) + 0.5
    data = mx.sym.Variable("data")
    for sym in [mx.sym.sqrt(data), mx.sym.exp(data), mx.sym.log(data),
                mx.sym.tanh(data), mx.sym.sigmoid(data), mx.sym.square(data)]:
        check_numeric_gradient(sym, {"data": x}, numeric_eps=1e-4)


def test_fully_connected():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    x = rng.randn(5, 3)
    check_numeric_gradient(fc, {"data": x,
                                "fc_weight": rng.randn(4, 3),
                                "fc_bias": rng.randn(4)})


def test_convolution_grad():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=2, pad=(1, 1),
                              name="conv")
    x = rng.randn(2, 3, 5, 5)
    check_numeric_gradient(conv, {"data": x,
                                  "conv_weight": rng.randn(2, 3, 3, 3) * 0.3,
                                  "conv_bias": rng.randn(2) * 0.3},
                           numeric_eps=1e-3, check_eps=0.05)


def test_conv_matches_reference_impl():
    # conv forward vs explicit im2col computation
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=3, no_bias=True,
                              name="c")
    out = mx.test_utils.simple_forward(conv, data=x, c_weight=w)
    ref = np.zeros((1, 3, 2, 2), np.float32)
    for o in range(3):
        for i in range(2):
            for p in range(2):
                for q in range(2):
                    ref[0, o, p, q] += (x[0, i, p:p + 3, q:q + 3]
                                        * w[o, i]).sum()
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_pooling():
    data = mx.sym.Variable("data")
    x = rng.randn(1, 1, 4, 4)
    maxp = mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="max")
    out = mx.test_utils.simple_forward(maxp, data=x)
    ref = x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5).reshape(
        1, 1, 2, 2, 4).max(axis=-1)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    avgp = mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    out = mx.test_utils.simple_forward(avgp, data=x)
    np.testing.assert_allclose(
        out, x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5).reshape(
            1, 1, 2, 2, 4).mean(axis=-1), rtol=1e-5)
    check_numeric_gradient(maxp, {"data": rng.randn(1, 1, 6, 6)})


def test_batchnorm_train_forward():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, fix_gamma=False, name="bn")
    x = rng.randn(8, 3, 2, 2).astype(np.float32)
    exe = bn.simple_bind(mx.cpu(), data=x.shape)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["bn_gamma"][:] = 1.0
    exe.arg_dict["bn_beta"][:] = 0.0
    exe.aux_dict["bn_moving_var"][:] = 1.0
    out = exe.forward(is_train=True)[0].asnumpy()
    mean = x.mean(axis=(0, 2, 3), keepdims=True)
    var = x.var(axis=(0, 2, 3), keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-3)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)
    # aux updated
    assert np.abs(exe.aux_dict["bn_moving_mean"].asnumpy()).sum() > 0


def test_activation_leakyrelu():
    data = mx.sym.Variable("data")
    x = rng.randn(4, 4)
    lr = mx.sym.LeakyReLU(data, act_type="leaky", slope=0.1)
    out = mx.test_utils.simple_forward(lr, data=x)
    np.testing.assert_allclose(out, np.where(x > 0, x, 0.1 * x), rtol=1e-5)
    elu = mx.sym.LeakyReLU(data, act_type="elu", slope=0.5)
    out = mx.test_utils.simple_forward(elu, data=x)
    np.testing.assert_allclose(out, np.where(x > 0, x, 0.5 * (np.exp(x) - 1)),
                               rtol=1e-5)


def test_softmax_output_grad():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    sm = mx.sym.SoftmaxOutput(data, label, name="sm")
    x = rng.randn(4, 5)
    lab = np.array([0, 2, 1, 4], np.float32)
    exe = sm.simple_bind(mx.cpu(), grad_req={"data": "write", "label": "null"},
                         data=(4, 5), label=(4,))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = lab
    out = exe.forward(is_train=True)[0].asnumpy()
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    ex = np.exp(x - x.max(axis=1, keepdims=True))
    p = ex / ex.sum(axis=1, keepdims=True)
    onehot = np.eye(5)[lab.astype(int)]
    np.testing.assert_allclose(out, p, rtol=1e-5)
    np.testing.assert_allclose(g, p - onehot, rtol=1e-4, atol=1e-6)


def test_regression_outputs():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    x = rng.randn(4, 3)
    y = rng.randn(4, 3)
    lin = mx.sym.LinearRegressionOutput(data, label)
    exe = lin.simple_bind(mx.cpu(), grad_req={"data": "write", "label": "null"},
                          data=(4, 3), label=(4, 3))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = y
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-6)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), (x - y) / 4,
                               rtol=1e-5)
    logi = mx.sym.LogisticRegressionOutput(data, label)
    out = mx.test_utils.simple_forward(logi, data=x, label=y)
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-x)), rtol=1e-5)


def test_block_grad():
    data = mx.sym.Variable("data")
    blocked = mx.sym.BlockGrad(data * 2)
    out = blocked + data
    exe = out.simple_bind(mx.cpu(), data=(3,))
    exe.arg_dict["data"][:] = [1, 2, 3]
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((3,))])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), [1, 1, 1])


def test_embedding():
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, input_dim=6, output_dim=3, name="emb")
    w = rng.randn(6, 3)
    idx = np.array([1, 3, 5, 0], np.float32)
    out = mx.test_utils.simple_forward(emb, data=idx, emb_weight=w)
    np.testing.assert_allclose(out, w[idx.astype(int)], rtol=1e-5)
    # scatter-add backward
    exe = emb.simple_bind(mx.cpu(), grad_req={"data": "null",
                                              "emb_weight": "write"},
                          data=(4,), emb_weight=(6, 3))
    exe.arg_dict["data"][:] = np.array([1, 1, 2, 0], np.float32)
    exe.arg_dict["emb_weight"][:] = w
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((4, 3))])
    g = exe.grad_dict["emb_weight"].asnumpy()
    expected = np.zeros((6, 3))
    for i in [1, 1, 2, 0]:
        expected[i] += 1
    np.testing.assert_allclose(g, expected, rtol=1e-5)


def test_concat_slicechannel_roundtrip():
    data = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(data, num_outputs=2, axis=1, name="sl")
    cat = mx.sym.Concat(parts[0], parts[1], num_args=2, dim=1)
    x = rng.randn(2, 4, 3)
    out = mx.test_utils.simple_forward(cat, data=x)
    np.testing.assert_allclose(out, x, rtol=1e-6)


def test_transpose_swapaxis_flip():
    data = mx.sym.Variable("data")
    x = rng.randn(2, 3, 4)
    out = mx.test_utils.simple_forward(mx.sym.transpose(data, axes=(2, 0, 1)),
                                       data=x)
    np.testing.assert_allclose(out, x.transpose(2, 0, 1))
    out = mx.test_utils.simple_forward(mx.sym.SwapAxis(data, dim1=0, dim2=2),
                                       data=x)
    np.testing.assert_allclose(out, x.swapaxes(0, 2))
    out = mx.test_utils.simple_forward(mx.sym.flip(data, axis=1), data=x)
    np.testing.assert_allclose(out, x[:, ::-1])


def test_sequence_ops():
    x = rng.randn(4, 3, 2).astype(np.float32)  # (T, N, D)
    lengths = np.array([2, 4, 1], np.float32)
    data = mx.sym.Variable("data")
    sl = mx.sym.Variable("sl")
    last = mx.sym.SequenceLast(data, sl, use_sequence_length=True)
    out = mx.test_utils.simple_forward(last, data=x, sl=lengths)
    expected = np.stack([x[1, 0], x[3, 1], x[0, 2]])
    np.testing.assert_allclose(out, expected, rtol=1e-5)

    mask = mx.sym.SequenceMask(data, sl, use_sequence_length=True, value=-1.0)
    out = mx.test_utils.simple_forward(mask, data=x, sl=lengths)
    assert (out[2, 0] == -1).all() and (out[1, 2] == -1).all()
    np.testing.assert_allclose(out[0], x[0], rtol=1e-5)

    rev = mx.sym.SequenceReverse(data, sl, use_sequence_length=True)
    out = mx.test_utils.simple_forward(rev, data=x, sl=lengths)
    np.testing.assert_allclose(out[0, 0], x[1, 0], rtol=1e-5)
    np.testing.assert_allclose(out[0, 1], x[3, 1], rtol=1e-5)
    np.testing.assert_allclose(out[0, 2], x[0, 2], rtol=1e-5)


def test_dropout():
    data = mx.sym.Variable("data")
    do = mx.sym.Dropout(data, p=0.5)
    x = np.ones((200, 200), np.float32)
    exe = do.simple_bind(mx.cpu(), data=x.shape, grad_req="null")
    exe.arg_dict["data"][:] = x
    out_train = exe.forward(is_train=True)[0].asnumpy()
    frac = (out_train == 0).mean()
    assert 0.4 < frac < 0.6
    kept = out_train[out_train != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-5)
    out_eval = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_eval, x)


def test_reduce_grads():
    data = mx.sym.Variable("data")
    x = rng.randn(3, 4)
    check_numeric_gradient(mx.sym.sum(data, axis=(1,)), {"data": x})
    check_numeric_gradient(mx.sym.mean(data), {"data": x})


def test_broadcast_ops():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    x = rng.randn(3, 4)
    y = rng.randn(1, 4)
    out = mx.test_utils.simple_forward(mx.sym.broadcast_plus(a, b), a=x, b=y)
    np.testing.assert_allclose(out, x + y, rtol=1e-6)
    check_numeric_gradient(mx.sym.broadcast_mul(a, b), {"a": x, "b": y})


def test_dot_batchdot():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    x = rng.randn(3, 4)
    y = rng.randn(4, 5)
    out = mx.test_utils.simple_forward(mx.sym.dot(a, b), a=x, b=y)
    np.testing.assert_allclose(out, x.dot(y), rtol=1e-5)
    xb = rng.randn(2, 3, 4)
    yb = rng.randn(2, 4, 5)
    out = mx.test_utils.simple_forward(mx.sym.batch_dot(a, b), a=xb, b=yb)
    np.testing.assert_allclose(out, np.einsum("bij,bjk->bik", xb, yb),
                               rtol=1e-5)
    check_numeric_gradient(mx.sym.dot(a, b), {"a": x, "b": y})


def test_upsampling_nearest():
    data = mx.sym.Variable("data")
    up = mx.sym.UpSampling(data, scale=2, sample_type="nearest")
    x = rng.randn(1, 2, 2, 2)
    out = mx.test_utils.simple_forward(up, data=x)
    assert out.shape == (1, 2, 4, 4)
    np.testing.assert_allclose(out[0, 0, :2, :2],
                               np.full((2, 2), x[0, 0, 0, 0]), rtol=1e-6)


def test_lrn_instance_norm_l2norm():
    data = mx.sym.Variable("data")
    x = rng.randn(2, 4, 3, 3).astype(np.float32)
    lrn = mx.sym.LRN(data, nsize=3)
    out = mx.test_utils.simple_forward(lrn, data=x)
    assert out.shape == x.shape
    inorm = mx.sym.InstanceNorm(data, name="in")
    out = mx.test_utils.simple_forward(
        inorm, data=x, in_gamma=np.ones(4, np.float32),
        in_beta=np.zeros(4, np.float32))
    np.testing.assert_allclose(out.mean(axis=(2, 3)), 0, atol=1e-4)
    l2 = mx.sym.L2Normalization(data)
    out = mx.test_utils.simple_forward(l2, data=x)
    norms = np.sqrt((out.reshape(2, -1) ** 2).sum(axis=1))
    np.testing.assert_allclose(norms, 1.0, rtol=1e-4)


def test_smooth_l1_and_maeregression():
    data = mx.sym.Variable("data")
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], np.float32)
    out = mx.test_utils.simple_forward(mx.sym.smooth_l1(data, sigma=1.0),
                                       data=x)
    expected = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_cast():
    data = mx.sym.Variable("data")
    c = mx.sym.Cast(data, dtype="float16")
    out = mx.test_utils.simple_forward(c, data=np.ones((2, 2), np.float32))
    assert out.dtype == np.float16


def test_makeloss_grad_scale():
    data = mx.sym.Variable("data")
    loss = mx.sym.MakeLoss(mx.sym.square(data), grad_scale=2.0)
    exe = loss.simple_bind(mx.cpu(), data=(3,))
    exe.arg_dict["data"][:] = [1.0, 2.0, 3.0]
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               2 * 2 * np.array([1, 2, 3.0]), rtol=1e-5)


def test_element_wise_sum():
    syms = [mx.sym.Variable(f"x{i}") for i in range(4)]
    out = mx.sym.ElementWiseSum(*syms, num_args=4)
    loc = {f"x{i}": rng.randn(3, 4) for i in range(4)}
    check_symbolic_forward(out, loc, [sum(loc[f"x{i}"] for i in range(4))])
    check_numeric_gradient(out, loc)
    # imperative path
    arrs = [mx.nd.array(loc[f"x{i}"]) for i in range(4)]
    got = mx.nd.ElementWiseSum(*arrs, num_args=4).asnumpy()
    assert reldiff(got, sum(a.asnumpy() for a in arrs)) < 1e-6


def test_broadcast_axis_and_to():
    data = mx.sym.Variable("data")
    x = rng.randn(2, 1, 3)
    out = mx.sym.broadcast_axis(data, axis=(1,), size=(4,))
    check_symbolic_forward(out, {"data": x},
                           [np.broadcast_to(x, (2, 4, 3))])
    check_numeric_gradient(out, {"data": x})
    out2 = mx.sym.broadcast_to(data, shape=(0, 5, 0))
    check_symbolic_forward(out2, {"data": x},
                           [np.broadcast_to(x, (2, 5, 3))])
    check_numeric_gradient(out2, {"data": x})
    # backward of broadcast is sum-reduce over the broadcast axis
    with pytest.raises(Exception):
        mx.sym.broadcast_axis(data, axis=(0,), size=(4,)).infer_shape(
            data=(2, 1, 3))


def test_element_mask():
    lhs = mx.sym.Variable("lhs")
    rhs = mx.sym.Variable("rhs")
    out = mx.sym.element_mask(lhs, rhs)
    x = rng.randn(4, 3, 2)
    m = np.array([1.0, 0.0, 1.0, 0.0])
    want = x * m.reshape(4, 1, 1)
    check_symbolic_forward(out, {"lhs": x, "rhs": m}, [want])
    # gradient flows only to lhs, masked by rhs
    e = out.simple_bind(mx.cpu(), lhs=x.shape, rhs=m.shape)
    e.arg_dict["lhs"][:] = x
    e.arg_dict["rhs"][:] = m
    e.forward(is_train=True)
    og = rng.randn(4, 3, 2)
    e.backward([mx.nd.array(og)])
    assert reldiff(e.grad_dict["lhs"].asnumpy(), og * m.reshape(4, 1, 1)) < 1e-6
    assert np.abs(e.grad_dict["rhs"].asnumpy()).max() == 0.0


def test_softmax_cross_entropy():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    out = mx.sym.softmax_cross_entropy(data, label)
    x = rng.randn(5, 7)
    y = rng.randint(0, 7, 5).astype(np.float64)
    ex = np.exp(x - x.max(axis=1, keepdims=True))
    prob = ex / ex.sum(axis=1, keepdims=True)
    want = -np.log(np.maximum(prob[np.arange(5), y.astype(int)], 1e-8)).sum()
    check_symbolic_forward(out, {"data": x, "label": y},
                           [np.array([want])], check_eps=1e-4)
    # explicit backward: scale * (softmax - onehot)
    e = out.simple_bind(mx.cpu(), grad_req={"data": "write", "label": "null"},
                        data=x.shape, label=y.shape)
    e.arg_dict["data"][:] = x
    e.arg_dict["label"][:] = y
    e.forward(is_train=True)
    e.backward([mx.nd.array(np.array([2.0]))])
    onehot = np.eye(7)[y.astype(int)]
    assert reldiff(e.grad_dict["data"].asnumpy(), 2.0 * (prob - onehot)) < 1e-5


def test_crop_assign():
    lhs = mx.sym.Variable("lhs")
    rhs = mx.sym.Variable("rhs")
    out = mx.sym._crop_assign(lhs, rhs, begin=(1, 0), end=(3, 2))
    x = rng.randn(4, 3)
    r = rng.randn(2, 2)
    want = x.copy()
    want[1:3, 0:2] = r
    check_symbolic_forward(out, {"lhs": x, "rhs": r}, [want])
    sc = mx.sym._crop_assign_scalar(lhs, begin=(0, 1), end=(2, 3), scalar=7.5)
    want2 = x.copy()
    want2[0:2, 1:3] = 7.5
    check_symbolic_forward(sc, {"lhs": x}, [want2])
    # imperative path must also reject out-of-bounds regions, not clamp
    # (jax dynamic_update_slice would silently shift the write)
    with pytest.raises(Exception):
        mx.nd._crop_assign_scalar(mx.nd.array(x), begin=(3, 0), end=(5, 2),
                                  scalar=99.0)
    with pytest.raises(Exception):
        mx.nd._crop_assign(mx.nd.array(x), mx.nd.array(r), begin=(3, 0),
                           end=(5, 2))
    with pytest.raises(Exception):  # rhs shape != region
        mx.nd._crop_assign(mx.nd.array(x), mx.nd.array(np.zeros((3, 2))),
                           begin=(1, 0), end=(3, 2))


def test_custom_dispatcher():
    import mxnet_tpu.operator as op

    @op.register("_test_scale2x")
    class ScaleProp(op.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Scale(op.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * 2.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0] * 2.0)

            return Scale()

    x = rng.randn(3, 4).astype(np.float32)
    got = mx.nd.Custom(mx.nd.array(x), op_type="_test_scale2x").asnumpy()
    assert reldiff(got, x * 2.0) < 1e-6
    data = mx.sym.Variable("data")
    s = mx.sym.Custom(data, op_type="_test_scale2x")
    check_symbolic_forward(s, {"data": x}, [x * 2.0])
    with pytest.raises(Exception):
        mx.sym.Custom(data, op_type="_no_such_custom_op")


def test_parity_op_validation():
    data = mx.sym.Variable("data")
    # mismatched ElementWiseSum shapes must fail at infer time
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    with pytest.raises(Exception):
        mx.sym.ElementWiseSum(a, b, num_args=2).infer_shape(a=(3, 4), b=(1, 4))
    # out-of-bounds crop regions must fail, not clamp
    with pytest.raises(Exception):
        mx.sym._crop_assign(a, b, begin=(3, 0), end=(5, 2)).infer_shape(
            a=(4, 3), b=(2, 2))
    with pytest.raises(Exception):
        mx.sym._crop_assign_scalar(data, begin=(2, 0), end=(1, 2),
                                   scalar=1.0).infer_shape(data=(4, 3))
    # malformed broadcast_axis params
    with pytest.raises(Exception):
        mx.sym.broadcast_axis(data, axis=(1, 2), size=(4,)).infer_shape(
            data=(2, 1, 1))
    # Custom with CamelCase registered name must dispatch (case-insensitive
    # registry membership)
    import mxnet_tpu.operator as op

    @op.register("CamelCaseScale")
    class CamelProp(op.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class S(op.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * 3.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0] * 3.0)

            return S()

    x = rng.randn(2, 3).astype(np.float32)
    got = mx.nd.Custom(mx.nd.array(x), op_type="CamelCaseScale").asnumpy()
    assert reldiff(got, x * 3.0) < 1e-6


def test_batchnorm_fused_backward_matches_autodiff():
    """The hand-written BN VJP (ops/nn.py _bn_train_bwd) must agree with
    autodiff through the naive two-pass formula."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(rng.randn(6, 3, 4, 5).astype(np.float32))
    gamma = jnp.asarray(rng.rand(3).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(3).astype(np.float32))
    dy = jnp.asarray(rng.randn(6, 3, 4, 5).astype(np.float32))
    axes, eps = (0, 2, 3), 1e-3

    from mxnet_tpu.ops.nn import _bn_train

    def naive(x, gamma, beta):
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        shape = (1, 3, 1, 1)
        xhat = (x - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + eps)
        return xhat * gamma.reshape(shape) + beta.reshape(shape)

    def fused(x, gamma, beta):
        return _bn_train(x, gamma, beta, axes, eps)[0]

    y_ref, vjp_ref = jax.vjp(naive, x, gamma, beta)
    y_got, vjp_got = jax.vjp(fused, x, gamma, beta)
    np.testing.assert_allclose(np.asarray(y_got), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)
    for g_got, g_ref in zip(vjp_got(dy), vjp_ref(dy)):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                                   rtol=1e-3, atol=1e-4)


def test_batchnorm_symbol_numeric_gradient():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, fix_gamma=False, eps=1e-3, name="bn")
    check_numeric_gradient(
        bn, {"data": rng.randn(4, 3, 2, 2), "bn_gamma": rng.rand(3) + 0.5,
              "bn_beta": rng.randn(3)},
        aux_states={"bn_moving_mean": np.zeros(3),
                    "bn_moving_var": np.ones(3)})


def test_reshape_special_codes():
    """Reshape 0/-1/-2/-3/-4 codes + reverse (reference test_reshape,
    tests/python/unittest/test_operator.py:933; reshape-inl.h)."""
    cases = [
        [(2, 3, 5, 5), (0, -1), False, (2, 75)],
        [(2, 3, 5, 5), (0, 0, -1), False, (2, 3, 25)],
        [(5, 3, 4, 5), (0, -1, 0), False, (5, 15, 4)],
        [(2, 3, 5, 4), (-1, 0, 0), False, (8, 3, 5)],
        [(2, 3, 5, 5), (0, 0, 0, 0), False, (2, 3, 5, 5)],
        [(2, 4, 5, 3), (-1, 2, 2, 1), False, (30, 2, 2, 1)],
        [(2, 3, 5, 6), (-2,), False, (2, 3, 5, 6)],
        [(2, 3, 5, 6), (6, 1, -2), False, (6, 1, 5, 6)],
        [(2, 3, 5, 6), (-3, -3), False, (6, 30)],
        [(2, 3, 5, 6), (-3, -1), False, (6, 30)],
        [(64,), (-4, 16, 4), False, (16, 4)],
        [(64,), (-4, 16, -1), False, (16, 4)],
        [(64, 1, 2, 3), (-4, 16, -1, -2), False, (16, 4, 1, 2, 3)],
        [(2, 3, 5, 5), (0, -1), True, (5, 30)],
        [(2, 3, 5, 5), (0, 0, -1), True, (3, 5, 10)],
        [(5, 3, 4, 5), (0, -1, 0), True, (3, 20, 5)],
        [(2, 3, 5, 4), (-1, 0, 0), True, (6, 5, 4)],
        [(2, 3, 4, 5), (3, -1, 0), True, (3, 8, 5)],
        [(2, 3, 5, 5), (5, 3, 0, -1), True, (5, 3, 5, 2)],
        [(2, 3, 5, 5), (0, 0, 0, 0), True, (2, 3, 5, 5)],
        [(2, 3, 5, 6), (-2,), True, (2, 3, 5, 6)],
        [(2, 3, 5, 6), (-2, 1, 30), True, (2, 3, 1, 30)],
        [(2, 3, 5, 6), (-3, -3), True, (6, 30)],
        [(64,), (16, 4, -4), True, (16, 4)],
        [(64,), (16, -1, -4), True, (16, 4)],
        [(1, 2, 3, 64), (-2, -1, 16, -4), True, (1, 2, 3, 4, 16)],
    ]
    for src, spec, reverse, dst in cases:
        net = mx.sym.Reshape(mx.sym.Variable("data"), shape=spec,
                             reverse=reverse)
        net = mx.sym.load_json(net.tojson())  # survives serialization
        _, out_shapes, _ = net.infer_shape(data=src)
        assert out_shapes[0] == dst, (src, spec, reverse, out_shapes[0], dst)
        x = np.random.RandomState(0).rand(*src).astype(np.float32)
        g = np.random.RandomState(1).rand(*dst).astype(np.float32)
        exe = net.simple_bind(mx.cpu(), grad_req="write", data=src)
        exe.arg_dict["data"][:] = x
        exe.forward(is_train=True)
        np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                                   x.reshape(dst), rtol=1e-6)
        exe.backward([mx.nd.array(g)])
        np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                                   g.reshape(src), rtol=1e-6)
    # legacy target_shape API: 0 infers the remainder
    net = mx.sym.Reshape(mx.sym.Variable("data"), target_shape=(2, 0))
    _, out_shapes, _ = net.infer_shape(data=(2, 3, 5, 5))
    assert out_shapes[0] == (2, 75)


def test_reshape_invalid_specs_raise_valueerror():
    data = mx.sym.Variable("data")
    for src, spec in [((6,), (-3,)),          # -3 needs two input dims
                      ((64,), (-4, 16)),      # -4 needs two spec entries
                      ((64,), (-4, -1, -1)),  # at most one -1 in a split
                      ((64,), (-4, -1, 0)),   # zero operand
                      ((2, 3), ()),           # empty spec on non-scalar
                      ((2, 3), (0, 0, 0))]:   # consumes too many dims
        net = mx.sym.Reshape(data, shape=spec)
        with pytest.raises((ValueError, mx.base.MXNetError)):
            net.infer_shape(data=src)


def test_convolution_grouping():
    """Grouped conv equals per-group convs concatenated (reference
    test_convolution_grouping, test_operator.py:739)."""
    num_filter, num_group, kernel = 4, 2, (3, 3)
    shape = (1, 4, 9, 9)
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.randn(num_filter, shape[1] // num_group, *kernel).astype(np.float32)
    b = rng.randn(num_filter).astype(np.float32)

    data = mx.sym.Variable("data")
    grouped = mx.sym.Convolution(data, name="conv", num_filter=num_filter,
                                 num_group=num_group, kernel=kernel)
    exe = grouped.simple_bind(mx.cpu(), grad_req="null", data=shape)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["conv_weight"][:] = w
    exe.arg_dict["conv_bias"][:] = b
    exe.forward(is_train=False)
    got = exe.outputs[0].asnumpy()

    # reference construction: slice channels, conv each, concat
    parts = []
    for g in range(num_group):
        sub = mx.sym.Convolution(data, name=f"c{g}",
                                 num_filter=num_filter // num_group,
                                 kernel=kernel)
        e = sub.simple_bind(mx.cpu(), grad_req="null",
                            data=(1, 2, 9, 9))
        e.arg_dict["data"][:] = x[:, 2 * g:2 * (g + 1)]
        e.arg_dict[f"c{g}_weight"][:] = w[2 * g:2 * (g + 1)]
        e.arg_dict[f"c{g}_bias"][:] = b[2 * g:2 * (g + 1)]
        e.forward(is_train=False)
        parts.append(e.outputs[0].asnumpy())
    want = np.concatenate(parts, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_convolution_dilated_impulse_response():
    """A dilated conv's receptive field on an impulse matches the
    dilation spacing (reference test_run_convolution_dilated_impulse_
    response, test_operator.py:863)."""
    for dil in [(1, 1), (2, 2), (3, 3)]:
        kernel_shape = (3, 3)
        data = mx.sym.Variable("data")
        conv = mx.sym.Convolution(data, name="conv", num_filter=1,
                                  kernel=kernel_shape, dilate=dil,
                                  no_bias=True)
        size = 2 * (kernel_shape[0] - 1) * dil[0] + 1
        exe = conv.simple_bind(mx.cpu(), grad_req="null",
                               data=(1, 1, size, size))
        impulse = np.zeros((1, 1, size, size), np.float32)
        center = size // 2
        impulse[0, 0, center, center] = 1.0
        exe.arg_dict["data"][:] = impulse
        exe.arg_dict["conv_weight"][:] = 1.0
        exe.forward(is_train=False)
        out = exe.outputs[0].asnumpy()[0, 0]
        # response is nonzero exactly at taps dil apart around the center
        nz = np.transpose(np.nonzero(out))
        c = out.shape[0] // 2
        for (r, s) in nz:
            assert (r - c) % dil[0] == 0 and (s - c) % dil[1] == 0, (r, s)
        assert out.sum() == pytest.approx(kernel_shape[0] * kernel_shape[1])


def test_binary_op_duplicate_input():
    """Gradient when the same input feeds both sides (reference
    test_binary_op_duplicate_input, test_operator.py:396):
    d(a*a)/da = 2a."""
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4).astype(np.float32)
    data = mx.sym.Variable("data")
    square = data * data
    exe = square.simple_bind(mx.cpu(), grad_req="write", data=(3, 4))
    exe.arg_dict["data"][:] = a
    exe.forward(is_train=True)
    np.testing.assert_allclose(exe.outputs[0].asnumpy(), a * a, rtol=1e-6)
    exe.backward([mx.nd.ones((3, 4))])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), 2 * a,
                               rtol=1e-5)


def test_pow_maximum_minimum_helpers():
    """Module-level pow/maximum/minimum with Symbol|Number operands
    (reference symbol.py:1122-1195, test_scalar_pow/test_symbol_pow/
    test_pow_fn/test_maximum_minimum[_scalar])."""
    rng = np.random.RandomState(0)
    xv = rng.rand(3, 4).astype(np.float32) + 0.5
    yv = rng.rand(3, 4).astype(np.float32) + 0.5
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")

    cases = [
        (mx.sym.pow(x, y), {"x": xv, "y": yv}, xv ** yv),
        (mx.sym.pow(x, 3.0), {"x": xv}, xv ** 3.0),
        (mx.sym.pow(2.0, y), {"y": yv}, 2.0 ** yv),
        (mx.sym.maximum(x, y), {"x": xv, "y": yv}, np.maximum(xv, yv)),
        (mx.sym.maximum(x, 0.8), {"x": xv}, np.maximum(xv, 0.8)),
        (mx.sym.minimum(0.8, y), {"y": yv}, np.minimum(0.8, yv)),
    ]
    for expr, args, want in cases:
        exe = expr.simple_bind(mx.cpu(), grad_req="null",
                               **{k: v.shape for k, v in args.items()})
        for k, v in args.items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=False)
        np.testing.assert_allclose(exe.outputs[0].asnumpy(), want, rtol=1e-5)
    assert mx.sym.pow(2.0, 3.0) == 8.0
    assert mx.sym.maximum(2, 5) == 5

    # imperative twins (reference ndarray.py:773-850)
    a, b = mx.nd.array(xv), mx.nd.array(yv)
    np.testing.assert_allclose(mx.nd.power(a, b).asnumpy(), xv ** yv,
                               rtol=1e-5)
    np.testing.assert_allclose(mx.nd.power(2.0, b).asnumpy(), 2.0 ** yv,
                               rtol=1e-5)
    np.testing.assert_allclose(mx.nd.maximum(a, 0.8).asnumpy(),
                               np.maximum(xv, 0.8), rtol=1e-6)
    np.testing.assert_allclose(mx.nd.minimum(0.8, b).asnumpy(),
                               np.minimum(0.8, yv), rtol=1e-6)


def _svm_bind(use_linear, x, lab, margin=1.0, reg=1.0):
    X = mx.sym.Variable("X")
    L = mx.sym.Variable("L")
    out = mx.sym.SVMOutput(data=X, label=L, use_linear=use_linear,
                           margin=margin, regularization_coefficient=reg)
    exe = out.simple_bind(mx.cpu(), grad_req={"X": "write", "L": "null"},
                          X=x.shape, L=lab.shape)
    exe.arg_dict["X"][:] = x
    exe.arg_dict["L"][:] = lab
    fwd = exe.forward(is_train=True)[0].asnumpy()
    exe.backward()
    return fwd, exe.grad_dict["X"].asnumpy()


def test_support_vector_machine_l1_svm():
    # reference one-vs-all hinge semantics (svm_output.cc L1_SVM):
    # grad_j = -s_j * 1[1 - s_j x_j > 0], s_j = +1 iff j == label
    shape = (20, 10)
    x = rng.rand(*shape).astype(np.float32)
    lab = rng.randint(0, shape[1], (shape[0],)).astype(np.float32)
    fwd, g = _svm_bind(True, x, lab)
    np.testing.assert_allclose(fwd, x, rtol=1e-6)
    l_mask = np.equal(lab.reshape(shape[0], 1), range(shape[1]))
    l_mask = l_mask.astype(np.float32) * 2 - 1
    expect = (-1) * l_mask * np.greater(1 - l_mask * x, 0)
    np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)


def test_support_vector_machine_l2_svm():
    shape = (20, 10)
    x = rng.rand(*shape).astype(np.float32)
    lab = rng.randint(0, shape[1], (shape[0],)).astype(np.float32)
    fwd, g = _svm_bind(False, x, lab)
    np.testing.assert_allclose(fwd, x, rtol=1e-6)
    l_mask = np.equal(lab.reshape(shape[0], 1), range(shape[1]))
    l_mask = l_mask.astype(np.float32) * 2 - 1
    expect = (-2) * l_mask * np.maximum(1 - l_mask * x, 0)
    np.testing.assert_allclose(g, expect, rtol=1e-4, atol=1e-6)


def test_svm_margin_and_reg_scaling():
    x = rng.rand(6, 4).astype(np.float32)
    lab = rng.randint(0, 4, (6,)).astype(np.float32)
    _, g1 = _svm_bind(True, x, lab, margin=0.5, reg=3.0)
    l_mask = (np.equal(lab.reshape(6, 1), range(4)).astype(np.float32) * 2 - 1)
    expect = (-1) * l_mask * np.greater(0.5 - l_mask * x, 0) * 3.0
    np.testing.assert_allclose(g1, expect, rtol=1e-5, atol=1e-6)


def test_deconvolution_forward_shape_and_transpose_identity():
    # Deconvolution forward must equal the data-gradient of Convolution
    # with the same kernel (transposed-conv identity the reference
    # realises via the shared im2col core, deconvolution-inl.h).
    n, cin, cout, h, w, k, s, p = 2, 3, 5, 7, 7, 3, 2, 1
    x = rng.randn(n, cin, h, w).astype(np.float32)
    wgt = rng.randn(cin, cout, k, k).astype(np.float32)

    dec = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(k, k),
                               num_filter=cout, stride=(s, s), pad=(p, p),
                               no_bias=True, name="dec")
    oh = (h - 1) * s + k - 2 * p
    arg_shapes, out_shapes, _ = dec.infer_shape(data=(n, cin, h, w))
    assert out_shapes[0] == (n, cout, oh, oh)

    exe = dec.simple_bind(mx.cpu(), data=(n, cin, h, w))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["dec_weight"][:] = wgt
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (n, cout, oh, oh)

    # conv that maps (n,cout,oh,oh) -> (n,cin,h,w) with the same weight;
    # deconv fwd == sum over input contributions == conv backward-data
    conv = mx.sym.Convolution(mx.sym.Variable("y"), kernel=(k, k),
                              num_filter=cin, stride=(s, s), pad=(p, p),
                              no_bias=True, name="conv")
    cexe = conv.simple_bind(mx.cpu(), grad_req={"y": "write",
                                                "conv_weight": "null"},
                            y=(n, cout, oh, oh))
    cexe.arg_dict["y"][:] = np.zeros((n, cout, oh, oh), np.float32)
    cexe.arg_dict["conv_weight"][:] = wgt
    cexe.forward(is_train=True)
    cexe.backward([mx.nd.array(x)])
    # grad of <conv(y), x> wrt y at y=0 equals deconv(x)
    np.testing.assert_allclose(out, cexe.grad_dict["y"].asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_deconvolution_gradient():
    n, cin, cout, h, k = 2, 2, 3, 5, 3
    dec = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(k, k),
                               num_filter=cout, stride=(1, 1), pad=(1, 1),
                               no_bias=True, name="dec")
    check_numeric_gradient(
        dec, {"data": rng.randn(n, cin, h, h),
              "dec_weight": rng.randn(cin, cout, k, k)},
        numeric_eps=1e-3, check_eps=0.05)


def test_deconvolution_bias_and_adj():
    n, cin, cout, h, k, s = 1, 2, 4, 4, 2, 2
    dec = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(k, k),
                               num_filter=cout, stride=(s, s), adj=(1, 1),
                               no_bias=False, name="dec")
    oh = (h - 1) * s + k + 1  # + adj
    _, out_shapes, _ = dec.infer_shape(data=(n, cin, h, h))
    assert out_shapes[0] == (n, cout, oh, oh)
    exe = dec.simple_bind(mx.cpu(), data=(n, cin, h, h))
    exe.arg_dict["data"][:] = rng.randn(n, cin, h, h)
    exe.arg_dict["dec_weight"][:] = rng.randn(cin, cout, k, k)
    assert exe.forward(is_train=False)[0].shape == (n, cout, oh, oh)
    bias = rng.randn(cout).astype(np.float32)
    exe.arg_dict["dec_bias"][:] = bias
    out = exe.forward(is_train=False)[0].asnumpy()
    exe.arg_dict["dec_bias"][:] = np.zeros(cout, np.float32)
    out0 = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out - out0,
                               np.broadcast_to(bias.reshape(1, -1, 1, 1),
                                               out.shape), rtol=1e-4,
                               atol=1e-5)


def test_deconvolution_grouped():
    # grouped transposed conv: per-group adjoint kernels (was a crash:
    # the raw weight has the wrong layout for feature_group_count)
    n, cin, cout, h, k, g = 2, 4, 6, 5, 3, 2
    dec = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(k, k),
                               num_filter=cout, num_group=g, pad=(1, 1),
                               no_bias=True, name="d")
    x = rng.randn(n, cin, h, h).astype(np.float32)
    w = rng.randn(cin, cout // g, k, k).astype(np.float32)
    exe = dec.simple_bind(mx.cpu(), data=(n, cin, h, h))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["d_weight"][:] = w
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (n, cout, h, h)
    # group 0 of the output must equal an ungrouped deconv over group-0
    # slices of data/weight
    sub = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(k, k),
                               num_filter=cout // g, pad=(1, 1),
                               no_bias=True, name="s")
    sexe = sub.simple_bind(mx.cpu(), data=(n, cin // g, h, h))
    for gi in range(g):
        sexe.arg_dict["data"][:] = x[:, gi * cin // g:(gi + 1) * cin // g]
        sexe.arg_dict["s_weight"][:] = w[gi * cin // g:(gi + 1) * cin // g]
        sout = sexe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(
            out[:, gi * cout // g:(gi + 1) * cout // g], sout,
            rtol=1e-5, atol=1e-5)
    check_numeric_gradient(dec, {"data": x, "d_weight": w},
                           numeric_eps=1e-3, check_eps=0.05)


def test_deconvolution_adj_ge_stride_rejected():
    # reference deconvolution-inl.h enforces adj < stride
    dec = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(3, 3),
                               num_filter=2, stride=(1, 1), adj=(1, 1),
                               no_bias=True)
    with pytest.raises(Exception):
        dec.infer_shape(data=(1, 2, 4, 4))


def test_softmax_output_soft_labels_and_out_grad():
    # probability labels: label.shape == data.shape -> grad = p - label
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    x = rng.randn(4, 5).astype(np.float32)
    soft = rng.rand(4, 5).astype(np.float32)
    soft /= soft.sum(axis=1, keepdims=True)
    sm = mx.sym.SoftmaxOutput(data, label, name="sm")
    exe = sm.simple_bind(mx.cpu(), grad_req={"data": "write", "label": "null"},
                         data=(4, 5), label=(4, 5))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = soft
    p = exe.forward(is_train=True)[0].asnumpy()
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), p - soft,
                               rtol=1e-4, atol=1e-6)

    # out_grad=True scales the gradient by the incoming output gradient
    smo = mx.sym.SoftmaxOutput(data, label, out_grad=True, name="sm2")
    exe2 = smo.simple_bind(mx.cpu(), grad_req={"data": "write",
                                               "label": "null"},
                           data=(4, 5), label=(4,))
    lab = np.array([0, 2, 1, 4], np.float32)
    exe2.arg_dict["data"][:] = x
    exe2.arg_dict["label"][:] = lab
    p2 = exe2.forward(is_train=True)[0].asnumpy()
    og = rng.rand(4, 5).astype(np.float32)
    exe2.backward([mx.nd.array(og)])
    onehot = np.eye(5)[lab.astype(int)]
    np.testing.assert_allclose(exe2.grad_dict["data"].asnumpy(),
                               (p2 - onehot) * og, rtol=1e-4, atol=1e-6)


def test_upsampling_multi_input_modes():
    # FCN-style skip connection: two inputs of different spatial size,
    # each upsampled by its own factor to in0*scale (upsampling-inl.h:90)
    a = rng.randn(1, 2, 4, 4).astype(np.float32)
    b = rng.randn(1, 2, 8, 8).astype(np.float32)
    up = mx.sym.UpSampling(mx.sym.Variable("a"), mx.sym.Variable("b"),
                           scale=4, sample_type="nearest", num_args=2)
    exe = up.simple_bind(mx.cpu(), a=a.shape, b=b.shape)
    exe.arg_dict["a"][:] = a
    exe.arg_dict["b"][:] = b
    out = exe.forward(is_train=False)[0].asnumpy()
    ra = a.repeat(4, axis=2).repeat(4, axis=3)
    rb = b.repeat(2, axis=2).repeat(2, axis=3)
    np.testing.assert_allclose(out, np.concatenate([ra, rb], axis=1),
                               rtol=1e-6)

    ups = mx.sym.UpSampling(mx.sym.Variable("a"), mx.sym.Variable("b"),
                            scale=4, sample_type="nearest", num_args=2,
                            multi_input_mode="sum")
    exe = ups.simple_bind(mx.cpu(), a=a.shape, b=b.shape)
    exe.arg_dict["a"][:] = a
    exe.arg_dict["b"][:] = b
    out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, ra + rb, rtol=1e-6)


def test_reshape_keep_highest():
    x = rng.randn(6, 8).astype(np.float32)
    r = mx.sym.Reshape(mx.sym.Variable("data"), target_shape=(0, 2, 2, 2),
                       keep_highest=True)
    out = mx.test_utils.simple_forward(r, data=x)
    np.testing.assert_allclose(out, x.reshape(6, 2, 2, 2))


def test_softmax_output_multi_output_label_variants():
    # all three accepted label layouts from the reference InferShape:
    # (n, d1...), (n, 1, d1...), (n, prod(d1...)) — identical gradients
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    x = rng.randn(2, 3, 2, 2).astype(np.float32)
    lab = rng.randint(0, 3, (2, 2, 2)).astype(np.float32)
    grads = []
    for lshape, lval in [((2, 2, 2), lab),
                         ((2, 1, 2, 2), lab.reshape(2, 1, 2, 2)),
                         ((2, 4), lab.reshape(2, 4))]:
        sm = mx.sym.SoftmaxOutput(data, label, multi_output=True, name="sm")
        exe = sm.simple_bind(mx.cpu(), grad_req={"data": "write",
                                                 "label": "null"},
                             data=x.shape, label=lshape)
        exe.arg_dict["data"][:] = x
        exe.arg_dict["label"][:] = lval
        exe.forward(is_train=True)
        exe.backward()
        g = exe.grad_dict["data"].asnumpy()
        assert g.shape == x.shape
        grads.append(g)
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-6)
    np.testing.assert_allclose(grads[0], grads[2], rtol=1e-6)


def test_softmax_output_multi_output_use_ignore():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    x = rng.randn(2, 3, 2, 2).astype(np.float32)
    lab = rng.randint(0, 3, (2, 1, 2, 2)).astype(np.float32)
    lab.reshape(-1)[0] = -1  # ignored position
    sm = mx.sym.SoftmaxOutput(data, label, multi_output=True,
                              use_ignore=True, ignore_label=-1, name="sm")
    exe = sm.simple_bind(mx.cpu(), grad_req={"data": "write",
                                             "label": "null"},
                         data=x.shape, label=lab.shape)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = lab
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    assert g.shape == x.shape
    # the ignored position's gradient column must be exactly zero
    np.testing.assert_allclose(g[0, :, 0, 0], 0.0)
    assert np.abs(g).sum() > 0


def test_upsampling_non_divisible_rejected():
    up = mx.sym.UpSampling(mx.sym.Variable("a"), mx.sym.Variable("b"),
                           scale=4, sample_type="nearest", num_args=2)
    with pytest.raises(Exception):
        up.infer_shape(a=(1, 2, 4, 4), b=(1, 2, 3, 3))


def test_make_loss_normalization_modes():
    data = mx.sym.Variable("data")
    x = np.array([[0.5, -0.2], [0.3, 0.0]], np.float32)

    def grad_of(**kw):
        ml = mx.sym.MakeLoss(data, **kw)
        exe = ml.simple_bind(mx.cpu(), data=x.shape)
        exe.arg_dict["data"][:] = x
        exe.forward(is_train=True)
        exe.backward()
        return exe.grad_dict["data"].asnumpy()

    np.testing.assert_allclose(grad_of(grad_scale=2.0),
                               np.full_like(x, 2.0))
    np.testing.assert_allclose(grad_of(grad_scale=2.0,
                                       normalization="batch"),
                               np.full_like(x, 1.0))
    # valid: grad_scale / #(x > thresh) at EVERY position, no masking
    # (make_loss-inl.h:84-93); here 2 elements exceed 0.1
    np.testing.assert_allclose(
        grad_of(grad_scale=3.0, normalization="valid", valid_thresh=0.1),
        np.full_like(x, 1.5))


def test_upsampling_bilinear_positional_weight_not_varargs():
    """Regression: key_var_num_args autofill must NOT apply to
    UpSampling, whose num_args means nearest-mode input count — a
    positional bilinear weight is a legal call that keeps num_args=1."""
    data = mx.sym.Variable("data")
    weight = mx.sym.Variable("weight")
    net = mx.sym.UpSampling(data, weight, sample_type="bilinear",
                            scale=2, num_filter=4)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 4, 5, 5))
    assert out_shapes[0] == (2, 4, 10, 10)


def test_upsampling_nearest_multi_input_positional():
    """Reference key_var_num_args on UpSampling (upsampling.cc:58): the
    FCN skip-connection pattern — multiple nearest inputs passed
    positionally with num_args inferred."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    net = mx.sym.UpSampling(a, b, scale=2, sample_type="nearest")
    arg_shapes, out_shapes, _ = net.infer_shape(a=(1, 3, 4, 4),
                                                b=(1, 2, 8, 8))
    # a upsampled 2x to 8x8, b upsampled 1x; channels concat: 3+2
    assert out_shapes[0] == (1, 5, 8, 8)
    exe = net.bind(mx.cpu(), args={"a": mx.nd.ones((1, 3, 4, 4)),
                                   "b": mx.nd.ones((1, 2, 8, 8))})
    assert exe.forward()[0].shape == (1, 5, 8, 8)


def test_var_arg_ops_imperative_autofill():
    """num_args autofill applies to the NDArray frontend too (the
    reference fills key_var_num_args in both frontends)."""
    x = mx.nd.ones((2, 3))
    y = mx.nd.ones((2, 4))
    out = mx.nd.Concat(x, y, dim=1)
    assert out.shape == (2, 7)
    s = mx.nd.ElementWiseSum(x, x, x)
    np.testing.assert_allclose(s.asnumpy(), 3.0)
