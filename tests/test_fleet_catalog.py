"""Fleet model catalog (mxnet_tpu/fleet): replicas declare what they
carry, the router filters by it, the collector aggregates per model,
and the supervisor's rebalancer moves adapters to follow traffic.

The contracts under test:

* advertisement — a replica's checkpoint id (``model=`` /
  ``MXTPU_FLEET_MODEL``) and registered adapter ids ride ``/healthz``
  and ``/statusz.json``;
* clean 400s — a model/adapter mismatch on ``/generate`` is a
  structured non-retriable 400 (``wrong_model`` / ``unknown_adapter``
  / ``adapters_off``), NEVER a 500 that would open breakers;
* routing — the router serves two model ids side by side, lands each
  request on a replica advertising its model (and adapter), and
  rejects an unknown model id with :class:`PermanentError` before any
  hop;
* runtime adapter movement — ``/adapter_export`` →
  ``/load_adapter`` copies an adapter replica-to-replica over the
  wire (sha1-verified), ``/unload_adapter`` de-catalogs it;
* aggregation — ``FleetCollector.fleet_view()["models"]`` groups
  replicas, traffic, and per-adapter goodput by model tag;
* rebalance — ``CatalogRebalancer`` plans spread moves for hot
  adapters missing from replicas of their model, applies them capped
  with per-move failure isolation, and the
  ``Supervisor.rebalance_catalog`` actuator wraps one pass.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.fleet import (CatalogRebalancer, FleetCollector,
                             PermanentError, ReplicaServer, Router,
                             Supervisor)

VOCAB = 53


@pytest.fixture(scope="module")
def model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _lora(model, rank=4, seed=11):
    from mxnet_tpu.serve import adapters as adapters_mod

    net, params = model
    rng = np.random.RandomState(seed)
    out = {}
    stems = adapters_mod.gpt_stems("gpt", 2, False, False, params)
    for stem, (dout, din) in stems.items():
        out[stem] = ((rng.randn(rank, din) * 0.1).astype(np.float32),
                     (rng.randn(dout, rank) * 0.1).astype(np.float32))
    return out


def _adapter_replica(model, rid, model_id, adapters=(), **kw):
    eng = _engine(model, adapters=4, adapter_rank=4)
    for j, aid in enumerate(adapters):
        eng.adapter_store.register(aid, _lora(model, seed=40 + j),
                                   alpha=8.0)
    return ReplicaServer(eng, replica_id=rid, model=model_id,
                         **kw).start()


def _prompt(n=10, seed=7):
    rng = np.random.RandomState(seed)
    return rng.randint(0, VOCAB, (n,)).astype(np.int32)


def _post(url, path, payload, timeout=30):
    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, path, timeout=10):
    with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


# -- advertisement + clean 400s -----------------------------------------------
def test_replica_catalog_advertisement_and_400s(model, fleet_cleanup):
    rep = _adapter_replica(model, "r0", "m-alpha", adapters=("t0",))
    fleet_cleanup.append(rep)
    hz = _get(rep.url, "/healthz")
    assert hz["model"] == "m-alpha"
    assert hz["adapters"] == ["t0"]
    sz = _get(rep.url, "/statusz.json")["replica"]
    assert sz["model"] == "m-alpha"
    assert sz["adapters"]["ids"] == ["t0"]

    base = {"prompt": _prompt().tolist(), "max_new_tokens": 4}
    # the happy paths
    code, out = _post(rep.url, "/generate", dict(base, model="m-alpha"))
    assert code == 200 and len(out["tokens"]) == 4
    code, out = _post(rep.url, "/generate",
                      dict(base, model="m-alpha", adapter="t0"))
    assert code == 200 and len(out["tokens"]) == 4
    # mismatches: structured, non-retriable, never 500
    code, out = _post(rep.url, "/generate", dict(base, model="m-beta"))
    assert code == 400 and out["error"] == "wrong_model"
    assert out["retriable"] is False and out["model"] == "m-alpha"
    code, out = _post(rep.url, "/generate", dict(base, adapter="nope"))
    assert code == 400 and out["error"] == "unknown_adapter"
    for bad in ({"model": 7}, {"model": ""}, {"adapter": 7},
                {"adapter": ""}):
        code, out = _post(rep.url, "/generate", dict(base, **bad))
        assert code == 400 and out["error"] == "bad_request"
    # an adapters-off replica 400s adapter requests the same way
    off = ReplicaServer(_engine(model), replica_id="off").start()
    fleet_cleanup.append(off)
    code, out = _post(off.url, "/generate", dict(base, adapter="t0"))
    assert code == 400 and out["error"] == "unknown_adapter"
    assert _get(off.url, "/healthz").get("model") is None


# -- runtime adapter movement -------------------------------------------------
def test_adapter_export_load_unload_endpoints(model, fleet_cleanup):
    src = _adapter_replica(model, "src", "m", adapters=("t0",))
    dst = _adapter_replica(model, "dst", "m")
    fleet_cleanup.extend([src, dst])
    code, payload = _post(src.url, "/adapter_export", {"adapter": "t0"})
    assert code == 200 and payload["adapter"] == "t0"
    assert payload["records"] and payload["replica"] == "src"
    code, out = _post(dst.url, "/load_adapter", payload)
    assert code == 200 and out["adapters"] == ["t0"]
    assert _get(dst.url, "/healthz")["adapters"] == ["t0"]
    # the moved copy SERVES the same tokens as the original
    body = {"prompt": _prompt().tolist(), "max_new_tokens": 6,
            "adapter": "t0"}
    _, a = _post(src.url, "/generate", dict(body, request_id="s1"))
    _, b = _post(dst.url, "/generate", dict(body, request_id="d1"))
    assert a["tokens"] == b["tokens"]
    # corrupt wire payload: caller's 400, never a 500
    bad = dict(payload, records=[dict(payload["records"][0],
                                      data="AAAA")])
    code, out = _post(dst.url, "/load_adapter", bad)
    assert code == 400 and out["error"] == "bad_adapter"
    # unload: de-catalogs; unknown and adapters-off are clean 400s
    code, out = _post(dst.url, "/unload_adapter", {"adapter": "t0"})
    assert code == 200 and out["adapters"] == []
    code, out = _post(dst.url, "/unload_adapter", {"adapter": "t0"})
    assert code == 400 and out["error"] == "unknown_adapter"
    code, out = _post(dst.url, "/adapter_export", {"adapter": "t0"})
    assert code == 400 and out["error"] == "unknown_adapter"
    off = ReplicaServer(_engine(model), replica_id="off2").start()
    fleet_cleanup.append(off)
    for path in ("/load_adapter", "/unload_adapter", "/adapter_export"):
        code, out = _post(off.url, path, {"adapter": "t0"})
        assert code == 400 and out["error"] == "adapters_off"


# -- routing by catalog identity ----------------------------------------------
def test_router_routes_two_models(model, fleet_cleanup):
    ra = _adapter_replica(model, "ra", "m-a", adapters=("t0",))
    rb = _adapter_replica(model, "rb", "m-b")
    fleet_cleanup.extend([ra, rb])
    router = Router([ra.url, rb.url], scrape_interval_s=0)
    fleet_cleanup.append(router)
    router.scrape()
    p = _prompt().tolist()
    for _ in range(3):
        assert router.generate(p, max_new_tokens=4,
                               model="m-a").replica == "ra"
        assert router.generate(p, max_new_tokens=4,
                               model="m-b").replica == "rb"
    # adapter filtering: only ra advertises t0
    for _ in range(3):
        assert router.generate(p, max_new_tokens=4,
                               adapter="t0").replica == "ra"
    # unknown model: permanent before any hop (routing it anywhere
    # could only produce per-replica 400s)
    with pytest.raises(PermanentError, match="unknown model"):
        router.generate(p, max_new_tokens=4, model="m-zzz")
    # model-less requests still balance across the whole pool
    seen = {router.generate(p, max_new_tokens=4).replica
            for _ in range(8)}
    assert seen == {"ra", "rb"}


# -- per-model aggregation ----------------------------------------------------
def test_collector_models_aggregation(model, fleet_cleanup):
    ra = _adapter_replica(model, "ra", "m-a", adapters=("t0", "t1"))
    rb = _adapter_replica(model, "rb", "m-a", adapters=("t0",))
    rc = ReplicaServer(_engine(model), replica_id="rc",
                       model="m-b").start()
    fleet_cleanup.extend([ra, rb, rc])
    body = {"prompt": _prompt().tolist(), "max_new_tokens": 4}
    for i in range(2):
        _post(ra.url, "/generate",
              dict(body, adapter="t0", request_id=f"a{i}"))
    _post(ra.url, "/generate", dict(body, adapter="t1",
                                    request_id="a9"))
    _post(rc.url, "/generate", dict(body, request_id="c0"))
    col = FleetCollector(urls=[ra.url, rb.url, rc.url], interval_s=0)
    fleet_cleanup.append(col)
    assert col.scrape()["ok"] == 3
    view = col.fleet_view()
    rows = {r["replica"]: r for r in view["replicas"]}
    assert rows["ra"]["model"] == "m-a"
    assert rows["ra"]["adapters"] == ["t0", "t1"]
    assert rows["rc"]["adapters"] is None       # adapters-off replica
    models = view["models"]
    assert set(models) == {"m-a", "m-b"}
    ma = models["m-a"]
    assert ma["replicas"] == 2 and ma["stale"] == 0
    assert ma["adapters"] == {"t0": 2, "t1": 1}   # placement counts
    assert ma["adapter_goodput"] == {"t0": 2, "t1": 1}
    assert ma["adapter_tokens"] == {"t0": 8, "t1": 4}
    assert ma["completed"] == 3
    assert models["m-b"]["completed"] == 1
    assert models["m-b"]["adapters"] == {}


# -- rebalance ----------------------------------------------------------------
def test_catalog_rebalancer_spread_cap_and_failures(model, fleet_cleanup):
    ra = _adapter_replica(model, "ra", "m", adapters=("t0", "t1"))
    rb = _adapter_replica(model, "rb", "m")
    fleet_cleanup.extend([ra, rb])
    body = {"prompt": _prompt().tolist(), "max_new_tokens": 4}
    for i in range(3):
        _post(ra.url, "/generate",
              dict(body, adapter="t0", request_id=f"t0-{i}"))
    _post(ra.url, "/generate", dict(body, adapter="t1",
                                    request_id="t1-0"))
    col = FleetCollector(urls=[ra.url, rb.url], interval_s=0)
    fleet_cleanup.append(col)
    col.scrape()
    reb = CatalogRebalancer(col)
    moves = reb.plan()
    # hot-first ordering: t0 (3 completions) spreads before t1 (1)
    assert [(m["action"], m["adapter"], m["dst"]) for m in moves] == \
        [("spread", "t0", rb.url), ("spread", "t1", rb.url)]
    assert moves[0]["src"] == ra.url
    # cap: max_moves bounds one pass (planned > applied stays visible)
    assert CatalogRebalancer(col, max_moves=1).apply(moves) and \
        len(CatalogRebalancer(col, max_moves=1).apply(moves)) == 1
    results = reb.rebalance()
    assert all(r["ok"] for r in results)
    assert _get(rb.url, "/healthz")["adapters"] == ["t0", "t1"]
    # converged: the next scrape+plan has nothing left to move
    col.scrape()
    assert reb.plan() == []
    # the moved copies serve (same tokens as the source's)
    _, a = _post(ra.url, "/generate", dict(body, adapter="t0",
                                           request_id="pa"))
    _, b = _post(rb.url, "/generate", dict(body, adapter="t0",
                                           request_id="pb"))
    assert a["tokens"] == b["tokens"]
    # failure isolation: a dead destination reports, never raises
    col2 = FleetCollector(urls=[ra.url], interval_s=0)
    fleet_cleanup.append(col2)
    col2.scrape()
    dead = [{"action": "spread", "model": "m", "adapter": "t0",
             "src": ra.url, "dst": "http://127.0.0.1:9"}]
    rows = CatalogRebalancer(col2, timeout_s=2.0).apply(dead)
    assert len(rows) == 1 and rows[0]["ok"] is False
    assert rows[0]["error"]


def test_retire_idle_policy(model, fleet_cleanup):
    ra = _adapter_replica(model, "ra", "m", adapters=("hot", "cold"))
    fleet_cleanup.append(ra)
    body = {"prompt": _prompt().tolist(), "max_new_tokens": 4}
    _post(ra.url, "/generate", dict(body, adapter="hot",
                                    request_id="h0"))
    col = FleetCollector(urls=[ra.url], interval_s=0)
    fleet_cleanup.append(col)
    col.scrape()
    # default policy never retires (zero traffic must not de-catalog
    # a freshly loaded adapter); opt-in retires exactly the idle one
    assert CatalogRebalancer(col).plan() == []
    moves = CatalogRebalancer(col, retire_idle=True).plan()
    assert moves == [{"action": "retire", "model": "m",
                      "adapter": "cold", "src": ra.url, "dst": None}]
    rows = CatalogRebalancer(col, retire_idle=True).apply(moves)
    assert rows[0]["ok"] is True
    assert _get(ra.url, "/healthz")["adapters"] == ["hot"]


def test_supervisor_rebalance_catalog_actuator(model, fleet_cleanup):
    ra = _adapter_replica(model, "ra", "m", adapters=("t0",))
    rb = _adapter_replica(model, "rb", "m")
    fleet_cleanup.extend([ra, rb])
    body = {"prompt": _prompt().tolist(), "max_new_tokens": 4}
    _post(ra.url, "/generate", dict(body, adapter="t0",
                                    request_id="s0"))
    col = FleetCollector(urls=[ra.url, rb.url], interval_s=0)
    fleet_cleanup.append(col)
    col.scrape()
    # no attached rebalancer: a clean no-op
    sup = Supervisor(lambda slot: None, 0, collector=col)
    assert sup.rebalance_catalog() == []
    sup = Supervisor(lambda slot: None, 0, collector=col,
                     catalog=CatalogRebalancer(col))
    results = sup.rebalance_catalog(reason="scale_up_decode")
    assert [r["adapter"] for r in results] == ["t0"]
    assert all(r["ok"] for r in results)
    assert _get(rb.url, "/healthz")["adapters"] == ["t0"]
    kinds = [a["kind"] for a in col.fleet_view()["annotations"]]
    assert "catalog_rebalance" in kinds
