"""SFrame bridge (mxnet_tpu/sframe.py — plugin/sframe analog): duck-typed
columnar-frame iteration, multi-column concat, image mean/scale."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.sframe import SFrameImageIter, SFrameIter


class FakeFrame:
    """Minimal columnar frame: frame[col] -> list of rows."""

    def __init__(self, cols):
        self._cols = cols

    def __getitem__(self, name):
        return self._cols[name]


def test_sframe_iter_single_column():
    rng = np.random.RandomState(0)
    X = rng.rand(10, 4).astype(np.float32)
    y = rng.randint(0, 2, 10).astype(np.float32)
    frame = FakeFrame({"feat": list(X), "target": list(y)})
    it = SFrameIter(frame, data_field="feat", label_field="target",
                    batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), X[:5])
    np.testing.assert_allclose(batches[1].label[0].asnumpy(), y[5:])


def test_sframe_iter_multi_column_concat():
    frame = FakeFrame({"a": [[1.0, 2.0], [3.0, 4.0]],
                       "b": [[5.0], [6.0]],
                       "y": [0.0, 1.0]})
    it = SFrameIter(frame, data_field=["a", "b"], label_field="y",
                    batch_size=2)
    batch = next(iter(it))
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               [[1, 2, 5], [3, 4, 6]])


def test_sframe_image_iter_mean_scale():
    rng = np.random.RandomState(1)
    imgs = [rng.rand(3, 4, 4).astype(np.float32) for _ in range(4)]
    frame = FakeFrame({"img": imgs, "y": [0.0, 1.0, 0.0, 1.0]})
    it = SFrameImageIter(frame, data_field="img", label_field="y",
                         batch_size=2, mean=0.5, scale=2.0)
    batch = next(iter(it))
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               (np.stack(imgs[:2]) - 0.5) * 2.0, rtol=1e-6)


def test_sframe_iter_trains_module():
    mx.random.seed(7)
    rng = np.random.RandomState(2)
    X = rng.rand(64, 8).astype(np.float32)
    w = rng.rand(8)
    y = (X @ w > np.median(X @ w)).astype(np.float32)
    frame = FakeFrame({"x": list(X), "y": list(y)})
    it = SFrameIter(frame, data_field="x", label_field="y", batch_size=16)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=25, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.5})
    score = dict(mod.score(it, mx.metric.create("acc")))
    assert score["accuracy"] > 0.8


def test_sframe_errors():
    frame = FakeFrame({"a": [[1.0], [2.0]], "ragged": [[1.0], [1.0, 2.0]]})
    with pytest.raises(MXNetError):
        SFrameIter(frame, data_field="missing", batch_size=1)
    with pytest.raises(MXNetError):
        SFrameIter(frame, data_field="ragged", batch_size=1)


def test_sframe_pandas_dataframe():
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({"f": [1.0, 2.0, 3.0, 4.0], "y": [0, 1, 0, 1]})
    it = SFrameIter(df, data_field="f", label_field="y", batch_size=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 1) or batch.data[0].shape == (2,)
