"""Worker script for the distributed kvstore exactness test (rebuild of
tests/nightly/dist_sync_kvstore.py): each rank pushes deterministic
values; every rank must observe the exact global sum each round.

Launched by test_dist.py via tools/launch.py -n N.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import mxnet_tpu as mx


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker == int(os.environ["MXTPU_NUM_PROCS"])

    shape = (5, 7)
    big_shape = (1200, 1100)  # the big-key striping path analog
    kv.init(3, mx.nd.ones(shape))
    kv.init(99, mx.nd.ones(big_shape))
    kv.barrier()

    for round_i in range(4):
        scale = rank + round_i + 1
        kv.push(3, mx.nd.ones(shape) * scale)
        kv.push(99, mx.nd.ones(big_shape) * scale)
        # expected exact sum over ranks: sum_{r}(r + round_i + 1)
        expect = sum(r + round_i + 1 for r in range(nworker))
        out = mx.nd.zeros(shape)
        kv.pull(3, out)
        np.testing.assert_array_equal(out.asnumpy(), expect)
        big = mx.nd.zeros(big_shape)
        kv.pull(99, big)
        np.testing.assert_array_equal(big.asnumpy(), expect)
        kv.barrier()

    print(f"RANK_{rank}_OK")


if __name__ == "__main__":
    main()
