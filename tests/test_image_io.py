"""ImageRecordIter pipeline over a synthetic packed .rec dataset
(rebuild of tests/python/unittest/test_io.py's ImageRecordIter case)."""

import os
import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image_io import ImageRecordIter


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("data") / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(48):
        label = i % 4
        img = np.full((40, 40, 3), label * 60, np.uint8)
        img += rng.randint(0, 10, img.shape).astype(np.uint8)
        header = recordio.IRHeader(0, float(label), i, 0)
        writer.write(recordio.pack_img(header, img, quality=90))
    writer.close()
    return path


def test_image_record_iter_basic(rec_file):
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                         batch_size=8, preprocess_threads=2)
    batches = list(iter_epoch(it))
    assert len(batches) == 6
    b = batches[0]
    assert b.data[0].shape == (8, 3, 32, 32)
    assert b.label[0].shape == (8,)
    # labels preserved through pack/decode
    np.testing.assert_allclose(b.label[0].asnumpy(), np.arange(8) % 4)
    # pixel content approximately label*60 (jpeg lossy)
    img0 = b.data[0].asnumpy()[1]
    assert abs(img0.mean() - 60) < 15


def iter_epoch(it):
    while True:
        try:
            yield it.next()
        except StopIteration:
            return


def test_image_record_iter_reset_and_shuffle(rec_file):
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                         batch_size=16, shuffle=True, preprocess_threads=2)
    e1 = [b.label[0].asnumpy().copy() for b in iter_epoch(it)]
    e2 = [b.label[0].asnumpy().copy() for b in iter_epoch(it)]
    assert len(e1) == len(e2) == 3
    assert not all((a == b).all() for a, b in zip(e1, e2))


def test_image_record_iter_sharding(rec_file):
    it0 = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                          batch_size=8, part_index=0, num_parts=2,
                          preprocess_threads=1)
    it1 = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                          batch_size=8, part_index=1, num_parts=2,
                          preprocess_threads=1)
    l0 = np.concatenate([b.label[0].asnumpy() for b in iter_epoch(it0)])
    l1 = np.concatenate([b.label[0].asnumpy() for b in iter_epoch(it1)])
    assert len(l0) == len(l1) == 24
    np.testing.assert_allclose(l0, np.arange(0, 48, 2) % 4)
    np.testing.assert_allclose(l1, np.arange(1, 48, 2) % 4)


def test_image_record_iter_augment(rec_file):
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 24, 24),
                         batch_size=8, rand_crop=True, rand_mirror=True,
                         scale=1.0 / 255, preprocess_threads=2)
    b = next(iter_epoch(it))
    assert b.data[0].shape == (8, 3, 24, 24)
    assert float(b.data[0].asnumpy().max()) <= 1.0


def test_mean_subtract(rec_file):
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                         batch_size=8, mean_r=30, mean_g=30, mean_b=30,
                         preprocess_threads=1)
    b = next(iter_epoch(it))
    img0 = b.data[0].asnumpy()[0]  # label 0: pixels ~0..10 minus mean 30
    assert img0.mean() < 0


# -- OpenCV bridge (plugin/opencv parity) -----------------------------------
def test_cv_imdecode_resize_border(tmp_path):
    import cv2

    img = (np.arange(32 * 48 * 3) % 255).reshape(32, 48, 3).astype(np.uint8)
    ok, enc = cv2.imencode(".png", img)
    assert ok
    dec = mx.cv.imdecode(enc.tobytes())
    np.testing.assert_array_equal(dec.asnumpy(), img)

    small = mx.cv.resize(dec, (24, 16))
    assert small.shape == (16, 24, 3)

    padded = mx.cv.copyMakeBorder(dec, 2, 2, 3, 3)
    assert padded.shape == (36, 54, 3)
    np.testing.assert_array_equal(padded.asnumpy()[2:-2, 3:-3], img)


def test_cv_crops_and_normalize():
    rng2 = np.random.RandomState(3)
    img = mx.nd.array(rng2.randint(0, 255, (40, 60, 3)), dtype=np.uint8)
    crop = mx.cv.fixed_crop(img, 5, 4, 20, 10)
    assert crop.shape == (10, 20, 3)
    out, (x0, y0, w, h) = mx.cv.random_crop(img, (30, 20))
    assert out.shape == (20, 30, 3)
    out2, _ = mx.cv.random_size_crop(img, (16, 16))
    assert out2.shape == (16, 16, 3)
    norm = mx.cv.color_normalize(img, mean=(1.0, 2.0, 3.0))
    np.testing.assert_allclose(norm.asnumpy()[0, 0],
                               img.asnumpy()[0, 0] - [1, 2, 3])


def test_cv_image_list_iter(tmp_path):
    import cv2

    root = tmp_path / "imgs"
    root.mkdir()
    lines = []
    for i in range(4):
        img = np.full((10 + i, 12, 3), i * 10, np.uint8)
        cv2.imwrite(str(root / f"im{i}.png"), img)
        lines.append(f"{i}\t{float(i)}\tim{i}.png")
    flist = tmp_path / "list.lst"
    flist.write_text("\n".join(lines) + "\n")

    it = mx.cv.ImageListIter(str(root), str(flist), batch_size=2,
                             size=(8, 8))
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (2, 8, 8, 3)
    assert batches[0].label[0].asnumpy().tolist() == [0.0, 1.0]


def test_cv_preserves_float_dtype():
    img = mx.nd.array(np.full((8, 8, 3), 300.0, np.float32))
    out = mx.cv.resize(img, (4, 4))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out.asnumpy(), 300.0)   # no uint8 wraparound
    pad = mx.cv.copyMakeBorder(img, 1, 1, 1, 1)
    assert pad.dtype == np.float32


def test_image_record_iter_mean_image_first_run(rec_file, tmp_path):
    """mean_img is computed over the partition and saved on first run,
    then loaded on subsequent runs (iter_normalize.h behavior)."""
    mean_path = str(tmp_path / "mean.params")
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                         batch_size=8, mean_img=mean_path)
    assert os.path.exists(mean_path)
    mean = mx.nd.load(mean_path)["mean_img"].asnumpy()
    assert mean.shape == (3, 32, 32)
    assert 0 < mean.mean() < 255
    batch = next(iter(it))
    # images are mean-subtracted: batch mean is near zero vs raw ~90
    assert abs(batch.data[0].asnumpy().mean()) < 30
    it2 = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 32, 32),
                          batch_size=8, mean_img=mean_path)
    np.testing.assert_allclose(it2._mean, mean)


def test_native_pipeline_active_and_matches_python(tmp_path):
    """The C++ pipeline (src/image_pipeline.cc) must be the active
    producer for standard configs, and deterministic configs must
    produce identical batches to the Python chain.  PNG records: JPEG
    decode differs by a few LSB between the cv2 wheel's bundled OpenCV
    and the system OpenCV the native pipeline links, so lossless input
    is what makes bit-parity a fair contract."""
    from mxnet_tpu.libinfo import find_lib

    lib = find_lib()
    if lib is None or not lib.MXTPUImgPipeAvailable():
        pytest.skip("native image pipeline unavailable")

    path = str(tmp_path / "parity.rec")
    writer = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(3)
    for i in range(24):
        img = rng.randint(0, 255, (40, 40, 3)).astype(np.uint8)
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i % 4), i, 0), img, quality=3,
            img_fmt=".png"))
    writer.close()

    kwargs = dict(path_imgrec=path, data_shape=(3, 32, 32),
                  batch_size=8, preprocess_threads=2,
                  mean_r=10.0, mean_g=20.0, mean_b=30.0, scale=1 / 255.0)
    it_native = ImageRecordIter(**kwargs)
    assert it_native._native_eligible()
    os.environ["MXNET_TPU_NATIVE_IMAGE"] = "0"
    try:
        it_py = ImageRecordIter(**kwargs)
        assert not it_py._native_eligible()
    finally:
        del os.environ["MXNET_TPU_NATIVE_IMAGE"]

    for bn, bp in zip(iter_epoch(it_native), iter_epoch(it_py)):
        np.testing.assert_allclose(bn.data[0].asnumpy(),
                                   bp.data[0].asnumpy(), atol=1e-5)
        np.testing.assert_allclose(bn.label[0].asnumpy(),
                                   bp.label[0].asnumpy())


def test_native_pipeline_rand_augment_and_epochs(rec_file):
    """Random crop/mirror via the native path: right shapes, values in
    the normalized range, stable across epochs."""
    from mxnet_tpu.libinfo import find_lib

    lib = find_lib()
    if lib is None or not lib.MXTPUImgPipeAvailable():
        pytest.skip("native image pipeline unavailable")
    it = ImageRecordIter(path_imgrec=rec_file, data_shape=(3, 24, 24),
                         batch_size=16, preprocess_threads=3, resize=28,
                         rand_crop=True, rand_mirror=True, shuffle=True,
                         scale=1 / 255.0)
    assert it._native_eligible()
    for _ in range(3):
        batches = list(iter_epoch(it))
        assert len(batches) == 3
        arr = batches[0].data[0].asnumpy()
        assert arr.shape == (16, 3, 24, 24)
        assert 0.0 <= arr.min() and arr.max() <= 1.0


def test_native_pipeline_label_vector(tmp_path):
    """flag>0 records (label vectors) decode through the native path."""
    from mxnet_tpu.libinfo import find_lib

    lib = find_lib()
    if lib is None or not lib.MXTPUImgPipeAvailable():
        pytest.skip("native image pipeline unavailable")
    path = str(tmp_path / "vec.rec")
    writer = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 255, (16, 16, 3)).astype(np.uint8)
        header = recordio.IRHeader(0, np.array([i, i + 0.5], np.float32),
                                   i, 0)
        writer.write(recordio.pack_img(header, img, quality=95))
    writer.close()
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                         batch_size=4, label_width=2, preprocess_threads=2)
    assert it._native_eligible()
    b = next(iter(it))
    assert b.label[0].shape == (4, 2)
    np.testing.assert_allclose(b.label[0].asnumpy(),
                               [[0, 0.5], [1, 1.5], [2, 2.5], [3, 3.5]])


def test_native_pipeline_resize_parity(tmp_path):
    """resize geometry must truncate identically on both paths (PNG for
    lossless decode)."""
    from mxnet_tpu.libinfo import find_lib

    lib = find_lib()
    if lib is None or not lib.MXTPUImgPipeAvailable():
        pytest.skip("native image pipeline unavailable")
    path = str(tmp_path / "rs.rec")
    writer = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(5)
    for i in range(8):
        img = rng.randint(0, 255, (20, 23, 3)).astype(np.uint8)
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=3,
            img_fmt=".png"))
    writer.close()
    kwargs = dict(path_imgrec=path, data_shape=(3, 16, 16), batch_size=4,
                  preprocess_threads=2, resize=26)
    it_native = ImageRecordIter(**kwargs)
    os.environ["MXNET_TPU_NATIVE_IMAGE"] = "0"
    try:
        it_py = ImageRecordIter(**kwargs)
    finally:
        del os.environ["MXNET_TPU_NATIVE_IMAGE"]
    for bn, bp in zip(iter_epoch(it_native), iter_epoch(it_py)):
        np.testing.assert_allclose(bn.data[0].asnumpy(),
                                   bp.data[0].asnumpy(), atol=1e-5)


def test_native_pipeline_error_surfaces(tmp_path, rec_file):
    """A corrupt record must raise in the consumer, not hang it."""
    import shutil

    from mxnet_tpu.libinfo import find_lib

    lib = find_lib()
    if lib is None or not lib.MXTPUImgPipeAvailable():
        pytest.skip("native image pipeline unavailable")
    path = str(tmp_path / "bad.rec")
    shutil.copyfile(rec_file, path)
    with open(path, "r+b") as f:  # clobber a record header mid-file
        f.seek(3000)
        f.write(b"\xde\xad\xbe\xef" * 40)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                         batch_size=8, preprocess_threads=2)
    with pytest.raises(Exception):
        for _ in range(12):
            it.next()


def test_image_record_iter_round_batch(tmp_path):
    # reference iter_batchloader.h round_batch: a ragged epoch ends in a
    # batch completed by wrap-around, with DataBatch.pad = fill count
    path = str(tmp_path / "small.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(10):
        img = np.full((24, 24, 3), (i % 4) * 50, np.uint8)
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i % 4), i, 0), img, quality=90))
    writer.close()

    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                         batch_size=16, preprocess_threads=1)
    batches = list(iter_epoch(it))
    assert len(batches) == 1
    assert batches[0].pad == 6
    assert batches[0].data[0].shape == (16, 3, 24, 24)
    # wrapped rows repeat the epoch head
    lab = batches[0].label[0].asnumpy()
    np.testing.assert_allclose(lab[10:], lab[:6])

    # round_batch=False on an undersized shard raises like before
    import pytest as _pytest
    it2 = ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                          batch_size=16, preprocess_threads=1,
                          round_batch=False)
    with _pytest.raises(Exception):
        list(iter_epoch(it2))


def test_augmenter_affine_scale_aspect_shear(tmp_path):
    """The reference's affine-family augmentations (random scale, aspect
    ratio, shear, size clamping, pad, random-size crop) produce valid
    target-shaped outputs and actually vary geometry."""
    from mxnet_tpu.image_io import ImageAugmenter

    rng = np.random.RandomState(0)
    img = np.zeros((80, 80, 3), np.uint8)
    img[20:60, 20:60] = 200  # bright square to track geometry

    aug = ImageAugmenter((3, 32, 32), rand_crop=True,
                         max_random_scale=1.5, min_random_scale=0.7,
                         max_aspect_ratio=0.25, max_shear_ratio=0.1,
                         max_rotate_angle=10)
    outs = [aug(img, rng) for _ in range(8)]
    assert all(o.shape == (32, 32, 3) for o in outs)
    means = [float(o.mean()) for o in outs]
    assert max(means) - min(means) > 1.0  # geometry actually varies

    # random-size square crop path
    aug2 = ImageAugmenter((3, 32, 32), rand_crop=True,
                          max_crop_size=64, min_crop_size=40)
    o2 = aug2(img, rng)
    assert o2.shape == (32, 32, 3)

    # pad + size clamping
    aug3 = ImageAugmenter((3, 32, 32), pad=4, max_random_scale=3.0,
                          min_random_scale=3.0, max_img_size=100)
    o3 = aug3(img, rng)
    assert o3.shape == (32, 32, 3)
