"""DCN multi-slice mesh layout (rebuild of the reference's multi-machine
kvstore topology concerns, kvstore_dist.h: workers within a machine pool
over PCIe, machines meet over the network; TPU-equivalent: chips within
a slice meet over ICI, slices over DCN — SURVEY §2.4 TPU-equivalent (b)).

``make_hybrid_mesh`` puts DCN axes outermost and keeps every ICI axis
inside one slice.  On the 8-device virtual CPU mesh the slice grouping
falls back to contiguous blocks, which is exactly what lets the driver
dry-run the layout without multi-slice hardware.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx


def test_hybrid_mesh_layout():
    mesh = mx.parallel.make_hybrid_mesh({"dp": 2}, {"tp": 4})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"dp": 2, "tp": 4}
    # each tp row must be one contiguous slice block: tp collectives
    # may never cross a slice boundary
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert ids.tolist() == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_hybrid_mesh_ici_wildcard_and_three_axes():
    mesh = mx.parallel.make_hybrid_mesh({"dp": 2}, {"pp": 2, "tp": -1})
    assert mesh.devices.shape == (2, 2, 2)
    assert mesh.axis_names == ("dp", "pp", "tp")
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    # slice 0 = devices 0-3, slice 1 = devices 4-7, dcn outermost
    assert ids[0].max() < 4 <= ids[1].min()


def test_hybrid_mesh_errors():
    with pytest.raises(ValueError, match="concrete"):
        mx.parallel.make_hybrid_mesh({"dp": -1}, {"tp": 4})
    with pytest.raises(ValueError, match="equal slices"):
        mx.parallel.make_hybrid_mesh({"dp": 3}, {"tp": 2})
    with pytest.raises(ValueError, match="chips/slice"):
        mx.parallel.make_hybrid_mesh({"dp": 2}, {"tp": 8})
    # undersized ici spec must be loud, not silently idle half the slice
    with pytest.raises(ValueError, match="absorb the remainder"):
        mx.parallel.make_hybrid_mesh({"dp": 2}, {"tp": 2})


def test_slice_groups_uses_slice_index_attribute():
    """Real multi-slice runtimes expose slice_index; it must win over
    positional order (devices can enumerate interleaved)."""
    from mxnet_tpu.parallel.mesh import _slice_groups

    class Dev:
        def __init__(self, id, slice_index):
            self.id = id
            self.slice_index = slice_index

        def __repr__(self):
            return f"Dev({self.id},s{self.slice_index})"

    # interleaved enumeration: 0,1 in slice0; 2,3 in slice1; etc.
    devs = [Dev(0, 0), Dev(2, 1), Dev(1, 0), Dev(3, 1)]
    groups = _slice_groups(devs)
    assert [[d.id for d in g] for g in groups] == [[0, 1], [2, 3]]
    # cross-check against a wrong caller expectation
    with pytest.raises(ValueError, match="span 2 slices"):
        _slice_groups(devs, n_slices=4)
    # a mixed list (some devices without the attribute) is a caller bug
    class Bare:
        def __init__(self, id):
            self.id = id
    with pytest.raises(ValueError, match="mixed device list"):
        _slice_groups(devs + [Bare(4), Bare(5)], n_slices=3)


def test_hybrid_mesh_topology_aware_ici_on_real_slices(monkeypatch):
    """When devices carry slice_index (real multi-slice hardware), each
    slice's ICI sub-grid must be built by mesh_utils.create_device_mesh
    (physical-torus-aware ordering), not the id-sorted reshape; virtual
    devices (no slice_index) keep the contiguous-block fallback."""
    from jax.experimental import mesh_utils

    from mxnet_tpu.parallel import mesh as mesh_mod

    class Dev:
        # enough surface for Mesh bookkeeping; no topology attributes,
        # so an un-monkeypatched create_device_mesh would raise and the
        # wiring under test would silently fall back (asserted against)
        def __init__(self, id, slice_index):
            self.id = id
            self.slice_index = slice_index
            self.platform = "tpu"
            self.process_index = 0

        def __repr__(self):
            return f"Dev({self.id})"

    calls = []
    real = mesh_utils.create_device_mesh

    def tracking(mesh_shape, devices=None, **kw):
        calls.append((tuple(mesh_shape), [d.id for d in devices]))
        # reversed order stands in for a topology-aware permutation —
        # the mesh must adopt it, proving the sub-grid came from here
        return np.asarray(list(reversed(devices)),
                          dtype=object).reshape(mesh_shape)

    monkeypatch.setattr(mesh_utils, "create_device_mesh", tracking)
    try:
        devs = [Dev(i, i // 4) for i in range(8)]
        mesh = mesh_mod.make_hybrid_mesh({"dp": 2}, {"pp": 2, "tp": 2},
                                         devices=devs)
    finally:
        monkeypatch.setattr(mesh_utils, "create_device_mesh", real)
    assert calls == [((2, 2), [0, 1, 2, 3]), ((2, 2), [4, 5, 6, 7])]
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert ids.tolist() == [[[3, 2], [1, 0]], [[7, 6], [5, 4]]]
    # virtual devices (the 8-CPU test mesh): no topology call, id order
    calls.clear()
    monkeypatch.setattr(mesh_utils, "create_device_mesh", tracking)
    mesh2 = mesh_mod.make_hybrid_mesh({"dp": 2}, {"tp": 4})
    assert calls == []
    ids2 = np.vectorize(lambda d: d.id)(mesh2.devices)
    assert ids2.tolist() == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_hybrid_mesh_trainer_matches_dp():
    """dp-over-DCN x tp-over-ICI sharding must not change the math."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 16).astype(np.float32)
    W = rng.randn(16, 4).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    net = mx.models.mlp(num_classes=4)

    def build(mesh, specs):
        mx.random.seed(0)
        np.random.seed(0)
        return mx.parallel.ShardedTrainer(
            net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
            param_specs=specs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.initializer.Xavier())

    t1 = build(mx.parallel.make_mesh({"dp": 8}), None)
    t2 = build(mx.parallel.make_hybrid_mesh({"dp": 2}, {"tp": 4}),
               {"fc1_weight": P("tp", None), "fc2_weight": P(None, "tp")})
    batch = {"data": X, "softmax_label": y}
    for _ in range(3):
        t1.step(batch)
        t2.step(batch)
    p1, p2 = t1.get_params(), t2.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], atol=2e-5, rtol=1e-4)
