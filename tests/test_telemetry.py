"""Unified telemetry layer tests (mxnet_tpu/telemetry).

Covers the registry semantics (labels, histogram buckets, kind/schema
consistency), the Chrome-trace tracer (JSON validity, span nesting,
pid/tid/ts fields), the Prometheus exposition golden output, the
fit-loop / io / serve instrumentation, and the two contracts the rest
of the repo relies on:

  * disabled path: with MXTPU_TELEMETRY unset, every instrumented call
    site resolves the shared no-op objects (near-zero overhead guard)
  * bench records: serve_bench payloads and bench_watch attempts-log
    lines carry the ``telemetry`` snapshot field
"""

import json
import logging
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.io import NDArrayIter, PrefetchingIter
from mxnet_tpu.telemetry import Registry


@pytest.fixture
def tel():
    """Enabled telemetry on a clean registry; restores disabled-empty."""
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.disable()
    telemetry.reset()


# -- registry semantics ------------------------------------------------------
def test_counter_labels_and_increments():
    r = Registry()
    c = r.counter("req_total", "requests", ("route",))
    c.labels(route="/a").inc()
    c.labels(route="/a").inc(3)
    c.labels("/b").inc()
    assert c.labels(route="/a").value == 4
    assert c.labels(route="/b").value == 1
    with pytest.raises(ValueError):
        c.labels(route="/a").inc(-1)          # counters only increase
    with pytest.raises(ValueError):
        c.labels(wrong="x")                   # label-name schema enforced
    with pytest.raises(ValueError):
        c.inc()                               # labeled family needs a child


def test_gauge_set_inc_dec():
    r = Registry()
    g = r.gauge("depth")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.labels().value == 8


def test_histogram_bucket_semantics():
    r = Registry()
    h = r.histogram("lat", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    child = h.labels()
    assert child.count == 4
    assert child.sum == pytest.approx(55.55)
    # cumulative le counts, +Inf last
    assert child.cumulative() == [(0.1, 1), (1.0, 2), (10.0, 3),
                                  (float("inf"), 4)]
    # boundary lands in its own bucket (le is inclusive)
    h2 = r.histogram("lat2", buckets=(1.0, 2.0))
    h2.observe(1.0)
    assert h2.labels().cumulative()[0] == (1.0, 1)


def test_registry_consistency_enforced():
    r = Registry()
    c = r.counter("x_total", "x", ("a",))
    assert r.counter("x_total", "x", ("a",)) is c      # get-or-create
    with pytest.raises(ValueError):
        r.gauge("x_total")                             # kind mismatch
    with pytest.raises(ValueError):
        r.counter("x_total", label_names=("b",))       # schema mismatch
    h = r.histogram("h_seconds", buckets=(1.0, 5.0))
    assert r.histogram("h_seconds", buckets=(1.0, 5.0)) is h
    with pytest.raises(ValueError):
        r.histogram("h_seconds", buckets=(0.1, 1.0))   # bucket mismatch


# -- disabled path (the overhead-guard contract) -----------------------------
def test_disabled_returns_noop_objects():
    assert not telemetry.enabled()
    assert telemetry.counter("anything_total") is telemetry.NOOP
    assert telemetry.gauge("anything") is telemetry.NOOP
    assert telemetry.histogram("anything_seconds") is telemetry.NOOP
    assert telemetry.span("anything") is telemetry.NOOP_SPAN
    # chainable and inert
    telemetry.NOOP.labels(a=1).inc()
    telemetry.NOOP.observe(3.0)
    with telemetry.span("x"):
        pass
    assert telemetry.registry().snapshot() == {}


def test_disabled_instrumented_sites_use_noop():
    """With MXTPU_TELEMETRY unset, the iterator, serve-stats and
    fit-loop call sites must all hold the shared no-op objects and the
    registry must stay empty."""
    assert not telemetry.enabled()
    it = NDArrayIter(np.zeros((8, 3), np.float32),
                     np.zeros(8, np.float32), batch_size=4)
    for _ in it:
        pass
    assert it._tel_batches is telemetry.NOOP

    rec = mx.serve.stats.StatsRecorder()
    assert rec._m_steps is telemetry.NOOP
    assert rec._m_ttft is telemetry.NOOP

    _fit_tiny_mlp(num_epoch=1)
    assert telemetry.registry().snapshot() == {}
    assert telemetry.tracer().trace_events() == [
        {"name": "process_name", "ph": "M",
         "pid": os.getpid(), "args": {"name": "mxtpu host"}}]


# -- tracer ------------------------------------------------------------------
def test_chrome_trace_json_valid_and_nested(tel, tmp_path):
    with tel.span("outer", step=1):
        with tel.span("inner"):
            pass
    path = tel.tracer().write(str(tmp_path / "trace.json"))
    payload = json.load(open(path))
    events = payload["traceEvents"]
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(xs) == {"outer", "inner"}
    for e in xs.values():
        assert e["pid"] == os.getpid()
        assert isinstance(e["tid"], int)
        assert e["ts"] >= 0 and e["dur"] >= 0
    # spans nest: inner inside outer's [ts, ts+dur]
    outer, inner = xs["outer"], xs["inner"]
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
    assert xs["outer"]["args"] == {"step": 1}
    # Perfetto track metadata present
    metas = {e["name"] for e in events if e["ph"] == "M"}
    assert {"process_name", "thread_name"} <= metas


def test_traced_decorator(tel):
    @telemetry.traced("work")
    def fn(x):
        return x + 1

    assert fn(1) == 2
    names = [e["name"] for e in tel.tracer().trace_events()
             if e["ph"] == "X"]
    assert names == ["work"]


def test_tracer_event_cap(tel):
    tr = telemetry.SpanTracer(max_events=2)
    for i in range(4):
        tr.add_complete("e", 0.0, 1.0)
    assert len([e for e in tr.trace_events() if e["ph"] == "X"]) == 2
    assert tr.dropped == 2


# -- exporters ---------------------------------------------------------------
def test_prometheus_exposition_golden():
    r = Registry()
    r.counter("req_total", "requests served", ("route",)).labels(
        route="/a").inc(4)
    r.gauge("depth").set(6)
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert telemetry.to_prometheus_text(r) == (
        "# TYPE depth gauge\n"
        "depth 6\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_sum 5.55\n"
        "lat_seconds_count 3\n"
        "# HELP req_total requests served\n"
        "# TYPE req_total counter\n"
        'req_total{route="/a"} 4\n')


def test_prometheus_label_escape_roundtrip():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import metrics_report

    r = Registry()
    nasty = 'dir\\name "q"\nline2'
    r.counter("esc_total", "", ("path",)).labels(path=nasty).inc()
    parsed = metrics_report.parse_prometheus_text(
        telemetry.to_prometheus_text(r))
    assert parsed["esc_total"]["samples"][0]["labels"]["path"] == nasty


def test_dump_and_http_endpoint(tel, tmp_path):
    tel.counter("x_total", "x").inc()
    with tel.span("s"):
        pass
    paths = tel.dump(str(tmp_path / "out"))
    assert "x_total 1" in open(paths["prometheus"]).read()
    line = json.loads(open(paths["jsonl"]).read())
    assert line["metrics"]["x_total"]["samples"][0]["value"] == 1
    json.load(open(paths["trace"]))          # valid JSON

    import urllib.request

    server = tel.serve_http(tel.registry(), 0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "x_total 1" in body
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10).read())
        assert js["x_total"]["samples"][0]["value"] == 1
    finally:
        server.shutdown()


# -- instrumented hot paths --------------------------------------------------
def _fit_tiny_mlp(num_epoch=1, batches=4, batch_size=16):
    rng = np.random.RandomState(0)
    X = rng.randn(batches * batch_size, 10).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=batch_size)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    # pin the CLASSIC loop: these tests contract the per-phase
    # instrumentation of the unfused path (the fused single-dispatch
    # path has its own phase contract in tests/test_fused_step.py)
    os.environ["MXTPU_FUSED_STEP"] = "0"
    try:
        mod.fit(it, num_epoch=num_epoch, kvstore=None)
    finally:
        os.environ.pop("MXTPU_FUSED_STEP", None)
    return batches * num_epoch


def test_fit_loop_phase_metrics(tel):
    n = _fit_tiny_mlp(num_epoch=2)
    snap = tel.registry().snapshot()
    assert snap["mxtpu_fit_batches_total"]["samples"][0]["value"] == n
    assert snap["mxtpu_fit_epochs_total"]["samples"][0]["value"] == 2
    assert snap["mxtpu_fit_epoch_seconds"]["samples"][0]["count"] == 2
    phases = {s["labels"]["phase"]: s["count"]
              for s in snap["mxtpu_fit_phase_seconds"]["samples"]}
    assert phases == {"data_wait": n, "forward_backward": n,
                      "update": n, "update_metric": n}
    # the iterator-side counter agrees with the loop-side one
    assert snap["mxtpu_io_batches_total"]["samples"][0]["value"] == n
    # host spans for every phase + the enclosing epoch span
    names = {e["name"] for e in tel.tracer().trace_events()
             if e["ph"] == "X"}
    assert {"fit.data_wait", "fit.forward_backward", "fit.update",
            "fit.update_metric", "fit.epoch"} <= names
    # jax.monitoring bridge: compiling the step program left compile
    # events in the registry
    assert snap["mxtpu_jax_events_total"]["samples"]


def test_prefetching_iter_wait_metric(tel):
    X = np.arange(32, dtype=np.float32).reshape(8, 4)
    base = NDArrayIter(X, np.zeros(8, np.float32), batch_size=4)
    pf = PrefetchingIter(base)
    n = sum(1 for _ in pf)
    assert n == 2
    snap = tel.registry().snapshot()
    wait = [s for s in snap["mxtpu_io_wait_seconds"]["samples"]
            if s["labels"]["iterator"] == "PrefetchingIter"]
    assert wait and wait[0]["count"] >= n
    produced = {s["labels"]["iterator"]: s["value"]
                for s in snap["mxtpu_io_batches_total"]["samples"]}
    assert produced["PrefetchingIter"] == n


# -- serve bridge ------------------------------------------------------------
VOCAB = 53


@pytest.fixture(scope="module")
def serve_model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def test_serve_engine_registry_bridge(tel, serve_model):
    net, params = serve_model
    eng = mx.serve.Engine(params, symbol=net, block_size=4, num_blocks=64,
                          max_batch=4, max_model_len=64,
                          max_prefills_per_step=2)
    rng = np.random.RandomState(7)
    for n in (8, 12, 16):
        eng.submit(rng.randint(0, VOCAB, (n,)).astype(np.int32),
                   max_new_tokens=6)
    eng.run()
    stats = eng.stats()
    snap = tel.registry().snapshot()

    def value(name):
        return snap[name]["samples"][0]["value"]

    # Prometheus counters and the ServeStats snapshot agree
    assert value("mxtpu_serve_steps_total") == stats.steps
    assert value("mxtpu_serve_tokens_generated_total") == \
        stats.tokens_generated
    assert value("mxtpu_serve_completed_total") == stats.completed == 3
    assert value("mxtpu_serve_prompt_tokens_total") == stats.prompt_tokens
    assert snap["mxtpu_serve_ttft_seconds"]["samples"][0]["count"] == 3
    assert value("mxtpu_serve_blocks_total") == stats.blocks_total
    # drained engine: live gauges read empty
    assert value("mxtpu_serve_queue_depth") == 0
    assert value("mxtpu_serve_running") == 0
    names = {e["name"] for e in tel.tracer().trace_events()
             if e["ph"] == "X"}
    assert {"serve.step", "serve.prefill", "serve.decode"} <= names
    eng.shutdown()


# -- monitor / profiler satellites -------------------------------------------
def test_serve_monitor_formats_none_and_rounds(serve_model, caplog):
    net, params = serve_model

    class _FakeEngine:
        def __init__(self, **overrides):
            from mxnet_tpu.serve.stats import ServeStats

            base = dict(steps=5, queue_depth=1, running=2, completed=3,
                        rejected=0, preemptions=0, evictions=0,
                        tokens_generated=10, prompt_tokens=12,
                        blocks_in_use=4, blocks_total=8,
                        block_utilization=0.5, peak_block_utilization=0.5,
                        ttft_ms_mean=None, ttft_ms_max=None,
                        decode_tok_per_sec=None, total_tok_per_sec=None)
            base.update(overrides)
            self._stats = ServeStats(**base)

        def stats(self):
            return self._stats

    logger = logging.getLogger("test_serve_monitor")
    with caplog.at_level(logging.INFO, logger=logger.name):
        mx.monitor.ServeMonitor(_FakeEngine(), interval=1,
                                logger=logger).log_now()
        mx.monitor.ServeMonitor(
            _FakeEngine(ttft_ms_mean=694.8472, decode_tok_per_sec=18.7501),
            interval=1, logger=logger).log_now()
    first, second = caplog.messages[:2]
    # None fields are '-' (grep-stable), floats one decimal
    assert "ttft_ms=- tok/s=-" in first
    assert "ttft_ms=694.8 tok/s=18.8" in second


def test_profiler_double_start_raises(monkeypatch):
    import mxnet_tpu.profiler as profiler

    calls = []
    monkeypatch.setattr(profiler.jax.profiler, "start_trace",
                        lambda d: calls.append(d))
    monkeypatch.setattr(profiler.jax.profiler, "stop_trace", lambda: None)
    monkeypatch.setattr(profiler, "_active_logdir", None)
    profiler.start("/tmp/prof-a")
    with pytest.raises(RuntimeError, match="already active"):
        profiler.start("/tmp/prof-b")
    assert calls == ["/tmp/prof-a"]          # second start never reached jax
    profiler.stop()
    profiler.start("/tmp/prof-b")            # fine after stop
    profiler.stop()


def test_profiler_stop_resets_state_on_error(monkeypatch):
    import mxnet_tpu.profiler as profiler

    monkeypatch.setattr(profiler.jax.profiler, "start_trace",
                        lambda d: None)

    def boom():
        raise RuntimeError("collector failed")

    monkeypatch.setattr(profiler.jax.profiler, "stop_trace", boom)
    monkeypatch.setattr(profiler, "_active_logdir", None)
    profiler.start("/tmp/prof-x")
    with pytest.raises(RuntimeError, match="collector failed"):
        profiler.stop()
    # a failed capture must not wedge the next start
    assert profiler._active_logdir is None
    with pytest.raises(RuntimeError, match="collector failed"):
        with profiler.trace("/tmp/prof-y"):
            pass
    assert profiler._active_logdir is None


# -- tools -------------------------------------------------------------------
def test_metrics_report_renders_all_artifact_forms(tel, tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import metrics_report

    tel.counter("req_total", "requests", ("route",)).labels(route="/a").inc(5)
    h = tel.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    out = str(tmp_path / "out")
    paths = tel.dump(out)
    for target in (out, paths["prometheus"], paths["jsonl"]):
        assert metrics_report.main([target]) == 0
        text = capsys.readouterr().out
        assert "req_total" in text and "route=/a" in text
        assert "lat_seconds" in text and "p99" in text
    # filter narrows the table
    metrics_report.main([out, "--filter", "lat"])
    text = capsys.readouterr().out
    assert "req_total" not in text and "lat_seconds" in text


def test_bench_watch_record_carries_telemetry_field(tmp_path, monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_watch

    log = tmp_path / "attempts.jsonl"
    monkeypatch.setattr(bench_watch, "LOG", str(log))
    bench_watch.record("tag-a", {"platform": "tpu", "value": 1})
    bench_watch.record("tag-b", {"platform": "tpu",
                                 "telemetry": {"enabled": True,
                                               "metrics": {"x": 1}}})
    lines = [json.loads(l) for l in open(log)]
    assert lines[0]["telemetry"] == {"enabled": False, "metrics": {}}
    # a child payload's own measured snapshot is preserved, not clobbered
    assert lines[1]["telemetry"]["enabled"] is True


def test_serve_bench_payload_carries_telemetry_field(tmp_path, monkeypatch):
    """serve_bench's --json artifact always has the telemetry snapshot
    field (the bench_watch stage contract) — tiny in-process run."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serve_bench

    out = tmp_path / "serve.json"
    argv = ["serve_bench.py", "--layers", "1", "--d-model", "32",
            "--heads", "2", "--vocab", "67", "--requests", "3",
            "--concurrency", "2", "--prompt-lens", "6,10",
            "--max-new", "3", "--no-serial", "--warmup", "0",
            "--json", str(out)]
    monkeypatch.setattr(sys, "argv", argv)
    serve_bench.main()
    payload = json.loads(open(out).read())
    assert payload["complete"] is True
    assert payload["telemetry"] == {"enabled": False, "metrics": {}}


def test_telemetry_env_gate_subprocess(tmp_path):
    """MXTPU_TELEMETRY=1 end to end in a fresh process: instrumented
    fit leaves the Prometheus file, the JSONL log and a loadable
    Chrome trace in MXTPU_TELEMETRY_DIR at exit."""
    import subprocess

    code = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.io import NDArrayIter

assert telemetry.enabled()
rng = np.random.RandomState(0)
X = rng.randn(32, 10).astype(np.float32)
y = (X.sum(axis=1) > 0).astype(np.float32)
it = NDArrayIter(X, y, batch_size=16)
data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
net = mx.sym.SoftmaxOutput(net, name="softmax")
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=1, kvstore=None)
"""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"MXTPU_TELEMETRY": "1",
                "MXTPU_TELEMETRY_DIR": str(tmp_path / "tel"),
                "MXTPU_PLATFORMS": "cpu", "JAX_PLATFORMS": "cpu",
                # classic-loop span contract (fit.forward_backward)
                "MXTPU_FUSED_STEP": "0"})
    r = subprocess.run([sys.executable, "-c", code], env=env, timeout=300,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    prom = open(tmp_path / "tel" / "metrics.prom").read()
    assert "mxtpu_fit_batches_total 2" in prom
    trace = json.load(open(tmp_path / "tel" / "host_trace.json"))
    assert any(e["name"] == "fit.forward_backward"
               for e in trace["traceEvents"])
    line = json.loads(open(tmp_path / "tel" / "metrics.jsonl").read())
    assert line["metrics"]["mxtpu_fit_epochs_total"]["samples"][0]["value"] == 1
