"""Caffe interop (mxnet_tpu/caffe.py — rebuild of plugin/caffe as
translation instead of embedding): prototxt text-format parsing, whole-net
import, and the CaffeOp/CaffeLoss plugin API."""

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.caffe as mc
from mxnet_tpu.base import MXNetError

LENET_PROTOTXT = """
name: "LeNet"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 4 dim: 1 dim: 28 dim: 28 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 20 kernel_size: 5 stride: 1 } }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
  convolution_param { num_output: 50 kernel_size: 5 stride: 1 } }
layer { name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool2" top: "ip1"
  inner_product_param { num_output: 500 } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 10 } }
layer { name: "accuracy" type: "Accuracy" bottom: "ip2" bottom: "label"
  include { phase: TEST } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label"
  top: "loss" }
"""


def test_parse_prototxt_structure():
    net = mc.parse_prototxt(LENET_PROTOTXT)
    assert net["name"] == "LeNet"
    layers = net["layer"]
    assert len(layers) == 10
    assert layers[1]["type"] == "Convolution"
    assert layers[1]["convolution_param"]["num_output"] == 20
    assert layers[2]["pooling_param"]["pool"] == "MAX"
    # repeated fields (two bottoms) become lists
    assert net["layer"][-1]["bottom"] == ["ip2", "label"]
    # nested repeated dims
    shape = layers[0]["input_param"]["shape"]
    assert shape["dim"] == [4, 1, 28, 28]


def test_lenet_import_shapes_and_forward():
    net = mc.prototxt_to_symbol(LENET_PROTOTXT)
    args, outs, _ = net.infer_shape(data=(4, 1, 28, 28))
    assert outs == [(4, 10)]
    arg_names = net.list_arguments()
    assert "conv1_weight" in arg_names and "ip2_bias" in arg_names

    exe = net.simple_bind(mx.cpu(), data=(4, 1, 28, 28),
                          softmax_label=(4,))
    for k, v in exe.arg_dict.items():
        v[:] = np.random.RandomState(0).uniform(-0.05, 0.05, v.shape)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (4, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_lenet_import_trains():
    net = mc.prototxt_to_symbol(LENET_PROTOTXT)
    rng = np.random.RandomState(0)
    X = rng.rand(64, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.float32)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod.fit(it, num_epoch=6, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32),
                      mx.metric.create("acc"))
    assert dict(score)["accuracy"] >= 0.2  # learns synthetic labels a bit


def test_caffe_op_plugin_api():
    """The plugin README's MLP composition pattern (caffe_net.py)."""
    data = mx.sym.Variable("data")
    fc1 = mc.CaffeOp(data, num_weight=2, name="fc1",
                     prototxt='layer{type:"InnerProduct" '
                              'inner_product_param{num_output: 128} }')
    act1 = mc.CaffeOp(fc1, prototxt='layer{type:"TanH"}')
    fc2 = mc.CaffeOp(act1, num_weight=2, name="fc2",
                     prototxt='layer{type:"InnerProduct" '
                              'inner_product_param{num_output: 10}}')
    label = mx.sym.Variable("softmax_label")
    mlp = mc.CaffeLoss(data=fc2, label=label, grad_scale=1.0,
                       prototxt='layer{type:"SoftmaxWithLoss"}')
    args, outs, _ = mlp.infer_shape(data=(8, 64), softmax_label=(8,))
    assert outs == [(8, 10)]
    # kwargs form: data_0=
    fc = mc.CaffeOp(data_0=data, num_weight=2,
                    prototxt='layer{type:"InnerProduct" '
                             'inner_product_param{num_output: 4}}')
    assert fc.infer_shape(data=(2, 6))[1] == [(2, 4)]


def test_eltwise_and_concat():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    s = mc.CaffeOp(a, b, num_data=2,
                   prototxt='layer{type:"Eltwise" '
                            'eltwise_param{operation: MAX}}')
    ex = s.simple_bind(mx.cpu(), a=(2, 3), b=(2, 3))
    ex.arg_dict["a"][:] = [[1, 5, 3], [0, 0, 0]]
    ex.arg_dict["b"][:] = [[4, 2, 6], [1, -1, 2]]
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, [[4, 5, 6], [1, 0, 2]])

    c = mc.CaffeOp(a, b, num_data=2,
                   prototxt='layer{type:"Concat" concat_param{axis: 1}}')
    assert c.infer_shape(a=(2, 3), b=(2, 5))[1] == [(2, 8)]


def test_unsupported_layer_raises():
    with pytest.raises(MXNetError):
        mc.prototxt_to_symbol('layer { name: "x" type: "Embed" }')
    with pytest.raises(MXNetError):
        mc.CaffeOp(mx.sym.Variable("d"), prototxt='layer{type:"PReLU"}')


def test_batchnorm_scale_folding():
    """BatchNorm + Scale pairs fold into one native BatchNorm op."""
    proto = """
    layer { name: "data" type: "Input" top: "data" }
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
      convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    layer { name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1"
      batch_norm_param { eps: 0.001 } }
    layer { name: "scale1" type: "Scale" bottom: "bn1" top: "bn1" }
    layer { name: "relu1" type: "ReLU" bottom: "bn1" top: "bn1" }
    layer { name: "ip" type: "InnerProduct" bottom: "bn1" top: "ip"
      inner_product_param { num_output: 2 } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
    """
    net = mc.prototxt_to_symbol(proto)
    args = net.list_arguments()
    assert "bn1_gamma" in args and "bn1_beta" in args
    assert not any("scale1" in a for a in args)  # folded away
    _, outs, aux = net.infer_shape(data=(2, 3, 6, 6))
    assert outs == [(2, 2)]
    assert len(aux) == 2  # moving mean/var
