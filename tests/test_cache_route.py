"""Cache-aware routing + fleet-global KV fabric (ISSUE 18).

The radix-summary advertisement (counting bloom + top-K exact keys,
incrementally maintained, size-bounded), the router's affinity plan
(tokenizer-side chain keys, longest-advertised-ancestor probe, stale
summaries scoring zero), the byte-inert ``MXTPU_ROUTE_AFFINITY=0``
contract (identical routing decisions, identical request bytes), the
keep-alive scrape connection pin, and the peer-to-peer chain pull over
``/chain_export`` — including the full degradation matrix: bloom false
positive (empty export), corrupted records, hung peer — every arm
recomputing instead of erroring and producing byte-identical tokens.

In-process CPU fleets over real engines (the test_fleet.py recipe); the
measured A/B contract lives in ``tools/fleet_bench.py --workload
cache-route`` (CACHE_ROUTE_BENCH.json).
"""

import http.server
import json
import math
import os
import socket
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.fleet import ReplicaServer, Router
from mxnet_tpu.serve import BlockManager
from mxnet_tpu.serve.kv_block_manager import (RadixSummary, _ROOT,
                                              _block_key, chain_keys)

VOCAB = 53
POOL = 1 << 22


@pytest.fixture(scope="module")
def model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


def _reference_tokens(model, prompt, max_new=8):
    eng = _engine(model)
    req = eng.submit(np.asarray(prompt, np.int32),
                     max_new_tokens=max_new)
    eng.run()
    assert req.status == "finished"
    out = list(req.tokens)
    eng.shutdown()
    return out


def _gen(url, body, timeout=60):
    req = urllib.request.Request(
        f"{url}/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _pull_stats(url):
    with urllib.request.urlopen(f"{url}/statusz.json",
                                timeout=10) as resp:
        return (json.loads(resp.read()).get("replica") or {}) \
            .get("pull") or {}


def _prompt(seed, prefix=None, suffix_len=6):
    rng = np.random.RandomState(seed)
    body = rng.randint(0, VOCAB, (suffix_len,)).tolist()
    return list(prefix or []) + body


PREFIX = np.random.RandomState(11).randint(0, VOCAB, (20,)).tolist()


# -- chain_keys + RadixSummary units ------------------------------------------
def test_chain_keys_match_block_manager_hash():
    """The router-side helper derives the SAME content keys the radix
    index publishes — chaining from the root, COW rule excluding the
    last span even when block-aligned."""
    toks = list(range(1, 14))            # 13 tokens, bs=4 -> 3 full
    keys = chain_keys(toks, 4)
    assert len(keys) == 3
    parent = _ROOT
    for b, key in enumerate(keys):
        expect = _block_key(parent, np.asarray(toks[b * 4:(b + 1) * 4],
                                               np.int32))
        assert key == expect
        parent = key
    # block-aligned prompt: the final block is COW (recomputed), so it
    # never joins the routable chain
    assert len(chain_keys(list(range(16)), 4)) == 3
    assert chain_keys([1, 2], 4) == []
    assert chain_keys(list(range(40)), 4, max_blocks=2) == \
        chain_keys(list(range(40)), 4)[:2]


def test_bloom_fp_rate_below_configured_bound():
    """Under load (n live keys) the measured false-positive rate stays
    below the classic bound ``(1 - e^(-kn/m))^k`` with margin.  top_k=0
    so the exact set cannot mask the bloom."""
    m, k, n = 4096, 4, 256
    s = RadixSummary(block_size=4, bloom_bits=m, top_k=0)
    rng = np.random.RandomState(5)
    for _ in range(n):
        s.add(rng.bytes(20))
    snap = s.snapshot()
    probes = 4000
    fps = sum(RadixSummary.match(snap, [rng.bytes(20)])
              for _ in range(probes))
    bound = (1.0 - math.exp(-k * n / m)) ** k
    assert fps / probes <= 2.0 * bound + 1e-3
    # membership has no false negatives
    s2 = RadixSummary(block_size=4, bloom_bits=m, top_k=0)
    keys = [rng.bytes(20) for _ in range(64)]
    for key in keys:
        s2.add(key)
    snap2 = s2.snapshot()
    assert all(RadixSummary.match(snap2, [key]) for key in keys)


def test_counting_bloom_remove_and_bounded_snapshot():
    """Evictions decrement real counts: add+remove leaves no residue,
    and the snapshot stays byte-bounded no matter how many keys passed
    through (the /healthz growth contract)."""
    s = RadixSummary(block_size=4, bloom_bits=1024, top_k=8)
    rng = np.random.RandomState(9)
    keys = [rng.bytes(20) for _ in range(500)]
    for key in keys:
        s.add(key)
    big = len(s.snapshot()["bloom"]["bits"])
    for key in keys:
        s.remove(key)
    snap = s.snapshot()
    assert snap["keys"] == 0
    assert snap["top"] == []
    assert not any(RadixSummary.match(snap, [key]) for key in keys)
    # bits field is packbits(m)/8 base64 — capacity-independent
    assert big <= (1024 // 8) * 4 // 3 + 4
    assert len(snap["top"]) <= 8
    # malformed snapshots never throw in the router
    assert RadixSummary.match(None, keys) == 0
    assert RadixSummary.match({"bloom": {"bits": "!!"}}, keys) == 0


def test_resurrection_counter_split():
    """A hit whose first reused block sat on the evictable LRU
    (refcount 0) counts as a resurrection; a hit on a still-referenced
    chain does not.  Both remain plain hits."""
    m = BlockManager(num_blocks=16, block_size=4, prefix_cache=True)
    toks = np.arange(1, 14, dtype=np.int32)        # 3 publishable
    m.allocate("a", len(toks), token_ids=toks)
    m.note_tokens("a", toks)
    m.free("a")                                    # chain -> LRU
    _, cached = m.allocate("b", len(toks), token_ids=toks)
    assert cached == 12
    st = m.prefix_stats()
    assert st["hits"] == 1 and st["resurrections"] == 1
    # "b" still holds the chain: the next hit is NOT a resurrection
    _, cached2 = m.allocate("c", len(toks), token_ids=toks)
    assert cached2 == 12
    st = m.prefix_stats()
    assert st["hits"] == 2 and st["resurrections"] == 1


def test_summary_tracks_publish_and_evict():
    """The advertised summary follows the radix index incrementally:
    publishes appear, unpublishes disappear, reset clears."""
    m = BlockManager(num_blocks=16, block_size=4, prefix_cache=True)
    toks = np.arange(1, 14, dtype=np.int32)
    keys = chain_keys(toks.tolist(), 4)
    assert m.summary()["keys"] == 0
    m.allocate("a", len(toks), token_ids=toks)
    m.note_tokens("a", toks)
    snap = m.summary()
    assert snap["keys"] == 3
    assert RadixSummary.match(snap, keys) == 3
    m.free("a")
    m.reset()
    snap = m.summary()
    assert snap["keys"] == 0
    assert RadixSummary.match(snap, keys) == 0


# -- keep-alive scrape (satellite: connection churn pin) ----------------------
def test_scrape_reuses_one_connection(model, fleet_cleanup):
    """N scrape passes ride ONE persistent keep-alive connection per
    replica — the regression pin for the per-poll TCP connect churn."""
    rep = ReplicaServer(_engine(model), replica_id="ka").start()
    fleet_cleanup.append(rep)
    router = Router([rep.url], scrape_interval_s=0, timeout_s=10)
    fleet_cleanup.append(router)
    for _ in range(8):
        router.scrape()
    (state,) = router.replicas()
    assert state.state == "ready"
    assert state.connects == 1
    assert state.conn is not None


# -- affinity routing ---------------------------------------------------------
def test_affinity_pins_returning_user(model, fleet_cleanup):
    """With affinity on, a returning user's requests pin to the
    replica advertising their prefix chain instead of round-robining
    across equally-idle siblings."""
    reps = [ReplicaServer(_engine(model), replica_id=f"r{i}").start()
            for i in range(2)]
    fleet_cleanup.extend(reps)
    router = Router([r.url for r in reps], scrape_interval_s=0,
                    timeout_s=30, retries=3, backoff_s=0.01,
                    backoff_max_s=0.05, affinity=1.0, pull=False)
    fleet_cleanup.append(router)
    router.scrape()
    first = router.generate(_prompt(1, PREFIX), max_new_tokens=4)
    router.scrape()                      # pick up the new summary
    plan = router._affinity_plan(_prompt(2, PREFIX))
    assert plan is not None
    assert plan["best"]["name"] == first.replica
    assert plan["best"]["tokens"] >= 16
    for seed in range(2, 6):
        res = router.generate(_prompt(seed, PREFIX), max_new_tokens=4)
        assert res.replica == first.replica
        router.scrape()


def test_affinity_zero_is_decision_inert(model, fleet_cleanup):
    """MXTPU_ROUTE_AFFINITY=0 (the default): same routing decisions as
    the pre-affinity router — pure least-loaded with round-robin
    tiebreak — even when summaries advertise a warm replica, and no
    request ever carries a kv_pull hint."""
    reps = [ReplicaServer(_engine(model), replica_id=f"z{i}").start()
            for i in range(2)]
    fleet_cleanup.extend(reps)
    router = Router([r.url for r in reps], scrape_interval_s=0,
                    timeout_s=30, retries=3, backoff_s=0.01,
                    backoff_max_s=0.05)
    fleet_cleanup.append(router)
    assert router.affinity == 0.0
    router.scrape()
    served = []
    for seed in range(4):
        res = router.generate(_prompt(seed, PREFIX), max_new_tokens=4)
        served.append(res.replica)
        router.scrape()
    # idle fleet + zero affinity = strict round-robin alternation (the
    # warm replica earns no pull): byte-inert routing decisions
    assert served == ["z0", "z1", "z0", "z1"]
    for rep in reps:
        pull = _pull_stats(rep.url)
        assert pull["attempts"] == 0 and pull["chain_exports"] == 0


def test_stale_summary_scores_zero_affinity(model, fleet_cleanup):
    """A summary past the age cap contributes no affinity: the plan
    comes back empty and routing degrades to least-loaded."""
    rep = ReplicaServer(_engine(model), replica_id="st").start()
    fleet_cleanup.append(rep)
    router = Router([rep.url], scrape_interval_s=0, timeout_s=30,
                    retries=3, backoff_s=0.01, backoff_max_s=0.05,
                    affinity=1.0, summary_stale=3.0)
    fleet_cleanup.append(router)
    router.scrape()
    router.generate(_prompt(1, PREFIX), max_new_tokens=4)
    router.scrape()
    prompt = _prompt(2, PREFIX)
    assert router._affinity_plan(prompt) is not None
    # age the advertisement past summary_stale * max(interval, 1s)
    (state,) = router.replicas()
    state.summary_t -= 3.0 * 1.0 + 0.5
    assert router._affinity_plan(prompt) is None


# -- peer-to-peer chain pull --------------------------------------------------
def test_pull_imports_chain_token_identical(model, fleet_cleanup):
    """The happy path: a cold replica handed a kv_pull hint imports
    the peer's chain over /chain_export (sha1 + chain-hash verified
    into the host tier) and serves byte-identical tokens."""
    warm = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                         replica_id="warm").start()
    cold = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                         replica_id="cold").start()
    fleet_cleanup.extend([warm, cold])
    prompt = _prompt(21, PREFIX)
    ref = _reference_tokens(model, prompt)
    first = _gen(warm.url, {"prompt": prompt, "max_new_tokens": 8,
                            "request_id": "w1"})
    assert first["tokens"] == ref
    pulled = _gen(cold.url, {"prompt": prompt, "max_new_tokens": 8,
                             "request_id": "c1",
                             "kv_pull": {"peer": warm.url,
                                         "tokens": 16}})
    assert pulled["tokens"] == ref
    pull = _pull_stats(cold.url)
    assert pull["attempts"] == 1
    assert pull["blocks_imported"] >= 4
    assert pull["failures"] == 0 and pull["false_positives"] == 0
    assert pull["bytes_received"] > 0
    exp = _pull_stats(warm.url)
    assert exp["chain_exports"] == 1
    assert exp["chain_export_blocks"] >= 4


def test_pull_false_positive_degrades_to_recompute(model,
                                                   fleet_cleanup):
    """A bloom FP sends the puller to a peer that has nothing: the
    export comes back empty, the replica recomputes, tokens exact."""
    peer = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                         replica_id="fp-peer").start()
    rep = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="fp").start()
    fleet_cleanup.extend([peer, rep])
    prompt = _prompt(22, PREFIX)
    res = _gen(rep.url, {"prompt": prompt, "max_new_tokens": 8,
                         "kv_pull": {"peer": peer.url, "tokens": 16}})
    assert res["tokens"] == _reference_tokens(model, prompt)
    pull = _pull_stats(rep.url)
    assert pull["attempts"] == 1 and pull["false_positives"] == 1
    assert pull["failures"] == 0 and pull["blocks_imported"] == 0


def test_pull_corruption_degrades_to_recompute(model, fleet_cleanup):
    """A peer answering garbage (bad digest / truncated records) never
    corrupts the puller: the import rejects, the request recomputes,
    tokens stay exact and the failure is counted."""
    class _EvilPeer(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps({"replica": "evil", "records": [
                {"key": "00" * 8, "parent": "11" * 8,
                 "tokens": [1, 2, 3, 4], "k": "AAAA", "v": "AAAA",
                 "digest": "feedfacefeedface"}]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _EvilPeer)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    rep = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="corrupt").start()
    fleet_cleanup.append(rep)
    try:
        prompt = _prompt(23, PREFIX)
        res = _gen(rep.url, {
            "prompt": prompt, "max_new_tokens": 8,
            "kv_pull": {"peer":
                        f"http://127.0.0.1:{srv.server_address[1]}",
                        "tokens": 16}})
        assert res["tokens"] == _reference_tokens(model, prompt)
        pull = _pull_stats(rep.url)
        assert pull["attempts"] == 1 and pull["failures"] == 1
        assert pull["blocks_imported"] == 0
    finally:
        srv.shutdown()
        srv.server_close()


def test_pull_timeout_degrades_to_recompute(model, fleet_cleanup,
                                            monkeypatch):
    """A hung peer burns only MXTPU_ROUTE_PULL_TIMEOUT, then the
    request recomputes — the serving path never wedges on the fabric."""
    hole = socket.socket()
    hole.bind(("127.0.0.1", 0))
    hole.listen(4)                       # accepts, never answers
    monkeypatch.setenv("MXTPU_ROUTE_PULL_TIMEOUT", "0.3")
    rep = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="hang").start()
    fleet_cleanup.append(rep)
    try:
        prompt = _prompt(24, PREFIX)
        res = _gen(rep.url, {
            "prompt": prompt, "max_new_tokens": 8,
            "kv_pull": {"peer":
                        f"http://127.0.0.1:{hole.getsockname()[1]}",
                        "tokens": 16}})
        assert res["tokens"] == _reference_tokens(model, prompt)
        pull = _pull_stats(rep.url)
        assert pull["attempts"] == 1 and pull["failures"] == 1
    finally:
        hole.close()


def test_pull_skipped_when_already_warm(model, fleet_cleanup):
    """A hint naming a span the replica already caches locally is a
    no-op — no probe, no wire bytes (the only-when-beneficial rule)."""
    rep = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="selfwarm").start()
    fleet_cleanup.append(rep)
    prompt = _prompt(25, PREFIX)
    _gen(rep.url, {"prompt": prompt, "max_new_tokens": 8})
    _gen(rep.url, {"prompt": _prompt(26, PREFIX), "max_new_tokens": 8,
                   "kv_pull": {"peer": "http://127.0.0.1:9",
                               "tokens": 16}})
    assert _pull_stats(rep.url)["attempts"] == 0


def test_chain_export_rejects_bad_prompt(model, fleet_cleanup):
    rep = ReplicaServer(_engine(model, host_kv_bytes=POOL),
                        replica_id="val").start()
    fleet_cleanup.append(rep)
    req = urllib.request.Request(
        f"{rep.url}/chain_export",
        data=json.dumps({"prompt": "nope"}).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_router_attaches_pull_hint_end_to_end(model, fleet_cleanup):
    """Full loop: user warms replica A through the router; the router
    is then forced onto replica B (A excluded by load), attaches the
    kv_pull hint, and B imports A's chain before serving."""
    reps = [ReplicaServer(_engine(model, host_kv_bytes=POOL),
                          replica_id=f"p{i}").start()
            for i in range(2)]
    fleet_cleanup.extend(reps)
    router = Router([r.url for r in reps], scrape_interval_s=0,
                    timeout_s=30, retries=3, backoff_s=0.01,
                    backoff_max_s=0.05, affinity=1.0, pull=True)
    fleet_cleanup.append(router)
    router.scrape()
    first = router.generate(_prompt(31, PREFIX), max_new_tokens=4)
    router.scrape()
    warm = next(r for r in reps if r.replica_id == first.replica)
    other = next(r for r in reps if r.replica_id != first.replica)
    # make the warm replica look saturated so load beats affinity and
    # the pick lands on the cold sibling WITH a pull hint
    with router._lock:
        for state in router._replicas:
            if state.name == first.replica:
                state.load = 50.0
    ref = _reference_tokens(model, _prompt(32, PREFIX), max_new=4)
    res = router.generate(_prompt(32, PREFIX), max_new_tokens=4)
    assert res.replica == other.replica_id
    assert list(res.tokens) == ref
    assert _pull_stats(other.url)["attempts"] == 1
    assert _pull_stats(other.url)["blocks_imported"] >= 4
    assert _pull_stats(warm.url)["chain_exports"] == 1
