"""Tools suite: im2rec packing, log parsing, local launcher
(reference tools/im2rec.py, tools/parse_log.py, tools/launch.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

cv2 = pytest.importorskip("cv2")

import im2rec  # noqa: E402
import parse_log  # noqa: E402


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir()
        for i in range(6):
            img = rng.randint(0, 255, (48, 64, 3), np.uint8)
            cv2.imwrite(str(d / f"{cls}{i}.jpg"), img)
    return str(root)


def test_im2rec_list_and_pack_roundtrip(image_dir, tmp_path):
    prefix = str(tmp_path / "data")
    im2rec.main(["--list", "--recursive", prefix, image_dir])
    assert os.path.exists(prefix + ".lst")
    lines = open(prefix + ".lst").read().strip().split("\n")
    assert len(lines) == 12
    labels = {float(l.split("\t")[1]) for l in lines}
    assert labels == {0.0, 1.0}

    im2rec.main([prefix, image_dir, "--resize", "32", "--quality", "90"])
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    from mxnet_tpu.image_io import ImageRecordIter

    it = ImageRecordIter(path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
                         batch_size=4, preprocess_threads=1)
    seen, label_set = 0, set()
    while True:
        try:
            b = it.next()
        except StopIteration:
            break
        seen += b.data[0].shape[0]
        label_set |= set(b.label[0].asnumpy().tolist())
    assert seen == 12
    assert label_set == {0.0, 1.0}


def test_im2rec_sharding(image_dir, tmp_path):
    prefix = str(tmp_path / "shard")
    im2rec.main(["--list", "--recursive", prefix, image_dir])
    im2rec.main([prefix, image_dir, "--num-parts", "2", "--resize", "32"])
    from mxnet_tpu import recordio

    n = 0
    for part in range(2):
        reader = recordio.MXRecordIO(f"{prefix}_{part}.rec", "r")
        while reader.read() is not None:
            n += 1
        reader.close()
    assert n == 12


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Batch [50] Speed: 1234.5 samples/sec "
        "Train-accuracy=0.51\n"
        "INFO:root:Epoch[0] Train-accuracy=0.612\n"
        "INFO:root:Epoch[0] Time cost=12.5\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.633\n"
        "INFO:root:Epoch[1] Train-accuracy=0.71\n"
        "INFO:root:Epoch[1] Time cost=11.9\n"
        "INFO:root:Epoch[1] Validation-accuracy=0.725\n")
    rows = parse_log.parse(log.read_text().split("\n"))
    assert rows[0]["val-accuracy"] == 0.633
    assert rows[1]["train-accuracy"] == 0.71
    assert rows[0]["time"] == 12.5
    assert rows[0]["speed"] == 1234.5
    md = parse_log.render(rows, "markdown")
    assert "| epoch |" in md and "0.725" in md
    csv = parse_log.render(rows, "csv")
    assert csv.splitlines()[0].startswith("epoch,")


@pytest.mark.slow
def test_launch_local_spawns_ranked_processes(tmp_path):
    out = tmp_path / "ranks"
    out.mkdir()
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        f"open(os.path.join({str(out)!r}, os.environ['MXTPU_PROC_ID']), 'w')"
        ".write(os.environ['MXTPU_COORDINATOR'] + ' ' +"
        " os.environ['MXTPU_NUM_PROCS'])\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "3", "--", sys.executable, str(script)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    contents = {open(out / f).read() for f in files}
    assert len(contents) == 1  # same coordinator + nprocs everywhere
    assert contents.pop().endswith(" 3")


def test_native_im2rec_roundtrip(tmp_path):
    """The C++ im2rec tool (src/im2rec.cc) packs a .lst into a .rec that
    ImageRecordIter (and the python recordio reader) consume."""
    exe = os.path.join(REPO, "tools", "im2rec")
    if not os.path.exists(exe):
        pytest.skip("native im2rec not built (no OpenCV)")

    root = tmp_path / "imgs"
    root.mkdir()
    lines = []
    for i in range(10):
        img = np.full((30 + i, 36, 3), i * 20, np.uint8)
        cv2.imwrite(str(root / f"im{i}.png"), img)
        lines.append(f"{i}\t{float(i % 4)}\tim{i}.png")
    prefix = str(tmp_path / "data")
    with open(prefix + ".lst", "w") as f:
        f.write("\n".join(lines) + "\n")

    r = subprocess.run([exe, prefix, str(root), "--resize", "32",
                        "--quality", "95"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stderr
    assert "wrote 10/10" in r.stdout

    # python reader sees headers + decodable images
    from mxnet_tpu import recordio

    reader = recordio.MXRecordIO(prefix + ".rec", "r")
    n = 0
    while True:
        raw = reader.read()
        if raw is None:
            break
        header, img = recordio.unpack_img(raw, iscolor=1)
        assert header.label == float(n % 4)
        assert min(img.shape[:2]) == 32
        n += 1
    assert n == 10
    reader.close()

    # and the full iterator consumes it
    from mxnet_tpu.image_io import ImageRecordIter

    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         data_shape=(3, 28, 28), batch_size=5,
                         preprocess_threads=2)
    batches = list(iter(it))
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].label[0].asnumpy(),
                               [0, 1, 2, 3, 0])


def test_compare_baseline_table(tmp_path):
    """tools/compare_baseline.py renders whatever artifact subset
    exists into one markdown table."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # synthetic artifact set in an isolated dir
    (tmp_path / "BENCH_TPU_LATEST.json").write_text(json.dumps({
        "metric": "resnet50_train_throughput", "value": 2845.0,
        "unit": "images/sec/chip", "vs_baseline": 1.138,
        "platform": "tpu", "mfu": 0.358,
        "vs_baseline_per_peak_tflop": 1.80}))
    (tmp_path / "IO_BENCH.json").write_text(json.dumps({
        "metric": "image_pipeline_throughput", "value": 539.5,
        "vs_baseline_per_core": 2.158, "host_cores": 1}))
    # bench_watch writes these artifacts as INDENTED multi-line JSON —
    # the loader must accept that format, not just one-liners
    (tmp_path / "QUANT_BENCH.json").write_text(json.dumps({
        "metric": "resnet50_int8_inference", "platform": "tpu",
        "int8_img_per_sec": 5200.0, "int8_speedup": 1.9}, indent=1))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "compare_baseline.py"),
         "--repo", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-500:]
    assert "1.138x" in r.stdout and "1.80x per peak TFLOP" in r.stdout
    assert "2.16x/core" in r.stdout
    assert "1.90x" in r.stdout  # the indented QUANT artifact parsed
    # empty dir renders the placeholder row, still exit 0
    empty = tmp_path / "empty"
    empty.mkdir()
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "compare_baseline.py"),
         "--repo", str(empty)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0 and "no TPU artifacts" in r.stdout
