"""Tunnel-independent performance contract (VERDICT r3 item 6).

Three layers of CPU-only gates that catch perf regressions the moment
they are introduced, instead of on round-end hardware:

1. **Kernel lowerability**: every Pallas kernel must pass Mosaic (TPU)
   lowering via cross-platform AOT (``.lower(lowering_platforms=
   ("tpu",))`` works without a chip — Mosaic compiles at lowering
   time).  Round 4 found the flash kernel failed this at EVERY shape
   (weak-f64 constants + an lse BlockSpec violating Mosaic tiling):
   the GPT bench would have crashed the moment the tunnel answered.
   These tests make that class of bug a CI failure.

2. **HLO structural audits** (tools/hlo_audit.py): the lowered bench
   train steps must keep the layout properties BENCH_NOTES.md documents
   — ResNet-50/CIFAR with zero activation transposes, sequence-major
   GPT with none beyond the tiny D-free lse row maps.

3. **Collective-shape audits**: the compiled dp x tp sharded step and
   the ring/Ulysses attention programs must contain exactly the
   collective families their designs call for (reference analog: the
   comm patterns ps-lite/NCCL hard-coded; here XLA inserts them and
   these tests pin what it inserted).

Plus the artifact regression gate (tools/compare_baseline.py --check).
"""

import json
import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import hlo_audit  # noqa: E402  (repo tool, imported for its builders)


@pytest.fixture(autouse=True)
def _default_trace_env(monkeypatch):
    """The audits pin properties of the DEFAULT bench program; shield
    them from env leaked by earlier in-process tests (found in round 4:
    examples/memcost.py left MXNET_BACKWARD_DO_MIRROR=1 behind, adding
    remat to every later trace and shifting the audited op counts)."""
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)


def _tpu_text(fn, *args):
    """StableHLO of ``fn`` lowered FOR TPU from the CPU backend."""
    return jax.jit(fn).trace(*args).lower(
        lowering_platforms=("tpu",)).as_text()


def _counts(text):
    return hlo_audit.audit_counts(text)


# -- 1. Pallas kernels must lower for TPU -----------------------------------

@pytest.mark.parametrize("layout,shape", [
    ("bhsd", (2, 8, 1024, 64)),     # bench_gpt-class shape
    ("bshd", (2, 1024, 8, 64)),     # sequence-major variant
    ("bhsd", (1, 1, 128, 128)),     # the _flash_available probe shape
    ("bshd", (1, 128, 1, 128)),
])
def test_flash_kernel_lowers_for_tpu(layout, shape):
    from mxnet_tpu.ops.flash_attention import flash_attention

    def fwd(q):
        return flash_attention(q, q, q, causal=True, interpret=False,
                               layout=layout)

    def bwd(q):
        return jax.grad(lambda x: flash_attention(
            x, x, x, causal=True, interpret=False,
            layout=layout).astype(jnp.float32).sum())(q)

    q = jnp.zeros(shape, jnp.bfloat16)
    t = _tpu_text(fwd, q)
    assert len(re.findall(r"tpu_custom_call", t)) == 1, \
        "forward did not lower to one Mosaic kernel"
    t = _tpu_text(bwd, q)
    # fwd (rerun in vjp) + dq kernel + dkv kernel
    assert len(re.findall(r"tpu_custom_call", t)) == 3, \
        "backward did not lower to three Mosaic kernels"


@pytest.mark.parametrize("opts,qshape,kshape", [
    # sliding window: band-masked tiles + tile skipping
    ({"window": 256}, (2, 8, 1024, 64), None),
    # GQA (bshd native): 8 q heads on 2 kv heads
    ({"layout": "bshd"}, (2, 1024, 8, 64), (2, 1024, 2, 64)),
    # GQA + window + causal composed
    ({"layout": "bshd", "window": 256}, (2, 1024, 8, 64), (2, 1024, 2, 64)),
])
def test_flash_kernel_features_lower_for_tpu(opts, qshape, kshape):
    """The window/GQA kernel variants must survive Mosaic lowering, not
    just the CPU interpreter — the x64-index-map bug class hid exactly
    here (pallas_util.idx32)."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros(qshape, jnp.bfloat16)
    k = q if kshape is None else jnp.zeros(kshape, jnp.bfloat16)

    def fwd(q, k):
        return flash_attention(q, k, k, causal=True, interpret=False,
                               **opts)

    def bwd(q, k):
        # differentiate BOTH operands: k/v grads unused would let XLA
        # DCE the dkv kernel and the count would vacuously pass at 2
        return jax.grad(lambda x, y: flash_attention(
            x, y, y, causal=True, interpret=False,
            **opts).astype(jnp.float32).sum(), argnums=(0, 1))(q, k)

    t = _tpu_text(fwd, q, k)
    assert len(re.findall(r"tpu_custom_call", t)) == 1
    t = _tpu_text(bwd, q, k)
    assert len(re.findall(r"tpu_custom_call", t)) == 3


def test_fused_rnn_kernels_lower_for_tpu():
    from mxnet_tpu.ops.pallas_gru import fused_gru
    from mxnet_tpu.ops.pallas_lstm import fused_lstm

    T, N, H = 128, 32, 512          # FLASH_BENCH/RNN-bench shape class
    h0 = jnp.zeros((N, H), jnp.float32)

    gx = jnp.zeros((T, N, 4 * H), jnp.float32)
    c0 = jnp.zeros((N, H), jnp.float32)
    wh = jnp.zeros((4 * H, H), jnp.float32)
    bh = jnp.zeros((4 * H,), jnp.float32)
    t = _tpu_text(lambda a: fused_lstm(a, h0, c0, wh, bh,
                                       interpret=False)[0], gx)
    assert "tpu_custom_call" in t
    t = _tpu_text(lambda a: jax.grad(lambda x: fused_lstm(
        x, h0, c0, wh, bh, interpret=False)[0].sum())(a), gx)
    assert len(re.findall(r"tpu_custom_call", t)) >= 2   # fwd + bwd kernels

    gxg = jnp.zeros((T, N, 3 * H), jnp.float32)
    whg = jnp.zeros((3 * H, H), jnp.float32)
    bhg = jnp.zeros((3 * H,), jnp.float32)
    t = _tpu_text(lambda a: fused_gru(a, h0, whg, bhg,
                                      interpret=False)[0], gxg)
    assert "tpu_custom_call" in t
    t = _tpu_text(lambda a: jax.grad(lambda x: fused_gru(
        x, h0, whg, bhg, interpret=False)[0].sum())(a), gxg)
    assert len(re.findall(r"tpu_custom_call", t)) >= 2


# -- 2. HLO structural audits over the bench train steps --------------------

@pytest.mark.slow
def test_resnet_step_structurally_clean():
    """The bench ResNet-50 (NHWC, s2d stem) train step: 3 transposes,
    all rank-2 (the FC-head weight), zero activation transposes, and no
    layout flips around the 159 convolutions (BENCH_NOTES round-3
    audit, now enforced)."""
    trainer, placed = hlo_audit.build("resnet")
    c = _counts(hlo_audit.lower_text(trainer, placed, platform="tpu"))
    assert c["activation_transposes"] == 0, c
    assert c["transposes"] <= 3, c
    assert c["convolutions"] == 159, c


@pytest.mark.slow
def test_cifar_step_structurally_clean():
    trainer, placed = hlo_audit.build("cifar")
    c = _counts(hlo_audit.lower_text(trainer, placed, platform="tpu"))
    assert c["activation_transposes"] == 0, c
    assert c["transposes"] <= 3, c
    assert c["convolutions"] == 56, c


@pytest.mark.slow
def test_gpt_bshd_step_structurally_clean():
    """Sequence-major GPT on the REAL TPU path (flash kernels engaged
    via force_flash): at most the two tiny D-free lse row maps remain;
    the bhsd default keeps its 8-per-layer activation shuffles, so the
    delta is what BENCH_ATTN_LAYOUT=bshd buys structurally."""
    tr_b, placed_b = hlo_audit.build("gpt_bshd")
    text_b = hlo_audit.lower_text(tr_b, placed_b, platform="tpu",
                                  force_flash=True)
    c_b = _counts(text_b)
    # 2 layers x (1 fwd + 2 bwd) Mosaic kernels
    assert len(re.findall(r"tpu_custom_call", text_b)) == 6, c_b
    # the only rank>=3 transposes are the (B, S, H) -> (BH, S) lse row
    # maps in the backward kernels' prologue — no D dimension, ~KB not
    # GB of traffic
    assert c_b["activation_transposes"] <= 2, c_b

    tr_a, placed_a = hlo_audit.build("gpt")
    c_a = _counts(hlo_audit.lower_text(tr_a, placed_a, platform="tpu",
                                       force_flash=True))
    assert c_a["activation_transposes"] >= 16, c_a  # 8/layer, 2 layers


# -- 3. Collective-shape audits ---------------------------------------------

@pytest.mark.slow
def test_dp_tp_step_collectives():
    """Compiled dp x tp training step (8 virtual devices): gradient
    sync + tensor-parallel psums appear as all-reduce; nothing in this
    program should need all-to-all or collective-permute — their
    appearance means the partitioner was fed wrong shardings."""
    from jax.sharding import PartitionSpec as P

    mesh = mx.parallel.make_mesh({"dp": 2, "tp": 2})
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=16, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (8, 32), "softmax_label": (8,)}, mesh=mesh,
        batch_axis="dp",
        param_specs={"fc1_weight": P("tp", None),
                     "fc2_weight": P(None, "tp")},
        optimizer="sgd", initializer=mx.initializer.Xavier())
    placed = tr._place_batch({"data": np.zeros((8, 32), np.float32),
                              "softmax_label": np.zeros((8,), np.float32)})
    text = tr._train_step.lower(tr.params, tr.opt_state, tr.aux, placed,
                                tr._key, np.float32(1.0)).compile().as_text()
    assert len(re.findall(r"all-reduce", text)) >= 1
    assert len(re.findall(r"all-to-all", text)) == 0
    assert len(re.findall(r"collective-permute", text)) == 0


@pytest.mark.slow
def test_ring_attention_collectives():
    """Ring attention's compiled program moves K/V shards with
    collective-permute (the ICI neighbor ring) and must NOT all-gather
    the sequence — gathering would reintroduce the O(S^2/chip) memory
    the ring exists to avoid."""
    from mxnet_tpu.parallel.ring_attention import ring_attention

    mesh = mx.parallel.make_mesh({"sp": 8})
    q = jnp.zeros((1, 2, 256, 16), jnp.float32)

    def run(q):
        return ring_attention(q, q, q, mesh, axis="sp", causal=True)

    text = jax.jit(run).lower(q).compile().as_text()
    assert len(re.findall(r"collective-permute", text)) >= 1
    assert len(re.findall(r"all-gather", text)) == 0
    assert len(re.findall(r"all-to-all", text)) == 0


@pytest.mark.slow
def test_ulysses_attention_collectives():
    """Ulysses moves heads with all-to-all (two per call: scatter heads
    / gather sequence, then back) and never all-gathers the sequence."""
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    mesh = mx.parallel.make_mesh({"sp": 8})
    q = jnp.zeros((1, 8, 256, 16), jnp.float32)

    def run(q):
        return ulysses_attention(q, q, q, mesh, axis="sp", causal=True)

    text = jax.jit(run).lower(q).compile().as_text()
    assert len(re.findall(r"all-to-all", text)) >= 2
    assert len(re.findall(r"all-gather", text)) == 0


# -- 4. Artifact regression gate --------------------------------------------

def _write(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f)


def _run_gate(repo, threshold=0.05):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "compare_baseline.py"),
         "--repo", str(repo), "--check", "--threshold", str(threshold)],
        capture_output=True, text=True, timeout=60)


def test_regression_gate_fails_on_regression(tmp_path):
    metric = "resnet50_train_throughput"
    _write(tmp_path / "BENCH_r02.json",
           {"metric": metric, "value": 2845.0, "unit": "images/sec/chip",
            "vs_baseline": 1.14, "platform": "tpu"})
    _write(tmp_path / "BENCH_TPU_LATEST.json",
           {"metric": metric, "value": 2500.0, "unit": "images/sec/chip",
            "vs_baseline": 1.0, "platform": "tpu"})
    r = _run_gate(tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout

    # within threshold: passes
    _write(tmp_path / "BENCH_TPU_LATEST.json",
           {"metric": metric, "value": 2800.0, "unit": "images/sec/chip",
            "vs_baseline": 1.12, "platform": "tpu"})
    r = _run_gate(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr


def test_regression_gate_ignores_cpu_and_missing(tmp_path):
    metric = "resnet50_train_throughput"
    # a CPU fallback LATEST (tunnel down) must not trip the gate even
    # with a better prior TPU record in history
    _write(tmp_path / "BENCH_r02.json",
           {"metric": metric, "value": 2845.0, "platform": "tpu"})
    _write(tmp_path / "BENCH_TPU_LATEST.json",
           {"metric": metric, "value": 5.2, "platform": "cpu",
            "best_tpu_record": {"value": 2845.0, "unit": "images/sec/chip"}})
    r = _run_gate(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr

    # empty repo: vacuous pass
    r = _run_gate(tmp_path / "nonexistent")
    assert r.returncode == 0


def test_regression_gate_on_real_repo():
    """The committed artifact set must currently satisfy its own gate."""
    r = _run_gate(REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# -- 5. Bucketing recompile audit -------------------------------------------

@pytest.mark.slow
def test_bucketing_compiles_once_per_bucket():
    """Steady-state bucket switching must not recompile: each bucket's
    executor programs compile on FIRST visit only (the reference's
    bucketing promise — switch_bucket reuses the bound executor,
    bucketing_module.py:195-220; here the jit cache is the mechanism).
    A regression that defeats the cache (e.g. a fresh lambda per
    switch, a shape leaking into a python closure) turns every bucket
    revisit into a 20-40 s TPU recompile and this test catches it on
    CPU by counting XLA compile log lines."""
    import logging

    from mxnet_tpu.io import DataBatch, DataDesc

    rng = np.random.RandomState(0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=6, name="emb")
        pooled = mx.sym.mean(emb, axis=(1,))
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(fc, name="softmax"), ["data"], [
            "softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    mod.bind([DataDesc("data", (8, 16))], [DataDesc("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None,
                       optimizer_params={"learning_rate": 0.1})

    compiles = []

    class _Counter(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            if msg.startswith("Finished XLA compilation"):
                compiles.append(msg)

    handler = _Counter()
    logger = logging.getLogger("jax._src.dispatch")
    prior_level = logger.level
    logger.addHandler(handler)
    logger.setLevel(logging.WARNING)
    import jax as _jax

    prior_log_compiles = _jax.config.jax_log_compiles
    _jax.config.update("jax_log_compiles", True)

    def run_round():
        for key in (16, 8, 4, 8, 16, 4):
            batch = DataBatch(
                [mx.nd.array(rng.randint(0, 20, (8, key)))],
                [mx.nd.array(rng.randint(0, 4, 8))],
                bucket_key=key,
                provide_data=[DataDesc("data", (8, key))],
                provide_label=[DataDesc("softmax_label", (8,))])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()

    try:
        run_round()          # first visits: compiles expected
        warm = len(compiles)
        assert warm > 0, "counter captured nothing — logging plumbing broke"
        run_round()          # every bucket already seen
        run_round()
        assert len(compiles) == warm, (
            f"bucket revisits recompiled: {len(compiles) - warm} new "
            f"compiles after warmup:\n" + "\n".join(compiles[warm:]))
    finally:
        _jax.config.update("jax_log_compiles", prior_log_compiles)
        logger.removeHandler(handler)
        logger.setLevel(prior_level)


# -- 6. Fused attention composes with data parallelism ----------------------

@pytest.mark.slow
def test_dp_sharded_flash_gpt_parity():
    """A multi-device dp ShardedTrainer over a flash-attention GPT must
    (a) match the single-device run numerically (the op shard_maps its
    Pallas call over the batch axis via the ambient-mesh context) and
    (b) lower for TPU — GSPMD alone cannot partition Mosaic custom
    calls, which used to make multi-chip dp + fused attention refuse to
    compile."""
    import importlib

    vocab, seq = 53, 32

    def build(mesh, impl):
        net = mx.models.gpt(vocab, seq, num_layers=1, d_model=32,
                            num_heads=2, attn_impl=impl)
        return mx.parallel.ShardedTrainer(
            net, {"data": (8, seq), "softmax_label": (8, seq)},
            mesh=mesh, batch_axis="dp", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.float32})

    mesh2 = mx.parallel.make_mesh({"dp": 2})
    mesh1 = mx.parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    t2 = build(mesh2, "flash")       # interpreter kernels on CPU
    t1 = build(mesh1, "flash")
    p0 = t2.get_params()
    t1.set_params(p0)
    key = np.asarray(jax.device_get(t2._key))
    t1._key = jax.device_put(key, t1._replicated)
    t2._key = jax.device_put(key, t2._replicated)
    rng = np.random.RandomState(0)
    batch = {"data": rng.randint(0, vocab, (8, seq)),
             "softmax_label": rng.randint(0, vocab, (8, seq)).astype(
                 np.float32)}
    o2, o1 = t2.step(batch), t1.step(batch)
    np.testing.assert_allclose(np.asarray(o2[0]), np.asarray(o1[0]),
                               atol=2e-5, rtol=2e-4)
    p2, p1 = t2.get_params(), t1.get_params()
    for k in p0:
        np.testing.assert_allclose(p2[k], p1[k], atol=5e-5, rtol=2e-4,
                                   err_msg=k)

    # (b) the dp=8 program lowers for TPU with Mosaic kernels inside
    fam = importlib.import_module("mxnet_tpu.ops.flash_attention")
    orig = fam._on_tpu
    fam._on_tpu = lambda: True
    try:
        net = mx.models.gpt(211, seq, num_layers=2, d_model=64,
                            num_heads=4, fused_qkv=True)
        mesh8 = mx.parallel.make_mesh({"dp": 8})
        tr8 = mx.parallel.ShardedTrainer(
            net, {"data": (16, seq), "softmax_label": (16, seq)},
            mesh=mesh8, batch_axis="dp", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.float32})
        placed = tr8._place_batch(
            {"data": np.zeros((16, seq), np.int64),
             "softmax_label": np.zeros((16, seq), np.float32)})
        text = tr8._train_step.trace(
            tr8.params, tr8.opt_state, tr8.aux, placed, tr8._key,
            np.float32(1.0)).lower(lowering_platforms=("tpu",)).as_text()
        assert len(re.findall(r"tpu_custom_call", text)) == 6  # 2 layers x 3
    finally:
        fam._on_tpu = orig


@pytest.mark.slow
def test_dp_sp_flash_gpt_lowers_for_tpu():
    """The combined dp x sp sequence-parallel GPT train step — flash
    kernels inside the ring schedule inside the sharded trainer — must
    lower for TPU: Mosaic custom calls present, collective-permutes
    moving K/V around the sp ring, and NO all-gather of the sequence."""
    import importlib

    from jax.sharding import PartitionSpec as P

    fam = importlib.import_module("mxnet_tpu.ops.flash_attention")
    orig = fam._on_tpu
    fam._on_tpu = lambda: True
    try:
        vocab, seq = 211, 512           # shard length 128 = kernel block
        net = mx.models.gpt(vocab, seq, num_layers=2, d_model=64,
                            num_heads=4, attn_impl="flash")
        mesh = mx.parallel.make_mesh({"dp": 2, "sp": 4})
        tr = mx.parallel.ShardedTrainer(
            net, {"data": (4, seq), "softmax_label": (4, seq)},
            mesh=mesh, batch_axis="dp",
            sequence_specs={"data": P("dp", "sp"),
                            "softmax_label": P("dp", "sp")},
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.float32})
        placed = tr._place_batch(
            {"data": np.zeros((4, seq), np.int64),
             "softmax_label": np.zeros((4, seq), np.float32)})
        text = tr._train_step.trace(
            tr.params, tr.opt_state, tr.aux, placed, tr._key,
            np.float32(1.0)).lower(lowering_platforms=("tpu",)).as_text()
        assert len(re.findall(r"tpu_custom_call", text)) >= 3
        assert len(re.findall(r"collective_permute", text)) >= 2
        assert len(re.findall(r"all_gather", text)) == 0
    finally:
        fam._on_tpu = orig


# -- 4. Serve program-family audits (perf-attribution gate) -----------------
# The serve-side analog of layer 2: lower the EXACT bucketed programs
# serve.Engine dispatches (via hlo_audit.build_serve_engine +
# engine._program_builder) and pin dot_general / transpose counts plus
# cost_analysis() flops, so a lowering regression in the decode hot
# path — an extra gather-induced transpose, a duplicated matmul, a
# flops blow-up — fails CI on CPU alone.  Counts measured identical
# under cpu and --tpu lowering at this config (no Pallas at these tiny
# shapes), so the CPU pins audit the real TPU program structure too.

SERVE_PINS = {
    # (kind, bucket): transposes, act_transposes, dot_generals, flops
    ("prefill", 8):     (17, 4, 17, 451136),
    ("chunk", 8):       (17, 4, 17, 518645),
    ("decode", 4):      (13, 0, 17, 275472),
    ("draft", 4):       (16, 0, 20, 106390),
    ("draft_chunk", 8): (9, 2, 9, 82665),
    ("verify", 4):      (17, 4, 17, 824608),
    ("restore", 4):     (0, 0, 0, 566),
}


@pytest.fixture(scope="module")
def serve_audit_engine():
    eng = hlo_audit.build_serve_engine()
    yield eng
    eng.shutdown()


@pytest.mark.parametrize("kind,bucket", sorted(SERVE_PINS))
def test_serve_program_op_counts(serve_audit_engine, kind, bucket):
    """Each serve program family keeps its pinned op structure."""
    transposes, act, dots, _ = SERVE_PINS[(kind, bucket)]
    c = _counts(hlo_audit.serve_lower_text(serve_audit_engine, kind,
                                           bucket))
    assert c["dot_generals"] == dots, (kind, c)
    assert c["transposes"] == transposes, (kind, c)
    assert c["activation_transposes"] == act, (kind, c)
    assert c["convolutions"] == 0 and c["all_to_alls"] == 0, (kind, c)


@pytest.mark.parametrize("kind,bucket", sorted(SERVE_PINS))
def test_serve_program_cost_flops(serve_audit_engine, kind, bucket):
    """cost_analysis() flops — the numbers the engine's perf cost
    table captures at resolve time — stay pinned per family."""
    flops = hlo_audit.serve_cost_flops(serve_audit_engine, kind, bucket)
    assert flops is not None, (kind, bucket)
    assert int(flops) == SERVE_PINS[(kind, bucket)][3], (kind, flops)


def test_analytic_flops_cross_check(serve_audit_engine):
    """flops.gpt_token_flops / gpt_prefill_flops (the analytic fallback
    and the MFU denominators surfaced in docs) agree with the XLA
    cost_analysis() numbers to within model-shape slop: the analytic
    count ignores softmax/layernorm flops while cost_analysis bills
    them, so the ratio analytic/measured sits in a tight band below 1
    at tiny d_model and approaches 1 as matmuls dominate."""
    from mxnet_tpu import flops as F

    spec = serve_audit_engine.spec
    d_model = spec["d_model"]
    head_dim, kvh = spec["head_dim"], spec["kv_heads"]
    heads = d_model // head_dim
    # decode attends over the PADDED paged context (the whole table)
    ctx = serve_audit_engine.max_model_len

    per_tok = F.gpt_token_flops(
        n_layers=spec["n_layers"], d_model=d_model, num_heads=heads,
        head_dim=head_dim, kv_heads=kvh, vocab=spec["vocab"],
        context=ctx)
    measured = hlo_audit.serve_cost_flops(serve_audit_engine,
                                          "decode", 4)
    ratio = (4 * per_tok) / measured
    assert 0.5 < ratio < 1.5, (4 * per_tok, measured)

    pre = F.gpt_prefill_flops(
        n_layers=spec["n_layers"], d_model=d_model, num_heads=heads,
        head_dim=head_dim, kv_heads=kvh, vocab=spec["vocab"],
        seq_len=8)
    measured = hlo_audit.serve_cost_flops(serve_audit_engine,
                                          "prefill", 8)
    ratio = pre / measured
    assert 0.5 < ratio < 1.5, (pre, measured)
