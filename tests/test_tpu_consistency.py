"""TPU-vs-CPU consistency (rebuild of tests/python/gpu/test_operator_gpu.py:
run the same symbols on both backends and compare forward/backward within
dtype tolerances).

The main suite pins JAX to the virtual-CPU backend (conftest.py), so
these tests drive the REAL chip from a subprocess with the session's
default (axon) platform.  Gated behind MXTPU_TPU_TESTS=1 — they need
the tunnel and pay first-compile latency — and skipped cleanly when the
chip is unreachable.

Run: MXTPU_TPU_TESTS=1 python -m pytest tests/test_tpu_consistency.py -q
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tunnel-state cache across cases: a down tunnel HANGS backend init, so
# without this every remaining case would burn its full 560s subprocess
# timeout (24 cases = hours of lost window).  After one observed init
# hang, later cases first run a cheap 45s probe and skip instantly
# while a recent probe failure is still fresh.
_TUNNEL = {"down_at": 0.0, "probe_failed_at": 0.0}
_PROBE_TTL_S = 120.0


def _probe_tpu(timeout=90):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # conftest pins pytest itself to CPU
    code = ("import jax, sys; "
            "sys.exit(0 if any(d.platform == 'tpu' for d in jax.devices()) "
            "else 1)")
    try:
        ok = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                            capture_output=True, env=env).returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        _TUNNEL["down_at"] = _TUNNEL["probe_failed_at"] = 0.0
    else:
        _TUNNEL["probe_failed_at"] = time.monotonic()
    return ok


def _skip_if_tunnel_down():
    """Skip (cheaply) while the tunnel is known down.  Used both before
    the CPU-side run — no point computing a reference the TPU side will
    discard — and before spawning the TPU worker."""
    if not _TUNNEL["down_at"]:
        return
    if time.monotonic() - _TUNNEL["probe_failed_at"] < _PROBE_TTL_S:
        pytest.skip("TPU unreachable (probe failed recently)")
    if not _probe_tpu():
        pytest.skip("TPU unreachable (probe)")
    # the tunnel came BACK: an empty TPU batch cached while it was down
    # is stale — evict it so the remaining cases spawn a fresh worker
    # instead of all skipping on "no TPU result"
    if not _BATCH.get("tpu", True):
        del _BATCH["tpu"]

pytestmark = pytest.mark.skipif(
    os.environ.get("MXTPU_TPU_TESTS") != "1",
    reason="TPU consistency tests gated behind MXTPU_TPU_TESTS=1")

_WORKER = r"""
import json, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
# full f32 matmul/conv precision: the default bf16 MXU passes are fine
# for training but flip ReLU boundaries, which makes gradient comparison
# against CPU meaningless at those elements
jax.config.update("jax_default_matmul_precision", "highest")
import mxnet_tpu as mx

# force backend init NOW and mark it: the harness distinguishes a
# tunnel hang (no marker -> skip) from a kernel/compile hang after
# init (marker present -> real failure)
jax.devices()
print("INIT_OK", flush=True)

cases = {}

def case(name):
    def deco(fn):
        cases[name] = fn
        return fn
    return deco

@case("conv_bn_relu")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="c")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = mx.sym.Activation(net, act_type="relu")
    return net, {"data": (4, 3, 8, 8)}, {"bn_moving_var": 1.0}

@case("fc_softmax")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax"), \
        {"data": (8, 12), "softmax_label": (8,)}, {}

@case("pool_flatten_dot")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    return net, {"data": (4, 2, 6, 6)}, {}

@case("rnn_lstm")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=8, num_layers=1, mode="lstm",
                     name="rnn")
    return net, {"data": (5, 2, 4)}, {}

@case("flash_attention_causal")
def _():
    # real Pallas kernel on TPU vs the interpreter on CPU, including the
    # causal block-skip path
    q = mx.sym.Variable("q")
    k = mx.sym.Variable("k")
    v = mx.sym.Variable("v")
    net = mx.sym.FlashAttention(q, k, v, causal=True)
    shp = (2, 2, 16, 8)
    return net, {"q": shp, "k": shp, "v": shp}, {}

@case("flash_attention_window_gqa")
def _():
    # sliding-window band + grouped-query (bshd native) composed: the
    # round-4 kernel variants, real Mosaic on TPU vs interpreter on CPU
    q = mx.sym.Variable("q")
    k = mx.sym.Variable("k")
    v = mx.sym.Variable("v")
    net = mx.sym.FlashAttention(q, k, v, causal=True, layout="bshd",
                                window=8, block_q=8, block_k=8)
    return net, {"q": (2, 16, 4, 8), "k": (2, 16, 2, 8),
                 "v": (2, 16, 2, 8)}, {}

@case("rope_gpt_block")
def _():
    # RoPE rotation feeding fused attention (rope is elementwise XLA,
    # but its trig must agree cross-platform through the kernel)
    q = mx.sym.Variable("q")
    k = mx.sym.Variable("k")
    v = mx.sym.Variable("v")
    net = mx.sym.FlashAttention(mx.sym.RoPE(q, layout="bshd"),
                                mx.sym.RoPE(k, layout="bshd"), v,
                                causal=True, layout="bshd",
                                block_q=8, block_k=8)
    shp = (2, 16, 2, 8)
    return net, {"q": shp, "k": shp, "v": shp}, {}

@case("llama_gpt_step")
def _():
    # the whole round-4 stack in one case: rmsnorm + swiglu + rope +
    # tied embeddings + GQA + windowed flash attention + fused CE head
    net = mx.models.gpt(13, 8, num_layers=1, d_model=16, num_heads=2,
                        kv_heads=1, attn_window=4, pos_embed="rope",
                        norm="rmsnorm", mlp="swiglu", tie_embeddings=True,
                        loss="ce")
    return net, {"data": (2, 8), "softmax_label": (2, 8)}, {}, {
        "data": lambda rng, shape: rng.randint(0, 13, shape)
        .astype(np.float32),
        "softmax_label": lambda rng, shape: rng.randint(0, 13, shape)
        .astype(np.float32)}

@case("layernorm_gelu")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.LayerNorm(data, name="ln")
    net = mx.sym.gelu(net)
    return net, {"data": (4, 32)}, {}

@case("rnn_lstm_pallas")
def _():
    # H=128 / N=8 / T>=8 meets the Mosaic eligibility gate
    # (ops/pallas_lstm.py fused_lstm_eligible), so on TPU this runs the
    # REAL fused Pallas kernel while the CPU side runs the lax.scan
    # cell — a genuine cross-implementation consistency check.
    # Weights get a 1/sqrt(H)-class init: at H=128 an N(0,1) recurrent
    # matrix saturates the gates and makes backward chaotic, so ANY two
    # correct implementations (even TPU scan vs CPU scan) disagree
    # wildly; on-chip fused-vs-scan agreement is separately pinned to
    # ~1e-6 by test_perf_contract's interpret parity plus this case
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=128, num_layers=1, mode="lstm",
                     name="rnnp")
    return net, {"data": (8, 8, 16)}, {}, {
        "rnnp_parameters": lambda rng, shape: rng.normal(
            0, 0.08, shape).astype(np.float32)}

@case("rnn_gru_pallas")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=128, num_layers=1, mode="gru",
                     name="rnng")
    return net, {"data": (8, 8, 16)}, {}, {
        "rnng_parameters": lambda rng, shape: rng.normal(
            0, 0.08, shape).astype(np.float32)}

@case("deconv")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Deconvolution(data, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=6, name="dc")
    return net, {"data": (2, 3, 7, 7)}, {}

@case("lrn_leaky")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.LRN(data, nsize=3, alpha=1e-4, beta=0.75, knorm=2.0)
    net = mx.sym.LeakyReLU(net, act_type="leaky", slope=0.1)
    return net, {"data": (2, 8, 6, 6)}, {}

@case("softmax_activation_channel")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxActivation(data, mode="channel")
    return net, {"data": (2, 5, 4, 4)}, {}

@case("upsampling_bilinear")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.UpSampling(data, scale=2, sample_type="bilinear",
                            num_filter=4, name="up")
    return net, {"data": (2, 4, 5, 5)}, {}

@case("spatial_transformer")
def _():
    data = mx.sym.Variable("data")
    loc = mx.sym.Variable("loc")
    net = mx.sym.SpatialTransformer(
        data, loc, target_shape=(6, 6), transform_type="affine",
        sampler_type="bilinear", name="st")
    return net, {"data": (2, 3, 8, 8), "loc": (2, 6)}, {}, {
        # near-identity affine params keep the sample grid in-bounds
        "loc": lambda rng, shape: (np.tile(
            np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
            + rng.normal(0, 0.05, (2, 6)).astype(np.float32))}

@case("roi_pooling")
def _():
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    net = mx.sym.ROIPooling(data, rois, pooled_size=(3, 3),
                            spatial_scale=1.0, name="roi")
    return net, {"data": (1, 4, 10, 10), "rois": (3, 5)}, {}, {
        "rois": lambda rng, shape: np.array(
            [[0, 1, 1, 7, 7], [0, 0, 0, 9, 9], [0, 2, 3, 6, 8]],
            np.float32)}

@case("correlation")
def _():
    a = mx.sym.Variable("data1")
    b = mx.sym.Variable("data2")
    net = mx.sym.Correlation(a, b, kernel_size=1, max_displacement=2,
                             stride1=1, stride2=1, pad_size=2)
    return net, {"data1": (1, 3, 8, 8), "data2": (1, 3, 8, 8)}, {}

@case("instance_l2norm")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.InstanceNorm(data, name="in")
    net = mx.sym.L2Normalization(net, mode="instance")
    return net, {"data": (3, 4, 5, 5)}, {}

@case("concat_slice_swap")
def _():
    a = mx.sym.Variable("data1")
    b = mx.sym.Variable("data2")
    net = mx.sym.Concat(a, b, dim=1)
    net = mx.sym.SwapAxis(net, dim1=1, dim2=2)
    parts = mx.sym.SliceChannel(net, num_outputs=2, axis=2)
    return parts[0] + parts[1], {"data1": (2, 3, 6), "data2": (2, 3, 6)}, {}

@case("pad_crop_pool_avg")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Pad(data, mode="edge", pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    net = mx.sym.Crop(net, offset=(1, 1), h_w=(6, 6))
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    return net, {"data": (2, 3, 6, 6)}, {}

@case("sequence_mask_reverse_last")
def _():
    data = mx.sym.Variable("data")
    lengths = mx.sym.Variable("len")
    net = mx.sym.SequenceMask(data, use_sequence_length=True,
                              sequence_length=lengths, value=0.0)
    net = mx.sym.SequenceReverse(net, use_sequence_length=True,
                                 sequence_length=lengths)
    net = mx.sym.SequenceLast(net, use_sequence_length=True,
                              sequence_length=lengths)
    return net, {"data": (6, 3, 4), "len": (3,)}, {}, {
        "len": lambda rng, shape: np.array([2, 6, 4], np.float32)}

@case("dropout_rng_invariance")
def _():
    # threefry is bit-identical across backends: the SAME mx seed must
    # produce the SAME dropout mask on CPU and TPU, making even a
    # stochastic op cross-platform comparable
    data = mx.sym.Variable("data")
    net = mx.sym.Dropout(data, p=0.4)
    return net * 3.0, {"data": (16, 32)}, {}

@case("embedding_gather_scatter")
def _():
    idx = mx.sym.Variable("idx")
    emb = mx.sym.Embedding(idx, input_dim=11, output_dim=6, name="emb")
    return mx.sym.sum(emb, axis=(1,)), {"idx": (4, 5)}, {}, {
        "idx": lambda rng, shape: rng.randint(0, 11, shape).astype(np.float32)}

def run_case(name):
    spec = cases[name]()
    sym, shapes, aux_init = spec[0], spec[1], spec[2]
    arg_init = spec[3] if len(spec) > 3 else {}
    rng = np.random.RandomState(0)
    mx.random.seed(0)  # RNG ops (dropout) draw identical keys on both sides
    exe = sym.simple_bind(mx.tpu(0) if %(tpu)s else mx.cpu(0),
                          grad_req="write", **shapes)
    for k, v in exe.arg_dict.items():
        if k in arg_init:
            v[:] = arg_init[k](rng, v.shape)
        else:
            v[:] = rng.normal(0, 1, v.shape)
    for k, v in exe.aux_dict.items():
        v[:] = aux_init.get(k, 0.0)
    outs = exe.forward(is_train=True)
    exe.backward([mx.nd.ones(o.shape) for o in outs])
    return {"outs": [np.asarray(o.asnumpy(), np.float64).tolist()
                     for o in outs],
            "grads": {k: np.asarray(g.asnumpy(), np.float64).tolist()
                      for k, g in exe.grad_dict.items() if g is not None}}


# one worker runs the WHOLE batch: jax import + backend init are paid
# once per platform instead of once per case (24x on a slow tunnel),
# and each finished case is flushed immediately so a mid-batch tunnel
# drop loses only the in-flight case
import traceback

for _name in sys.argv[1].split(","):
    print("CASE " + _name, flush=True)
    try:
        _res = run_case(_name)
    except Exception:
        _res = {"error": traceback.format_exc()[-2000:]}
    print("RESULT " + json.dumps({_name: _res}), flush=True)
print("BATCH_DONE", flush=True)
"""


CASES = ["conv_bn_relu", "fc_softmax",
         "pool_flatten_dot", "rnn_lstm",
         "flash_attention_causal",
         "flash_attention_window_gqa",
         "rope_gpt_block",
         "llama_gpt_step",
         "layernorm_gelu",
         "rnn_lstm_pallas", "rnn_gru_pallas",
         "deconv", "lrn_leaky",
         "softmax_activation_channel",
         "upsampling_bilinear",
         "spatial_transformer", "roi_pooling",
         "correlation", "instance_l2norm",
         "concat_slice_swap",
         "pad_crop_pool_avg",
         "sequence_mask_reverse_last",
         "dropout_rng_invariance",
         "embedding_gather_scatter"]

# one batch worker per platform, results cached for every test: jax
# import + backend init (the dominant cost on a cold/slow tunnel) are
# paid once instead of once per case
_BATCH = {}


def _batch_timeout(n_cases, tpu):
    """Worker budget scaled to the batch it actually runs: a fixed
    allowance for jax import + backend init (the tunnel-dominated
    cost) plus a per-case compile+run slice.  At the full 24-case
    batch this lands on the historical 1800s/1200s budgets; a 2-case
    retry batch no longer inherits a 24-case timeout."""
    return int((300 if tpu else 240) + (62 if tpu else 40) * n_cases)


def _spawn(names, tpu, timeout):
    """Run one worker over ``names``; returns (results, init_ok).
    Results map case -> payload dict or {"error": traceback}; cases
    missing from the map didn't run (worker died or timed out first)."""
    env = dict(os.environ)
    if not tpu:
        env["JAX_PLATFORMS"] = "cpu"  # worker calls config.update below
    elif env.get("JAX_PLATFORMS") == "cpu":
        # conftest pins the pytest process to CPU; the TPU worker must
        # not inherit that or it compares CPU against CPU vacuously
        del env["JAX_PLATFORMS"]
    src = _WORKER % {"repo": REPO, "tpu": "True" if tpu else "False"}
    if not tpu:
        src = src.replace(
            "import mxnet_tpu as mx",
            "import jax\njax.config.update('jax_platforms', 'cpu')\n"
            "import mxnet_tpu as mx")
    timed_out, stderr = False, ""
    try:
        r = subprocess.run([sys.executable, "-c", src, ",".join(names)],
                           capture_output=True, text=True, timeout=timeout,
                           env=env, cwd=REPO)
        out, stderr = r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired as e:
        timed_out = True
        out = e.stdout or b""
        out = (out.decode(errors="replace")
               if isinstance(out, bytes) else out)
    results, in_flight = {}, None
    for ln in out.splitlines():
        if ln.startswith("CASE "):
            in_flight = ln[len("CASE "):].strip()
        elif ln.startswith("RESULT "):
            results.update(json.loads(ln[len("RESULT "):]))
            in_flight = None
    init_ok = "INIT_OK" in out
    if in_flight is not None and in_flight not in results:
        # the worker died (timeout / hard crash, e.g. a Mosaic abort)
        # with this case on the device — a real per-case failure IF
        # init had completed AND the case plausibly hung on its own.
        # A timeout with earlier cases already completed means THEY
        # consumed the batch budget; blaming the in-flight case would
        # turn a slow tunnel into a false failure — leave it missing
        # (retried in a smaller follow-up batch, else skipped).
        if init_ok and not (timed_out and results):
            results[in_flight] = {
                "error": f"worker died mid-case ({'timeout' if timed_out else 'crash'}): "
                         + stderr[-1500:]}
    return results, init_ok


def _get_results(tpu):
    """Batch results for one platform, computed once per pytest run.
    Any case the first batch missed (crash kills the rest of a batch)
    is retried once in a follow-up batch."""
    key = "tpu" if tpu else "cpu"
    if key in _BATCH:
        return _BATCH[key]
    if tpu:
        _skip_if_tunnel_down()
        # cheap gate before committing the batch's 1800s worker timeout
        # to a hanging init: a 90s probe answers reachability first
        if not _probe_tpu():
            _TUNNEL["down_at"] = time.monotonic()
            _BATCH[key] = {}
            return _BATCH[key]
    results, init_ok = _spawn(CASES, tpu,
                              timeout=_batch_timeout(len(CASES), tpu))
    if tpu and not init_ok and not results:
        # a down tunnel HANGS backend init rather than failing fast
        _TUNNEL["down_at"] = _TUNNEL["probe_failed_at"] = time.monotonic()
        _BATCH[key] = {}
        return _BATCH[key]
    missing = [c for c in CASES if c not in results]
    if missing and (init_ok or not tpu):
        retry, _ = _spawn(missing, tpu,
                          timeout=_batch_timeout(len(missing), tpu))
        results.update(retry)
    _BATCH[key] = results
    return results


@pytest.mark.parametrize("case", CASES)
def test_tpu_matches_cpu(case):
    # check tunnel state BEFORE the CPU reference run too: while the
    # tunnel is down the CPU worker would spend tens of seconds per case
    # computing a reference the TPU side immediately discards
    _skip_if_tunnel_down()
    cpu = _get_results(tpu=False).get(case)
    assert cpu is not None, "CPU reference worker produced no result"
    assert "error" not in cpu, f"CPU reference failed:\n{cpu.get('error')}"
    _skip_if_tunnel_down()
    tpu_all = _get_results(tpu=True)
    tpu = tpu_all.get(case)
    if tpu is None:
        _skip_if_tunnel_down()
        pytest.skip("no TPU result (worker batch ended early)")
    assert "error" not in tpu, f"TPU case failed:\n{tpu.get('error')}"
    # The fused recurrent kernels compare DIFFERENT implementations
    # (Pallas kernel on the TPU VPU vs lax.scan on CPU): per-step
    # sigmoid/tanh approximation differences (~1e-3 in the output) feed
    # back through the recurrence for T steps, so forward gets the same
    # order-looser tolerance backward always had.  Measured drift at
    # T=8: max 2e-3 abs on 0.06% of elements.
    fwd_rtol, fwd_atol = ((1e-2, 5e-3)
                          if case in ("rnn_lstm_pallas", "rnn_gru_pallas")
                          else (2e-3, 1e-3))
    for o_t, o_c in zip(tpu["outs"], cpu["outs"]):
        np.testing.assert_allclose(np.array(o_t), np.array(o_c),
                                   rtol=fwd_rtol, atol=fwd_atol)
    for k in cpu["grads"]:
        # backward through batch statistics cancels catastrophically;
        # keep gradient tolerance an order looser than forward
        np.testing.assert_allclose(np.array(tpu["grads"][k]),
                                   np.array(cpu["grads"][k]),
                                   rtol=1e-2, atol=5e-3,
                                   err_msg=f"{case}:{k}")
