"""TPU-vs-CPU consistency (rebuild of tests/python/gpu/test_operator_gpu.py:
run the same symbols on both backends and compare forward/backward within
dtype tolerances).

The main suite pins JAX to the virtual-CPU backend (conftest.py), so
these tests drive the REAL chip from a subprocess with the session's
default (axon) platform.  Gated behind MXTPU_TPU_TESTS=1 — they need
the tunnel and pay first-compile latency — and skipped cleanly when the
chip is unreachable.

Run: MXTPU_TPU_TESTS=1 python -m pytest tests/test_tpu_consistency.py -q
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    os.environ.get("MXTPU_TPU_TESTS") != "1",
    reason="TPU consistency tests gated behind MXTPU_TPU_TESTS=1")

_WORKER = r"""
import json, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
# full f32 matmul/conv precision: the default bf16 MXU passes are fine
# for training but flip ReLU boundaries, which makes gradient comparison
# against CPU meaningless at those elements
jax.config.update("jax_default_matmul_precision", "highest")
import mxnet_tpu as mx

# force backend init NOW and mark it: the harness distinguishes a
# tunnel hang (no marker -> skip) from a kernel/compile hang after
# init (marker present -> real failure)
jax.devices()
print("INIT_OK", flush=True)

cases = {}

def case(name):
    def deco(fn):
        cases[name] = fn
        return fn
    return deco

@case("conv_bn_relu")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="c")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = mx.sym.Activation(net, act_type="relu")
    return net, {"data": (4, 3, 8, 8)}, {"bn_moving_var": 1.0}

@case("fc_softmax")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax"), \
        {"data": (8, 12), "softmax_label": (8,)}, {}

@case("pool_flatten_dot")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    return net, {"data": (4, 2, 6, 6)}, {}

@case("rnn_lstm")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=8, num_layers=1, mode="lstm",
                     name="rnn")
    return net, {"data": (5, 2, 4)}, {}

@case("flash_attention_causal")
def _():
    # real Pallas kernel on TPU vs the interpreter on CPU, including the
    # causal block-skip path
    q = mx.sym.Variable("q")
    k = mx.sym.Variable("k")
    v = mx.sym.Variable("v")
    net = mx.sym.FlashAttention(q, k, v, causal=True)
    shp = (2, 2, 16, 8)
    return net, {"q": shp, "k": shp, "v": shp}, {}

@case("layernorm_gelu")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.LayerNorm(data, name="ln")
    net = mx.sym.gelu(net)
    return net, {"data": (4, 32)}, {}

@case("rnn_lstm_pallas")
def _():
    # H=128 / N=8 / T>=8 meets the Mosaic eligibility gate
    # (ops/pallas_lstm.py fused_lstm_eligible), so on TPU this runs the
    # REAL fused Pallas kernel while the CPU side runs the lax.scan
    # cell — a genuine cross-implementation consistency check
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=128, num_layers=1, mode="lstm",
                     name="rnnp")
    return net, {"data": (8, 8, 16)}, {}

@case("rnn_gru_pallas")
def _():
    data = mx.sym.Variable("data")
    net = mx.sym.RNN(data, state_size=128, num_layers=1, mode="gru",
                     name="rnng")
    return net, {"data": (8, 8, 16)}, {}

name = sys.argv[1]
sym, shapes, aux_init = cases[name]()
rng = np.random.RandomState(0)
exe = sym.simple_bind(mx.tpu(0) if %(tpu)s else mx.cpu(0),
                      grad_req="write", **shapes)
for k, v in exe.arg_dict.items():
    v[:] = rng.normal(0, 1, v.shape)
for k, v in exe.aux_dict.items():
    v[:] = aux_init.get(k, 0.0)
outs = exe.forward(is_train=True)
exe.backward([mx.nd.ones(o.shape) for o in outs])
result = {"outs": [np.asarray(o.asnumpy(), np.float64).tolist()
                   for o in outs],
          "grads": {k: np.asarray(g.asnumpy(), np.float64).tolist()
                    for k, g in exe.grad_dict.items() if g is not None}}
print("RESULT " + json.dumps(result))
"""


def _run(case, tpu):
    env = dict(os.environ)
    if not tpu:
        env["JAX_PLATFORMS"] = "cpu"  # worker calls config.update below
    elif env.get("JAX_PLATFORMS") == "cpu":
        # conftest pins the pytest process to CPU; the TPU worker must
        # not inherit that or it compares CPU against CPU vacuously
        del env["JAX_PLATFORMS"]
    src = _WORKER % {"repo": REPO, "tpu": "True" if tpu else "False"}
    if not tpu:
        src = src.replace(
            "import mxnet_tpu as mx",
            "import jax\njax.config.update('jax_platforms', 'cpu')\n"
            "import mxnet_tpu as mx")
    try:
        r = subprocess.run([sys.executable, "-c", src, case],
                           capture_output=True, text=True, timeout=560,
                           env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        out = (out.decode(errors="replace")
               if isinstance(out, bytes) else out)
        if tpu and "INIT_OK" not in out:
            # a down tunnel HANGS backend init rather than failing fast
            pytest.skip("TPU unreachable (backend init hang)")
        # init completed but the case hung: a real kernel/compile hang
        raise
    if r.returncode != 0:
        if tpu and ("Unable to initialize backend" in r.stderr
                    or "DEADLINE" in r.stderr):
            pytest.skip("TPU unreachable")
        raise AssertionError(r.stderr[-2000:])
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("RESULT ")]
    assert line, r.stdout[-1000:]
    return json.loads(line[-1][len("RESULT "):])


@pytest.mark.parametrize("case", ["conv_bn_relu", "fc_softmax",
                                  "pool_flatten_dot", "rnn_lstm",
                                  "flash_attention_causal",
                                  "layernorm_gelu",
                                  "rnn_lstm_pallas", "rnn_gru_pallas"])
def test_tpu_matches_cpu(case):
    cpu = _run(case, tpu=False)
    tpu = _run(case, tpu=True)
    for o_t, o_c in zip(tpu["outs"], cpu["outs"]):
        np.testing.assert_allclose(np.array(o_t), np.array(o_c),
                                   rtol=2e-3, atol=1e-3)
    for k in cpu["grads"]:
        # backward through batch statistics cancels catastrophically;
        # keep gradient tolerance an order looser than forward
        np.testing.assert_allclose(np.array(tpu["grads"][k]),
                                   np.array(cpu["grads"][k]),
                                   rtol=1e-2, atol=5e-3,
                                   err_msg=f"{case}:{k}")
