"""Sharded (per-host) checkpointing: save/restore across DIFFERENT
sharding layouts on the 8-device CPU mesh (conftest.py forces
xla_force_host_platform_device_count=8).

The dense two-artifact checkpoint gathers to one host; the sharded path
(parallel/checkpoint.py) writes per-process shards and reassembles any
target layout on load — the pod-scale/orbax-class story."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import checkpoint as ckpt


def _mlp():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _trainer(mesh, param_specs=None, lr_sched=None):
    return mx.parallel.ShardedTrainer(
        _mlp(), {"data": (16, 8), "softmax_label": (16,)}, mesh=mesh,
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        param_specs=param_specs, lr_scheduler=lr_sched)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {"data": rng.standard_normal((16, 8)).astype(np.float32),
            "softmax_label": rng.randint(0, 10, 16).astype(np.float32)}


def test_save_load_roundtrip_same_layout(tmp_path):
    mesh = mx.parallel.make_mesh({"dp": 8})
    t = _trainer(mesh)
    for i in range(3):
        t.step(_batch(i))
    t.save_checkpoint_sharded(str(tmp_path), epoch=2)

    t2 = _trainer(mesh)
    t2.load_checkpoint_sharded(str(tmp_path), epoch=2)
    assert t2._num_update == t._num_update
    p1, p2 = t.get_params(), t2.get_params()
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k])
    np.testing.assert_array_equal(np.asarray(t._key), np.asarray(t2._key))
    # resumed training is bit-identical to continuing the original
    o1 = t.step(_batch(7))
    o2 = t2.step(_batch(7))
    np.testing.assert_array_equal(np.asarray(o1[0]), np.asarray(o2[0]))


def test_reshard_on_load(tmp_path):
    """Save under dp=8, restore under dp=2 x tp=4 with tensor-sharded
    FC weights — the layouts share no shard boundaries."""
    mesh1 = mx.parallel.make_mesh({"dp": 8})
    t = _trainer(mesh1)
    for i in range(2):
        t.step(_batch(i))
    t.save_checkpoint_sharded(str(tmp_path))

    mesh2 = mx.parallel.make_mesh({"dp": 2, "tp": 4})
    specs = {"fc1_weight": PartitionSpec("tp", None),
             "fc2_weight": PartitionSpec(None, "tp")}
    t2 = _trainer(mesh2, param_specs=specs)
    t2.load_checkpoint_sharded(str(tmp_path))
    p1, p2 = t.get_params(), t2.get_params()
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k])
    # the restored arrays really carry the new sharding
    ns = t2.params["fc1_weight"].sharding
    assert ns.spec == specs["fc1_weight"]
    # and the resharded trainer still trains (one step, finite loss)
    out = t2.step(_batch(5))
    assert np.isfinite(np.asarray(out[0])).all()


def test_async_save_and_wait(tmp_path):
    mesh = mx.parallel.make_mesh({"dp": 8})
    t = _trainer(mesh)
    t.step(_batch())
    t.save_checkpoint_sharded(str(tmp_path), epoch=0, async_save=True)
    t.wait_checkpoints()
    t2 = _trainer(mesh)
    t2.load_checkpoint_sharded(str(tmp_path), epoch=0)
    for k, v in t.get_params().items():
        np.testing.assert_array_equal(v, t2.get_params()[k])


def test_scheduler_state_rides_sharded_checkpoint(tmp_path):
    mesh = mx.parallel.make_mesh({"dp": 8})
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    t = _trainer(mesh, lr_sched=sched)
    for i in range(3):
        t.step(_batch(i))
    t.save_checkpoint_sharded(str(tmp_path), epoch=3)

    t2 = _trainer(mesh,
                  lr_sched=mx.lr_scheduler.FactorScheduler(step=2,
                                                           factor=0.5))
    t2.load_checkpoint_sharded(str(tmp_path), epoch=3)
    assert t2._num_update == 3
    # constant-lr trainer must NOT inherit the schedule
    t3 = _trainer(mesh)
    t3.load_checkpoint_sharded(str(tmp_path), epoch=3)
    assert t3._lr_scheduler is None


def test_missing_key_and_torn_checkpoint(tmp_path):
    mesh = mx.parallel.make_mesh({"dp": 8})
    t = _trainer(mesh)
    t.step(_batch())
    t.save_checkpoint_sharded(str(tmp_path), epoch=0)
    step_dir = os.path.join(str(tmp_path), "step-0000")

    # unknown key in target -> clear error
    bad = {"params": {"nope": t.params["fc1_weight"]}}
    with pytest.raises(MXNetError, match="no entry"):
        ckpt.load_sharded(step_dir, bad)

    # a save that lost shards -> coverage error, not silent zeros
    import json
    meta_path = os.path.join(step_dir, "meta-proc0.json")
    with open(meta_path) as f:
        meta = json.load(f)
    key = "['params']['fc1_weight']"
    assert key in meta
    meta[key]["shards"] = meta[key]["shards"][:0]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(MXNetError, match="do not cover"):
        t.load_checkpoint_sharded(str(tmp_path), epoch=0)


def test_generic_pytree_roundtrip(tmp_path):
    """save_sharded/load_sharded work on any pytree, not just trainers."""
    mesh = mx.parallel.make_mesh({"dp": 8})
    sh = NamedSharding(mesh, PartitionSpec("dp"))
    rep = NamedSharding(mesh, PartitionSpec())
    tree = {"w": jax.device_put(np.arange(64, dtype=np.float32), sh),
            "nested": [jax.device_put(np.float32(3.5), rep),
                       jax.device_put(
                           np.arange(24, dtype=np.int32).reshape(8, 3),
                           sh)]}
    ckpt.save_sharded(str(tmp_path / "c"), tree, extra={"note": 7})
    restored, extra = ckpt.load_sharded(str(tmp_path / "c"), tree)
    assert extra == {"note": 7}
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_dtype_restore(tmp_path):
    """The live trainer's dtype is authoritative: an f32 checkpoint
    restored into a bf16 trainer must come back bf16."""
    mesh = mx.parallel.make_mesh({"dp": 8})
    t = _trainer(mesh)
    t.step(_batch())
    t.save_checkpoint_sharded(str(tmp_path))

    t2 = mx.parallel.ShardedTrainer(
        _mlp(), {"data": (16, 8), "softmax_label": (16,)}, mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        dtype="bfloat16")
    t2.load_checkpoint_sharded(str(tmp_path))
    bf16 = jax.numpy.bfloat16.dtype
    assert t2.params["fc1_weight"].dtype == bf16
    np.testing.assert_allclose(
        np.asarray(t2.params["fc1_weight"], np.float32),
        t.get_params()["fc1_weight"], rtol=1e-2, atol=1e-2)


def test_custom_optimizer_kwargs_and_legacy_4arg():
    """update(**kwargs) and legacy update(g, s, p, scale) forms both
    keep working with the keyword lr_scale call convention."""
    mesh = mx.parallel.make_mesh({"dp": 8})

    def init_fn(params):
        return {}

    def update_kw(grads, state, params, **kw):
        lr = 0.1 * kw.get("lr_scale", 1.0)
        return {k: p - lr * grads[k] for k, p in params.items()}, state

    t = mx.parallel.ShardedTrainer(
        _mlp(), {"data": (16, 8), "softmax_label": (16,)}, mesh=mesh,
        optimizer=(init_fn, update_kw),
        lr_scheduler=mx.lr_scheduler.FactorScheduler(step=1, factor=0.5))
    out = t.step(_batch())
    assert np.isfinite(np.asarray(out[0])).all()

    def update_legacy(grads, state, params, scale):
        return ({k: p - 0.1 * scale * grads[k]
                 for k, p in params.items()}, state)

    t2 = mx.parallel.ShardedTrainer(
        _mlp(), {"data": (16, 8), "softmax_label": (16,)}, mesh=mesh,
        optimizer=(init_fn, update_legacy))
    out = t2.step(_batch())
    assert np.isfinite(np.asarray(out[0])).all()


def test_bf16_shards_roundtrip(tmp_path):
    mesh = mx.parallel.make_mesh({"dp": 8})
    sh = NamedSharding(mesh, PartitionSpec("dp"))
    x = np.arange(32).astype("float32") / 7.0
    tree = {"w": jax.device_put(x.astype(jax.numpy.bfloat16.dtype), sh)}
    ckpt.save_sharded(str(tmp_path / "c"), tree)
    restored, _ = ckpt.load_sharded(str(tmp_path / "c"), tree)
    assert restored["w"].dtype == jax.numpy.bfloat16.dtype
    np.testing.assert_array_equal(np.asarray(restored["w"], np.float32),
                                  np.asarray(tree["w"], np.float32))


def test_latest_complete_step_numeric_and_partial(tmp_path):
    """Resume-point scan: numeric ordering past the 4-digit padding
    (step-10000 > step-9999 despite lexicographic order) and torn-save
    skipping (a step missing any proc's meta/npz is not complete)."""
    import os

    from mxnet_tpu.parallel.checkpoint import latest_complete_step

    def make(step, procs, torn=False):
        d = tmp_path / f"step-{step:04d}"
        d.mkdir()
        for p in range(procs):
            (d / f"meta-proc{p}.json").write_text("{}")
            if not (torn and p == procs - 1):
                (d / f"shards-proc{p}.npz").write_text("x")

    assert latest_complete_step(str(tmp_path), n_procs=2) is None
    make(3, 2)
    make(9999, 2)
    make(10000, 2)          # lexicographically BELOW step-9999
    make(10001, 2, torn=True)   # newest but incomplete -> skipped
    (tmp_path / "step-bogus").mkdir()   # non-numeric dir ignored
    assert latest_complete_step(str(tmp_path), n_procs=2) == 10000
    # no step carries a third proc's shards: nothing is complete at 3
    assert latest_complete_step(str(tmp_path), n_procs=3) is None
