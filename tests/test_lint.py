"""mxtpu-lint: the tier-1 static-analysis gate plus the suite's own
contract tests.

Three layers:

1. **The gate** — ``python tools/mxtpu_lint.py mxnet_tpu tools`` must
   exit 0 against the committed baseline (tools/lint_baseline.json,
   kept EMPTY: every waiver in the tree is a per-line suppression with
   a reason, not a baseline entry).  This is what keeps the bug
   classes of PRs 2-6 from regrowing.
2. **Fixture tests** — for every checker, a ``*_bad.py`` fixture under
   tests/lint_fixtures/ reproduces the PRE-FIX shape of real code this
   PR cleaned up (it must produce findings) and a ``*_ok.py`` fixture
   carries the post-fix shape (it must be clean).  If a checker stops
   firing on its bad fixture, the gate has silently gone blind.
3. **Workflow tests** — suppression comments, the baseline round trip,
   and the check_env_docs regression pin (the env-docs drift gate from
   PR 5 survives its refactor onto the linter's scanner).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from mxnet_tpu.lint import (LintContext, SourceFile, all_checkers,  # noqa: E402
                            apply_baseline, hot_path, load_baseline,
                            run_lint, save_baseline)

FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

REQUIRED_CHECKERS = {
    "wall-clock", "host-sync", "jit-cache-capture", "use-after-donate",
    "env-discipline", "unlocked-shared-state", "swallowed-exception"}


def lint_fixture(name, checks=None):
    """Findings for one fixture file, linted against the REAL repo
    context (so documented env vars resolve)."""
    findings, errors = run_lint(
        [os.path.join(FIXTURES, name)], repo=REPO, checks=checks)
    assert not errors, errors
    return findings


def counts(findings):
    out = {}
    for f in findings:
        out[f.check] = out.get(f.check, 0) + 1
    return out


# -- 1. the tier-1 gate ------------------------------------------------------
def test_registry_has_all_required_checkers():
    assert REQUIRED_CHECKERS <= set(all_checkers())


def test_repo_is_lint_clean():
    """THE gate: zero non-baselined findings over mxnet_tpu/ + tools/.

    Run in-process (same linter the CLI wraps) so the failure message
    lists the findings directly."""
    findings, errors = run_lint(
        [os.path.join(REPO, "mxnet_tpu"), os.path.join(REPO, "tools")],
        repo=REPO)
    assert not errors, f"unparseable sources: {errors}"
    baseline = load_baseline(os.path.join(REPO, "tools",
                                          "lint_baseline.json"))
    new, _, stale = apply_baseline(findings, baseline)
    msg = "\n".join(f.render() for f in new)
    assert not new, f"new lint findings (fix or suppress with a " \
                    f"reason):\n{msg}"
    assert not stale, f"stale baseline entries (delete them): {stale}"


def test_cli_acceptance_invocation():
    """The acceptance-criteria command exits 0 and the JSON report is
    machine-readable (bench_watch's lint stage consumes it)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtpu_lint.py"),
         "mxnet_tpu", "tools", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["clean"] is True
    assert doc["findings"] == []
    assert REQUIRED_CHECKERS <= set(doc["checks"])


def test_baseline_is_empty_or_justified():
    """The committed baseline must stay empty — or every entry must
    carry a non-trivial 'why' (acceptance criterion)."""
    path = os.path.join(REPO, "tools", "lint_baseline.json")
    with open(path) as f:
        data = json.load(f)
    for e in data.get("entries", []):
        assert len(e.get("why", "").strip()) >= 10, \
            f"baseline entry without a justification: {e}"


# -- 2. per-checker fixtures (pre-fix shape fails, post-fix is clean) --------
@pytest.mark.parametrize("check,bad,expect_min", [
    ("wall-clock", "wall_clock_bad.py", 3),
    ("host-sync", "host_sync_bad.py", 3),
    ("jit-cache-capture", "jit_cache_capture_bad.py", 4),
    ("use-after-donate", "use_after_donate_bad.py", 3),
    ("env-discipline", "env_discipline_bad.py", 5),
    ("unlocked-shared-state", "unlocked_shared_state_bad.py", 2),
    ("swallowed-exception", "swallowed_exception_bad.py", 2),
])
def test_checker_fires_on_prefix_shape(check, bad, expect_min):
    found = counts(lint_fixture(bad, checks=[check]))
    assert found.get(check, 0) >= expect_min, \
        f"{check} went blind on {bad}: {found}"


@pytest.mark.parametrize("check,ok", [
    ("wall-clock", "wall_clock_ok.py"),
    ("host-sync", "host_sync_ok.py"),
    ("jit-cache-capture", "jit_cache_capture_ok.py"),
    ("use-after-donate", "use_after_donate_ok.py"),
    ("env-discipline", "env_discipline_ok.py"),
    ("unlocked-shared-state", "unlocked_shared_state_ok.py"),
    ("swallowed-exception", "swallowed_exception_ok.py"),
])
def test_checker_clean_on_postfix_shape(check, ok):
    found = lint_fixture(ok, checks=[check])
    msg = "\n".join(f.render() for f in found)
    assert not found, f"false positives on {ok}:\n{msg}"


def test_bad_fixtures_pinpoint_the_planted_lines():
    """Spot-check line anchoring: the wall-clock fixture's findings
    land on the exact time.time() lines."""
    lines = {f.line for f in lint_fixture("wall_clock_bad.py",
                                          checks=["wall-clock"])}
    src = open(os.path.join(FIXTURES, "wall_clock_bad.py")).read()
    expected = {i for i, l in enumerate(src.splitlines(), 1)
                if "time.time()" in l}
    assert lines == expected


# -- 3. suppression / baseline / hot_path workflow ---------------------------
def _lint_src(tmp_path, src, checks=None, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    findings, errors = run_lint([str(p)], repo=REPO, checks=checks)
    assert not errors, errors
    return findings


def test_suppression_same_line_and_line_above(tmp_path):
    base = """
    import time

    def f():
        return time.time()
    """
    assert len(_lint_src(tmp_path, base, ["wall-clock"])) == 1

    same_line = """
    import time

    def f():
        return time.time()  # mxtpu-lint: disable=wall-clock (ts)
    """
    assert _lint_src(tmp_path, same_line, ["wall-clock"]) == []

    line_above = """
    import time

    def f():
        # a multi-line waiver, the reason on its own line:
        # mxtpu-lint: disable=wall-clock (record timestamp for logs)
        return time.time()
    """
    assert _lint_src(tmp_path, line_above, ["wall-clock"]) == []


def test_suppression_disable_all_and_unrelated_check(tmp_path):
    src = """
    import time

    def f():
        return time.time()  # mxtpu-lint: disable=all (generated)
    """
    assert _lint_src(tmp_path, src, ["wall-clock"]) == []
    unrelated = """
    import time

    def f():
        return time.time()  # mxtpu-lint: disable=host-sync (wrong id)
    """
    assert len(_lint_src(tmp_path, unrelated, ["wall-clock"])) == 1


def test_baseline_round_trip(tmp_path):
    """findings -> write baseline -> re-run = clean; a NEW finding
    still fails; fixing the baselined line turns the entry stale."""
    p = tmp_path / "mod.py"
    p.write_text("import time\n\n"
                 "def f():\n    return time.time()\n")
    findings, _ = run_lint([str(p)], repo=REPO, checks=["wall-clock"])
    assert len(findings) == 1

    bl_path = tmp_path / "baseline.json"
    save_baseline(str(bl_path), findings, why="grandfathered for test")
    baseline = load_baseline(str(bl_path))
    new, matched, stale = apply_baseline(findings, baseline)
    assert new == [] and len(matched) == 1 and stale == []

    # a second offending line is NOT covered by the single-count entry
    p.write_text("import time\n\n"
                 "def f():\n    return time.time()\n\n"
                 "def g():\n    return time.time()\n")
    findings2, _ = run_lint([str(p)], repo=REPO, checks=["wall-clock"])
    new2, matched2, _ = apply_baseline(findings2, baseline)
    assert len(new2) == 1 and len(matched2) == 1

    # fixing the file leaves the baseline entry stale (reported so it
    # gets deleted — baselines shrink, never linger)
    p.write_text("import time\n\n"
                 "def f():\n    return time.perf_counter()\n")
    findings3, _ = run_lint([str(p)], repo=REPO, checks=["wall-clock"])
    new3, _, stale3 = apply_baseline(findings3, baseline)
    assert new3 == [] and len(stale3) == 1


def test_baseline_survives_line_drift(tmp_path):
    """Baseline entries key on (check, path, code), not line numbers —
    inserting lines above must not un-baseline a finding."""
    p = tmp_path / "mod.py"
    p.write_text("import time\n\ndef f():\n    return time.time()\n")
    findings, _ = run_lint([str(p)], repo=REPO, checks=["wall-clock"])
    bl_path = tmp_path / "baseline.json"
    save_baseline(str(bl_path), findings)
    p.write_text("import time\n\n# new comment\n# more lines\n\n"
                 "def f():\n    return time.time()\n")
    findings2, _ = run_lint([str(p)], repo=REPO, checks=["wall-clock"])
    new, matched, stale = apply_baseline(findings2,
                                         load_baseline(str(bl_path)))
    assert new == [] and len(matched) == 1 and stale == []


def test_hot_path_decorator_is_runtime_inert():
    @hot_path
    def fn(x):
        return x + 1

    assert fn(1) == 2
    assert getattr(fn, "__mxtpu_hot_path__") is True


def test_parse_error_is_loud(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings, errors = run_lint([str(p)], repo=REPO)
    assert findings == []
    assert len(errors) == 1 and "syntax error" in errors[0][1]


def test_guard_annotation_binds_to_its_own_line():
    src = ("import threading\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.q = []   # guarded-by: _lock\n"
           "    def bad(self):\n"
           "        self.q = []\n"
           "    def ok(self):\n"
           "        with self._lock:\n"
           "            self.q = []\n")
    sf = SourceFile("s.py", src)
    chk = all_checkers()["unlocked-shared-state"]()
    found = list(chk.check(sf, LintContext(REPO)))
    assert [f.line for f in found] == [7]


# -- 4. env-docs drift gate regression (check_env_docs -> linter) ------------
def _fake_repo(tmp_path, code, docs):
    (tmp_path / "mxnet_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "mxnet_tpu" / "mod.py").write_text(code)
    (tmp_path / "docs" / "env_vars.md").write_text(docs)
    return tmp_path


def test_env_docs_gate_previous_behavior_survives_refactor(tmp_path):
    """Pin check_env_docs.py's contract on the linter scanner: an
    undocumented MXTPU_* read fails, documenting it passes, and the
    linter's env-discipline checker reports the same drift."""
    import check_env_docs

    repo = _fake_repo(
        tmp_path,
        code="import os\nX = os.environ.get('MXTPU_SHINY_NEW_KNOB')\n",
        docs="| MXTPU_TELEMETRY | off | metrics |\n")
    missing, documented = check_env_docs.check(str(repo))
    assert set(missing) == {"MXTPU_SHINY_NEW_KNOB"}
    assert "MXTPU_TELEMETRY" in documented
    assert check_env_docs.main(["--repo", str(repo)]) == 1

    findings, _ = run_lint([str(repo / "mxnet_tpu")], repo=str(repo),
                           checks=["env-discipline"])
    assert any("MXTPU_SHINY_NEW_KNOB" in f.message for f in findings)

    # documenting the knob clears both faces of the gate
    (repo / "docs" / "env_vars.md").write_text(
        "| MXTPU_TELEMETRY | off | metrics |\n"
        "| MXTPU_SHINY_NEW_KNOB | - | new knob |\n")
    missing2, _ = check_env_docs.check(str(repo))
    assert missing2 == {}
    assert check_env_docs.main(["--repo", str(repo)]) == 0
    findings2, _ = run_lint([str(repo / "mxnet_tpu")], repo=str(repo),
                            checks=["env-discipline"])
    assert findings2 == []


def test_env_docs_real_repo_still_clean():
    import check_env_docs

    missing, documented = check_env_docs.check(REPO)
    assert missing == {}, f"undocumented MXTPU_* vars: {missing}"
    assert len(documented) >= 30
