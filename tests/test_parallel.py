"""Mesh sharding / ShardedTrainer / collectives on the 8-device virtual
CPU mesh (the multi-chip path the driver dry-runs on real topology)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx


def _toy(n=256, d=16, c=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, c).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    return X, y


def test_make_mesh():
    mesh = mx.parallel.make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh2 = mx.parallel.make_mesh({"dp": -1})
    assert mesh2.devices.size == len(jax.devices())


def test_allreduce():
    mesh = mx.parallel.make_mesh({"dp": 8})
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    x = jax.device_put(jnp.arange(8.0).reshape(8, 1),
                       NamedSharding(mesh, P("dp")))
    out = mx.parallel.allreduce(x, mesh, "dp")
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))


def test_allreduce_bench_runs():
    res = mx.parallel.allreduce_bench(sizes_mb=(1,), n_iter=2, verbose=False)
    assert res[0]["gbps_per_device"] > 0


def test_sharded_trainer_dp():
    np.random.seed(0)  # Xavier draws from numpy's global state
    X, y = _toy()
    net = mx.models.mlp(num_classes=4)
    mesh = mx.parallel.make_mesh({"dp": 8})
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.3,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    for i in range(60):
        b = (i * 64) % (256 - 64)
        tr.step({"data": X[b:b + 64], "softmax_label": y[b:b + 64]})
    pred = np.asarray(tr.eval({"data": X[:64],
                               "softmax_label": y[:64]})[0]).argmax(1)
    assert (pred == y[:64]).mean() > 0.9


def test_sharded_trainer_dp_tp_matches_dp():
    """Tensor-parallel sharding must not change the math."""
    X, y = _toy()
    net = mx.models.mlp(num_classes=4)

    def build(mesh, specs):
        mx.random.seed(0)
        np.random.seed(0)
        return mx.parallel.ShardedTrainer(
            net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
            param_specs=specs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.initializer.Xavier())

    t1 = build(mx.parallel.make_mesh({"dp": 8}), None)
    t2 = build(mx.parallel.make_mesh({"dp": 2, "tp": 4}),
               {"fc1_weight": P("tp", None), "fc2_weight": P(None, "tp")})
    batch = {"data": X[:64], "softmax_label": y[:64]}
    for _ in range(3):
        t1.step(batch)
        t2.step(batch)
    p1 = t1.get_params()
    p2 = t2.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], atol=2e-5, rtol=1e-4)


def test_sharded_trainer_sequence_axis():
    """Sequence/context parallel: activations sharded over 'sp'."""
    T, N, D, C = 8, 16, 8, 3
    rng = np.random.RandomState(0)
    X = rng.randn(N, T, D).astype(np.float32)
    y = rng.randint(0, C, N).astype(np.float32)
    data = mx.sym.Variable("data")
    # mean-pool over time then classify
    pooled = mx.sym.mean(data, axis=(1,))
    fc = mx.sym.FullyConnected(pooled, num_hidden=C, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mesh = mx.parallel.make_mesh({"dp": 2, "sp": 4})
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (N, T, D), "softmax_label": (N,)}, mesh=mesh,
        sequence_specs={"data": P("dp", "sp", None)},
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        initializer=mx.initializer.Xavier())
    out = tr.step({"data": X, "softmax_label": y})
    assert np.asarray(out[0]).shape == (N, C)


def test_trainer_checkpoint_surface():
    net = mx.models.mlp(num_classes=4)
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (8, 16), "softmax_label": (8,)},
        mesh=mx.parallel.make_mesh({"dp": 2}),
        initializer=mx.initializer.Xavier())
    params = tr.get_params()
    tr2 = mx.parallel.ShardedTrainer(
        net, {"data": (8, 16), "softmax_label": (8,)},
        mesh=mx.parallel.make_mesh({"dp": 4}),
        initializer=mx.initializer.Xavier())
    tr2.set_params(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(jax.device_get(tr2.params[k])),
                                   params[k], rtol=1e-6)


def test_module_multi_device_training_parity():
    """1-context vs 2-context data-parallel Module training produces the
    same parameters given the same init and batches (the nightly
    multi_lenet.py equality concept, tests/nightly/multi_lenet.py)."""
    import mxnet_tpu as mx

    rng = np.random.RandomState(0)
    X = rng.standard_normal((64, 20)).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)

    def build():
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
        act = mx.sym.Activation(fc1, act_type="relu")
        fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=4)
        return mx.sym.SoftmaxOutput(fc2, name="softmax")

    def train(ctxs):
        np.random.seed(7)  # initializers draw from numpy's global state
        mod = mx.mod.Module(build(), context=ctxs)
        it = mx.io.NDArrayIter(X, y, 32)
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(initializer=mx.init.Xavier(rnd_type="gaussian"))
        mod.init_optimizer(optimizer="sgd", optimizer_params={
            "learning_rate": 0.1, "rescale_grad": 1.0 / 32})
        for _ in range(3):
            it.reset()
            for batch in it:
                mod.forward_backward(batch)
                mod.update()
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    p1 = train([mx.cpu(0)])
    p2 = train([mx.cpu(0), mx.cpu(0)])
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_sharded_trainer_adamw():
    np.random.seed(1)
    X, y = _toy()
    net = mx.models.mlp(num_classes=4)
    mesh = mx.parallel.make_mesh({"dp": 4})
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
        optimizer="adamw",
        optimizer_params={"learning_rate": 0.01, "weight_decay": 0.01},
        initializer=mx.initializer.Xavier())
    for i in range(40):
        b = (i * 64) % (256 - 64)
        tr.step({"data": X[b:b + 64], "softmax_label": y[b:b + 64]})
    pred = np.asarray(tr.eval({"data": X[:64],
                               "softmax_label": y[:64]})[0]).argmax(1)
    assert (pred == y[:64]).mean() > 0.85


def test_sharded_trainer_fit_and_checkpoint(tmp_path):
    """fit() with prefetch overlap converges, and the checkpoint
    round-trip (params + aux + optimizer state) resumes exactly."""
    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    net = mx.models.mlp(num_classes=2)
    mesh = mx.parallel.make_mesh({"dp": 8})

    mx.random.seed(0)
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.3,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    metric = tr.fit(it, num_epochs=8, eval_metric="accuracy")
    assert metric.get()[1] > 0.9

    prefix = str(tmp_path / "st")
    tr.save_checkpoint(prefix, 8)

    # fresh trainer, restore, step both with the same batch: identical
    mx.random.seed(0)
    tr2 = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.3,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    tr2.load_checkpoint(prefix, 8)
    key = np.asarray(jax.device_get(tr._key))
    tr._key = jax.device_put(key, tr._replicated)
    tr2._key = jax.device_put(key, tr2._replicated)
    batch = {"data": X[:64], "softmax_label": y[:64]}
    tr.step(batch)
    tr2.step(batch)
    p1, p2 = tr.get_params(), tr2.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], atol=1e-6, rtol=1e-6)


def test_grad_accumulation_matches_full_batch():
    """grad_accum_steps=4 must produce the same update as one full-batch
    step (deterministic net: no dropout), with microbatch outputs
    reassembled to the global batch."""
    rng = np.random.RandomState(1)
    X = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    net = mx.models.mlp(num_classes=4)
    mesh = mx.parallel.make_mesh({"dp": 8})

    def build(accum):
        mx.random.seed(0)
        np.random.seed(0)
        return mx.parallel.ShardedTrainer(
            net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
            optimizer="sgd", optimizer_params={"learning_rate": 0.2},
            initializer=mx.initializer.Xavier(), grad_accum_steps=accum)

    t1, t4 = build(1), build(4)
    batch = {"data": X, "softmax_label": y}
    o1 = t1.step(batch)
    o4 = t4.step(batch)
    np.testing.assert_allclose(np.asarray(o1[0]), np.asarray(o4[0]),
                               atol=2e-5, rtol=1e-4)
    p1, p4 = t1.get_params(), t4.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], atol=2e-5, rtol=1e-4)


def test_zero1_optimizer_state_sharding():
    """shard_optimizer_state=True (ZeRO-1): Adam moments of replicated
    params shard over dp; the math must not change."""
    from jax.sharding import PartitionSpec

    rng = np.random.RandomState(2)
    X = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    net = mx.models.mlp(num_classes=4)
    mesh = mx.parallel.make_mesh({"dp": 8})

    def build(zero):
        mx.random.seed(0)
        np.random.seed(0)
        return mx.parallel.ShardedTrainer(
            net, {"data": (64, 16), "softmax_label": (64,)}, mesh=mesh,
            optimizer="adam", optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(),
            shard_optimizer_state=zero)

    t0, tz = build(False), build(True)
    # the moment buffers really are dp-sharded (divisible leading dims)
    sharded_leaves = [
        l for l in jax.tree_util.tree_leaves(tz.opt_state)
        if getattr(l, "ndim", 0) >= 1
        and l.sharding.spec == PartitionSpec("dp")]
    assert sharded_leaves, "no optimizer state actually sharded"

    batch = {"data": X, "softmax_label": y}
    for _ in range(3):
        t0.step(batch)
        tz.step(batch)
    p0, pz = t0.get_params(), tz.get_params()
    for k in p0:
        np.testing.assert_allclose(p0[k], pz[k], atol=2e-5, rtol=1e-4)


def test_async_checkpoint_overlaps_and_restores(tmp_path):
    """async_save stages writes on the engine IO lane; training can
    continue immediately, wait_checkpoints() makes the files durable,
    and the snapshot reflects the state AT save time (later steps must
    not leak in)."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 16).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    net = mx.models.mlp(num_classes=2)
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)},
        mesh=mx.parallel.make_mesh({"dp": 8}),
        optimizer="sgd", optimizer_params={"learning_rate": 0.3},
        initializer=mx.initializer.Xavier())
    batch = {"data": X, "softmax_label": y}
    tr.step(batch)
    snap = tr.get_params()
    prefix = str(tmp_path / "ac")
    tr.save_checkpoint(prefix, 1, async_save=True)
    tr.step(batch)  # keeps training while the write is in flight
    tr.wait_checkpoints()

    tr2 = mx.parallel.ShardedTrainer(
        net, {"data": (64, 16), "softmax_label": (64,)},
        mesh=mx.parallel.make_mesh({"dp": 8}),
        optimizer="sgd", optimizer_params={"learning_rate": 0.3},
        initializer=mx.initializer.Xavier())
    tr2.load_checkpoint(prefix, 1)
    for k, v in tr2.get_params().items():
        np.testing.assert_allclose(v, snap[k], atol=1e-6)

    # failure surfacing: an async writer that fails must re-raise at
    # wait_checkpoints (exercises the staged path, not the sync
    # symbol.save precheck)
    from mxnet_tpu import model as model_mod

    def bad_writer(tmp):
        raise OSError("disk full")

    model_mod.stage_async_write(str(tmp_path / "bad.bin"), bad_writer)
    with pytest.raises(Exception, match="disk full"):
        tr.wait_checkpoints()


def test_lr_scheduler_in_trainer():
    """lr_scheduler feeds the compiled step as a traced scalar: a
    MultiFactorScheduler run matches two manual fixed-lr phases, and lr
    changes do NOT recompile the step (asserted via the jit cache)."""
    from mxnet_tpu.lr_scheduler import MultiFactorScheduler

    rng = np.random.RandomState(0)
    X = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.float32)
    net = mx.models.mlp(num_classes=4)

    def build(**kw):
        mx.random.seed(0)
        np.random.seed(0)
        return mx.parallel.ShardedTrainer(
            net, {"data": (32, 16), "softmax_label": (32,)},
            mesh=mx.parallel.make_mesh({"dp": 1}), optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.0}, **kw)

    # trainer wires base_lr from the optimizer (reference contract)
    sched = MultiFactorScheduler(step=[2], factor=0.5)
    t1 = build(lr_scheduler=sched)
    assert sched.base_lr == 0.2
    batch = {"data": X, "softmax_label": y}
    for _ in range(2):
        t1.step(batch)
    pre = t1._train_step._cache_size()
    for _ in range(2):
        t1.step(batch)  # scheduler halves lr here
    # the changed lr value must NOT trigger a new compilation
    assert t1._train_step._cache_size() == pre

    # manual: 2 steps at 0.2 then 2 at 0.1
    t2 = build()
    for i in range(4):
        scale = 1.0 if i < 2 else 0.5
        placed = t2._place_batch(batch)
        t2.params, t2.opt_state, t2.aux, _, t2._key = t2._train_step(
            t2.params, t2.opt_state, t2.aux, placed, t2._key,
            np.float32(scale))
    p1, p2 = t1.get_params(), t2.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], atol=1e-6, rtol=1e-5)


def test_gradient_clipping_semantics():
    """clip_gradient clamps per element (reference optimizer.py
    clip_gradient); clip_by_global_norm rescales the whole tree."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel.trainer import _clip_grads

    grads = {"a": jnp.array([3.0, -5.0, 0.5]),
             "b": jnp.array([[4.0, -0.1]])}
    clipped = _clip_grads(grads, clip_gradient=1.0)
    np.testing.assert_allclose(np.asarray(clipped["a"]), [1.0, -1.0, 0.5])
    np.testing.assert_allclose(np.asarray(clipped["b"]), [[1.0, -0.1]])

    norm = np.sqrt(sum((np.asarray(g) ** 2).sum() for g in grads.values()))
    scaled = _clip_grads(grads, clip_by_global_norm=1.0)
    for k in grads:
        np.testing.assert_allclose(np.asarray(scaled[k]),
                                   np.asarray(grads[k]) / norm, rtol=1e-6)
    # under the bound: untouched
    small = _clip_grads({"a": jnp.array([0.1])}, clip_by_global_norm=5.0)
    np.testing.assert_allclose(np.asarray(small["a"]), [0.1], rtol=1e-6)


def test_trainer_clip_by_global_norm_trains():
    """The clipped step runs sharded and matches a manual clipped
    update on step 1 (zero momentum state)."""
    mesh = mx.parallel.make_mesh({"dp": 8})
    d = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(d, num_hidden=4, name="fc"), name="softmax")
    X, y = _toy(n=16, d=8)

    def build():
        t = mx.parallel.ShardedTrainer(
            net, {"data": (16, 8), "softmax_label": (16,)}, mesh=mesh,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.0,
                              "clip_by_global_norm": 1e-3})
        return t

    t = build()
    p0 = t.get_params()
    t.step({"data": X, "softmax_label": y})
    p1 = t.get_params()
    # with a tiny norm bound the update magnitude is exactly lr * c
    # distributed over the tree: ||delta||_2 == lr * 1e-3
    delta = np.sqrt(sum(((p1[k] - p0[k]) ** 2).sum() for k in p0))
    np.testing.assert_allclose(delta, 0.5 * 1e-3, rtol=1e-4)


def test_sharded_trainer_deterministic_replay():
    """Two trainers built with the same seed must produce BITWISE
    identical parameters after the same batch sequence — the engine
    suite's deterministic-replay property (SURVEY §5 race detection)
    applied to the modern sharded path, with dropout RNG in the graph."""
    rng = np.random.RandomState(0)
    X = rng.randn(32, 10).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    def run():
        mx.random.seed(11)
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=16, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Dropout(net, p=0.3)   # RNG rides the step chain
        net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        tr = mx.parallel.ShardedTrainer(
            net, {"data": (32, 10), "softmax_label": (32,)},
            mesh=mx.parallel.local_mesh("dp"), optimizer="adam",
            optimizer_params={"learning_rate": 1e-2},
            initializer=mx.initializer.Xavier())
        for _ in range(5):
            tr.step({"data": X, "softmax_label": y})
        return {k: np.asarray(v) for k, v in tr.get_params().items()}

    p1, p2 = run(), run()
    assert p1.keys() == p2.keys()
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k], err_msg=k)


def test_sgd_opt_state_dtype():
    """Momentum storage dtype is selectable independently of the param
    dtype (the sweep's optimizer-state experiment): f32 state under bf16
    params matches the f32-everything update exactly."""
    import jax.numpy as jnp

    from mxnet_tpu.parallel.trainer import sgd_opt

    p32 = {"w": jnp.linspace(-1, 1, 8, dtype=jnp.float32)}
    g = {"w": jnp.full((8,), 0.25, jnp.float32)}

    # bf16 params + f32 state
    init_f32, upd_f32 = sgd_opt(learning_rate=0.1, momentum=0.9,
                                state_dtype="float32")
    pb = {"w": p32["w"].astype(jnp.bfloat16)}
    s = init_f32(pb)
    assert s["w"].dtype == jnp.float32
    # default: state follows the (bf16) param dtype
    init_d, _ = sgd_opt(learning_rate=0.1, momentum=0.9)
    assert init_d(pb)["w"].dtype == jnp.bfloat16

    # two steps with f32 state match the all-f32 reference to bf16
    # rounding of the params only (state itself carries no rounding)
    init_r, upd_r = sgd_opt(learning_rate=0.1, momentum=0.9)
    pr, sr = dict(p32), init_r(p32)
    for _ in range(2):
        pb, s = upd_f32(g, s, pb)
        pr, sr = upd_r(g, sr, pr)
    np.testing.assert_allclose(np.asarray(s["w"]), np.asarray(sr["w"]),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pb["w"], np.float32),
                               np.asarray(pr["w"]), atol=1e-2)


def test_fsdp_parity_and_sharding():
    """FSDP (ZeRO-3) param storage: params shard over dp, training math
    identical to the replicated trainer (same init, same key)."""
    devices = jax.devices()[:8]
    mesh = mx.parallel.make_mesh({"dp": 8}, devices=devices)

    def net():
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, num_hidden=256, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    batch, d_in = 16, 32
    shapes = {"data": (batch, d_in), "softmax_label": (batch,)}
    lr = 0.1

    mx.random.seed(0)
    fsdp = mx.parallel.ShardedTrainer(
        net(), shapes, mesh=mesh, batch_axis="dp",
        optimizer="sgd", optimizer_params={"learning_rate": lr,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier(),
        fsdp=True, fsdp_min_size=1024)
    # the big matrices shard over dp, small biases stay replicated
    spec = fsdp.param_shardings["fc1_weight"].spec
    assert "dp" in tuple(spec), spec
    assert tuple(fsdp.param_shardings["fc1_bias"].spec) == ()

    mx.random.seed(0)
    ref = mx.parallel.ShardedTrainer(
        net(), shapes, mesh=mesh, batch_axis="dp",
        optimizer="sgd", optimizer_params={"learning_rate": lr,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    ref.set_params(fsdp.get_params())
    key = np.asarray(jax.device_get(fsdp._key))
    ref._key = jax.device_put(key, ref._replicated)

    rng = np.random.RandomState(0)
    feed = {"data": rng.standard_normal((batch, d_in)).astype(np.float32),
            "softmax_label": rng.randint(0, 64, batch).astype(np.float32)}
    for _ in range(2):
        jax.block_until_ready(fsdp.step(feed))
        jax.block_until_ready(ref.step(feed))
    pf, pr = fsdp.get_params(), ref.get_params()
    for k in pf:
        np.testing.assert_allclose(pf[k], pr[k], atol=5e-6, rtol=1e-5,
                                   err_msg=k)

    # FSDP must also compose with explicit tp specs (explicit wins)
    mx.random.seed(0)
    both = mx.parallel.ShardedTrainer(
        net(), shapes,
        mesh=mx.parallel.make_mesh({"dp": 4, "tp": 2}, devices=devices),
        batch_axis="dp",
        param_specs={"fc1_weight": P("tp", None)},
        optimizer="sgd", initializer=mx.initializer.Xavier(),
        fsdp=True, fsdp_min_size=1024)
    assert tuple(both.param_shardings["fc1_weight"].spec) == ("tp", None)
    assert "dp" in tuple(both.param_shardings["fc2_weight"].spec)
    jax.block_until_ready(both.step(feed))


def test_fsdp_checkpoint_reshard_roundtrip(tmp_path):
    """FSDP-sharded params save through the sharded checkpoint path and
    reload into a trainer with a DIFFERENT sharding (replicated) and
    vice versa — the reshard-on-load contract covers ZeRO-3 storage."""
    mesh = mx.parallel.make_mesh({"dp": 8})
    net = mx.models.mlp(num_classes=4)
    shapes = {"data": (16, 16), "softmax_label": (16,)}
    kw = dict(mesh=mesh, optimizer="sgd",
              optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
              initializer=mx.initializer.Xavier())

    mx.random.seed(1)
    fsdp = mx.parallel.ShardedTrainer(net, shapes, fsdp=True,
                                      fsdp_min_size=64, **kw)
    rng = np.random.RandomState(0)
    feed = {"data": rng.randn(16, 16).astype(np.float32),
            "softmax_label": rng.randint(0, 4, 16).astype(np.float32)}
    fsdp.step(feed)
    ckpt = str(tmp_path / "fsdp_ck")
    fsdp.save_checkpoint_sharded(ckpt, 1)

    # reload into a REPLICATED trainer (reshard-on-load)
    mx.random.seed(2)
    rep = mx.parallel.ShardedTrainer(net, shapes, **kw)
    rep.load_checkpoint_sharded(ckpt, 1)
    for k, v in fsdp.get_params().items():
        np.testing.assert_allclose(rep.get_params()[k], v, atol=1e-6,
                                   err_msg=k)
    # and back into an FSDP trainer from the replicated one's save
    ckpt2 = str(tmp_path / "rep_ck")
    rep.save_checkpoint_sharded(ckpt2, 1)
    mx.random.seed(3)
    fsdp2 = mx.parallel.ShardedTrainer(net, shapes, fsdp=True,
                                       fsdp_min_size=64, **kw)
    fsdp2.load_checkpoint_sharded(ckpt2, 1)
    key = np.asarray(jax.device_get(fsdp._key))
    for t in (fsdp, fsdp2):
        t._key = jax.device_put(key, t._replicated)
    fsdp.step(feed)
    fsdp2.step(feed)
    for k, v in fsdp.get_params().items():
        np.testing.assert_allclose(fsdp2.get_params()[k], v, atol=1e-5,
                                   err_msg=k)


def test_fsdp_llama_gpt_tied_parity():
    """ZeRO-3 over the llama-style GPT: the TIED embedding matrix (one
    named array used by Embedding and the LM head) shards over dp and
    the two-step training math still matches the replicated trainer
    exactly — the all-gather/reduce-scatter schedule must reassemble
    the shared weight for BOTH uses and sum both gradient paths."""
    devices = jax.devices()[:4]
    mesh = mx.parallel.make_mesh({"dp": 4}, devices=devices)
    vocab, seq = 37, 8

    def net():
        return mx.models.gpt(vocab, seq, num_layers=1, d_model=32,
                             num_heads=2, kv_heads=1, pos_embed="rope",
                             norm="rmsnorm", mlp="swiglu",
                             tie_embeddings=True, loss="ce")

    shapes = {"data": (8, seq), "softmax_label": (8, seq)}
    lr = 0.1

    def build(fsdp):
        mx.random.seed(11)
        return mx.parallel.ShardedTrainer(
            net(), shapes, mesh=mesh, batch_axis="dp",
            optimizer="sgd", optimizer_params={"learning_rate": lr},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.float32},
            fsdp=fsdp, fsdp_min_size=256)

    fsdp = build(True)
    assert "dp" in tuple(fsdp.param_shardings["gpt_tok_embed_weight"].spec)
    ref = build(False)
    ref.set_params(fsdp.get_params())
    key = np.asarray(jax.device_get(fsdp._key))
    ref._key = jax.device_put(key, ref._replicated)

    rng = np.random.RandomState(1)
    feed = {"data": rng.randint(0, vocab, (8, seq)),
            "softmax_label": rng.randint(0, vocab, (8, seq)).astype(
                np.float32)}
    for _ in range(2):
        jax.block_until_ready(fsdp.step(feed))
        jax.block_until_ready(ref.step(feed))
    pf, pr = fsdp.get_params(), ref.get_params()
    for k in pf:
        np.testing.assert_allclose(pf[k], pr[k], atol=5e-5, rtol=2e-4,
                                   err_msg=k)
