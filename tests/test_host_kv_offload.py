"""Host-RAM KV offload tier (mxnet_tpu/serve, ISSUE 12).

The parity suite for the DRAM second tier under the radix prefix
cache: ``HostKVPool`` unit semantics (byte budget, LRU with the
leaf-only radix discipline, claim/unclaim, the chaos restore-delay
degrade), BlockManager offload-on-eviction / host-chain walk /
restore-and-publish bookkeeping, a randomized interleaved stress test
over the full block lifecycle, and the engine-level acceptance gates —
byte-identical tokens vs the cold path after HBM churn (gpt,
llama/GQA + int8 KV, tp=2, preemption pressure, chunked prefill,
spec-decode verify), tier-off inertness (same grids, same AOT
fingerprints), deterministic shutdown of the pool, and the
stats/statusz/metrics three-view agreement.

Everything is CPU-deterministic on tiny models; the measured offload
A/B contract lives in test_bench_contract.py (slow tier) against
tools/serve_bench.py --workload offload.
"""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.serve import BlockManager, HostKVPool, NoFreeBlocks
from mxnet_tpu.serve.kv_block_manager import blocks_for
from mxnet_tpu.telemetry import statusz as statusz_mod

VOCAB = 53


def _arrs(tag, nbytes=64):
    """A fake per-block host copy: one float32 array of ``nbytes``."""
    return (np.full(nbytes // 4, float(tag), np.float32),)


# -- HostKVPool units --------------------------------------------------------
def test_pool_put_claim_budget_and_lru():
    p = HostKVPool(256, block_tokens=4)
    assert p.put(b"a", None, _arrs(1)) and p.put(b"b", None, _arrs(2))
    assert len(p) == 2 and p.bytes_used == 128
    # oversize entry rejected outright (never evicts the world for it)
    assert not p.put(b"huge", None, _arrs(9, nbytes=512))
    assert p.rejects == 1 and len(p) == 2
    # budget pressure: two more 64-byte entries evict the two oldest
    assert p.put(b"c", None, _arrs(3)) and p.put(b"d", None, _arrs(4))
    assert p.put(b"e", None, _arrs(5))
    assert not p.has(b"a") and p.evictions >= 1
    assert p.discarded_tokens == p.evictions * 4
    assert p.bytes_used <= p.max_bytes
    # claim pops; a second claim misses
    got = p.claim(b"e")
    assert got is not None and got[0][0] == 5.0
    assert p.claim(b"e") is None and p.restores == 1
    p.clear()
    assert len(p) == 0 and p.bytes_used == 0


def test_pool_leaf_discipline_protects_hosted_chains():
    """An interior entry whose child is hosted is never evicted first:
    without it the deeper entries are unreachable by the chain walk."""
    p = HostKVPool(192, block_tokens=4)
    # device eviction order is leaf-first, so the CHILD parks first
    assert p.put(b"child", b"root", _arrs(1))
    assert p.put(b"root", None, _arrs(2))
    # root is now OLDER in recency terms than nothing — child is the
    # oldest entry, and also the only leaf (root has a hosted child)
    assert p.put(b"x", None, _arrs(3))     # fills the budget
    assert p.put(b"y", None, _arrs(4))     # forces one eviction
    # child (oldest leaf) went; root survived even though x/y are newer
    assert not p.has(b"child") and p.has(b"root")
    # with its hosted child gone, root is evictable again
    assert p.put(b"z", None, _arrs(5))
    assert not p.has(b"root")


def test_pool_insert_never_evicts_own_parent():
    """Making room for a child must never reclaim the child's own
    hosted parent — that would park bytes the chain walk can no longer
    reach (the child link registers before the eviction loop)."""
    p = HostKVPool(128, block_tokens=4)        # exactly two entries
    p.put(b"A", None, _arrs(1))
    p.put(b"x", None, _arrs(2))                # budget full
    assert p.put(b"B", b"A", _arrs(3))         # evicts x, NOT A
    assert p.has(b"A") and p.has(b"B") and not p.has(b"x")
    assert p.stats()["bytes_peak"] == 128


def test_pool_restore_delay_degrades_claim():
    p = HostKVPool(1024, block_tokens=4)
    p.put(b"k", None, _arrs(7))
    p.fault_delay_s = 1.0
    p.restore_budget_s = 0.05
    assert p.claim(b"k") is None          # degraded, not served slowly
    assert p.degraded == 1 and p.has(b"k")  # the entry STAYS hosted
    p.fault_delay_s = 0.0
    assert p.claim(b"k") is not None      # fault cleared: normal claim


# -- BlockManager + pool bookkeeping -----------------------------------------
def _mgr(num_blocks=16, block_size=4, pool_bytes=4096):
    pool = HostKVPool(pool_bytes, block_tokens=block_size) \
        if pool_bytes else None
    m = BlockManager(num_blocks, block_size, prefix_cache=True,
                     host_pool=pool)
    fetched = []
    if pool is not None:
        def fetch(blk):
            fetched.append(blk)
            return (np.full(16, float(blk), np.float32),)
        m.set_offload_source(fetch)
    return m, pool, fetched


def test_eviction_offloads_and_host_walk_restores():
    m, pool, fetched = _mgr(num_blocks=6)     # 5 allocatable
    ids = list(range(10, 19))                 # 2 full blocks + tail
    t1, _ = m.allocate("a", 9, token_ids=ids)
    m.note_tokens("a", ids)
    m.free("a", retain=True)                  # chain parks in device LRU
    # pressure: both published blocks leave HBM — and park in DRAM
    m.allocate("b", 17)                       # needs 5 blocks
    assert m.prefix_evictions >= 2 and len(pool) == 2
    assert fetched and m.prefix_discarded_tokens == 0
    assert m.prefix_stats()["discarded_tokens"] == 0
    m.free("b", retain=False)
    # probe: 0 device blocks to reuse, but 8 tokens restorable
    assert m.prefix_probe(ids) == (0, 8)
    t2, cached = m.allocate("c", 10, token_ids=ids)
    assert cached == 8 and m.host_hits == 1
    assert m.host_restored_tokens == 8 and len(pool) == 0
    # restored blocks are published again and queue their H2D copies
    pend = m.take_pending_restores()
    assert sorted(b for b, _ in pend) == sorted(t2[:2])
    assert [a[0][0] for _, a in pend]         # host copies ride along
    assert m.take_pending_restores() == []    # drained exactly once
    assert m.host_tokens("c") == 8
    # the restored chain is a normal published chain: a sharer hits it
    t3, c3 = m.allocate("d", 10, token_ids=ids)
    assert c3 == 8 and t3[:2] == t2[:2]


def test_failed_allocate_unclaims_host_entries():
    m, pool, _ = _mgr(num_blocks=6)
    ids = list(range(20, 29))
    m.allocate("a", 9, token_ids=ids)
    m.note_tokens("a", ids)
    m.free("a", retain=True)
    m.allocate("b", 17)                       # evicts chain into DRAM
    assert len(pool) == 2
    # "c" would reuse 8 host tokens but cannot get blocks: the claim
    # must roll back — hosted K/V is not dropped on a failed admission
    with pytest.raises(NoFreeBlocks):
        m.allocate("c", 17, token_ids=ids)
    assert len(pool) == 2
    m.free("b", retain=False)
    _, cached = m.allocate("c2", 10, token_ids=ids)
    assert cached == 8                        # still restorable


def test_discarded_tokens_without_pool():
    m, _, _ = _mgr(num_blocks=5, pool_bytes=0)
    ids = list(range(30, 39))
    m.allocate("a", 9, token_ids=ids)
    m.note_tokens("a", ids)
    m.free("a", retain=True)
    m.allocate("b", 13)                       # evicts published blocks
    stats = m.prefix_stats()
    assert m.prefix_evictions >= 1
    assert stats["discarded_tokens"] == m.prefix_evictions * 4
    assert stats["host_hits"] == 0 and m.host_stats() is None


def test_free_before_restore_drain_reparks_host_copy():
    """A block freed before its queued restore is dispatched (possible
    through the public API, never through the engine) must not stay
    published with never-written K/V: the host copy re-parks and the
    chain stays restorable."""
    m, pool, _ = _mgr(num_blocks=6)
    ids = list(range(50, 59))
    m.allocate("a", 9, token_ids=ids)
    m.note_tokens("a", ids)
    m.free("a", retain=True)
    m.allocate("b", 17)                       # chain -> DRAM
    m.free("b", retain=False)
    t, cached = m.allocate("c", 10, token_ids=ids)
    assert cached == 8 and len(m._pending_restores) == 2
    m.free("c", retain=True)                  # BEFORE the engine drain
    assert m.take_pending_restores() == []    # restores dropped...
    assert len(pool) == 2                     # ...and re-parked
    assert m.prefix_probe(ids) == (0, 8)      # not falsely published
    _, cached = m.allocate("d", 10, token_ids=ids)
    assert cached == 8                        # still restorable
    assert len(m.take_pending_restores()) == 2


def test_degraded_claim_truncates_restored_span():
    m, pool, _ = _mgr(num_blocks=6)
    ids = list(range(40, 49))
    m.allocate("a", 9, token_ids=ids)
    m.note_tokens("a", ids)
    m.free("a", retain=True)
    m.allocate("b", 17)
    m.free("b", retain=False)
    pool.fault_delay_s, pool.restore_budget_s = 1.0, 0.01
    _, cached = m.allocate("c", 10, token_ids=ids)
    assert cached == 0                        # degraded -> recompute
    assert pool.degraded >= 1 and m.host_hits == 0
    assert m.take_pending_restores() == []
    assert len(pool) == 2                     # entries stayed hosted


# -- randomized lifecycle stress ---------------------------------------------
def _check_invariants(m, pool):
    with m._lock:
        free = list(m._free)
        assert len(free) == len(set(free)), "duplicate free blocks"
        assert 0 not in free, "null block freed"
        refs = {}
        for table in m._tables.values():
            for blk in table:
                refs[blk] = refs.get(blk, 0) + 1
        assert refs == m._refs, "refcounts drifted from table contents"
        lru = set(m._lru.values())
        retained = [b for bs in m._retained.values() for b in bs]
        assert len(retained) == len(set(retained))
        groups = [set(free), set(refs), lru, set(retained)]
        for i in range(len(groups)):
            for j in range(i + 1, len(groups)):
                assert groups[i].isdisjoint(groups[j]), \
                    "a block is free+referenced+parked at once"
        assert set().union(*groups) == set(range(1, m.num_blocks)), \
            "a block leaked out of the accounting"
        for key, blk in m._index.items():
            assert m._key_of[blk] == key
        assert set(m._lru) <= set(m._index)
        for blk, _ in m._pending_restores:
            assert m._refs.get(blk, 0) >= 1, \
                "pending restore targets an unreferenced block"
            assert blk in m._key_of, \
                "pending restore targets an unpublished block"
    if pool is not None:
        with pool._lock:
            assert pool.bytes_used <= pool.max_bytes
            assert pool.bytes_used == sum(
                n for _, _, n in pool._entries.values())


def test_block_manager_stress_interleaved_lifecycle():
    """Randomized allocate/free/evict/offload/restore/truncate churn
    preserves every structural invariant: refcounts == table
    membership, the free/referenced/parked partitions stay disjoint
    and exhaustive, no block is simultaneously free+parked, pending
    restores only target live referenced blocks, and the host tier
    never exceeds its byte budget."""
    rng = np.random.RandomState(1234)
    # a tiny pool budget forces host-tier eviction/reject churn too
    m, pool, _ = _mgr(num_blocks=12, block_size=4, pool_bytes=256)
    master = rng.randint(0, 7, 64).tolist()   # tiny alphabet: collisions
    live = []
    rid_n = [0]

    def some_ids():
        take = int(rng.randint(4, 40))
        tail = rng.randint(0, 7, int(rng.randint(0, 6))).tolist()
        return master[:take] + tail

    for step in range(400):
        op = rng.randint(0, 6)
        if op == 0 or not live:                      # allocate
            rid = f"r{rid_n[0]}"
            rid_n[0] += 1
            ids = some_ids()
            try:
                m.allocate(rid, len(ids) + 1, token_ids=ids)
                live.append((rid, ids))
            except NoFreeBlocks:
                pass
        elif op == 1:                                # publish
            rid, ids = live[rng.randint(len(live))]
            m.note_tokens(rid, ids)
        elif op == 2:                                # free
            rid, _ = live.pop(rng.randint(len(live)))
            m.free(rid, retain=bool(rng.randint(2)))
        elif op == 3:                                # truncate
            rid, ids = live[rng.randint(len(live))]
            m.truncate(rid, int(rng.randint(1, len(ids) + 2)))
        elif op == 4:                                # decode growth
            rid, ids = live[rng.randint(len(live))]
            try:
                m.ensure_capacity(rid, m.capacity(rid) + 1)
            except NoFreeBlocks:
                pass
        else:                                        # engine drains
            m.take_pending_restores()
        _check_invariants(m, pool)
    # fixed seed: this sequence offloads, restores AND host-evicts
    assert pool.offloads > 0 and pool.restores > 0 \
        and pool.evictions > 0, \
        "stress never exercised the host tier — vacuous"


# -- engine-level parity gates (tiny models, real jit programs on CPU) -------
@pytest.fixture(scope="module")
def model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    return net, _rand_params(net, S, seed=3)


@pytest.fixture(scope="module")
def llama_model():
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4,
                        kv_heads=2, norm="rmsnorm", mlp="swiglu",
                        pos_embed="rope", tie_embeddings=True)
    return net, _rand_params(net, S, seed=9)


def _rand_params(net, S, seed):
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(seed)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return params


def _engine(model, params=None, **kw):
    net, p = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 48)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params if params is not None else p,
                           symbol=net, **kw)


POOL = 1 << 24


def _churn_identity(model, ref_kw=None, on_kw=None, max_new=8, seed=7):
    """The acceptance recipe: serve a prompt, churn its chain out of a
    deliberately tiny HBM cache, serve it again.  Returns (ref, first,
    again, stats) with ref from a calm reference engine."""
    rng = np.random.RandomState(seed)
    prompt = rng.randint(0, VOCAB, (24,)).astype(np.int32)
    fills = [rng.randint(0, VOCAB, (24,)).astype(np.int32)
             for _ in range(3)]

    ref_eng = _engine(model, prefix_cache=False, **(ref_kw or {}))
    ref = ref_eng.submit(prompt, max_new_tokens=max_new)
    ref_eng.run()
    ref_eng.shutdown()

    eng = _engine(model, num_blocks=16, host_kv_bytes=POOL,
                  **(on_kw or {}))
    first = eng.submit(prompt, max_new_tokens=max_new)
    eng.run()
    for f in fills:
        eng.submit(f, max_new_tokens=max_new)
        eng.run()
    again = eng.submit(prompt, max_new_tokens=max_new)
    eng.run()
    st = eng.stats()
    eng.shutdown()
    return ref, first, again, st


def test_offload_identity_gpt(model):
    """Acceptance: after the HBM prefix LRU churns the chain out, the
    re-served prompt restores from DRAM and stays byte-identical to
    the cold path — with real host-tier traffic (vacuity-guarded)."""
    ref, first, again, st = _churn_identity(model)
    assert first.tokens == ref.tokens
    assert again.tokens == ref.tokens
    assert st.host_kv_hits > 0, "no host-tier hit — test is vacuous"
    assert st.host_kv_restored_tokens > 0
    assert st.host_kv_offloads > 0
    assert st.prefix_discarded_tokens == 0    # nothing thrown away


def test_offload_identity_llama_gqa_int8(llama_model):
    """Same gate on the llama/GQA variant with int8 KV blocks: the
    quantized slots AND their scale slots round-trip DRAM (identity is
    within the int8 pair — int8 legitimately moves tokens vs fp)."""
    ref, first, again, st = _churn_identity(
        llama_model, ref_kw=dict(kv_dtype="int8"),
        on_kw=dict(kv_dtype="int8"))
    assert first.tokens == ref.tokens
    assert again.tokens == ref.tokens
    assert st.host_kv_hits > 0


def test_offload_identity_tp2(model):
    """tp=2 head-sharded blocks round-trip the host tier (the D2H
    gather folds both chips' head shards into one host block; the
    replicated restore operand scatters back onto the sharded cache)."""
    ref, first, again, st = _churn_identity(
        model, ref_kw=dict(tp=2), on_kw=dict(tp=2))
    assert first.tokens == ref.tokens
    assert again.tokens == ref.tokens
    assert st.host_kv_hits > 0


def test_offload_under_preemption_pressure(model):
    """Concurrent requests tight enough to preempt, with the host tier
    live: resume-by-recomputation, refcounted sharing and DRAM restores
    compose without perturbing a single token."""
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, VOCAB, (16,)).astype(np.int32)
               for _ in range(6)]

    def run(**kw):
        eng = _engine(model, **kw)
        reqs = [eng.submit(p, max_new_tokens=16) for p in prompts]
        eng.run()
        st = eng.stats()
        eng.shutdown()
        return reqs, st

    calm_reqs, calm_st = run(num_blocks=64)
    tight_reqs, tight_st = run(num_blocks=22, host_kv_bytes=POOL)
    assert calm_st.preemptions == 0
    assert tight_st.preemptions > 0, "no cache pressure — vacuous"
    assert tight_st.host_kv_offloads > 0
    for calm, tight in zip(calm_reqs, tight_reqs):
        assert calm.status == tight.status == "finished"
        assert calm.tokens == tight.tokens


def test_offload_with_chunked_prefill(model):
    """A DRAM-restored prefix followed by a chunked suffix prefill:
    the restore fence holds across multi-iteration prefills too."""
    rng = np.random.RandomState(23)
    prefix = rng.randint(0, VOCAB, (16,)).astype(np.int32)
    long_a = np.concatenate([prefix,
                             rng.randint(0, VOCAB, (20,)).astype(np.int32)])
    long_b = np.concatenate([prefix,
                             rng.randint(0, VOCAB, (20,)).astype(np.int32)])
    fills = [rng.randint(0, VOCAB, (24,)).astype(np.int32)
             for _ in range(3)]

    def run(**kw):
        eng = _engine(model, prefill_chunk=8, **kw)
        out = []
        for p in (long_a, *fills, long_b):
            out.append(eng.submit(p, max_new_tokens=8))
            eng.run()
        st = eng.stats()
        eng.shutdown()
        return out, st

    ref_reqs, _ = run(num_blocks=64, prefix_cache=False)
    got_reqs, st = run(num_blocks=16, host_kv_bytes=POOL)
    assert st.host_kv_hits > 0, "chunked run never hit the host tier"
    for a, b in zip(ref_reqs, got_reqs):
        assert a.tokens == b.tokens


def test_offload_with_spec_decode_verify(model):
    """Speculative decoding over a DRAM-restored prefix: the verify
    dispatch reads restored blocks and the share-safe truncate rollback
    composes with republished chains — still byte-identical."""
    net, params = model
    src, draft = dict(params), {k: v for k, v in params.items()
                                if not k.startswith("gpt_l1_")}
    for k, v in params.items():
        if k.startswith("gpt_l1_") and (k.endswith("proj_weight")
                                        or k.endswith("ff_down_weight")):
            src[k] = v * 0.05
    spec_kw = dict(spec_k=2, draft_params=draft, draft_num_heads=4,
                   draft_window=0)
    ref, first, again, st = _churn_identity(
        (net, src), ref_kw=spec_kw, on_kw=spec_kw)
    assert first.tokens == ref.tokens
    assert again.tokens == ref.tokens
    assert st.host_kv_hits > 0
    assert st.spec_verifies > 0, "spec never verified — vacuous"


def test_restore_delay_fault_degrades_to_recompute(model):
    """The chaos gate: a restore delay past the budget must not stall
    the step loop — the hit degrades to recompute, tokens stay
    identical, and the degradation is counted."""
    os.environ["MXTPU_FAULT_HOST_RESTORE_DELAY"] = "30"
    os.environ["MXTPU_SERVE_HOST_KV_RESTORE_BUDGET"] = "0.05"
    try:
        ref, first, again, st = _churn_identity(model)
    finally:
        del os.environ["MXTPU_FAULT_HOST_RESTORE_DELAY"]
        del os.environ["MXTPU_SERVE_HOST_KV_RESTORE_BUDGET"]
    assert first.tokens == ref.tokens
    assert again.tokens == ref.tokens
    assert st.host_kv_hits == 0               # every claim degraded
    assert st.host_kv_degraded > 0
    assert st.host_kv_offloads > 0            # the tier still parked


def test_host_kv_off_is_inert(model):
    """MXTPU_SERVE_HOST_KV_BYTES=0 is byte-for-byte inert: no pool, no
    restore program family, identical warmup grid and AOT fingerprints,
    zeroed stats — the PR 10/11 only-when-on rule."""
    os.environ["MXTPU_SERVE_HOST_KV_BYTES"] = "0"
    try:
        eng0 = _engine(model)
    finally:
        del os.environ["MXTPU_SERVE_HOST_KV_BYTES"]
    eng_def = _engine(model)                  # env unset: same default
    assert eng0._host_pool is None and eng_def._host_pool is None
    assert eng0._warmup_grid() == eng_def._warmup_grid()
    assert all(g["kind"] != "restore" for g in eng0._warmup_grid())
    assert eng0._aot_base_fp() == eng_def._aot_base_fp()
    assert eng0.statusz()["host_kv"] is None
    st = eng0.stats()
    assert st.host_kv_hits == st.host_kv_offloads == 0
    assert st.host_kv_bytes_used == 0
    eng0.shutdown()
    eng_def.shutdown()
    # the tier ON adds ONLY the restore family, and the base
    # fingerprint is unchanged (restore artifacts key on kind)
    on = _engine(model, host_kv_bytes=POOL)
    off_kinds = {g["kind"] for g in eng_def._warmup_grid()}
    on_kinds = {g["kind"] for g in on._warmup_grid()}
    assert on_kinds - off_kinds == {"restore"}
    on.shutdown()


def test_warmup_from_tier_off_manifest_warms_restore(model):
    """An upgraded (tier-on) engine replaying a tier-off predecessor's
    traffic manifest must still pre-compile the restore family — the
    first host-tier hit after the upgrade must never trace mid-step."""
    from mxnet_tpu.serve.engine import _STEP_CACHE

    off = _engine(model)
    rng = np.random.RandomState(53)
    off.submit(rng.randint(0, VOCAB, (12,)).astype(np.int32),
               max_new_tokens=4)
    off.run()
    man = off.manifest()
    assert man and all(e["kind"] != "restore" for e in man)
    off.shutdown()

    on = _engine(model, host_kv_bytes=POOL)
    ready = on.warmup(man)
    assert ready > len(man)                   # the forced ladder ran
    key = on._spec_key()
    assert any(k[1] == "restore" for k in _STEP_CACHE if k[0] == key)
    on.shutdown()


def test_env_budget_default_and_arg_wins(model):
    os.environ["MXTPU_SERVE_HOST_KV_BYTES"] = "65536"
    try:
        eng = _engine(model)
        assert eng._host_pool is not None
        assert eng._host_pool.max_bytes == 65536
        eng.shutdown()
        eng = _engine(model, host_kv_bytes=0)     # explicit arg wins
        assert eng._host_pool is None
        eng.shutdown()
    finally:
        del os.environ["MXTPU_SERVE_HOST_KV_BYTES"]


def test_shutdown_releases_pool_back_to_back_engines(model):
    """Engine.shutdown() releases the DRAM pool deterministically with
    the device buffers, and the statusz weakref section (host_kv
    included) drops — two engines back-to-back never hold two pools."""
    ref, first, again, st = _churn_identity(model)
    assert st.host_kv_offloads > 0
    eng = _engine(model, num_blocks=16, host_kv_bytes=POOL)
    rng = np.random.RandomState(31)
    for _ in range(4):
        eng.submit(rng.randint(0, VOCAB, (24,)).astype(np.int32),
                   max_new_tokens=8)
        eng.run()
    pool = eng._host_pool
    assert len(pool) > 0
    name = eng._statusz_name
    assert name in statusz_mod.snapshot()
    sz = eng.statusz()
    assert sz["host_kv"] is not None and sz["host_kv"]["entries"] > 0
    eng.shutdown()
    assert len(pool) == 0 and pool.bytes_used == 0
    assert eng._host_pool is None
    assert name not in statusz_mod.snapshot()
    # a second engine starts clean and serves correctly
    eng2 = _engine(model, num_blocks=16, host_kv_bytes=POOL)
    req = eng2.submit(rng.randint(0, VOCAB, (12,)).astype(np.int32),
                      max_new_tokens=4)
    eng2.run()
    assert req.status == "finished"
    assert eng2.host_kv_stats()["offloads"] == 0    # fresh pool
    eng2.shutdown()


def test_stats_statusz_metrics_three_view_agreement(model):
    """ServeStats, /statusz and the telemetry registry agree on the
    host-tier counters (offloads, restored tokens, discarded tokens)
    — the series an operator reads to size the DRAM budget."""
    from mxnet_tpu import telemetry
    telemetry.reset()
    telemetry.enable()
    try:
        ref, first, again, st = _churn_identity(model)
        snap = telemetry.registry().snapshot()

        def val(name):
            return snap[name]["samples"][0]["value"]

        assert st.host_kv_offloads > 0        # vacuity guard
        assert val("mxtpu_serve_host_kv_offloads_total") == \
            float(st.host_kv_offloads)
        assert val("mxtpu_serve_host_kv_restored_tokens_total") == \
            float(st.host_kv_restored_tokens)
        fam = snap.get("mxtpu_serve_prefix_discarded_tokens_total")
        discarded = (fam["samples"][0]["value"]
                     if fam and fam["samples"] else 0.0)
        assert discarded == float(st.prefix_discarded_tokens)
    finally:
        telemetry.disable()
        telemetry.reset()


def test_degraded_counter_reaches_metrics_registry():
    """Satellite (ISSUE 13): `HostKVPool` counts restore-budget
    degradations locally, and the registry must see the SAME number as
    `mxtpu_serve_host_kv_degraded_total` — a fleet silently falling
    back to recompute was invisible in Prometheus before this."""
    from mxnet_tpu import telemetry
    telemetry.reset()
    telemetry.enable()
    try:
        p = HostKVPool(1024, block_tokens=4)
        p.put(b"k1", None, _arrs(1))
        p.put(b"k2", b"k1", _arrs(2))
        p.fault_delay_s, p.restore_budget_s = 1.0, 0.05
        assert p.claim(b"k1") is None
        assert p.claim(b"k2") is None
        assert p.claim(b"missing") is None    # a MISS never counts
        assert p.degraded == 2
        snap = telemetry.registry().snapshot()
        fam = snap["mxtpu_serve_host_kv_degraded_total"]
        assert fam["samples"][0]["value"] == float(p.degraded)
        # and the ServeStats view is fed from the same pool counter
        assert p.stats()["degraded"] == p.degraded
    finally:
        telemetry.disable()
        telemetry.reset()


def test_statusz_and_stats_expose_host_tier(model):
    eng = _engine(model, num_blocks=16, host_kv_bytes=POOL)
    rng = np.random.RandomState(41)
    prompt = rng.randint(0, VOCAB, (24,)).astype(np.int32)
    eng.submit(prompt, max_new_tokens=8)
    eng.run()
    for _ in range(3):
        eng.submit(rng.randint(0, VOCAB, (24,)).astype(np.int32),
                   max_new_tokens=8)
        eng.run()
    eng.submit(prompt, max_new_tokens=8)
    eng.run()
    sz = eng.statusz()
    st = eng.stats()
    hk = sz["host_kv"]
    assert hk["max_bytes"] == POOL
    assert hk["offloads"] == st.host_kv_offloads
    assert hk["bytes_used"] == st.host_kv_bytes_used
    assert hk["block_bytes"] > 0
    pfx = sz["prefix_cache"]
    assert pfx["host_hits"] == st.host_kv_hits
    assert pfx["host_restored_tokens"] == st.host_kv_restored_tokens
    assert pfx["discarded_tokens"] == st.prefix_discarded_tokens
    eng.shutdown()


def test_replica_load_signal_includes_host_tier(model):
    """The fleet replica's /healthz and balancing signal carry the
    host-tier occupancy (None with the tier off)."""
    from mxnet_tpu.fleet.replica import ReplicaServer

    eng = _engine(model, num_blocks=16, host_kv_bytes=POOL)
    rep = ReplicaServer(eng, replica_id="r0")
    h = rep._health()
    s = rep._replica_state()
    assert h["host_kv_utilization"] is not None
    assert s["host_kv_utilization"] == eng.host_kv_stats()["utilization"]
    eng.shutdown()
    eng2 = _engine(model)
    rep2 = ReplicaServer(eng2, replica_id="r1")
    assert rep2._health()["host_kv_utilization"] is None
    eng2.shutdown()
