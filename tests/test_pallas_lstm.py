"""Fused Pallas LSTM (ops/pallas_lstm.py) vs the lax.scan reference
cell — forward and full backward parity through the interpreter (the
identical kernel code runs jit-compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_lstm import fused_lstm, fused_lstm_eligible


def _scan_lstm(gx, h0, c0, wh, bh):
    """The ops/rnn.py scan cell, inlined as the numerical reference."""
    def step(carry, g):
        h, c = carry
        gates = g + jnp.dot(h, wh.T) + bh
        i, f, gg, o = jnp.split(gates, 4, axis=-1)
        c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
        h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
        return (h2, c2), h2

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), gx)
    return ys, hT, cT


def _rand(T=6, N=4, H=8, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    gx = rng.randn(T, N, 4 * H).astype(dtype) * 0.5
    h0 = rng.randn(N, H).astype(dtype) * 0.5
    c0 = rng.randn(N, H).astype(dtype) * 0.5
    wh = rng.randn(4 * H, H).astype(dtype) * 0.3
    bh = rng.randn(4 * H).astype(dtype) * 0.1
    return gx, h0, c0, wh, bh


@pytest.mark.parametrize("shape", [(6, 4, 8), (13, 3, 16), (1, 2, 8)])
def test_forward_matches_scan(shape):
    T, N, H = shape
    gx, h0, c0, wh, bh = _rand(T, N, H)
    ys, hT, cT = fused_lstm(gx, h0, c0, wh, bh, interpret=True)
    rys, rhT, rcT = _scan_lstm(gx, h0, c0, wh, bh)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(rys),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(rhT),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(rcT),
                               rtol=1e-5, atol=1e-5)


def test_backward_matches_scan_all_outputs():
    """Gradients w.r.t. every input, through a loss touching ys, hT
    and cT so every cotangent path is exercised."""
    gx, h0, c0, wh, bh = _rand(T=7, N=4, H=8, seed=1)

    def loss_fused(gx, h0, c0, wh, bh):
        ys, hT, cT = fused_lstm(gx, h0, c0, wh, bh, interpret=True)
        return (jnp.sum(ys * ys) + jnp.sum(jnp.sin(hT))
                + 2.0 * jnp.sum(cT))

    def loss_scan(gx, h0, c0, wh, bh):
        ys, hT, cT = _scan_lstm(gx, h0, c0, wh, bh)
        return (jnp.sum(ys * ys) + jnp.sum(jnp.sin(hT))
                + 2.0 * jnp.sum(cT))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(gx, h0, c0, wh, bh)
    gr = jax.grad(loss_scan, argnums=(0, 1, 2, 3, 4))(gx, h0, c0, wh, bh)
    for name, a, b in zip(("gx", "h0", "c0", "wh", "bh"), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_backward_ys_only_loss():
    """hT/cT cotangents are zero arrays; the reverse stream must still
    initialize correctly from them."""
    gx, h0, c0, wh, bh = _rand(T=5, N=2, H=8, seed=2)

    def f(impl):
        def loss(gx):
            ys, _, _ = impl(gx, h0, c0, wh, bh)
            return jnp.sum(ys[2])  # gradient flows only to steps <= 2
        return jax.grad(loss)(gx)

    gf = f(lambda *a: fused_lstm(*a, interpret=True))
    gr = f(_scan_lstm)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)
    # causality: steps after 2 get exactly zero gradient
    assert np.all(np.asarray(gf)[3:] == 0.0)


def test_bf16_inputs():
    gx, h0, c0, wh, bh = _rand(T=4, N=2, H=8, seed=3)
    bf = jnp.bfloat16
    ys, hT, cT = fused_lstm(gx.astype(bf), h0.astype(bf), c0.astype(bf),
                            wh.astype(bf), bh.astype(bf), interpret=True)
    assert ys.dtype == bf
    rys, _, _ = _scan_lstm(*[jnp.asarray(a, jnp.float32)
                             for a in (gx, h0, c0, wh, bh)])
    np.testing.assert_allclose(np.asarray(ys, np.float32),
                               np.asarray(rys), rtol=5e-2, atol=5e-2)


def test_bf16_gradients():
    """bf16 fwd AND bwd: matmul operands run in the activation dtype
    (MXU fast path); gradients must stay within bf16 tolerance of the
    f32 scan reference, incl. f32 master weights with bf16 activations
    (the mixed regime that must still engage the cast)."""
    gx, h0, c0, wh, bh = _rand(T=4, N=2, H=8, seed=5)
    bf = jnp.bfloat16

    def loss_fused(gx_, wh_):
        ys, hT, cT = fused_lstm(gx_, h0.astype(gx_.dtype),
                                c0.astype(gx_.dtype), wh_, bh.astype(bf),
                                interpret=True)
        return jnp.sum(ys.astype(jnp.float32) ** 2)

    def loss_ref(gx_, wh_):
        ys, _, _ = _scan_lstm(gx_, h0, c0, wh_, bh)
        return jnp.sum(ys ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(
        jnp.asarray(gx, jnp.float32), jnp.asarray(wh, jnp.float32))
    for wdtype in (bf, jnp.float32):     # bf16 and master-f32 weights
        g = jax.grad(loss_fused, argnums=(0, 1))(
            gx.astype(bf), wh.astype(wdtype))
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b), rtol=8e-2, atol=8e-2)


def test_rnn_op_uses_fused_when_forced(monkeypatch):
    """MXNET_TPU_FUSED_RNN=1 routes the RNN symbol op through the
    kernel (interpret off-TPU) with unchanged results."""
    monkeypatch.setenv("MXNET_TPU_FUSED_RNN", "1")
    import mxnet_tpu as mx

    rng = np.random.RandomState(4)
    T, N, I, H = 5, 3, 6, 8
    x = rng.randn(T, N, I).astype(np.float32)

    def run():
        data = mx.sym.Variable("data")
        net = mx.sym.RNN(data, mx.sym.Variable("parameters"),
                         mx.sym.Variable("state"),
                         mx.sym.Variable("state_cell"),
                         state_size=H, num_layers=1, mode="lstm",
                         name="rnn")
        exe = net.simple_bind(mx.cpu(), grad_req="write",
                              data=(T, N, I))
        for name, arr in exe.arg_dict.items():
            if name == "data":
                arr[:] = x
            else:
                arr[:] = (rng.randn(*arr.shape) * 0.2).astype(np.float32)
        return exe

    rng = np.random.RandomState(4)
    exe1 = run()
    exe1.forward(is_train=True)
    fused_out = exe1.outputs[0].asnumpy()
    head = np.ones_like(fused_out)
    exe1.backward([mx.nd.array(head)])
    fused_grads = {k: v.asnumpy() for k, v in exe1.grad_dict.items()}

    monkeypatch.setenv("MXNET_TPU_FUSED_RNN", "0")
    rng = np.random.RandomState(4)
    exe2 = run()
    exe2.forward(is_train=True)
    scan_out = exe2.outputs[0].asnumpy()
    exe2.backward([mx.nd.array(head)])
    scan_grads = {k: v.asnumpy() for k, v in exe2.grad_dict.items()}

    np.testing.assert_allclose(fused_out, scan_out, rtol=1e-5, atol=1e-5)
    for k in scan_grads:
        np.testing.assert_allclose(fused_grads[k], scan_grads[k],
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_eligibility_gates(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_FUSED_RNN", raising=False)
    assert not fused_lstm_eligible(4, 8, 128)        # off-TPU, not forced
    assert fused_lstm_eligible(16, 8, 128, force=True)
    monkeypatch.setenv("MXNET_TPU_FUSED_RNN", "0")
    assert not fused_lstm_eligible(128, 8, 128, force=True)


@pytest.mark.tpu
@pytest.mark.skipif("MXTPU_TPU_TESTS" not in __import__("os").environ,
                    reason="real-chip compile test; MXTPU_TPU_TESTS=1")
def test_fused_lstm_compiles_on_tpu():
    """Mosaic-compile and run the jit (non-interpret) kernel on the real
    chip at an eligible shape, checking numerics against the scan."""
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = f"""
import sys; sys.path.insert(0, {repo!r})
import numpy as np, jax, jax.numpy as jnp
from mxnet_tpu.ops.pallas_lstm import fused_lstm
from tests.test_pallas_lstm import _scan_lstm, _rand
gx, h0, c0, wh, bh = _rand(T=32, N=8, H=128, seed=11)
ys, hT, cT = fused_lstm(gx, h0, c0, wh, bh, interpret=False)
rys, rhT, rcT = _scan_lstm(*map(jnp.asarray, (gx, h0, c0, wh, bh)))
np.testing.assert_allclose(np.asarray(ys), np.asarray(rys), rtol=2e-3, atol=2e-3)
g = jax.grad(lambda w: jnp.sum(fused_lstm(gx, h0, c0, w, bh,
                                          interpret=False)[0]))(wh)
gr = jax.grad(lambda w: jnp.sum(_scan_lstm(gx, h0, c0, w, bh)[0]))(wh)
np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=5e-3, atol=5e-3)
print("TPU_FUSED_LSTM_OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    r = subprocess.run([sys.executable, "-c", src], capture_output=True,
                       text=True, timeout=600, env=env)
    assert "TPU_FUSED_LSTM_OK" in r.stdout, r.stderr[-2000:]


def test_mixed_dtype_bias_gradient():
    """bf16 weights with an f32 bias: the bias cotangent must keep the
    bias's own dtype (custom-VJP aval check)."""
    gx, h0, c0, wh, bh = _rand(T=4, N=2, H=8, seed=12)
    bf = jnp.bfloat16
    g = jax.grad(lambda b: jnp.sum(
        fused_lstm(gx.astype(bf), h0.astype(bf), c0.astype(bf),
                   wh.astype(bf), b, interpret=True)[0]
        .astype(jnp.float32)))(bh)
    assert g.dtype == jnp.float32
    assert np.isfinite(np.asarray(g)).all()
