"""Region-proposal toolkit (mxnet_tpu/contrib/rcnn.py — capability
rebuild of example/rcnn's helper/processing + rpn stack)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import rcnn

rng = np.random.RandomState(11)


def test_generate_anchors_geometry():
    a = rcnn.generate_anchors(base_size=16, ratios=(0.5, 1, 2),
                              scales=(8, 16, 32))
    assert a.shape == (9, 4)
    # all anchors centered on the base box center (7.5, 7.5)
    cx = (a[:, 0] + a[:, 2]) / 2
    cy = (a[:, 1] + a[:, 3]) / 2
    np.testing.assert_allclose(cx, 7.5)
    np.testing.assert_allclose(cy, 7.5)
    # areas scale ~ scale^2, aspect ratios follow the ratio list
    w = a[:, 2] - a[:, 0] + 1
    h = a[:, 3] - a[:, 1] + 1
    ratios = h / w
    for i, r in enumerate((0.5, 1, 2)):
        np.testing.assert_allclose(ratios[3 * i:3 * i + 3], r, rtol=0.1)
        np.testing.assert_allclose(
            (w * h)[3 * i:3 * i + 3] / (16 * 16 * np.array([64, 256, 1024])),
            1.0, rtol=0.15)


def test_bbox_transform_pred_roundtrip():
    ex = np.abs(rng.rand(12, 4)) * 40
    ex[:, 2:] = ex[:, :2] + 10 + ex[:, 2:]
    gt = np.abs(rng.rand(12, 4)) * 40
    gt[:, 2:] = gt[:, :2] + 8 + gt[:, 2:]
    deltas = rcnn.bbox_transform(ex, gt)
    back = rcnn.bbox_pred(ex, deltas)
    np.testing.assert_allclose(back, gt, rtol=1e-5, atol=1e-4)


def test_clip_boxes_and_overlaps():
    boxes = np.array([[-5.0, -5, 30, 30], [10, 10, 200, 90]])
    clipped = rcnn.clip_boxes(boxes, (100, 80))
    assert clipped.min() >= 0
    assert clipped[:, 0::4].max() <= 79 and clipped[:, 2::4].max() <= 79
    assert clipped[:, 1::4].max() <= 99 and clipped[:, 3::4].max() <= 99
    iou = rcnn.bbox_overlaps(np.array([[0.0, 0, 9, 9]]),
                             np.array([[0.0, 0, 9, 9], [5, 5, 14, 14],
                                       [20, 20, 29, 29]]))
    np.testing.assert_allclose(iou[0, 0], 1.0)
    np.testing.assert_allclose(iou[0, 1], 25 / 175, rtol=1e-6)
    np.testing.assert_allclose(iou[0, 2], 0.0)


def test_nms_suppresses_overlaps():
    dets = np.array([
        [0, 0, 10, 10, 0.9],
        [1, 1, 11, 11, 0.8],     # heavy overlap with first -> suppressed
        [50, 50, 60, 60, 0.7],
        [0, 0, 10, 10, 0.95],    # best scoring duplicate kept first
    ])
    keep = rcnn.nms(dets, 0.5)
    assert keep[0] == 3
    assert 2 in keep and 1 not in keep and 0 not in keep


def test_assign_anchor_labels_and_targets():
    gt = np.array([[20.0, 20, 60, 60]])
    out = rcnn.assign_anchor((1, 18, 8, 8), gt, im_info=(128, 128, 1.0),
                             feat_stride=16, scales=(2, 4), ratios=(1.0,),
                             batch_rois=32, rng=np.random.RandomState(0))
    A = 2
    assert out["label"].shape == (8 * 8 * A,)
    assert out["bbox_target"].shape == (8 * 8 * A, 4)
    fg = np.where(out["label"] == 1)[0]
    assert len(fg) >= 1
    # fg anchors regress toward the gt box
    base = rcnn.generate_anchors(base_size=16, ratios=(1.0,), scales=(2, 4))
    anchors = rcnn.shift_anchors(base, 8, 8, 16)
    pred = rcnn.bbox_pred(anchors[fg], out["bbox_target"][fg])
    iou = rcnn.bbox_overlaps(pred, gt)
    assert iou.max() > 0.99
    # weights nonzero only at fg
    assert (out["bbox_weight"][fg] == 1).all()
    assert out["bbox_weight"][out["label"] != 1].sum() == 0


def _rpn_inputs(gt, H=8, W=8, stride=16, scales=(2, 4), ratios=(1.0,)):
    """Perfect RPN outputs for the given gt: high score + exact deltas at
    each anchor's best-gt match."""
    base = rcnn.generate_anchors(base_size=stride, ratios=ratios,
                                 scales=scales)
    A = base.shape[0]
    anchors = rcnn.shift_anchors(base, H, W, stride)
    iou = rcnn.bbox_overlaps(anchors, gt)
    best = iou.max(axis=1)
    argb = iou.argmax(axis=1)
    scores = np.zeros((1, 2 * A, H, W), np.float32)
    deltas = np.zeros((1, 4 * A, H, W), np.float32)
    t = rcnn.bbox_transform(anchors, gt[argb])
    fg = best.reshape(H, W, A)
    scores[0, A:] = fg.transpose(2, 0, 1)
    scores[0, :A] = 1 - fg.transpose(2, 0, 1)
    d = t.reshape(H, W, A, 4).transpose(2, 3, 0, 1)  # (A,4,H,W)
    deltas[0] = d.reshape(4 * A, H, W)
    return scores, deltas


def test_proposal_custom_op_recovers_gt():
    gt = np.array([[20.0, 20, 60, 60], [70, 70, 110, 100]])
    scores, deltas = _rpn_inputs(gt)
    cls = mx.sym.Variable("cls_prob")
    bbox = mx.sym.Variable("bbox_pred")
    info = mx.sym.Variable("im_info")
    prop = mx.sym.Custom(cls, bbox, info, op_type="proposal",
                         feat_stride=16, scales="(2, 4)", ratios="(1.0,)",
                         rpn_pre_nms_top_n=200, rpn_post_nms_top_n=8,
                         threshold=0.5, rpn_min_size=4)
    exe = prop.simple_bind(mx.cpu(), grad_req="null",
                           cls_prob=scores.shape, bbox_pred=deltas.shape,
                           im_info=(1, 3))
    exe.arg_dict["cls_prob"][:] = scores
    exe.arg_dict["bbox_pred"][:] = deltas
    exe.arg_dict["im_info"][:] = np.array([[128, 128, 1.0]], np.float32)
    rois = exe.forward(is_train=False)[0].asnumpy()
    assert rois.shape == (8, 5)
    iou = rcnn.bbox_overlaps(rois[:, 1:].astype(np.float64), gt)
    # each gt recovered by some proposal
    assert (iou.max(axis=0) > 0.9).all()


def test_proposal_target_sampling():
    rois = np.hstack([np.zeros((20, 1)),
                      rng.rand(20, 4) * 30]).astype(np.float32)
    rois[:, 3:] = rois[:, 1:3] + 20 + rois[:, 3:]
    gt = np.array([[10.0, 10, 40, 40, 2]], np.float32)
    r = mx.sym.Variable("rois")
    g = mx.sym.Variable("gt_boxes")
    pt = mx.sym.Custom(r, g, op_type="proposal_target", num_classes=3,
                       batch_rois=16, fg_fraction=0.25, fg_overlap=0.5)
    exe = pt.simple_bind(mx.cpu(), grad_req="null", rois=rois.shape,
                         gt_boxes=gt.shape)
    exe.arg_dict["rois"][:] = rois
    exe.arg_dict["gt_boxes"][:] = gt
    outs = [o.asnumpy() for o in exe.forward(is_train=True)]
    out_rois, labels, targets, weights = outs
    assert out_rois.shape == (16, 5)
    assert labels.shape == (16,)
    assert targets.shape == (16, 12) and weights.shape == (16, 12)
    fg = labels > 0
    # gt itself joins the candidates, so at least one fg roi exists
    assert fg.sum() >= 1
    assert set(np.unique(labels[fg])) == {2.0}
    # bbox targets live in the class-2 column block for fg rois
    assert (weights[fg][:, 8:12] == 1).all()
    assert weights[~fg].sum() == 0


def test_assign_anchor_no_inside_anchors():
    # anchors larger than the image: all-ignore targets, no crash
    out = rcnn.assign_anchor((1, 18, 2, 2), np.array([[1.0, 1, 10, 10]]),
                             im_info=(16, 16, 1.0), feat_stride=16,
                             scales=(8, 16, 32), ratios=(0.5, 1, 2))
    assert (out["label"] == -1).all()
    assert out["bbox_weight"].sum() == 0


def test_proposal_target_pad_labels_consistent():
    # fewer candidates than batch_rois: padded repeats must never carry
    # a different label than the original entry
    rois = np.array([[0, 10.0, 10, 40, 40],   # IoU 1.0 with gt -> fg
                     [0, 60, 60, 90, 90]],    # no overlap -> bg
                    np.float32)
    gt = np.array([[10.0, 10, 40, 40, 1]], np.float32)
    r = mx.sym.Variable("rois")
    g = mx.sym.Variable("gt_boxes")
    pt = mx.sym.Custom(r, g, op_type="proposal_target", num_classes=2,
                       batch_rois=12, fg_fraction=0.5, fg_overlap=0.5)
    exe = pt.simple_bind(mx.cpu(), grad_req="null", rois=rois.shape,
                         gt_boxes=gt.shape)
    exe.arg_dict["rois"][:] = rois
    exe.arg_dict["gt_boxes"][:] = gt
    out_rois, labels, _, _ = [o.asnumpy() for o in exe.forward(is_train=True)]
    # every (roi, label) pair must be self-consistent: identical rois
    # agree on their label
    seen = {}
    for roi, lab in zip(map(tuple, out_rois.round(3).tolist()),
                        labels.tolist()):
        assert seen.setdefault(roi, lab) == lab, (roi, seen[roi], lab)
    # the gt-overlapping roi stays foreground somewhere in the batch
    assert (labels > 0).any()


def test_im_detect_decodes_and_suppresses():
    # two rois near one object of class 2; deltas refine roi->gt; NMS
    # keeps a single detection, scores thresholded
    gt = np.array([[20.0, 20, 50, 50]])
    rois = np.array([[0, 18.0, 18, 48, 48],
                     [0, 22, 22, 52, 52],
                     [0, 70, 70, 90, 90]])
    nc = 3
    deltas = np.zeros((3, 4 * nc))
    for i in range(2):
        deltas[i, 8:12] = rcnn.bbox_transform(rois[i:i + 1, 1:5], gt)[0]
    probs = np.array([[0.1, 0.1, 0.8],
                      [0.2, 0.1, 0.7],
                      [0.9, 0.05, 0.05]])   # roi 2: background
    dets = rcnn.im_detect(rois, probs, deltas, im_shape=(100, 100),
                          score_thresh=0.1, nms_thresh=0.3)
    assert dets[2].shape[0] == 1             # NMS merged the duplicates
    iou = rcnn.bbox_overlaps(dets[2][:, :4], gt)
    assert iou.max() > 0.95
    assert dets[2][0, 4] == 0.8              # best score kept
    assert dets[1].shape[0] == 0             # below threshold everywhere


def test_im_detect_rejects_multi_image_rois():
    rois = np.array([[0, 1.0, 1, 10, 10], [1, 1, 1, 10, 10]])
    probs = np.full((2, 2), 0.5)
    deltas = np.zeros((2, 8))
    with pytest.raises(ValueError):
        rcnn.im_detect(rois, probs, deltas, im_shape=(32, 32))


# ---------------------------------------------------------------- dataset
def _make_voc(tmp_path, n_images=3):
    """Synthesize a minimal VOCdevkit tree with known annotations."""
    import xml.etree.ElementTree as ET

    year = "2007"
    devkit = tmp_path / "VOCdevkit"
    data = devkit / ("VOC" + year)
    (data / "Annotations").mkdir(parents=True)
    (data / "ImageSets" / "Main").mkdir(parents=True)
    (data / "JPEGImages").mkdir(parents=True)
    gt = {}
    for i in range(n_images):
        idx = f"im{i:03d}"
        boxes = [(10 + 20 * i, 10, 60 + 20 * i, 80, "cat", 0),
                 (100, 30 + 10 * i, 180, 90 + 10 * i, "dog", 0)]
        gt[idx] = boxes
        root = ET.Element("annotation")
        size = ET.SubElement(root, "size")
        ET.SubElement(size, "width").text = "300"
        ET.SubElement(size, "height").text = "200"
        for (x1, y1, x2, y2, name, diff) in boxes:
            obj = ET.SubElement(root, "object")
            ET.SubElement(obj, "name").text = name
            ET.SubElement(obj, "difficult").text = str(diff)
            bb = ET.SubElement(obj, "bndbox")
            for t, v in zip(("xmin", "ymin", "xmax", "ymax"),
                            (x1, y1, x2, y2)):
                ET.SubElement(bb, t).text = str(v)
        ET.ElementTree(root).write(data / "Annotations" / (idx + ".xml"))
        (data / "JPEGImages" / (idx + ".jpg")).touch()
    with open(data / "ImageSets" / "Main" / "trainval.txt", "w") as f:
        f.write("\n".join(sorted(gt)) + "\n")
    return devkit, gt


def test_pascal_voc_gt_roidb(tmp_path):
    from mxnet_tpu.contrib.rcnn_dataset import PascalVOC

    devkit, gt = _make_voc(tmp_path)
    classes = ("__background__", "cat", "dog")
    imdb = PascalVOC("trainval", "2007", str(tmp_path), str(devkit),
                     classes=classes)
    assert imdb.num_images == 3
    roidb = imdb.gt_roidb()
    assert len(roidb) == 3
    rec = roidb[0]
    assert rec["boxes"].shape == (2, 4)
    # 0-based conversion and class ids
    np.testing.assert_allclose(rec["boxes"][0], [9, 9, 59, 79])
    assert list(rec["gt_classes"]) == [1, 2]
    assert rec["gt_overlaps"][0, 1] == 1.0
    # cache round-trip
    roidb2 = imdb.gt_roidb()
    np.testing.assert_allclose(roidb2[0]["boxes"], rec["boxes"])


def test_pascal_voc_flip_and_proposals(tmp_path):
    from mxnet_tpu.contrib.rcnn_dataset import IMDB, PascalVOC

    devkit, gt = _make_voc(tmp_path)
    classes = ("__background__", "cat", "dog")
    imdb = PascalVOC("trainval", "2007", str(tmp_path), str(devkit),
                     classes=classes)
    roidb = imdb.gt_roidb()

    # proposals npz: gt boxes jittered + one background box per image
    props = {}
    rng = np.random.RandomState(0)
    for i, idx in enumerate(imdb.image_set_index):
        jit = roidb[i]["boxes"] + rng.randint(-2, 3, (2, 4))
        props[idx] = np.vstack([jit, [[0, 0, 5, 5]]])
    pfile = str(tmp_path / "props.npz")
    np.savez(pfile, **props)
    merged = imdb.proposal_roidb(roidb, pfile)
    assert merged[0]["boxes"].shape[0] == 5  # 2 gt + 3 proposals
    # jittered copies overlap their gt class strongly
    assert merged[0]["gt_overlaps"][2:, 1:].max() > 0.7

    # flipping doubles the set and mirrors x coords within the width
    flipped = imdb.append_flipped_images(merged)
    assert len(flipped) == 6 and imdb.num_images == 6
    w = 300
    orig, flip = flipped[0]["boxes"], flipped[3]["boxes"]
    np.testing.assert_allclose(flip[:, 0], w - orig[:, 2] - 1)

    rec = imdb.evaluate_recall(merged[:3])
    assert rec["ar"] > 0.5  # jittered proposals cover the gt


def test_voc_eval_map(tmp_path):
    """Perfect detections give mAP 1.0; adding a confident false
    positive on one class drops only that class's AP (voc_eval parity:
    greedy matching, double-detection = fp, 11-point vs integral)."""
    from mxnet_tpu.contrib.rcnn_dataset import PascalVOC

    devkit, gt = _make_voc(tmp_path)
    classes = ("__background__", "cat", "dog")
    imdb = PascalVOC("trainval", "2007", str(tmp_path), str(devkit),
                     classes=classes)
    roidb = imdb.gt_roidb()

    # all_boxes[cls][img] = (n,5) detections in 0-based pixels
    all_boxes = [[np.zeros((0, 5))] * 3 for _ in classes]
    for i in range(3):
        for ci, cls in enumerate(classes):
            dets = [np.hstack([roidb[i]["boxes"][j], [0.9]])
                    for j in range(2) if roidb[i]["gt_classes"][j] == ci]
            if dets:
                all_boxes[ci][i] = np.vstack(dets)
    aps, mean_ap = imdb.evaluate_detections(all_boxes)
    assert mean_ap > 0.99, aps

    # confident fp on 'cat' in image 0
    all_boxes[1][0] = np.vstack([all_boxes[1][0],
                                 [200.0, 100.0, 250.0, 150.0, 0.95]])
    # fresh imdb to avoid annotation cache cross-talk? cache is fine —
    # detections changed, not annotations
    aps2, mean2 = imdb.evaluate_detections(all_boxes)
    assert aps2["dog"] > 0.99
    assert aps2["cat"] < aps["cat"]
