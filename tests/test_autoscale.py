"""Fleet control-plane tests (mxnet_tpu/fleet/autoscaler.py +
deploy.py): role-aware autoscaling policy, supervisor pool resizing /
slot replacement, and rolling weight-reload deploys with SLO-gated
rollback.

Two tiers of harness, both tier-1 CPU-deterministic:

* **policy tests** drive ``Autoscaler.evaluate`` with a fake clock, a
  fake collector (settable role aggregates + SLO section) and fake
  per-role pools — no engines, no HTTP — pinning the decision rules:
  scale-up on a queue step, scale-down only after quiet windows,
  flapping load never actuates more than once per cooldown, prefill
  pressure never grows the decode pool, min/max bounds hold, and a
  role whose replicas are all stale is never scaled (dead data).
* **fleet tests** use real in-process ``ReplicaServer`` HTTP fronts
  over real engines (the test_fleet.py tiny-model recipe) to pin
  ``Supervisor.replace_slot`` (including crash-during-replace),
  ``add_slot``/``remove_slot`` retirement, the deployer's token-parity
  gate (pass and fail), rollback-on-burn, and mixed-version routing
  with per-slot versions in ``/fleetz``.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.fleet import (Autoscaler, Deployer, FleetCollector,
                             ReplicaServer, Router, Supervisor,
                             parse_autoscale_spec)

VOCAB = 53


@pytest.fixture(scope="module")
def model():
    """Tiny gpt2-style net + params (the test_fleet recipe)."""
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


@pytest.fixture(scope="module")
def model_b(model):
    """Same architecture, DIFFERENT weights (seed 11) — the "new
    checkpoint that is not the weights it claims to be" of the parity
    failure arm."""
    net, _ = model
    S = 96
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(11)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _get(url, path, timeout=10):
    with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.fixture
def fleet_cleanup():
    items = []
    yield items
    for obj in reversed(items):
        try:
            obj.stop()
        except Exception:
            pass


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.reset()


class _InProcHandle:
    def __init__(self, replica):
        self.replica = replica
        self.url = replica.url

    def poll(self):
        return None if self.replica.state != "dead" else 1

    def terminate(self, grace_s=None):
        self.replica.stop()


def _factory(model, fleet_cleanup, version):
    """spawn(slot) -> in-process replica handle tagged ``version``."""
    def spawn(slot):
        rep = ReplicaServer(_engine(model),
                            replica_id=f"{version}-s{slot}",
                            version=version).start()
        fleet_cleanup.append(rep)
        return _InProcHandle(rep)
    return spawn


# -- policy-test fakes --------------------------------------------------------
class _FakePool:
    """Actuator stub: real pool bookkeeping, no spawning."""

    def __init__(self, n=1):
        self.slots = list(range(n))
        self._next = n
        self.added = []
        self.removed = []

    def pool_size(self):
        return len(self.slots)

    def active_slots(self):
        return list(self.slots)

    def add_slot(self, factory=None):
        slot = self._next
        self._next += 1
        self.slots.append(slot)
        self.added.append(slot)
        return slot

    def remove_slot(self, slot):
        self.slots.remove(slot)
        self.removed.append(slot)
        return True


class _FakeCollector:
    def __init__(self):
        self.roles = {}
        self.slo = None
        self.slo_section = None
        self.notes = []

    def fleet_view(self):
        return {"roles": self.roles, "slo": self.slo_section}

    def annotate(self, kind, **fields):
        self.notes.append(dict(kind=kind, **fields))


def _agg(replicas=1, stale=0, queue=0, running=0, handoffs=0,
         kv=None, hkv=None):
    return {"replicas": replicas, "stale": stale,
            "queue_depth": queue, "running": running,
            "waiting_handoffs": handoffs,
            "kv_utilization_mean": kv,
            "host_kv_utilization_mean": hkv}


# -- spec grammar -------------------------------------------------------------
def test_autoscale_spec_grammar():
    cfg = parse_autoscale_spec(
        "prefill=1:4;decode=1:8;up_queue=16;down_idle_s=30")
    assert cfg["bounds"] == {"prefill": (1, 4), "decode": (1, 8)}
    assert cfg["up_queue"] == 16.0 and cfg["down_idle_s"] == 30.0
    assert cfg["up_handoffs"] == 4.0 and cfg["cooldown_s"] == 15.0
    assert parse_autoscale_spec("both=2:2")["bounds"] == {
        "both": (2, 2)}
    for bad in ("prefill=4:1",          # min > max
                "prefill=1",            # no :max
                "replica=1:2",          # unknown role
                "up_queue=-3",          # negative knob
                "prefill=1:2;prefill=1:3",   # duplicate role
                "up_queue=16",          # knobs only: nothing to manage
                "prefill=a:b",
                "wat"):
        with pytest.raises(ValueError):
            parse_autoscale_spec(bad)


# -- scaling policy (fake clock, fake pools) ----------------------------------
def test_scale_up_on_queue_step(tel):
    col = _FakeCollector()
    pool = _FakePool(1)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"prefill": pool},
                   spec="prefill=1:4;up_queue=16",
                   clock=lambda: clock["now"])
    col.roles = {"prefill": _agg(replicas=1, queue=3)}
    assert a.evaluate() == []                 # under threshold: hold
    col.roles = {"prefill": _agg(replicas=1, queue=40)}
    assert a.evaluate() == [("prefill", "up", "queue")]
    assert pool.pool_size() == 2
    # threshold is per FRESH replica: 40 queued over 2 replicas is
    # still 20 >= 16 -> next window (cooldown first) scales again
    clock["now"] = 20.0
    col.roles = {"prefill": _agg(replicas=2, queue=40)}
    assert a.evaluate() == [("prefill", "up", "queue")]
    snap = telemetry.registry().snapshot()
    events = {tuple(sorted(s["labels"].items())): s["value"]
              for s in snap["mxtpu_fleet_scale_events_total"]["samples"]}
    key = (("direction", "up"), ("reason", "queue"),
           ("role", "prefill"))
    assert events[key] == 2.0
    # the actuation trail: timeline annotations carry the decision
    assert [n for n in col.notes if n["kind"] == "autoscale"]


def test_scale_down_only_after_quiet_windows():
    col = _FakeCollector()
    pool = _FakePool(3)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"both": pool},
                   spec="both=1:4;down_idle_s=30;cooldown_s=5",
                   clock=lambda: clock["now"])
    col.roles = {"both": _agg(replicas=3)}    # fully quiet
    assert a.evaluate() == []                 # ledger starts at t=0
    clock["now"] = 29.0
    assert a.evaluate() == []                 # not quiet long enough
    clock["now"] = 31.0
    assert a.evaluate() == [("both", "down", "idle")]
    assert pool.pool_size() == 2
    # the actuation resets the ledger: a FULL fresh window is needed
    clock["now"] = 36.0
    assert a.evaluate() == []                 # ledger restarts at 36
    clock["now"] = 60.0
    assert a.evaluate() == []                 # 24s quiet < 30
    clock["now"] = 67.0
    assert a.evaluate() == [("both", "down", "idle")]
    assert pool.pool_size() == 1              # at min now
    clock["now"] = 200.0
    assert a.evaluate() == []                 # min bound holds
    assert pool.removed == [3 - 1, 2 - 1]     # newest slots first


def test_hysteresis_flapping_load_one_actuation_per_cooldown():
    col = _FakeCollector()
    pool = _FakePool(1)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"both": pool},
                   spec="both=1:8;up_queue=4;cooldown_s=10",
                   clock=lambda: clock["now"])
    pressured = {"both": _agg(replicas=1, queue=50)}
    quiet = {"both": _agg(replicas=1)}
    actions = []
    for i in range(20):                       # flap every 0.5s for 10s
        clock["now"] = i * 0.5
        col.roles = pressured if i % 2 == 0 else quiet
        actions += a.evaluate()
    assert len(actions) == 1                  # <= 1 per cooldown window
    clock["now"] = 10.5                       # cooldown elapsed
    col.roles = pressured
    assert a.evaluate() == [("both", "up", "queue")]
    # a pressure blip also resets the scale-down ledger: quiet resumes
    # from scratch, it does not inherit pre-blip quiet time
    assert len(actions) + 1 == len(pool.added)


def test_per_role_independence_and_decode_signals():
    col = _FakeCollector()
    pre, dec = _FakePool(1), _FakePool(1)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"prefill": pre, "decode": dec},
                   spec="prefill=1:4;decode=1:4;up_queue=8;"
                        "up_handoffs=4;cooldown_s=0",
                   clock=lambda: clock["now"])
    # prefill pressure NEVER grows decode
    col.roles = {"prefill": _agg(replicas=1, queue=100),
                 "decode": _agg(replicas=1)}
    assert a.evaluate() == [("prefill", "up", "queue")]
    assert dec.added == []
    # decode scales on its own signals: handoffs, then KV headroom
    clock["now"] = 1.0
    col.roles = {"prefill": _agg(replicas=2),
                 "decode": _agg(replicas=1, handoffs=9)}
    assert a.evaluate() == [("decode", "up", "handoffs")]
    clock["now"] = 2.0
    col.roles = {"prefill": _agg(replicas=2),
                 "decode": _agg(replicas=2, hkv=0.95)}
    assert a.evaluate() == [("decode", "up", "host_kv")]
    assert pre.added == [1]                   # prefill grew exactly once
    # decode queue pressure means nothing to a prefill pool and
    # vice-versa: queue_depth on decode is not a decode signal
    clock["now"] = 3.0
    col.roles = {"prefill": _agg(replicas=2),
                 "decode": _agg(replicas=3, queue=100)}
    assert a.evaluate() == []


def test_min_max_bounds_and_burn_signals():
    col = _FakeCollector()
    pool = _FakePool(2)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"both": pool},
                   spec="both=1:2;cooldown_s=0",
                   clock=lambda: clock["now"])
    # at max: pressure cannot grow the pool
    col.roles = {"both": _agg(replicas=2, queue=500)}
    assert a.evaluate() == []
    # a firing ttft objective is prefill-side pressure (here: capped)
    col.roles = {"both": _agg(replicas=2)}
    col.slo_section = {"objectives": [
        {"objective": "ttft_p99_ms", "firing": True}]}
    assert a.evaluate() == []                 # still capped at max=2
    pool.slots = [0]                          # shrink out-of-band
    assert a.evaluate() == [("both", "up", "ttft_burn")]
    # a firing objective also blocks scale-down quiet credit
    col.roles = {"both": _agg(replicas=2)}
    clock["now"] = 1000.0
    assert a.evaluate() == []
    col.slo_section = None
    # below min: restored even with no aggregates scraped yet
    empty = _FakePool(0)
    b = Autoscaler(col, {"both": empty}, spec="both=1:2;cooldown_s=0",
                   clock=lambda: clock["now"])
    assert b.evaluate() == [("both", "up", "min_bound")]
    assert empty.pool_size() == 1


def test_never_scales_on_stale_aggregates():
    """A role whose replicas are ALL stale reports load numbers the
    autoscaler must ignore entirely — dead data scales nothing, in
    either direction."""
    col = _FakeCollector()
    pool = _FakePool(2)
    clock = {"now": 0.0}
    a = Autoscaler(col, {"both": pool},
                   spec="both=1:4;down_idle_s=1;cooldown_s=0",
                   clock=lambda: clock["now"])
    col.roles = {"both": _agg(replicas=2, stale=2, queue=500)}
    for t in (0.0, 5.0, 50.0):
        clock["now"] = t
        assert a.evaluate() == []
    assert pool.added == [] and pool.removed == []


# -- collector age cap (regression pin) ---------------------------------------
def test_collector_stale_row_drops_load_signals(model, fleet_cleanup):
    """Regression: the collector used to keep serving a stale
    replica's last-scraped load signals forever; past the staleness
    age cap the row must carry identity/failure fields ONLY."""
    rep = ReplicaServer(_engine(model), replica_id="r0",
                        version="v1").start()
    fleet_cleanup.append(rep)
    clock = {"now": 0.0}
    col = FleetCollector(urls=[rep.url], interval_s=0, stale_after=3.0,
                         clock=lambda: clock["now"])
    fleet_cleanup.append(col)
    col.scrape()
    view = col.fleet_view()
    row = view["replicas"][0]
    assert not row["stale"]
    assert "queue_depth" in row and "kv_utilization" in row
    assert row["version"] == "v1"
    assert view["roles"]["both"]["versions"] == {"v1": 1}
    clock["now"] = 10.0              # > stale_after * max(interval, 1)
    view = col.fleet_view()
    row = view["replicas"][0]
    assert row["stale"]
    for f in ("queue_depth", "running", "in_flight", "kv_utilization",
              "tok_per_sec", "tokens_generated", "ttft_ms_p99"):
        assert f not in row, f       # the dead data the fix removes
    # identity and failure accounting stay visible
    assert row["replica"] == "r0" and row["version"] == "v1"
    assert row["scrapes"] == 1
    agg = view["roles"]["both"]
    assert agg["stale"] == 1 and agg["versions"] == {}


# -- supervisor: replace_slot + pool resizing ---------------------------------
def test_replace_slot_swaps_factory_and_router_membership(
        model, fleet_cleanup, tel):
    old = _factory(model, fleet_cleanup, "v1")
    new = _factory(model, fleet_cleanup, "v2")
    col = FleetCollector(urls=[], interval_s=0)
    fleet_cleanup.append(col)
    router = Router([], scrape_interval_s=0)
    fleet_cleanup.append(router)
    sup = Supervisor(old, 1, drain_timeout_s=10, router=router,
                     collector=col)
    fleet_cleanup.append(sup)
    sup.start()
    old_url = sup.urls()[0]
    assert _get(old_url, "/healthz")["version"] == "v1"
    handle = sup.replace_slot(0, new, reason="deploy")
    assert handle.url != old_url
    assert _get(handle.url, "/healthz")["version"] == "v2"
    assert _get(handle.url, "/statusz.json")["replica"]["version"] \
        == "v2"
    assert [r.url for r in router.replicas()] == [handle.url]
    phases = [a["phase"] for a in col.annotations()
              if a["kind"] == "deploy_replace_slot"]
    assert phases == ["drain", "terminate", "respawned"]
    snap = telemetry.registry().snapshot()
    reasons = {s["labels"]["reason"]: s["value"]
               for s in snap["mxtpu_fleet_restarts_total"]["samples"]}
    assert reasons == {"deploy": 1}


def test_replace_slot_crash_during_replace(model, fleet_cleanup):
    """A replica that dies mid-replace (here: before the drain can
    even be posted) is still replaced — wait_drained observes the
    death, terminate is a no-op, the factory spawn proceeds."""
    old = _factory(model, fleet_cleanup, "v1")
    new = _factory(model, fleet_cleanup, "v2")
    sup = Supervisor(old, 1, drain_timeout_s=10)
    fleet_cleanup.append(sup)
    sup.start()
    sup.handles()[0].replica.hard_stop()      # crash
    handle = sup.replace_slot(0, new, reason="deploy")
    assert handle is not None
    assert _get(handle.url, "/healthz")["version"] == "v2"
    # and the crash monitor never double-spawned: one live handle
    assert len(sup.urls()) == 1


def test_add_remove_slot_retires_indices(model, fleet_cleanup):
    spawn = _factory(model, fleet_cleanup, "v1")
    router = Router([], scrape_interval_s=0)
    fleet_cleanup.append(router)
    sup = Supervisor(spawn, 1, drain_timeout_s=10, router=router)
    fleet_cleanup.append(sup)
    sup.start()
    slot = sup.add_slot()
    assert slot == 1
    assert sup.pool_size() == 2 and len(router.replicas()) == 2
    assert sup.remove_slot(1) is True
    assert sup.pool_size() == 1 and len(router.replicas()) == 1
    assert sup.active_slots() == [0]
    assert sup.remove_slot(1) is False        # already retired
    assert sup.check() == []                  # monitor skips retired
    # retired indices are never reused: the next growth is slot 2
    assert sup.add_slot() == 2
    assert sup.pool_size() == 2
    # rolling restart walks ACTIVE slots only (a retired slot would
    # crash the drain path with its None handle)
    assert len(sup.rolling_restart()) == 2


# -- rolling deploys ----------------------------------------------------------
def test_rolling_deploy_parity_gate_pass(model, fleet_cleanup, tel):
    old = _factory(model, fleet_cleanup, "v1")
    new = _factory(model, fleet_cleanup, "v2")   # same weights, new tag
    sup = Supervisor(old, 2, drain_timeout_s=10)
    fleet_cleanup.append(sup)
    sup.start()
    dep = Deployer(sup)                       # bare sup -> {"both": sup}
    ref = dep.probe(sup.urls()[0], "both")
    report = dep.rollout(new, version="v2")
    assert report["status"] == "ok" and report["reason"] is None
    assert report["replaced"] == 2 and report["rolled_back"] == 0
    for url in sup.urls():
        assert _get(url, "/healthz")["version"] == "v2"
        assert dep.probe(url, "both") == ref  # weight-reload: parity
    snap = telemetry.registry().snapshot()
    assert snap["mxtpu_deploy_slots_replaced_total"]["samples"][0][
        "value"] == 2.0
    assert sum(s["value"] for s in snap.get(
        "mxtpu_deploy_rollbacks_total", {}).get("samples", ())) == 0.0


def test_rolling_deploy_parity_failure_rolls_back(model, model_b,
                                                  fleet_cleanup, tel):
    old = _factory(model, fleet_cleanup, "v1")
    bad = _factory(model_b, fleet_cleanup, "v2")  # DIFFERENT weights
    sup = Supervisor(old, 2, drain_timeout_s=10)
    fleet_cleanup.append(sup)
    sup.start()
    dep = Deployer(sup)
    ref = dep.probe(sup.urls()[0], "both")
    report = dep.rollout(bad, version="v2", old_factory=old)
    assert report["status"] == "rolled_back"
    assert report["reason"] == "parity"
    assert report["replaced"] == 1            # first slot failed the gate
    assert report["rolled_back"] == 1
    # the restored fleet serves tokens IDENTICAL to the pre-rollout
    # reference, on every slot
    assert sup.pool_size() == 2
    for url in sup.urls():
        assert _get(url, "/healthz")["version"] == "v1"
        assert dep.probe(url, "both") == ref
    snap = telemetry.registry().snapshot()
    assert snap["mxtpu_deploy_rollbacks_total"]["samples"][0][
        "value"] == 1.0


class _FiringSLO:
    def __init__(self):
        self.firing = False

    def statusz(self):
        return {"objectives": [{"objective": "ttft_p99_ms",
                                "firing": self.firing}]}


def test_rolling_deploy_rollback_on_slo_burn(model, fleet_cleanup,
                                             tel):
    old = _factory(model, fleet_cleanup, "v1")
    new = _factory(model, fleet_cleanup, "v2")
    col = FleetCollector(urls=[], interval_s=0)
    fleet_cleanup.append(col)
    col.slo = _FiringSLO()
    sup = Supervisor(old, 2, drain_timeout_s=10, collector=col)
    fleet_cleanup.append(sup)
    sup.start()
    dep = Deployer(sup, collector=col)
    col.slo.firing = True                     # the fleet is burning
    report = dep.rollout(new, version="v2", old_factory=old)
    assert report["status"] == "rolled_back"
    assert report["reason"] == "slo_burn"
    for url in sup.urls():
        assert _get(url, "/healthz")["version"] == "v1"
    kinds = [a["kind"] for a in col.annotations()]
    assert "deploy_rollback" in kinds


def test_mixed_version_fleet_routes_and_surfaces_versions(
        model, fleet_cleanup):
    """Mid-rollout reality: one v1 and one v2 replica (same weights)
    coexist — the router serves the mixed fleet token-identically and
    /fleetz tells the versions apart per slot and per role."""
    r1 = ReplicaServer(_engine(model), replica_id="old-r",
                       version="v1").start()
    r2 = ReplicaServer(_engine(model), replica_id="new-r",
                       version="v2").start()
    fleet_cleanup += [r1, r2]
    router = Router([r1.url, r2.url], scrape_interval_s=0)
    fleet_cleanup.append(router)
    col = FleetCollector(urls=[r1.url, r2.url], interval_s=0)
    fleet_cleanup.append(col)
    col.scrape()
    view = col.fleet_view()
    rows = {r["replica"]: r for r in view["replicas"]}
    assert rows["old-r"]["version"] == "v1"
    assert rows["new-r"]["version"] == "v2"
    assert view["roles"]["both"]["versions"] == {"v1": 1, "v2": 1}
    # same weights => the mixed fleet is token-transparent: every
    # request lands somewhere and both versions answer identically
    dep = Deployer({"both": None}, canary_max_new=6)
    assert dep.probe(r1.url, "both") == dep.probe(r2.url, "both")
    rng = np.random.RandomState(5)
    for i in range(6):
        prompt = [int(t) for t in rng.randint(0, VOCAB, (7,))]
        res = router.generate(prompt, max_new_tokens=5,
                              request_id=f"mix-{i}")
        assert res.tokens


def test_control_plane_env_knobs_documented():
    with open(os.path.join(REPO, "docs", "env_vars.md")) as f:
        text = f.read()
    for var in ("MXTPU_AUTOSCALE_SPEC", "MXTPU_DEPLOY_CANARY_NEW",
                "MXTPU_DEPLOY_PROBE_TIMEOUT"):
        assert f"`{var}`" in text, var
