"""Native C++ runtime components (src/*.cc via ctypes): engine
dependency semantics, recordio scanner, storage pool."""

import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.libinfo import find_lib

pytestmark = pytest.mark.skipif(find_lib() is None,
                                reason="native library not built")


def test_native_engine_workload():
    from mxnet_tpu.engine import NativeEngine

    engine = NativeEngine(num_workers=4)
    import random

    rng = random.Random(0)
    history = []
    lock = threading.Lock()
    variables = [engine.new_variable(f"v{i}") for i in range(6)]
    n_ops = 80
    for op_id in range(n_ops):
        n_read = rng.randint(0, 2)
        n_write = rng.randint(1, 2)
        picks = rng.sample(range(6), n_read + n_write)
        reads = [variables[i] for i in picks[:n_read]]
        writes = [variables[i] for i in picks[n_read:]]

        def fn(op_id=op_id, w=tuple(picks[n_read:])):
            with lock:
                history.append((op_id, w))

        engine.push(fn, const_vars=reads, mutable_vars=writes)
    engine.wait_for_all()
    assert sorted(h[0] for h in history) == list(range(n_ops))
    last_write = {}
    for op_id, writes in history:
        for v in writes:
            if v in last_write:
                assert last_write[v] < op_id
            last_write[v] = op_id


def test_native_engine_wait_for_var():
    from mxnet_tpu.engine import NativeEngine

    engine = NativeEngine(num_workers=2)
    v = engine.new_variable()
    done = []
    engine.push(lambda: (time.sleep(0.05), done.append(1)), mutable_vars=(v,))
    engine.wait_for_var(v)
    assert done == [1]
    engine.wait_for_all()


def test_native_engine_exception():
    from mxnet_tpu.engine import NativeEngine

    engine = NativeEngine(num_workers=2)

    def bad():
        raise RuntimeError("native boom")

    engine.push(bad)
    with pytest.raises(RuntimeError, match="native boom"):
        engine.wait_for_all()


def test_native_recordio_index(tmp_path):
    import ctypes

    from mxnet_tpu import recordio

    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [bytes([i]) * (10 + i * 7) for i in range(20)]
    for p in payloads:
        w.write(p)
    w.close()

    lib = find_lib()
    n = ctypes.c_int64()
    idx = lib.MXTPURecordIOIndex(path.encode(), ctypes.byref(n))
    assert idx and n.value == 20
    off = ctypes.c_uint64()
    length = ctypes.c_uint32()
    lib.MXTPURecordIOIndexGet(idx, 3, ctypes.byref(off), ctypes.byref(length))
    assert length.value == len(payloads[3])

    indices = (ctypes.c_int64 * 3)(5, 0, 19)
    total = sum(len(payloads[i]) for i in (5, 0, 19))
    buf = (ctypes.c_uint8 * (total + 16))()
    sizes = (ctypes.c_uint32 * 3)()
    got = lib.MXTPURecordIOReadBatch(path.encode(), idx, indices, 3, buf,
                                     len(buf), sizes)
    assert got == total
    pos = 0
    for j, i in enumerate((5, 0, 19)):
        assert bytes(buf[pos:pos + sizes[j]]) == payloads[i]
        pos += sizes[j]
    lib.MXTPURecordIOIndexFree(idx)


def test_storage_pool():
    from mxnet_tpu import storage

    s0 = storage.stats()
    assert s0["native"]
    p1 = storage.alloc(1 << 20)
    assert p1
    storage.free(p1, 1 << 20)
    p2 = storage.alloc(1 << 20)  # should come from the pool
    s1 = storage.stats()
    assert s1["pool_hits"] > s0.get("pool_hits", 0)
    storage.free(p2, 1 << 20)
    storage.release_all()
    assert storage.stats()["pooled_bytes"] == 0


def test_staging_buffer_numpy_view():
    from mxnet_tpu.storage import StagingBuffer

    with StagingBuffer((4, 8), np.float32) as arr:
        arr[:] = np.arange(32).reshape(4, 8)
        assert arr.sum() == np.arange(32).sum()


# -- flat C API: error ring + op discovery (include/mxtpu/c_api.h) ----------
def test_c_api_error_ring():
    from mxnet_tpu import c_api, libinfo

    lib = libinfo.find_lib()
    if lib is None:
        pytest.skip("native lib unavailable")
    lib.MXTPUSetLastError(b"boom")
    assert c_api.last_error() == "boom"
    lib.MXTPUSetLastError(b"")
    assert c_api.last_error() == ""


def test_c_api_op_discovery_roundtrip():
    from mxnet_tpu import c_api, libinfo

    if libinfo.find_lib() is None:
        pytest.skip("native lib unavailable")
    names = c_api.list_ops()
    assert len(names) > 100
    # canonical display names (what docs/examples compose), not the
    # registry's lowercase lookup keys; lookups stay case-insensitive
    assert "Convolution" in names and "SoftmaxOutput" in names
    assert "convolution" not in names

    doc, args, params = c_api.get_op_info("convolution")
    assert args[0] == "data"
    assert "kernel" in params
    type_str, _ = params["kernel"]
    assert "required" in type_str
    assert "num_filter" in params

    doc, args, params = c_api.get_op_info("softmaxoutput")
    assert args == ["data", "label"]
    assert "grad_scale" in params
    assert "optional" in params["grad_scale"][0]


def test_c_api_unknown_op_sets_error():
    from mxnet_tpu import c_api, libinfo

    if libinfo.find_lib() is None:
        pytest.skip("native lib unavailable")
    with pytest.raises(KeyError):
        c_api.get_op_info("no_such_op_xyz")
    assert "no_such_op_xyz" in c_api.last_error()


def test_c_api_usable_from_c(tmp_path):
    """Compile and run a real C consumer of include/mxtpu/c_api.h —
    the reference's thin-frontend contract (tests/cpp analog)."""
    import shutil
    import subprocess

    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "tests", "cpp", "c_api_consumer.c")
    exe = str(tmp_path / "capi_test")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["gcc", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    out = subprocess.run([exe], capture_output=True, text=True, check=True)
    assert "C_API_OK" in out.stdout


def test_c_api_sees_late_registered_custom_ops():
    import mxnet_tpu as mx
    from mxnet_tpu import c_api
    from mxnet_tpu.operator import CustomOpProp, register

    @register("late_custom_op_test")
    class _P(CustomOpProp):
        pass

    assert "late_custom_op_test" in c_api.list_ops()


def test_c_predict_api_from_c(tmp_path):
    """End-to-end C predict path: train a tiny MLP in Python, save the
    two-artifact checkpoint, run inference from a pure-C program through
    MXTPUPred* (embedded-interpreter bridge), compare outputs."""
    import shutil
    import subprocess

    import numpy as np

    import mxnet_tpu as mx

    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    net = mx.models.mlp(num_classes=3)
    rng = np.random.RandomState(0)
    X = rng.randn(4, 16).astype(np.float32)
    ex = net.simple_bind(mx.cpu(), data=(4, 16), softmax_label=(4,))
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = rng.randn(*arr.shape) * 0.1
    ex.arg_dict["data"][:] = X
    ex.forward(is_train=False)
    want = ex.outputs[0].asnumpy()

    json_path = str(tmp_path / "m.json")
    params_path = str(tmp_path / "m.params")
    net.save(json_path)
    mx.nd.save(params_path,
               {f"arg:{k}": v for k, v in ex.arg_dict.items()
                if k not in ("data", "softmax_label")})

    src = os.path.join(repo, "tests", "cpp", "predict_consumer.c")
    exe = str(tmp_path / "pred_test")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["gcc", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    stdin = "\n".join(f"{v:.8f}" for v in X.reshape(-1))
    r = subprocess.run([exe, json_path, params_path, "4", "16"],
                       input=stdin, capture_output=True, text=True,
                       timeout=280, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    got = np.array([float(x) for x in r.stdout.split()]).reshape(want.shape)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_cpp_engine_stress(tmp_path):
    """Pure-C++ randomized workload-equivalence stress for the native
    engine (tests/cpp/engine_stress.cc — the threaded_engine_test.cc
    analog): serial run and threaded run must agree exactly."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "tests", "cpp", "engine_stress.cc")
    exe = str(tmp_path / "engine_stress")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["g++", "-O2", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    out = subprocess.run([exe], capture_output=True, text=True, check=True,
                         timeout=120)
    assert "ENGINE_STRESS_OK" in out.stdout


def _write_idx(path, arr):
    """Write MNIST idx format (big-endian magic + dims + raw bytes)."""
    import struct

    with open(path, "wb") as f:
        f.write(struct.pack(">i", (8 << 8) + arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">i", d))
        f.write(arr.tobytes())


def _make_idx_dataset(tmp_path, seed, n=300):
    """Synthetic learnable MNIST-format idx pair: each class stamps a
    bright patch at a deterministic position, so LeNet fits it to ~1.0."""
    import numpy as np

    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = rng.randint(0, 40, (n, 28, 28)).astype(np.uint8)
    for i, k in enumerate(labels):
        r, c = (int(k) // 5) * 14 + 2, (int(k) % 5) * 5 + 1
        images[i, r:r + 9, c:c + 4] = 220
    img_path = str(tmp_path / "img.idx")
    lab_path = str(tmp_path / "lab.idx")
    _write_idx(img_path, images)
    _write_idx(lab_path, labels)
    return img_path, lab_path


def test_c_train_api_from_c(tmp_path):
    """End-to-end *training* from pure C through the flat ABI — the
    reference's thin-frontend training contract (c_api.cc:956-1110:
    symbol compose + infer_shape + executor bind/forward/backward +
    kvstore push/pull + MNISTIter), exercised by
    tests/cpp/train_consumer.c on MNIST-format idx data whose class is a
    deterministic bright-patch position (learnable to ~1.0 accuracy)."""
    import shutil
    import subprocess

    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    img_path, lab_path = _make_idx_dataset(tmp_path, seed=0)

    src = os.path.join(repo, "tests", "cpp", "train_consumer.c")
    exe = str(tmp_path / "train_consumer")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["gcc", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([exe, img_path, lab_path, "50", "12"],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + "\n" + r.stderr)[-3000:]
    assert "C_TRAIN_OK" in r.stdout


def test_cpp_frontend_trains(tmp_path):
    """Second-language frontend proof: the header-only C++ binding
    (include/mxtpu/cpp/mxtpu.hpp, the reference cpp-package analog)
    builds LeNet, trains through DataIter + Executor + KVStore SGD, and
    reaches high accuracy — all through the C ABI, no Python headers."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    img_path, lab_path = _make_idx_dataset(tmp_path, seed=1)

    src = os.path.join(repo, "tests", "cpp", "cpp_frontend_train.cc")
    exe = str(tmp_path / "cpp_frontend_train")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["g++", "-std=c++17", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([exe, img_path, lab_path, "50", "12"],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + "\n" + r.stderr)[-3000:]
    assert "CPP_TRAIN_OK" in r.stdout


@pytest.mark.slow
@pytest.mark.parametrize("sanitizer", ["thread", "address"])
def test_cpp_engine_sanitizers(tmp_path, sanitizer):
    """Engine stress under TSAN/ASAN — race/memory gates the reference
    never had (SURVEY.md §5 notes 'No TSAN/ASAN CI' as a gap to improve
    on).  src/engine.cc is freestanding C++, so the whole binary is
    instrumented: any data race in the dependency tracker or worker
    pools fails the run, not just wrong final state."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # availability probe on a trivial program: skip ONLY when the
    # toolchain lacks the sanitizer runtime — a compile failure of the
    # real sources must FAIL the gate, not silently disable it
    probe = tmp_path / "san_probe.cc"
    probe.write_text("int main() { return 0; }\n")
    pr = subprocess.run(
        ["g++", f"-fsanitize={sanitizer}", str(probe), "-o",
         str(tmp_path / "san_probe")],
        capture_output=True, text=True)
    if pr.returncode != 0:
        pytest.skip(f"no lib{sanitizer[0]}san runtime: {pr.stderr[-200:]}")
    exe = str(tmp_path / f"engine_stress_{sanitizer}")
    r = subprocess.run(
        ["g++", "-std=c++17", f"-fsanitize={sanitizer}", "-O1", "-g",
         "-I" + os.path.join(repo, "include"),
         os.path.join(repo, "src", "engine.cc"),
         os.path.join(repo, "tests", "cpp", "engine_stress.cc"),
         "-o", exe, "-lpthread"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    out = subprocess.run([exe], capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, (out.stdout + "\n" + out.stderr)[-3000:]
    assert "ENGINE_STRESS_OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr
    assert "ERROR: AddressSanitizer" not in out.stderr


def test_cpp_module_lenet_gate(tmp_path):
    """The graduated C++ frontend (VERDICT r4 item 5): LeNet built from
    the RUNTIME-DISCOVERED op registry (ListOps/GetOpInfo), trained via
    the Module-style fit over DataIter with the imperative C-API
    optimizer, params checkpoint round-trip, predict — to the SAME
    accuracy gate as the Python tier (test_train.py acc > 0.95)."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    img_path, lab_path = _make_idx_dataset(tmp_path, seed=2)

    src = os.path.join(repo, "examples", "cpp", "train_lenet.cc")
    exe = str(tmp_path / "train_lenet")
    lib_dir = os.path.join(repo, "mxnet_tpu", "lib")
    subprocess.run(
        ["g++", "-std=c++17", "-I" + os.path.join(repo, "include"), src,
         "-L" + lib_dir, "-lmxtpu", "-Wl,-rpath," + lib_dir, "-o", exe],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([exe, img_path, lab_path, "50", "6"],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + "\n" + r.stderr)[-3000:]
    assert "CPP_LENET_OK" in r.stdout
    assert "registry:" in r.stderr
