"""Module API tests (rebuild of tests/python/unittest/test_module.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io import DataBatch, NDArrayIter


def _toy_data(n=256, d=10, c=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, c).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    return X, y


def _mlp(c=3):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=c)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_and_score():
    X, y = _toy_data()
    it = NDArrayIter(X, y, batch_size=32, shuffle=False)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), kvstore=None)
    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.9


def test_module_predict():
    X, y = _toy_data(64)
    it = NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(mx.initializer.Xavier())
    out = mod.predict(it)
    assert out.shape == (64, 3)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), 1.0, rtol=1e-4)


def test_module_save_load_checkpoint(tmp_path):
    X, y = _toy_data(64)
    it = NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    mod2 = mx.mod.Module.load(prefix, 1)
    mod2.bind(it.provide_data, it.provide_label, for_training=False)
    arg1, _ = mod.get_params()
    arg2, _ = mod2.get_params()
    for k in arg1:
        np.testing.assert_allclose(arg1[k].asnumpy(), arg2[k].asnumpy())
    # predictions identical
    p1 = mod.predict(it).asnumpy()
    p2 = mod2.predict(it).asnumpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_module_multi_device_matches_single():
    X, y = _toy_data()
    init = {}
    shapes, _, _ = _mlp().infer_shape(data=(32, 10))
    rng = np.random.RandomState(3)
    for name, s in zip(_mlp().list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        init[name] = mx.nd.array(rng.randn(*s) * 0.1)

    results = {}
    for ndev in (1, 2):
        mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(ndev)])
        mod.bind([("data", (32, 10))], [("softmax_label", (32,))])
        mod.init_params(arg_params={k: v.copy() for k, v in init.items()},
                        aux_params={}, initializer=None, force_init=True)
        mod.init_optimizer(kvstore="local", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for i in range(5):
            b = i * 32
            batch = DataBatch([mx.nd.array(X[b:b + 32])],
                              [mx.nd.array(y[b:b + 32])])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        arg, _ = mod.get_params()
        results[ndev] = {k: v.asnumpy() for k, v in arg.items()}
    for k in results[1]:
        np.testing.assert_allclose(results[1][k], results[2][k], atol=1e-5)


def test_module_input_grads():
    X, y = _toy_data(32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind([("data", (32, 10))], [("softmax_label", (32,))],
             inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier())
    batch = DataBatch([mx.nd.array(X[:32])], [mx.nd.array(y[:32])])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0].asnumpy()
    assert g.shape == (32, 10)
    assert np.abs(g).sum() > 0


def test_bucketing_module():
    # variable-length sequences padded to bucket sizes 8 and 16; weights
    # (embedding + classifier) are shared across buckets like the
    # reference's bucketing LM
    rng = np.random.RandomState(0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=6, name="emb")
        pooled = mx.sym.mean(emb, axis=(1,))
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        sm = mx.sym.SoftmaxOutput(fc, name="softmax")
        return sm, ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    from mxnet_tpu.io import DataDesc

    mod.bind([DataDesc("data", (8, 16))], [DataDesc("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None,
                       optimizer_params={"learning_rate": 0.1})
    for key in (16, 8, 16, 8):
        batch = DataBatch([mx.nd.array(rng.randint(0, 20, (8, key)))],
                          [mx.nd.array(rng.randint(0, 4, 8))],
                          bucket_key=key,
                          provide_data=[DataDesc("data", (8, key))],
                          provide_label=[DataDesc("softmax_label", (8,))])
        mod.forward(batch, is_train=True)
        assert mod.get_outputs()[0].shape == (8, 4)
        mod.backward()
        mod.update()
    # params shared: emb weight identical across bucket modules
    w16 = mod._buckets[16]._exec_group.execs[0].arg_dict["emb_weight"].asnumpy()
    w8 = mod._buckets[8]._exec_group.execs[0].arg_dict["emb_weight"].asnumpy()
    np.testing.assert_allclose(w16, w8, atol=1e-6)


def test_sequential_module():
    X, y = _toy_data(64)
    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                 num_hidden=8)
    net1 = mx.sym.Activation(net1, act_type="relu", name="a1")
    net2_data = mx.sym.Variable("fc1_act")
    net2 = mx.sym.FullyConnected(net2_data, name="fc2", num_hidden=3)
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net1, label_names=[], context=mx.cpu()))
    seq.add(mx.mod.Module(net2, data_names=["fc1_act"], context=mx.cpu()),
            take_labels=True, auto_wiring=True)
    it = NDArrayIter(X, y, batch_size=16)
    seq.bind(it.provide_data, it.provide_label)
    seq.init_params(mx.initializer.Xavier())
    seq.init_optimizer(kvstore=None,
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.create("acc")
    for epoch in range(4):
        it.reset()
        metric.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
            seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.6


def test_feedforward_save_load(tmp_path):
    np.random.seed(5)
    X, y = _toy_data(128)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=10,
                           learning_rate=0.3, momentum=0.9,
                           initializer=mx.initializer.Xavier())
    model.fit(X, y)
    acc = model.score(X, y)
    assert acc > 0.8
    prefix = str(tmp_path / "ff")
    model.save(prefix)
    model2 = mx.FeedForward.load(prefix, 10, ctx=mx.cpu())
    p1 = model.predict(X)
    p2 = model2.predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_executor_group_no_batch_axis_input():
    """Inputs with a layout lacking 'N' (DataDesc batch axis -1) are
    replicated whole, not sliced — the reference's rcnn rois pattern."""
    data = mx.sym.Variable("data")            # (batch, 4)
    rois = mx.sym.Variable("rois")            # (R, 2), no batch axis
    # broadcastable combine: mean of rois added to every sample's fc
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    pooled = mx.sym.sum(rois, axis=0) * 0.01
    out = mx.sym.MakeLoss(mx.sym.sum(fc) + mx.sym.sum(pooled))
    mod = mx.mod.Module(out, data_names=("data", "rois"), label_names=None,
                        context=[mx.cpu(0), mx.cpu(0)])  # 2-exec slicing
    R = 7  # deliberately != batch and odd, unsliceable across 2 devices
    mod.bind(data_shapes=[("data", (8, 4)),
                          mx.io.DataDesc("rois", (R, 2), layout="")])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd")
    batch = mx.io.DataBatch([mx.nd.ones((8, 4)), mx.nd.ones((R, 2))], [])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    out = mod.get_outputs(merge_multi_context=False)[0]
    assert len(out) == 2  # one scalar loss per device-slice
    assert all(np.isfinite(o.asnumpy()).all() for o in out)


def test_executor_group_mismatched_batch_sizes_error():
    data = mx.sym.Variable("data")
    other = mx.sym.Variable("other")
    out = mx.sym.MakeLoss(mx.sym.sum(data) + mx.sym.sum(other))
    mod = mx.mod.Module(out, data_names=("data", "other"), label_names=None,
                        context=mx.cpu(0))
    with pytest.raises(mx.base.MXNetError, match="batch size"):
        mod.bind(data_shapes=[("data", (8, 4)), ("other", (6, 4))])


def test_executor_group_replicated_input_grads_sum():
    """inputs_need_grad + a replicated (axis -1) input: per-device grads
    sum instead of concatenating."""
    data = mx.sym.Variable("data")
    shared = mx.sym.Variable("shared")
    out = mx.sym.MakeLoss(mx.sym.sum(data * mx.sym.sum(shared)))
    mod = mx.mod.Module(out, data_names=("data", "shared"), label_names=None,
                        context=[mx.cpu(0), mx.cpu(0)])
    mod.bind(data_shapes=[("data", (8, 3)),
                          mx.io.DataDesc("shared", (5,), layout="")],
             inputs_need_grad=True)
    mod.init_params()
    x = np.arange(24, dtype=np.float32).reshape(8, 3)
    s = np.ones(5, np.float32)
    mod.forward(mx.io.DataBatch([mx.nd.array(x), mx.nd.array(s)], []),
                is_train=True)
    mod.backward()
    gd, gs = mod.get_input_grads()
    assert gd.shape == (8, 3) and gs.shape == (5,)
    # d/d shared sum(data * sum(shared)) = sum(data) per element
    np.testing.assert_allclose(gs.asnumpy(), np.full(5, x.sum()), rtol=1e-5)
    np.testing.assert_allclose(gd.asnumpy(), np.full((8, 3), s.sum()),
                               rtol=1e-5)


def test_module_deterministic_replay():
    """Same seed -> bitwise-identical fitted params through the Module
    path (shuffled NDArrayIter + dropout + Xavier init all ride
    mx.random.seed)."""
    rng = np.random.RandomState(3)
    X = rng.randn(64, 12).astype(np.float32)
    y = (X[:, 1] > 0).astype(np.float32)

    def run():
        mx.random.seed(21)
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=8, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Dropout(net, p=0.25)
        net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.fit(mx.io.NDArrayIter(X, y, 16, shuffle=True), num_epoch=3,
                initializer=mx.initializer.Xavier(),
                optimizer="sgd", optimizer_params={"learning_rate": 0.1})
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    p1, p2 = run(), run()
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k], err_msg=k)


# -- shared_module (memory sharing across bound modules) --------------------
# reference: Module.bind shared_module (module.py:259-295) + the shared
# executor memory of bucketing (executor_group.py:439-533).  Bucketing/
# Sequential external sharing goes BEYOND the reference, which asserts
# shared_module is None there.


def test_shared_module_params_alias():
    """A module bound with shared_module= aliases the donor's parameter
    arrays: no set_params copy is ever needed between them."""
    net = _mlp()
    X, y = _toy_data()
    train = mx.mod.Module(net, context=mx.cpu())
    train.bind([("data", (32, 10))], [("softmax_label", (32,))])
    train.init_params(mx.initializer.Xavier())
    train.init_optimizer(kvstore=None,
                         optimizer_params={"learning_rate": 0.1})

    # different batch size, shared params (the classic train/val pair)
    val = mx.mod.Module(net, context=mx.cpu())
    val.bind([("data", (64, 10))], [("softmax_label", (64,))],
             for_training=False, shared_module=train)
    assert val.params_initialized          # inherited, no init_params call
    assert val.optimizer_initialized       # borrowed

    t_exe = train._exec_group.execs[0]
    v_exe = val._exec_group.execs[0]
    for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
        assert v_exe.arg_dict[name] is t_exe.arg_dict[name]
    # data arrays differ in shape -> NOT shared
    assert v_exe.arg_dict["data"] is not t_exe.arg_dict["data"]

    # one train step; val must see the new weights with NO copying
    before = v_exe.arg_dict["fc1_weight"].asnumpy().copy()
    batch = DataBatch([mx.nd.array(X[:32])], [mx.nd.array(y[:32])])
    train.forward(batch, is_train=True)
    train.backward()
    train.update()
    after = v_exe.arg_dict["fc1_weight"].asnumpy()
    assert not np.allclose(before, after)
    np.testing.assert_allclose(after,
                               t_exe.arg_dict["fc1_weight"].asnumpy())
    # and the master dicts are one object
    assert val._arg_params is train._arg_params


def test_shared_module_unbound_donor_raises():
    net = _mlp()
    donor = mx.mod.Module(net, context=mx.cpu())
    mod = mx.mod.Module(net, context=mx.cpu())
    with pytest.raises(mx.MXNetError, match="binded"):
        mod.bind([("data", (8, 10))], [("softmax_label", (8,))],
                 shared_module=donor)


def test_bucketing_internal_buckets_alias_memory():
    """switch_bucket's shared_exec wiring gives every bucket THE SAME
    parameter arrays (reference: one GraphStoragePool across bucket
    executors) — update in one bucket is visible in all, no copies."""

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=6, name="emb")
        pooled = mx.sym.mean(emb, axis=(1,))
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(fc, name="softmax"), ["data"], \
            ["softmax_label"]

    from mxnet_tpu.io import DataDesc

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    mod.bind([DataDesc("data", (8, 16))], [DataDesc("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.switch_bucket(8, [DataDesc("data", (8, 8))],
                      [DataDesc("softmax_label", (8,))])
    e16 = mod._buckets[16]._exec_group.execs[0]
    e8 = mod._buckets[8]._exec_group.execs[0]
    for name in ("emb_weight", "fc_weight", "fc_bias"):
        assert e8.arg_dict[name] is e16.arg_dict[name]


def test_bucketing_shared_module_external():
    rng = np.random.RandomState(3)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=6, name="emb")
        pooled = mx.sym.mean(emb, axis=(1,))
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(fc, name="softmax"), ["data"], \
            ["softmax_label"]

    from mxnet_tpu.io import DataDesc

    train = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                   context=mx.cpu())
    train.bind([DataDesc("data", (8, 16))],
               [DataDesc("softmax_label", (8,))])
    train.init_params(mx.initializer.Xavier())
    train.init_optimizer(kvstore=None,
                         optimizer_params={"learning_rate": 0.1})

    val = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    val.bind([DataDesc("data", (8, 16))], [DataDesc("softmax_label", (8,))],
             for_training=False, shared_module=train)
    assert val.params_initialized

    # train one step on the default bucket; val sees the result directly
    batch = DataBatch([mx.nd.array(rng.randint(0, 20, (8, 16)))],
                      [mx.nd.array(rng.randint(0, 4, 8))],
                      bucket_key=16,
                      provide_data=[DataDesc("data", (8, 16))],
                      provide_label=[DataDesc("softmax_label", (8,))])
    train.forward(batch, is_train=True)
    train.backward()
    train.update()
    tw = train._buckets[16]._exec_group.execs[0].arg_dict["emb_weight"]
    vw = val._buckets[16]._exec_group.execs[0].arg_dict["emb_weight"]
    assert vw is tw

    # val can still score through its own (shared-memory) graph
    val.forward(batch, is_train=False)
    assert val.get_outputs()[0].shape == (8, 4)


def test_sequential_shared_module_external():
    def make_seq():
        seq = mx.mod.SequentialModule()
        net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                     num_hidden=8)
        net2 = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc2",
                                  num_hidden=3), name="softmax")
        seq.add(mx.mod.Module(net1, label_names=None, context=mx.cpu()))
        seq.add(mx.mod.Module(net2, context=mx.cpu()),
                take_labels=True, auto_wiring=True)
        return seq

    X, y = _toy_data()
    train = make_seq()
    train.bind([("data", (32, 10))], [("softmax_label", (32,))])
    train.init_params(mx.initializer.Xavier())

    val = make_seq()
    val.bind([("data", (32, 10))], [("softmax_label", (32,))],
             for_training=False, shared_module=train)
    assert val.params_initialized
    t0 = train._modules[0]._exec_group.execs[0]
    v0 = val._modules[0]._exec_group.execs[0]
    assert v0.arg_dict["fc1_weight"] is t0.arg_dict["fc1_weight"]

    val.forward(DataBatch([mx.nd.array(X[:32])], [mx.nd.array(y[:32])]),
                is_train=False)
    assert val.get_outputs()[0].shape == (32, 3)


def test_sequential_shared_module_mismatch_raises():
    seq1 = mx.mod.SequentialModule()
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                              name="fc"), name="softmax")
    seq1.add(mx.mod.Module(net, context=mx.cpu()))
    seq1.bind([("data", (8, 10))], [("softmax_label", (8,))])
    seq2 = mx.mod.SequentialModule()
    seq2.add(mx.mod.Module(net, context=mx.cpu()))
    seq2.add(mx.mod.Module(net, context=mx.cpu()))
    with pytest.raises(mx.MXNetError, match="number of sub-modules"):
        seq2.bind([("data", (8, 10))], [("softmax_label", (8,))],
                  shared_module=seq1)


def test_bucketing_switch_after_update_preserves_trained_params():
    """Regression: binding a NEW bucket after updates must not push the
    stale CPU master params back into the (aliased) trained arrays."""
    rng = np.random.RandomState(5)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=6, name="emb")
        pooled = mx.sym.mean(emb, axis=(1,))
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(fc, name="softmax"), ["data"], \
            ["softmax_label"]

    from mxnet_tpu.io import DataDesc

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    mod.bind([DataDesc("data", (8, 16))], [DataDesc("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None,
                       optimizer_params={"learning_rate": 0.5})
    batch16 = DataBatch([mx.nd.array(rng.randint(0, 20, (8, 16)))],
                        [mx.nd.array(rng.randint(0, 4, 8))],
                        bucket_key=16,
                        provide_data=[DataDesc("data", (8, 16))],
                        provide_label=[DataDesc("softmax_label", (8,))])
    init_w = mod._buckets[16]._exec_group.execs[0].arg_dict[
        "fc_weight"].asnumpy().copy()
    for _ in range(3):
        mod.forward(batch16, is_train=True)
        mod.backward()
        mod.update()
    trained_w = mod._buckets[16]._exec_group.execs[0].arg_dict[
        "fc_weight"].asnumpy().copy()
    assert not np.allclose(init_w, trained_w)

    # first bind of bucket 8 happens AFTER training steps (master dirty)
    batch8 = DataBatch([mx.nd.array(rng.randint(0, 20, (8, 8)))],
                       [mx.nd.array(rng.randint(0, 4, 8))],
                       bucket_key=8,
                       provide_data=[DataDesc("data", (8, 8))],
                       provide_label=[DataDesc("softmax_label", (8,))])
    mod.forward(batch8, is_train=True)
    now_w = mod._buckets[16]._exec_group.execs[0].arg_dict[
        "fc_weight"].asnumpy()
    np.testing.assert_allclose(now_w, trained_w, rtol=1e-6)


def test_shared_module_shape_mismatch_raises():
    """A donor holding a same-named param at a different shape must be
    rejected, not silently partially shared."""
    netA = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                              name="fc"), name="softmax")
    netB = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                              name="fc"), name="softmax")
    donor = mx.mod.Module(netA, context=mx.cpu())
    donor.bind([("data", (4, 10))], [("softmax_label", (4,))])
    donor.init_params(mx.initializer.Xavier())
    mod = mx.mod.Module(netB, context=mx.cpu())
    with pytest.raises(mx.MXNetError, match="incompatible"):
        mod.bind([("data", (4, 10))], [("softmax_label", (4,))],
                 shared_module=donor)


def test_shared_module_failed_bind_leaves_module_unbound():
    net = _mlp()
    donor = mx.mod.Module(net, context=mx.cpu())   # never bound
    mod = mx.mod.Module(net, context=mx.cpu())
    with pytest.raises(mx.MXNetError):
        mod.bind([("data", (8, 10))], [("softmax_label", (8,))],
                 shared_module=donor)
    assert not mod.binded
    # a later clean bind must work
    mod.bind([("data", (8, 10))], [("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    assert mod.binded


def test_bucketing_gpt_rope():
    """Variable-context GPT through BucketingModule: with
    pos_embed='rope' every parameter is bucket-length-independent (a
    learned position table would be per-bucket-shaped and unshareable),
    so buckets 8 and 16 share ALL weights — the transformer form of the
    reference's bucketing LM."""
    rng = np.random.RandomState(5)
    vocab = 19

    def sym_gen(seq_len):
        net = mx.models.gpt(vocab, seq_len, num_layers=1, d_model=16,
                            num_heads=2, pos_embed="rope",
                            tie_embeddings=True)
        return net, ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    from mxnet_tpu.io import DataDesc

    mod.bind([DataDesc("data", (4, 16))],
             [DataDesc("softmax_label", (4, 16))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None,
                       optimizer_params={"learning_rate": 0.05})
    for key in (16, 8, 16, 8):
        batch = DataBatch(
            [mx.nd.array(rng.randint(0, vocab, (4, key)))],
            [mx.nd.array(rng.randint(0, vocab, (4, key)))],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, key))],
            provide_label=[DataDesc("softmax_label", (4, key))])
        mod.forward(batch, is_train=True)
        assert mod.get_outputs()[0].shape == (4 * key, vocab)
        mod.backward()
        mod.update()
    w16 = mod._buckets[16]._exec_group.execs[0] \
        .arg_dict["gpt_tok_embed_weight"].asnumpy()
    w8 = mod._buckets[8]._exec_group.execs[0] \
        .arg_dict["gpt_tok_embed_weight"].asnumpy()
    np.testing.assert_allclose(w16, w8, atol=1e-6)
