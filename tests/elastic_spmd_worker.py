"""Worker for the collectives-mode elastic drill (gang restart).

The SPMD (jax.distributed collectives) world cannot absorb a single
member restart the way the PS mode can: one dead rank hangs everyone
else inside a collective.  Elasticity is therefore gang-level —
tools/launch.py --gang-restarts kills the survivors and respawns the
WHOLE job, and each new life resumes from the latest COMPLETE sharded
checkpoint (parallel/checkpoint.py latest_complete_step).  This is the
TPU-pod analog of the reference tracker restarting a dead job from its
``model.save`` files (tests/nightly dist fault-tolerance intent).

Script: 2 procs x 2 virtual devices = one global dp=4 mesh; 6
deterministic training steps, a synchronized sharded checkpoint after
every step.  On the first life (MXTPU_RESTART_COUNT=0) with
ELASTIC_SPMD_CRASH=1, rank 1 kills itself after the step-3 checkpoint
barrier.  Recovery lives resume from the newest complete step.  Every
rank prints a params digest at step 6; the test asserts the crashed
run's digest equals an uninterrupted run's digest EXACTLY.

Launched by test_dist.py via tools/launch.py -n 2 --gang-restarts 1.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import hashlib

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.kvstore import _maybe_init_distributed
from mxnet_tpu.parallel import checkpoint as ckpt

STEPS = 6
CRASH_AFTER = 3


def _net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _batch(step):
    rng = np.random.RandomState(1000 + step)  # same batch on every rank
    return {"data": rng.standard_normal((8, 10)).astype(np.float32),
            "softmax_label": rng.randint(0, 4, 8).astype(np.float32)}


def main():
    _maybe_init_distributed()
    rank = jax.process_index()
    life = int(os.environ.get("MXTPU_RESTART_COUNT", "0"))
    crash = os.environ.get("ELASTIC_SPMD_CRASH") == "1" and life == 0
    ckpt_dir = os.environ["ELASTIC_SPMD_CKPT"]

    mesh = mx.parallel.make_mesh({"dp": 4}, devices=jax.devices())
    mx.random.seed(0)
    trainer = mx.parallel.ShardedTrainer(
        _net(), {"data": (8, 10), "softmax_label": (8,)},
        mesh=mesh, batch_axis="dp",
        optimizer="sgd", optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9},
        initializer=mx.initializer.Xavier())
    kv = mx.kv.create("dist_sync")  # barrier surface for save sync

    start = 0
    resume = ckpt.latest_complete_step(ckpt_dir)
    if life > 0:
        assert os.environ.get("MXTPU_IS_RECOVERY") == "1"
        assert resume is not None, "gang restart found no checkpoint"
        trainer.load_checkpoint_sharded(ckpt_dir, epoch=resume)
        start = resume
        print(f"RANK_{rank}_RESUMED_FROM {resume}", flush=True)

    for step in range(start + 1, STEPS + 1):
        jax.block_until_ready(trainer.step(_batch(step)))
        trainer.save_checkpoint_sharded(ckpt_dir, epoch=step)
        # both procs' shards durable before anyone proceeds: the crash
        # (and any real failure) can then never strand a torn newest
        # step that latest_complete_step would have to skip past a
        # never-written older one
        kv.barrier()
        if crash and rank == 1 and step == CRASH_AFTER:
            os._exit(3)

    params = trainer.get_params()
    digest = hashlib.sha1()
    for k in sorted(params):
        digest.update(np.ascontiguousarray(params[k]).tobytes())
    print(f"RANK_{rank}_DIGEST {digest.hexdigest()}", flush=True)
    print(f"RANK_{rank}_ELASTIC_SPMD_OK life={life}", flush=True)


if __name__ == "__main__":
    main()
