"""Post-fix shapes: count or log before continuing, narrow to the
expected exception, assign a fallback — or carry a justified
suppression for a genuine last-resort guard."""
import logging

errors = {"atexit_dump": 0}


def atexit_dump(dump):
    try:
        dump()
    except Exception:
        errors["atexit_dump"] += 1


def drain(queue, handle):
    for item in queue:
        try:
            handle(item)
        except Exception as e:
            logging.warning("drain: %s failed: %s", item, e)


def delete_buffers(arrays):
    for arr in arrays:
        try:
            arr.delete()
        except (RuntimeError, ValueError):
            pass               # narrow: already donated-away/deleted


def teardown_guard(close):
    try:
        close()
    # mxtpu-lint: disable=swallowed-exception (interpreter-teardown
    # guard: there is nowhere left to report)
    except Exception:
        pass
