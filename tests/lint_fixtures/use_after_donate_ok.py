"""The commit idiom every donated call site in this repo uses: the
donated operand is reassigned from the program's outputs in the SAME
statement, so nothing can observe the dead buffer."""
import jax


def _donate(*argnums):
    return argnums


def train_loop(step_fn, params, batches):
    step = jax.jit(step_fn, donate_argnums=(0,))
    loss = None
    for batch in batches:
        loss, _, params = step(params, batch, 0.01)
    return params, loss


def factory_train(make_step, params, batches):
    step = make_step()                     # mxtpu-lint: donates=0
    loss = None
    for b in batches:
        loss, _, params = step(params, b)  # rebinds: never flagged
    return params, loss


class Trainer:
    def __init__(self, program):
        self._train_step = jax.jit(program, donate_argnums=(0, 1, 2))

    def step(self, batch):
        self.params, self.opt_state, self.aux, outs = self._train_step(
            self.params, self.opt_state, self.aux, batch)
        return outs
