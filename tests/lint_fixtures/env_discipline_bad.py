"""Pre-fix shapes from this PR: inline int()/bool()/float() parses of
MXTPU_* knobs (kvstore.py, ps.py) and a private truthiness helper
(telemetry's _env_truthy) — every one a chance for accepted spellings
to fork between features.  Uses vars documented in docs/env_vars.md so
only the parse rule fires here (the undocumented-var rule has its own
tmp-repo test)."""
import os


def _env_truthy(value):
    return value not in (None, "", "0")


def load_config():
    nproc = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
    rank = int(os.environ["MXTPU_PROC_ID"])
    recovery = bool(os.environ.get("MXTPU_IS_RECOVERY"))
    timeout = float(os.environ.get("MXTPU_PS_SYNC_TIMEOUT", 300))
    telemetry_on = _env_truthy(os.environ.get("MXTPU_TELEMETRY"))
    return nproc, rank, recovery, timeout, telemetry_on
