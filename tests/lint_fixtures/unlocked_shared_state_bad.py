"""Pre-fix shape of telemetry/flight.py (this PR): ``dumps`` was
declared lock-guarded but incremented outside the lock — and the bare
scheduler queues carried no lock at all."""
import threading


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []          # guarded-by: _lock
        self.dumps = 0             # guarded-by: _lock

    def record(self, ev):
        with self._lock:
            self._events.append(ev)

    def dump(self):
        with self._lock:
            events = list(self._events)
        self._write(events)
        self.dumps += 1            # mutation outside the lock

    def clear(self):
        self._events.clear()       # mutation outside the lock

    def _write(self, events):
        pass
