"""Post-fix shape: ONE batched device_get for the watchdog scalars,
carrying a suppression that names the designed sync point; host-only
helpers are not reachable from a hot entry and stay unflagged."""
import jax
import numpy as np

from mxnet_tpu.lint.annotations import hot_path


class FusedStep:
    @hot_path
    def step(self, batch):
        outs, outs_ok, gnorm = self._program(batch)
        # mxtpu-lint: disable=host-sync (the watchdog's designed
        # once-per-step sync point)
        ok_h, gn = map(float, jax.device_get((outs_ok, gnorm)))
        if not ok_h:
            self._note_anomaly()
        return outs, gn

    def host_side_report(self, table):
        # NOT reachable from a hot entry point: plain host numpy is fine
        return np.asarray(table).sum()

    def _program(self, batch):
        raise NotImplementedError

    def _note_anomaly(self):
        pass
