"""Post-fix shape: perf_counter for durations, monotonic for
deadlines, and a justified suppression where the wall timestamp IS the
payload."""
import time


def check_speed(run, N):
    tic = time.perf_counter()
    for _ in range(N):
        run()
    return (time.perf_counter() - tic) / N


def watch_deadline(hours):
    return time.monotonic() + 3600 * hours


def snapshot_record(metrics):
    # mxtpu-lint: disable=wall-clock (JSONL record timestamp)
    return {"ts": round(time.time(), 3), "metrics": metrics}
