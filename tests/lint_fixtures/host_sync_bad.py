"""Pre-fix shape of module/fused_step.py's numeric-watch branch (this
PR): TWO separate forced syncs per step — float(gnorm) blocks, then
bool(outs_ok) blocks again — inside the hot step loop.  Also covers
reachability: the sync hides in a helper the hot entry point calls."""
import numpy as np

from mxnet_tpu.lint.annotations import hot_path


class FusedStep:
    @hot_path
    def step(self, batch):
        outs, outs_ok, gnorm = self._program(batch)
        gn = float(gnorm)          # sync #1
        if not bool(outs_ok):      # sync #2
            self._note_anomaly()
        return self._collect(outs), gn

    def _collect(self, outs):
        # reachable from @hot_path step() -> flagged too
        return np.asarray(outs)

    def _program(self, batch):
        raise NotImplementedError

    def _note_anomaly(self):
        pass
