"""Pre-fix shape of mxnet_tpu/test_utils.py check_speed (this PR):
elapsed-time math on the wall clock, which an NTP step bends."""
import time


def check_speed(run, N):
    tic = time.time()
    for _ in range(N):
        run()
    return (time.time() - tic) / N


def watch_deadline(hours):
    # pre-fix tools/bench_watch.py: a deadline on the wall clock moves
    # when NTP does
    return time.time() + 3600 * hours
