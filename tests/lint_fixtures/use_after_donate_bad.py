"""The donate-then-read bug shape cached_sgd_step's callers must never
regress into (params donated on TPU; CPU tests pass regardless — which
is exactly why only static analysis catches it).  Both the local-jit
and the self-attribute (fused-step style, via the _donate TPU guard)
variants."""
import jax


def _donate(*argnums):
    return argnums


def train_loop(step_fn, params, batches):
    step = jax.jit(step_fn, donate_argnums=(0,))
    for batch in batches:
        loss, _, new_params = step(params, batch, 0.01)
    return params, loss        # read of the donated pytree


def factory_train(trainer, make_step, batches):
    # factory-returned donating program (cached_sgd_step style): the
    # annotation is what makes the call sites checkable cross-module
    step = make_step(trainer.loss_fn)      # mxtpu-lint: donates=0
    for b in batches:
        loss, _, new_params = step(trainer.params, b)
    return trainer.params                  # read of the donated pytree


class FusedStep:
    def __init__(self, program):
        self._program = jax.jit(program, donate_argnums=_donate(0, 3))

    def step(self, others, aux, batch):
        params = self.params
        state = self.state
        outs, new_params, new_state = self._program(params, others,
                                                    aux, state)
        self.commit(new_params, new_state)
        return outs, state     # donated state read after the call

    def commit(self, p, s):
        self.params, self.state = p, s
