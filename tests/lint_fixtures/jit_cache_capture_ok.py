"""Post-fix shapes: key by the object itself (bounded) or by an
immutable config tuple; id()-keyed LOCAL traversal dicts stay legal
(ephemeral state over objects the traversal holds alive)."""
import jax

_STEP_CACHE = {}


def cached_step(cache, loss_fn, build):
    key = (loss_fn, True)
    step = cache.get(key)
    if step is None:
        step = jax.jit(build(loss_fn))
        while len(cache) >= 64:
            cache.pop(next(iter(cache)))
        cache[key] = step
    return step


class Engine:
    def _spec_key(self):
        return ("gpt", 12, 64)      # immutable config, never self

    def compile(self, bucket):
        key = (self._spec_key(), bucket)
        if key not in _STEP_CACHE:
            _STEP_CACHE[key] = jax.jit(lambda x: x)
        return _STEP_CACHE[key]


def copy_graph(nodes):
    # local id()-keyed dict: the standard ephemeral traversal idiom
    copies = {}
    for node in nodes:
        copies[id(node)] = object()
    return [copies[id(n)] for n in nodes]
