"""Pre-fix shapes of the program-cache capture bug class:

* parallel/trainer.py's cached_sgd_step (this PR): a caller-owned cache
  keyed by id(loss_fn) — ids recycle after GC, and the entry pins the
  captured closure forever;
* the module-level-cache-keyed-by-self variant (the PR 6 _STEP_CACHE
  rule: an engine key retains a retired engine's parameter dict);
* functools.lru_cache on a method (self becomes a cache key).
"""
import functools

import jax

_PROGRAMS = {}


def cached_step(cache, loss_fn, build):
    step = cache.get((id(loss_fn), True))
    if step is None:
        step = jax.jit(build(loss_fn))
        cache[(id(loss_fn), True)] = step
    return step


class Engine:
    def compile(self, bucket):
        key = (self, bucket)
        if key not in _PROGRAMS:
            _PROGRAMS[key] = jax.jit(lambda x: x)
        return _PROGRAMS[key]

    @functools.lru_cache(maxsize=None)
    def program_for(self, bucket):
        return jax.jit(lambda x: x)
