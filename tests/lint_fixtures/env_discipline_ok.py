"""Post-fix shape: one parser per type in mxnet_tpu.base; raw string
reads (paths, addresses) stay plain os.environ."""
import os

from mxnet_tpu.base import env_flag, env_float, env_int


def load_config():
    nproc = env_int("MXTPU_NUM_PROCS", 1)
    rank = env_int("MXTPU_PROC_ID", 0)
    recovery = env_flag("MXTPU_IS_RECOVERY", False)
    timeout = env_float("MXTPU_PS_SYNC_TIMEOUT", 300)
    telemetry_on = env_flag("MXTPU_TELEMETRY", False)
    trace_path = os.environ.get("MXTPU_REQUEST_TRACE")   # string: fine
    return nproc, rank, recovery, timeout, telemetry_on, trace_path
