"""Pre-fix shapes from this PR (telemetry atexit dump, request-trace
hooks, engine shutdown): broad handlers whose body is only
pass/continue — the failure evaporates."""


def atexit_dump(dump):
    try:
        dump()
    except Exception:
        pass


def drain(queue, handle):
    for item in queue:
        try:
            handle(item)
        except:  # noqa: E722
            continue
