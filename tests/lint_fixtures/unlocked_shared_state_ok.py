"""Post-fix shape: every mutation of a guarded attribute sits inside
``with self._lock`` (— __init__ is exempt: construction precedes
sharing)."""
import threading


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []          # guarded-by: _lock
        self.dumps = 0             # guarded-by: _lock
        self.unguarded_note = None     # no annotation, no contract

    def record(self, ev):
        with self._lock:
            self._events.append(ev)

    def dump(self):
        with self._lock:
            events = list(self._events)
            self.dumps += 1
        self._write(events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def note(self, msg):
        self.unguarded_note = msg      # unannotated: not checked

    def _write(self, events):
        pass
