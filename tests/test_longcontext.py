"""Ring attention (sequence parallelism), pipeline parallelism and
Mixture-of-Experts (expert parallelism) on the virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.parallel.pipeline import pipeline_apply
from mxnet_tpu.parallel.ring_attention import (attention_reference,
                                               ring_attention)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    B, H, S, D = 2, 4, 32, 8
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    mesh = mx.parallel.make_mesh({"sp": 4})
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, axis="sp", causal=causal)
    ref = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_8way():
    rng = np.random.RandomState(1)
    B, H, S, D = 1, 2, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = mx.parallel.make_mesh({"sp": 8})
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_pipeline_matches_sequential():
    rng = np.random.RandomState(2)
    n_stages, B, Dm = 4, 16, 8
    mesh = mx.parallel.make_mesh({"pp": n_stages})
    Ws = rng.randn(n_stages, Dm, Dm).astype(np.float32) * 0.3
    bs = rng.randn(n_stages, Dm).astype(np.float32) * 0.1
    params = {"w": jnp.asarray(Ws), "b": jnp.asarray(bs)}
    x = rng.randn(B, Dm).astype(np.float32)

    def stage(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    out = pipeline_apply(stage, params, jnp.asarray(x), n_microbatches=4,
                         mesh=mesh, axis="pp")
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ Ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_gradients():
    rng = np.random.RandomState(3)
    n_stages, B, Dm = 2, 8, 4
    mesh = mx.parallel.make_mesh({"pp": n_stages})
    params = {"w": jnp.asarray(rng.randn(n_stages, Dm, Dm).astype(np.float32)
                               * 0.3)}
    x = jnp.asarray(rng.randn(B, Dm).astype(np.float32))

    def stage(p, h):
        return jnp.tanh(h @ p["w"])

    def objective(params):
        out = pipeline_apply(stage, params, x, n_microbatches=2, mesh=mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(objective)(params)["w"]

    # dense reference gradient
    def ref_obj(ws):
        h = x
        for i in range(n_stages):
            h = jnp.tanh(h @ ws[i])
        return jnp.sum(h ** 2)

    g_ref = jax.grad(ref_obj)(params["w"])
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4,
                               atol=1e-5)


# -- Mixture-of-Experts / expert parallelism --------------------------------
def test_moe_matches_reference():
    rng = np.random.RandomState(0)
    mesh = mx.parallel.make_mesh({"ep": 8})
    E, D, H, T = 8, 16, 32, 64
    params = mx.parallel.init_moe_params(rng, D, H, E)
    x = rng.standard_normal((T, D)).astype(np.float32)

    y, aux = mx.parallel.moe_apply(params, jnp.asarray(x), mesh, "ep")
    y_ref, aux_ref = mx.parallel.moe_reference(params, jnp.asarray(x), 8)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-4)
    # with softmax gates and top-1 routing, most tokens contribute output
    assert (np.abs(np.asarray(y)).sum(axis=1) > 0).mean() > 0.5


def test_moe_dp_ep_composition():
    """MoE folded into a combined dp x ep mesh (batch_axis="dp"): tokens
    shard over dp x ep jointly (dp-major), each dp replica's ep group
    routes independently with per-shard capacity T/(dp*ep) — numerical
    parity vs the single-device reference with n_shards = dp*ep, plus
    gradient parity through the combined mesh."""
    rng = np.random.RandomState(5)
    mesh = mx.parallel.make_mesh({"dp": 2, "ep": 4})
    E, D, H, T = 4, 8, 16, 64
    params = jax.tree_util.tree_map(
        jnp.asarray, mx.parallel.init_moe_params(rng, D, H, E))
    x = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))

    y, aux = mx.parallel.moe_apply(params, x, mesh, "ep",
                                   capacity_factor=2.0, batch_axis="dp")
    y_ref, aux_ref = mx.parallel.moe_reference(params, x, 8,
                                               capacity_factor=2.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-4)

    def obj(p):
        y, aux = mx.parallel.moe_apply(p, x, mesh, "ep",
                                       capacity_factor=2.0, batch_axis="dp")
        return jnp.sum(y ** 2) + 0.01 * aux

    def obj_ref(p):
        y, aux = mx.parallel.moe_reference(p, x, 8, capacity_factor=2.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(obj)(params)
    g_ref = jax.grad(obj_ref)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)

    # token count must divide the COMBINED dp x ep shard count
    with pytest.raises(ValueError, match="not divisible"):
        mx.parallel.moe_apply(params, x[:12], mesh, "ep", batch_axis="dp")


@pytest.mark.slow
def test_moe_topk_and_grads():
    rng = np.random.RandomState(1)
    mesh = mx.parallel.make_mesh({"ep": 4})
    E, D, H, T = 8, 8, 16, 32
    params = mx.parallel.init_moe_params(rng, D, H, E)
    x = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))

    y2, _ = mx.parallel.moe_apply(params, x, mesh, "ep", k=2,
                                  capacity_factor=2.0)
    y2_ref, _ = mx.parallel.moe_reference(params, x, 4, k=2,
                                          capacity_factor=2.0)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y2_ref),
                               rtol=1e-4, atol=1e-5)

    def obj(p):
        y, aux = mx.parallel.moe_apply(p, x, mesh, "ep", k=2,
                                       capacity_factor=2.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    def obj_ref(p):
        y, aux = mx.parallel.moe_reference(p, x, 4, k=2, capacity_factor=2.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(obj)(params)
    g_ref = jax.grad(obj_ref)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_moe_layer_trains():
    rng = np.random.RandomState(2)
    mesh = mx.parallel.make_mesh({"ep": 4})
    layer = mx.parallel.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                                 mesh=mesh, k=1, capacity_factor=2.0)
    x = jnp.asarray(rng.standard_normal((32, 8)).astype(np.float32))
    tgt = jnp.asarray(rng.standard_normal((32, 8)).astype(np.float32))

    def loss_fn(y):
        return jnp.mean((y - tgt) ** 2)

    l0 = float(layer.grad_step(x, loss_fn, lr=0.05))
    for _ in range(30):
        l = float(layer.grad_step(x, loss_fn, lr=0.05))
    assert l < l0


# -- Pallas flash attention -------------------------------------------------
def test_flash_attention_matches_dense():
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng2 = np.random.RandomState(7)
    B, H, S, D = 2, 3, 32, 16
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    for causal in (False, True):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        o_ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   rtol=1e-5, atol=1e-5)


def test_flash_attention_grads():
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng2 = np.random.RandomState(8)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))

    for causal in (False, True):
        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=16, block_k=16) ** 2)

        def fr(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_flash_attention_lse_and_offsets():
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng2 = np.random.RandomState(9)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    scale = 1.0 / np.sqrt(D)
    _, lse = flash_attention(q, k, v, block_q=16, block_k=16,
                             return_lse=True)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    lse_ref = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-5, atol=1e-5)
    # causal-mask offsets: lower-half rows of the full attention
    o_full = attention_reference(q, k, v, causal=True)
    o_hi = flash_attention(q[:, :, 16:], k, v, causal=True, q_offset=16,
                           block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(o_hi),
                               np.asarray(o_full[:, :, 16:]),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_impl(causal):
    rng2 = np.random.RandomState(10)
    mesh = mx.parallel.make_mesh({"sp": 4})
    B, H, S, D = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    o = ring_attention(q, k, v, mesh, "sp", causal=causal, impl="flash",
                       block_q=16, block_k=16)
    o_ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=1e-5, atol=2e-5)


def test_ring_attention_flash_grads():
    rng2 = np.random.RandomState(11)
    mesh = mx.parallel.make_mesh({"sp": 2})
    B, H, S, D = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))

    def f(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, "sp", causal=True,
                                      impl="flash", block_q=16,
                                      block_k=16) ** 2)

    def fr(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_fully_masked_rows():
    """A query block entirely before the key block must return zeros and
    lse == -inf-like, not the uniform mean of V."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng2 = np.random.RandomState(12)
    B, H, S, D = 1, 1, 16, 8
    q, k, v = (jnp.asarray(rng2.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    o, lse = flash_attention(q, k, v, causal=True, k_offset=S,
                             block_q=16, block_k=16, return_lse=True)
    assert float(jnp.max(jnp.abs(o))) == 0.0
    assert float(jnp.max(lse)) < -1e29


def test_ring_attention_flash_bf16():
    rng2 = np.random.RandomState(13)
    mesh = mx.parallel.make_mesh({"sp": 4})
    B, H, S, D = 1, 2, 64, 8
    qf, kf, vf = (rng2.standard_normal((B, H, S, D)).astype(np.float32)
                  for _ in range(3))
    q, k, v = (jnp.asarray(a, jnp.bfloat16) for a in (qf, kf, vf))
    o = ring_attention(q, k, v, mesh, "sp", causal=True, impl="flash",
                       block_q=16, block_k=16)
    assert o.dtype == jnp.bfloat16
    o_ref = attention_reference(jnp.asarray(qf), jnp.asarray(kf),
                                jnp.asarray(vf), causal=True)
    np.testing.assert_allclose(np.asarray(o, np.float32), np.asarray(o_ref),
                               rtol=5e-2, atol=5e-2)


def test_moe_router_bf16_slot_uniqueness():
    """bf16 tokens must not produce duplicate capacity slots (cumsum in
    bf16 is inexact past 256)."""
    from mxnet_tpu.parallel.moe import _router

    rng2 = np.random.RandomState(14)
    T, D, E = 320, 8, 2
    x = jnp.asarray(rng2.standard_normal((T, D)), jnp.bfloat16)
    gate_w = jnp.asarray(rng2.standard_normal((D, E)), jnp.bfloat16)
    dispatch, combine, _ = _router(x, gate_w, E, 1, T)
    occupancy = np.asarray(jnp.sum(dispatch.astype(jnp.float32), axis=0))
    assert occupancy.max() <= 1.0 + 1e-6, "duplicate capacity slot"


# -- Ulysses all-to-all sequence parallelism --------------------------------
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    rng = np.random.RandomState(3)
    B, H, S, D = 2, 4, 32, 8
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = mx.parallel.make_mesh({"sp": 4})
    out = ulysses_attention(q, k, v, mesh, axis="sp", causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.slow
def test_ulysses_attention_8way_grads():
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    rng = np.random.RandomState(4)
    B, H, S, D = 1, 8, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = mx.parallel.make_mesh({"sp": 8})
    out = ulysses_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)

    def loss(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, "sp", causal=True))

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True))

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_ulysses_attention_head_divisibility_error():
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    mesh = mx.parallel.make_mesh({"sp": 4})
    q = jnp.zeros((1, 3, 32, 8))
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, mesh, axis="sp")


def test_pipeline_dp_tp_pp_composition():
    """Megatron-inside-GPipe: stage weights tensor-sharded over 'tp'
    (explicit psum in the stage fn), batch sharded over 'dp', stages over
    'pp' — forward and one SGD step must match a dense single-device
    computation (param_specs/feed_spec extension of pipeline_apply)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import PipelineModule, make_mesh

    mesh = make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rng = np.random.RandomState(3)
    D, H, B = 8, 16, 8
    w1 = (rng.standard_normal((2, D, H)) * 0.3).astype(np.float32)
    w2 = (rng.standard_normal((2, H, D)) * 0.3).astype(np.float32)
    x = rng.standard_normal((B, D)).astype(np.float32)

    def stage(p, h):
        part = jnp.maximum(h @ p["w1"], 0.0) @ p["w2"]
        return jnp.tanh(lax.psum(part, "tp"))

    pmod = PipelineModule(
        stage, {"w1": jnp.asarray(w1), "w2": jnp.asarray(w2)}, mesh,
        n_microbatches=2,
        param_specs={"w1": P("pp", None, "tp"), "w2": P("pp", "tp", None)},
        feed_spec=P(None, "dp", None))
    out = np.asarray(pmod.forward(jnp.asarray(x)))

    ref = x
    for s in range(2):
        ref = np.tanh(np.maximum(ref @ w1[s], 0.0) @ w2[s])
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def dense_loss(params):
        h = jnp.asarray(x)
        for s in range(2):
            h = jnp.tanh(jnp.maximum(h @ params["w1"][s], 0.0)
                         @ params["w2"][s])
        return jnp.sum(h ** 2)

    dense_grads = jax.grad(dense_loss)(
        {"w1": jnp.asarray(w1), "w2": jnp.asarray(w2)})
    pmod.grad_step(jnp.asarray(x), lambda o: jnp.sum(o ** 2), lr=0.01)
    for k, w0 in (("w1", w1), ("w2", w2)):
        got = np.asarray(jax.device_get(pmod.params[k]))
        want = w0 - 0.01 * np.asarray(jax.device_get(dense_grads[k]))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_bshd_layout_parity():
    """Sequence-major (BSHD) kernel path: forward and gradients match
    the BHSD path bit-for-tolerance; blocks index the head dim instead
    of transposing activations."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(3)
    B, H, S, D = 2, 3, 64, 16
    q = jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
    qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    for causal in (False, True):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        os_ = flash_attention(qs, ks, vs, causal=causal, block_q=32,
                              block_k=32, layout="bshd")
        np.testing.assert_allclose(np.asarray(os_.transpose(0, 2, 1, 3)),
                                   np.asarray(o), atol=1e-5, rtol=1e-5)

        g_ref = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, causal=causal, block_q=32, block_k=32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_bshd = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, causal=causal, block_q=32, block_k=32,
            layout="bshd") ** 2), argnums=(0, 1, 2))(qs, ks, vs)
        for gr, gs in zip(g_ref, g_bshd):
            np.testing.assert_allclose(
                np.asarray(gs.transpose(0, 2, 1, 3)), np.asarray(gr),
                atol=1e-4, rtol=1e-4)


def test_ring_attention_bshd_layout():
    """Sequence-major ring attention matches the dense reference and
    the bhsd ring result, for both impls, causal and not."""
    mesh = mx.parallel.make_mesh({"sp": 4})
    rng = np.random.RandomState(11)
    B, H, S, D = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    for causal in (False, True):
        want = attention_reference(q, k, v, causal=causal)
        for impl in ("xla", "flash"):
            got = ring_attention(qs, ks, vs, mesh, axis="sp",
                                 causal=causal, impl=impl,
                                 block_q=16, block_k=16, layout="bshd")
            np.testing.assert_allclose(
                np.asarray(got).transpose(0, 2, 1, 3), np.asarray(want),
                atol=2e-5, rtol=1e-4,
                err_msg=f"impl={impl} causal={causal}")


def test_ring_attention_bad_layout_raises():
    mesh = mx.parallel.make_mesh({"sp": 2})
    x = jnp.zeros((1, 2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="layout"):
        ring_attention(x, x, x, mesh, layout="BSHD")


def test_ulysses_attention_bshd_layout():
    """Sequence-major Ulysses: the all-to-alls preserve BSHD order and
    results match the dense reference for both impls."""
    mesh = mx.parallel.make_mesh({"sp": 4})
    rng = np.random.RandomState(13)
    B, H, S, D = 2, 4, 64, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D))
                           .astype(np.float32)) for _ in range(3))
    qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    for causal in (False, True):
        want = attention_reference(q, k, v, causal=causal)
        for impl in ("xla", "flash"):
            got = mx.parallel.ulysses_attention(
                qs, ks, vs, mesh, axis="sp", causal=causal, impl=impl,
                block_q=16, block_k=16, layout="bshd")
            np.testing.assert_allclose(
                np.asarray(got).transpose(0, 2, 1, 3), np.asarray(want),
                atol=2e-5, rtol=1e-4,
                err_msg=f"impl={impl} causal={causal}")


@pytest.mark.parametrize("sp_impl,heads,pos_embed,window", [
    ("ring", 2, "learned", 0), ("ulysses", 4, "learned", 0),
    # rope positions must stay GLOBAL under sequence sharding (the
    # iota is computed at full traced length and GSPMD partitions it)
    ("ring", 2, "rope", 0),
    # sliding window through the symbol-level sp path (band masked
    # with global positions inside the ring)
    ("ring", 2, "rope", 12)])
def test_sharded_trainer_sequence_parallel_gpt(sp_impl, heads, pos_embed,
                                               window):
    """Symbol-level sequence parallelism end to end: a ShardedTrainer
    over models.gpt with sequence_specs sharding (B, S) tokens across a
    dp x sp mesh routes the FlashAttention ops to the sharded schedule
    named by attn_sp_impl (ring ppermutes / Ulysses all-to-alls) via
    the ambient-mesh context — one train step matches the single-device
    run exactly, params included.  Per-shard local attention instead
    would fail this test (tokens would only attend within their
    shard)."""
    from jax.sharding import PartitionSpec as P

    vocab, seq = 53, 32

    def build(mesh, seq_specs=None):
        net = mx.models.gpt(vocab, seq, num_layers=1, d_model=32,
                            num_heads=heads, attn_sp_impl=sp_impl,
                            pos_embed=pos_embed, attn_window=window)
        return mx.parallel.ShardedTrainer(
            net, {"data": (8, seq), "softmax_label": (8, seq)},
            mesh=mesh, batch_axis="dp", sequence_specs=seq_specs,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.float32})

    mesh_sp = mx.parallel.make_mesh({"dp": 2, "sp": 4})
    mesh1 = mx.parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    tsp = build(mesh_sp, {"data": P("dp", "sp"),
                          "softmax_label": P("dp", "sp")})
    assert tsp._attn_seq_axis == "sp"
    t1 = build(mesh1)
    p0 = tsp.get_params()
    t1.set_params(p0)
    key = np.asarray(jax.device_get(tsp._key))
    t1._key = jax.device_put(key, t1._replicated)
    tsp._key = jax.device_put(key, tsp._replicated)
    rng = np.random.RandomState(0)
    batch = {"data": rng.randint(0, vocab, (8, seq)),
             "softmax_label": rng.randint(0, vocab, (8, seq)).astype(
                 np.float32)}
    osp, o1 = tsp.step(batch), t1.step(batch)
    np.testing.assert_allclose(np.asarray(osp[0]), np.asarray(o1[0]),
                               atol=2e-5, rtol=2e-4)
    psp, p1 = tsp.get_params(), t1.get_params()
    for k in p0:
        np.testing.assert_allclose(psp[k], p1[k], atol=5e-5, rtol=2e-4,
                                   err_msg=k)


def test_block_size_autofit():
    """Requested flash block sizes are upper bounds that shrink by
    halving to divide the sequence; eligibility rejects degenerate
    fits (ops/flash_attention.py:_fit_block / flash_eligible)."""
    from mxnet_tpu.ops.flash_attention import (_block_sizes, _fit_block,
                                               flash_attention,
                                               flash_eligible)

    assert _fit_block(2048, 512) == 512       # divides: untouched
    assert _fit_block(768, 512) == 256        # halves to a divisor
    assert _fit_block(16, 512) == 16          # short seq: whole seq
    assert _fit_block(1000, 512) == 8         # degenerate fit
    assert _block_sizes(768, 2048, 512, 512) == (256, 512)
    with pytest.raises(ValueError):           # explicit flash at S=1000
        _block_sizes(1000, 1000, 512, 512)    # must croak, not crawl
    assert _block_sizes(40, 40, 8, 8) == (8, 8)   # deliberate small

    # VMEM-aware shrink: bshd blocks span all heads, so high-H configs
    # must scale back below the 512 default; bhsd D=64 keeps it
    from mxnet_tpu.ops.flash_attention import _fit_vmem, _vmem_bytes
    assert _fit_vmem(512, 512, 2048, 2048, 64, None) == (512, 512)
    bq, bk = _fit_vmem(512, 512, 2048, 2048, 128, 16)
    assert (bq, bk) == (128, 128)                 # shrank to the floor
    assert _vmem_bytes(bq, bk, 128, 16) < \
        _vmem_bytes(512, 512, 128, 16) / 4        # far off the 50MB ask
    assert flash_eligible(2048, 2048)
    assert flash_eligible(768, 768)           # 256-tile: MXU-scale
    assert flash_eligible(16, 16)             # whole-sequence tile
    assert not flash_eligible(1000, 1000)     # 8-tile would crawl

    # numerics are block-size independent (interpret mode)
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 48, 16), jnp.float32)
               for _ in range(3))
    hi = flash_attention(q, k, v, causal=True)            # fits to 48
    lo = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(hi), np.asarray(lo),
                               atol=1e-5, rtol=1e-5)


def test_flash_attention_bf16_path():
    """bf16 inputs keep the matmuls in the input dtype (MXU fast path;
    f32 accumulation via preferred_element_type) — numerics must stay
    within bf16 tolerance of the f32 dense reference, fwd and bwd."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(7)
    B, H, S, D = 2, 2, 64, 32
    qf, kf, vf = (jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
                  for _ in range(3))
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (qf, kf, vf))

    ref = attention_reference(qf, kf, vf, causal=True)
    out = flash_attention(qb, kb, vb, causal=True).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gb = jax.grad(loss_flash, argnums=(0, 1, 2))(qb, kb, vb)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
    for a, b in zip(gb, gf):
        np.testing.assert_allclose(np.asarray(a.astype(jnp.float32)),
                                   np.asarray(b), atol=2e-1, rtol=5e-2)


def test_flash_attention_cross_lengths():
    """Sq != Sk (decoder cross-attention shapes): the kernel grids and
    causal offsets are defined over separate q/k lengths — pin it."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(9)
    B, H, D = 2, 2, 16
    Sq, Sk = 32, 64
    q = jnp.asarray(rng.randn(B, H, Sq, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, Sk, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, Sk, D), jnp.float32)

    out = flash_attention(q, k, v, block_q=16, block_k=16)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-4)

    g = jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, block_q=16, block_k=16) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(jnp.einsum(
        "bhqk,bhkd->bhqd",
        jax.nn.softmax(jnp.einsum("bhqd,bhkd->bhqk", a, b) / np.sqrt(D),
                       axis=-1), c) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_sliding_window(causal):
    """window > 0 = Mistral-class local attention: causal keeps the
    trailing (q-window, q] band, bidirectional keeps |q-k| < window.
    Kernel (with tile skipping) vs a dense masked reference, fwd+bwd."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(11)
    B, H, S, D, W = 2, 2, 64, 16, 24
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
               for _ in range(3))

    def dense_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        pq, pk = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
        keep = pq - pk < W
        if causal:
            keep = jnp.logical_and(keep, pq >= pk)
        else:
            keep = jnp.logical_and(keep, pk - pq < W)
        s = jnp.where(keep, s, -jnp.inf)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)

    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=causal, window=-1)
    out = flash_attention(q, k, v, causal=causal, window=W,
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_ref(q, k, v)),
                               atol=2e-5, rtol=2e-4)

    g = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=causal, window=W, block_q=16, block_k=16) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(dense_ref(a, b, c) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_flash_attention_window_symbol_level():
    """The FlashAttention symbol op exposes window= and the XLA dense
    fallback applies the same band mask (parity flash vs dense impl)."""
    data_shapes = {"q": (1, 2, 32, 8), "k": (1, 2, 32, 8),
                   "v": (1, 2, 32, 8)}
    rng = np.random.RandomState(12)
    feed = {n: rng.randn(*s).astype(np.float32)
            for n, s in data_shapes.items()}
    outs = {}
    for impl in ("flash", "xla"):
        q = mx.sym.Variable("q")
        k = mx.sym.Variable("k")
        v = mx.sym.Variable("v")
        net = mx.sym.FlashAttention(q, k, v, causal=True, window=8,
                                    impl=impl, block_q=8, block_k=8)
        exe = net.simple_bind(mx.cpu(0), **data_shapes)
        for n, val in feed.items():
            exe.arg_dict[n][:] = val
        outs[impl] = np.asarray(exe.forward()[0].asnumpy())
    np.testing.assert_allclose(outs["flash"], outs["xla"],
                               atol=2e-5, rtol=2e-4)


@pytest.mark.parametrize("layout", ["bshd", "bhsd"])
def test_flash_attention_grouped_query(layout):
    """GQA/MQA: Hkv < H with H % Hkv == 0.  bshd runs it natively in the
    kernels (shared K/V head per group, dK/dV accumulated per kv head in
    VMEM); bhsd expands K/V.  Both must match the dense repeat-based
    reference, forward and gradients — incl. dK/dV summing over the
    group."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(13)
    B, H, Hkv, S, D = 2, 4, 2, 32, 16
    if layout == "bshd":
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        expand = lambda t: jnp.repeat(t, H // Hkv, axis=2)
        to_bhsd = lambda t: t.transpose(0, 2, 1, 3)
    else:
        q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        expand = lambda t: jnp.repeat(t, H // Hkv, axis=1)
        to_bhsd = lambda t: t

    def dense_ref(q, k, v):
        qb, kb, vb = to_bhsd(q), to_bhsd(expand(k)), to_bhsd(expand(v))
        s = jnp.einsum("bhqd,bhkd->bhqk", qb, kb) / np.sqrt(D)
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vb)
        return o if layout == "bhsd" else o.transpose(0, 2, 1, 3)

    out = flash_attention(q, k, v, causal=True, layout=layout,
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_ref(q, k, v)),
                               atol=2e-5, rtol=2e-4)

    g = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=True, layout=layout,
        block_q=16, block_k=16) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(dense_ref(a, b, c) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), g, gr):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4, err_msg=name)

    hax = 2 if layout == "bshd" else 1
    k3 = jnp.take(expand(k), jnp.arange(3), axis=hax)
    v3 = jnp.take(expand(v), jnp.arange(3), axis=hax)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k3, v3, causal=True, layout=layout)


def test_flash_attention_gqa_symbol_level():
    """Symbol-level GQA: k/v with fewer heads flow through infer_shape,
    and the flash and dense impls agree."""
    shapes = {"q": (1, 16, 4, 8), "k": (1, 16, 2, 8), "v": (1, 16, 2, 8)}
    rng = np.random.RandomState(14)
    feed = {n: rng.randn(*s).astype(np.float32) for n, s in shapes.items()}
    outs = {}
    for impl in ("flash", "xla"):
        q = mx.sym.Variable("q")
        k = mx.sym.Variable("k")
        v = mx.sym.Variable("v")
        net = mx.sym.FlashAttention(q, k, v, causal=True, layout="bshd",
                                    impl=impl, block_q=8, block_k=8)
        exe = net.simple_bind(mx.cpu(0), **shapes)
        for n, val in feed.items():
            exe.arg_dict[n][:] = val
        outs[impl] = np.asarray(exe.forward()[0].asnumpy())
    assert outs["flash"].shape == (1, 16, 4, 8)
    np.testing.assert_allclose(outs["flash"], outs["xla"],
                               atol=2e-5, rtol=2e-4)


def test_flash_attention_gqa_sequence_parallel():
    """GQA k/v under a sharded seq axis: the op expands K/V to full
    heads, then runs the ring schedule — parity vs the same op without
    the sp context (uncommitted arrays so the shard_map mesh can place
    them; the trainer path does this with real shardings)."""
    from mxnet_tpu.ops.attention import (FlashAttentionOp,
                                         FlashAttentionParam,
                                         spmd_attention)

    mesh = mx.parallel.make_mesh({"sp": 4})
    B, S, H, Hkv, D = 1, 16, 4, 2, 8
    rng = np.random.RandomState(15)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))

    op = FlashAttentionOp()
    params = FlashAttentionParam(causal=True, layout="bshd",
                                 block_q=4, block_k=4)
    with spmd_attention(mesh, None, "sp"):
        out_sp = op.forward(params, [q, k, v], [], False, None)[0][0]
    out = op.forward(params, [q, k, v], [], False, None)[0][0]
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out),
                               atol=2e-5, rtol=2e-4)


@pytest.mark.parametrize("fused_qkv", [False, True])
def test_gpt_model_gqa_trains(fused_qkv):
    """models.gpt(kv_heads=..., attn_window=...): the GQA+window GPT
    builds, shape-infers, and takes a finite train step both projection
    layouts."""
    vocab, seq = 17, 16
    net = mx.models.gpt(vocab, seq, num_layers=1, d_model=32, num_heads=4,
                        kv_heads=2, attn_window=8, attn_layout="bshd",
                        fused_qkv=fused_qkv)
    exe = net.simple_bind(mx.cpu(0), grad_req="write",
                          data=(2, seq), softmax_label=(2, seq))
    rng = np.random.RandomState(16)
    # param sanity: K/V projections carry kv_heads * head_dim columns
    shapes = dict(zip(net.list_arguments(),
                      net.infer_shape(data=(2, seq),
                                      softmax_label=(2, seq))[0]))
    if fused_qkv:
        assert shapes["gpt_l0_qkv_weight"][0] == 32 + 2 * 16
    else:
        assert shapes["gpt_l0_k_weight"][0] == 16
    for name, arr in exe.arg_dict.items():
        if name == "data":
            arr[:] = rng.randint(0, vocab, (2, seq)).astype(np.float32)
        elif name == "softmax_label":
            arr[:] = rng.randint(0, vocab, (2, seq)).astype(np.float32)
        else:
            arr[:] = rng.normal(0, 0.05, arr.shape)
    outs = exe.forward(is_train=True)
    exe.backward([mx.nd.ones(o.shape) for o in outs])
    assert np.isfinite(np.asarray(outs[0].asnumpy())).all()
    gnorm = sum(float(np.abs(np.asarray(g.asnumpy())).sum())
                for g in exe.grad_dict.values() if g is not None)
    assert np.isfinite(gnorm) and gnorm > 0


def test_rope_math_and_relative_property():
    """RoPE rotates head-dim pairs by pos * base^(-2i/D): check against
    a direct reference, and the defining property — rotated Q.K^T
    depends only on RELATIVE position (shifting both by the same offset
    leaves scores unchanged)."""
    from mxnet_tpu.ops.attention import RoPEOp, RoPEParam

    rng = np.random.RandomState(17)
    B, S, H, D = 1, 8, 2, 16
    x = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    op = RoPEOp()

    out = op.forward(RoPEParam(layout="bshd"), [x], [], False, None)[0][0]
    half = D // 2
    inv = 10000.0 ** (-np.arange(half) / half)
    ang = np.arange(S)[:, None] * inv[None, :]
    cos, sin = np.cos(ang), np.sin(ang)
    xn = np.asarray(x)
    ref = np.concatenate(
        [xn[..., :half] * cos[None, :, None, :]
         - xn[..., half:] * sin[None, :, None, :],
         xn[..., :half] * sin[None, :, None, :]
         + xn[..., half:] * cos[None, :, None, :]], axis=-1)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)

    # relative property: scores(q, k) == scores(q shifted, k shifted)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def scores(off):
        p = RoPEParam(layout="bshd", offset=off)
        qr = op.forward(p, [q], [], False, None)[0][0]
        kr = op.forward(p, [k], [], False, None)[0][0]
        return np.asarray(jnp.einsum("bqhd,bkhd->bhqk", qr, kr))

    np.testing.assert_allclose(scores(0), scores(37), atol=1e-3, rtol=1e-4)


def test_gpt_model_rope_trains():
    """pos_embed='rope': no position table in the checkpoint, model
    takes a finite train step, and the bhsd layout composes."""
    vocab, seq = 13, 12
    net = mx.models.gpt(vocab, seq, num_layers=1, d_model=32, num_heads=2,
                        pos_embed="rope", attn_layout="bshd")
    args = net.list_arguments()
    assert not any("pos_embed" in a for a in args)
    exe = net.simple_bind(mx.cpu(0), grad_req="write",
                          data=(2, seq), softmax_label=(2, seq))
    rng = np.random.RandomState(18)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            arr[:] = rng.randint(0, vocab, (2, seq)).astype(np.float32)
        else:
            arr[:] = rng.normal(0, 0.05, arr.shape)
    outs = exe.forward(is_train=True)
    exe.backward([mx.nd.ones(o.shape) for o in outs])
    assert np.isfinite(np.asarray(outs[0].asnumpy())).all()
    gnorm = sum(float(np.abs(np.asarray(g.asnumpy())).sum())
                for g in exe.grad_dict.values() if g is not None)
    assert np.isfinite(gnorm) and gnorm > 0


@pytest.mark.parametrize("layout", ["bhsd", "bshd"])
def test_ring_attention_gqa_native(layout):
    """Ring attention carries grouped-query K/V natively: the REDUCED
    shards go around the ring (flash body groups in-kernel; dense body
    expands per shard) — parity vs the expanded dense reference, both
    impls."""
    rng = np.random.RandomState(21)
    B, H, Hkv, S, D = 1, 4, 2, 32, 16
    mesh = mx.parallel.make_mesh({"sp": 4})
    if layout == "bshd":
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        kx = jnp.repeat(k, H // Hkv, axis=2).transpose(0, 2, 1, 3)
        vx = jnp.repeat(v, H // Hkv, axis=2).transpose(0, 2, 1, 3)
        ref = attention_reference(q.transpose(0, 2, 1, 3), kx, vx,
                                  causal=True).transpose(0, 2, 1, 3)
    else:
        q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        ref = attention_reference(q, jnp.repeat(k, H // Hkv, axis=1),
                                  jnp.repeat(v, H // Hkv, axis=1),
                                  causal=True)
    for impl in ("xla", "flash"):
        out = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                             impl=impl, block_q=8, block_k=8,
                             layout=layout)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4,
                                   err_msg=f"{layout}:{impl}")


def test_ulysses_attention_gqa_expands():
    """Ulysses GQA: when kv heads do NOT divide the sp axis the K/V
    expand before the all-to-alls; parity vs the dense reference, plus
    the clean error for a non-multiple head count."""
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    rng = np.random.RandomState(22)
    B, H, Hkv, S, D = 1, 4, 2, 32, 16
    mesh = mx.parallel.make_mesh({"sp": 4})
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
    ref = attention_reference(q, jnp.repeat(k, 2, axis=1),
                              jnp.repeat(v, 2, axis=1), causal=True)
    out = ulysses_attention(q, k, v, mesh, axis="sp", causal=True,
                            impl="xla", block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-4)
    with pytest.raises(ValueError, match="multiple"):
        ulysses_attention(q, k[:, :1][:, [0, 0, 0]], v[:, :1][:, [0, 0, 0]],
                          mesh, axis="sp", causal=True, impl="xla")


def test_ulysses_attention_gqa_native():
    """kv_heads % sp == 0: the K/V all-to-alls split the REDUCED head
    axis and the kernel runs GQA natively per head group — parity vs
    the expanded dense reference, both impls."""
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    rng = np.random.RandomState(28)
    # Hkv/sp = 2 kv heads per group vs 4 q heads: einsum cannot
    # broadcast this — the per-shard expansion in the dense body is
    # genuinely exercised (flash groups natively)
    B, H, Hkv, S, D = 1, 8, 4, 32, 16
    mesh = mx.parallel.make_mesh({"sp": 2})
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
    kx = jnp.repeat(k, 2, axis=2).transpose(0, 2, 1, 3)
    vx = jnp.repeat(v, 2, axis=2).transpose(0, 2, 1, 3)
    ref = attention_reference(q.transpose(0, 2, 1, 3), kx, vx,
                              causal=True).transpose(0, 2, 1, 3)
    for impl in ("xla", "flash"):
        out = ulysses_attention(q, k, v, mesh, axis="sp", causal=True,
                                impl=impl, block_q=16, block_k=16,
                                layout="bshd")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4, err_msg=impl)

    # the bhsd dense branch and window x native-GQA composition
    qb, kb, vb = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    W = 12
    sw = jnp.einsum("bhqd,bhkd->bhqk", qb.repeat(1, axis=1),
                    jnp.repeat(kb, 2, axis=1)) / np.sqrt(D)
    pq, pk = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
    keep = jnp.logical_and(pq >= pk, pq - pk < W)
    sw = jnp.where(keep, sw, -jnp.inf)
    ref_w = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(sw, axis=-1),
                       jnp.repeat(vb, 2, axis=1))
    for impl in ("xla", "flash"):
        out = ulysses_attention(qb, kb, vb, mesh, axis="sp", causal=True,
                                impl=impl, block_q=16, block_k=16,
                                layout="bhsd", window=W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_w),
                                   atol=2e-5, rtol=2e-4,
                                   err_msg=f"bhsd:{impl}")


def test_gpt_fused_ce_loss_parity():
    """loss='ce' (fused SoftmaxCELoss head): per-position NLL equals
    -log(probs[label]) of the SoftmaxOutput head, and the parameter
    gradients of one train step match exactly (same backward math,
    no (N, V) probability materialization)."""
    vocab, seq = 29, 8
    rng = np.random.RandomState(23)
    feed_x = rng.randint(0, vocab, (2, seq)).astype(np.float32)
    feed_y = rng.randint(0, vocab, (2, seq)).astype(np.float32)

    def run(loss):
        net = mx.models.gpt(vocab, seq, num_layers=1, d_model=16,
                            num_heads=2, loss=loss)
        exe = net.simple_bind(mx.cpu(0), grad_req="write",
                              data=(2, seq), softmax_label=(2, seq))
        prng = np.random.RandomState(3)
        for name, arr in exe.arg_dict.items():
            if name == "data":
                arr[:] = feed_x
            elif name == "softmax_label":
                arr[:] = feed_y
            else:
                arr[:] = prng.normal(0, 0.1, arr.shape)
        outs = exe.forward(is_train=True)
        exe.backward([mx.nd.ones(o.shape) for o in outs])
        grads = {k: np.asarray(g.asnumpy())
                 for k, g in exe.grad_dict.items() if g is not None}
        return np.asarray(outs[0].asnumpy()), grads

    probs, g_soft = run("softmax")
    losses, g_ce = run("ce")
    lab = feed_y.reshape(-1).astype(int)
    nll_ref = -np.log(probs[np.arange(lab.size), lab] + 1e-12)
    np.testing.assert_allclose(losses, nll_ref, atol=1e-5, rtol=1e-5)
    assert set(g_ce) == set(g_soft)
    for k in g_soft:
        np.testing.assert_allclose(g_ce[k], g_soft[k], atol=1e-5,
                                   rtol=1e-4, err_msg=k)


@pytest.mark.parametrize("normalization,use_ignore", [
    ("batch", False), ("valid", False), ("valid", True)])
def test_fused_ce_normalization_matches_softmax_output(normalization,
                                                       use_ignore):
    """SoftmaxCELoss(normalization=...) reproduces SoftmaxOutput's
    effective gradient scale (round-4 advisor: switching loss='softmax'
    -> 'ce' must not silently change it)."""
    N, V = 6, 11
    rng = np.random.RandomState(31)
    x = rng.randn(N, V).astype(np.float32)
    y = rng.randint(0, V, N).astype(np.float32)
    if use_ignore:
        y[:2] = 0.0                       # ignored rows
    kw = dict(normalization=normalization, use_ignore=use_ignore,
              ignore_label=0.0, grad_scale=1.7)

    def grad_of(op_name):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("label")
        out = getattr(mx.sym, op_name)(data, label, **kw)
        exe = out.simple_bind(mx.cpu(0), grad_req="write",
                              data=(N, V), label=(N,))
        exe.arg_dict["data"][:] = x
        exe.arg_dict["label"][:] = y
        outs = exe.forward(is_train=True)
        exe.backward([mx.nd.ones(o.shape) for o in outs])
        return exe.grad_dict["data"].asnumpy()

    np.testing.assert_allclose(grad_of("SoftmaxCELoss"),
                               grad_of("SoftmaxOutput"),
                               atol=1e-6, rtol=1e-5)


@pytest.mark.parametrize("causal,impl", [(True, "xla"), (True, "flash"),
                                         (False, "xla"), (False, "flash")])
def test_ring_attention_windowed(causal, impl):
    """Sliding window over the sharded sequence: the band mask uses
    GLOBAL positions across ring steps; for causal windows the ring
    shrinks to the shards that can intersect the band (n_steps bound) —
    parity vs the dense banded reference either way."""
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(24)
    B, H, S, D, W = 1, 2, 64, 16, 12     # W < S_blk=16: neighbor-only ring
    mesh = mx.parallel.make_mesh({"sp": 4})
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    ref = flash_attention(q, k, v, causal=causal, window=W,
                          block_q=16, block_k=16)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=causal,
                         impl=impl, block_q=16, block_k=16, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-4)


def test_ulysses_attention_windowed():
    """Window passes straight through ulysses (full sequence per head
    group after the all-to-all)."""
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    from mxnet_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(25)
    B, H, S, D, W = 1, 4, 64, 16, 20
    mesh = mx.parallel.make_mesh({"sp": 4})
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    ref = flash_attention(q, k, v, causal=True, window=W,
                          block_q=16, block_k=16)
    for impl in ("xla", "flash"):
        out = ulysses_attention(q, k, v, mesh, axis="sp", causal=True,
                                impl=impl, block_q=16, block_k=16,
                                window=W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4, err_msg=impl)


def test_gpt_tied_embeddings_gradients():
    """tie_embeddings=True: one named array serves Embedding AND LM
    head; its gradient must be the SUM of both paths (checked against
    the untied model's embed-grad + head-grad with identical params)."""
    vocab, seq = 17, 8
    rng = np.random.RandomState(26)
    fx = rng.randint(0, vocab, (2, seq)).astype(np.float32)
    fy = rng.randint(0, vocab, (2, seq)).astype(np.float32)
    w_embed = rng.normal(0, 0.1, (vocab, 16)).astype(np.float32)

    def run(tied):
        net = mx.models.gpt(vocab, seq, num_layers=1, d_model=16,
                            num_heads=2, tie_embeddings=tied)
        exe = net.simple_bind(mx.cpu(0), grad_req="write",
                              data=(2, seq), softmax_label=(2, seq))
        prng = np.random.RandomState(4)
        for name, arr in exe.arg_dict.items():
            if name == "data":
                arr[:] = fx
            elif name == "softmax_label":
                arr[:] = fy
            elif name == "gpt_tok_embed_weight":
                arr[:] = w_embed
            elif name == "gpt_head_weight":
                arr[:] = w_embed          # untied twin starts tied
            elif name == "gpt_head_bias":
                arr[:] = 0.0
            else:
                arr[:] = prng.normal(0, 0.1, arr.shape)
        outs = exe.forward(is_train=True)
        exe.backward([mx.nd.ones(o.shape) for o in outs])
        return {k: np.asarray(g.asnumpy())
                for k, g in exe.grad_dict.items() if g is not None}

    g_tied = run(True)
    g_untied = run(False)
    np.testing.assert_allclose(
        g_tied["gpt_tok_embed_weight"],
        g_untied["gpt_tok_embed_weight"] + g_untied["gpt_head_weight"],
        atol=1e-5, rtol=1e-4)


def test_rmsnorm_op():
    """RMSNorm = x / rms(x) * gamma (no centering/shift), f32 stats."""
    from mxnet_tpu.ops.attention import RMSNormOp, RMSNormParam

    rng = np.random.RandomState(27)
    x = jnp.asarray(rng.randn(4, 16) * 3 + 1, jnp.float32)
    g = jnp.asarray(rng.randn(16), jnp.float32)
    out = RMSNormOp().forward(RMSNormParam(), [x, g], [], False, None)[0][0]
    xn = np.asarray(x)
    ref = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-5) \
        * np.asarray(g)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)

    # symbol-level: builds, infers, differentiates
    data = mx.sym.Variable("data")
    net = mx.sym.RMSNorm(data, name="rn")
    exe = net.simple_bind(mx.cpu(0), grad_req="write", data=(2, 8))
    exe.arg_dict["data"][:] = rng.randn(2, 8)
    exe.arg_dict["rn_gamma"][:] = 1.0
    outs = exe.forward(is_train=True)
    exe.backward([mx.nd.ones(o.shape) for o in outs])
    assert np.isfinite(np.asarray(exe.grad_dict["data"].asnumpy())).all()
