"""Ring attention (sequence parallelism) and pipeline parallelism on the
virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.parallel.pipeline import pipeline_apply
from mxnet_tpu.parallel.ring_attention import (attention_reference,
                                               ring_attention)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    B, H, S, D = 2, 4, 32, 8
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    mesh = mx.parallel.make_mesh({"sp": 4})
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, axis="sp", causal=causal)
    ref = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_8way():
    rng = np.random.RandomState(1)
    B, H, S, D = 1, 2, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = mx.parallel.make_mesh({"sp": 8})
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_pipeline_matches_sequential():
    rng = np.random.RandomState(2)
    n_stages, B, Dm = 4, 16, 8
    mesh = mx.parallel.make_mesh({"pp": n_stages})
    Ws = rng.randn(n_stages, Dm, Dm).astype(np.float32) * 0.3
    bs = rng.randn(n_stages, Dm).astype(np.float32) * 0.1
    params = {"w": jnp.asarray(Ws), "b": jnp.asarray(bs)}
    x = rng.randn(B, Dm).astype(np.float32)

    def stage(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    out = pipeline_apply(stage, params, jnp.asarray(x), n_microbatches=4,
                         mesh=mesh, axis="pp")
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ Ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_gradients():
    rng = np.random.RandomState(3)
    n_stages, B, Dm = 2, 8, 4
    mesh = mx.parallel.make_mesh({"pp": n_stages})
    params = {"w": jnp.asarray(rng.randn(n_stages, Dm, Dm).astype(np.float32)
                               * 0.3)}
    x = jnp.asarray(rng.randn(B, Dm).astype(np.float32))

    def stage(p, h):
        return jnp.tanh(h @ p["w"])

    def objective(params):
        out = pipeline_apply(stage, params, x, n_microbatches=2, mesh=mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(objective)(params)["w"]

    # dense reference gradient
    def ref_obj(ws):
        h = x
        for i in range(n_stages):
            h = jnp.tanh(h @ ws[i])
        return jnp.sum(h ** 2)

    g_ref = jax.grad(ref_obj)(params["w"])
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4,
                               atol=1e-5)
