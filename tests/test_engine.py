"""Dependency engine: threaded-vs-serial equivalence under random
read/write workloads (rebuild of tests/cpp/threaded_engine_test.cc)."""

import random
import threading
import time

import pytest

from mxnet_tpu.engine import FnProperty, NaiveEngine, ThreadedEngine


def _random_workload(engine, n_vars=8, n_ops=60, seed=0):
    """Push ops appending to per-var logs; writes must serialize with
    reads/writes on the same var (GenerateWorkload analog)."""
    rng = random.Random(seed)
    history = []
    hist_lock = threading.Lock()
    variables = [engine.new_variable(f"v{i}") for i in range(n_vars)]
    for op_id in range(n_ops):
        n_read = rng.randint(0, 3)
        n_write = rng.randint(1, 2)
        picks = rng.sample(range(n_vars), n_read + n_write)
        reads = [variables[i] for i in picks[:n_read]]
        writes = [variables[i] for i in picks[n_read:]]

        def fn(op_id=op_id, reads=tuple(picks[:n_read]),
               writes=tuple(picks[n_read:])):
            with hist_lock:
                history.append((op_id, reads, writes))

        engine.push(fn, const_vars=reads, mutable_vars=writes)
    engine.wait_for_all()
    return history


def _check_serialization(history, n_ops):
    """All ops ran exactly once, and per-var write ordering respects push
    order: for each var, the op-ids that wrote it appear in increasing
    order (engine guarantees FIFO per var)."""
    assert sorted(h[0] for h in history) == list(range(n_ops))
    last_write = {}
    for op_id, reads, writes in history:
        for v in writes:
            if v in last_write:
                assert last_write[v] < op_id, f"write order violated on var {v}"
            last_write[v] = op_id


@pytest.mark.parametrize("engine_cls", [NaiveEngine, ThreadedEngine])
def test_workload_equivalence(engine_cls):
    engine = engine_cls()
    n_ops = 60
    history = _random_workload(engine, n_ops=n_ops)
    _check_serialization(history, n_ops)


def test_readers_run_concurrently():
    engine = ThreadedEngine(num_workers=4)
    v = engine.new_variable()
    barrier = threading.Barrier(3, timeout=5)

    def reader():
        barrier.wait()  # all three readers must be in flight at once

    for _ in range(3):
        engine.push(reader, const_vars=(v,))
    engine.wait_for_all()


def test_writer_excludes_readers():
    engine = ThreadedEngine(num_workers=4)
    v = engine.new_variable()
    state = {"writer_active": False, "violation": False}
    lock = threading.Lock()

    def writer():
        with lock:
            state["writer_active"] = True
        time.sleep(0.01)
        with lock:
            state["writer_active"] = False

    def reader():
        with lock:
            if state["writer_active"]:
                state["violation"] = True

    for i in range(20):
        if i % 3 == 0:
            engine.push(writer, mutable_vars=(v,))
        else:
            engine.push(reader, const_vars=(v,))
    engine.wait_for_all()
    assert not state["violation"]


def test_wait_for_var():
    engine = ThreadedEngine(num_workers=2)
    v = engine.new_variable()
    done = []
    engine.push(lambda: (time.sleep(0.05), done.append(1)), mutable_vars=(v,))
    engine.wait_for_var(v)
    assert done == [1]
    engine.wait_for_all()


def test_exception_propagates():
    engine = ThreadedEngine(num_workers=2)

    def bad():
        raise RuntimeError("boom")

    engine.push(bad)
    with pytest.raises(RuntimeError, match="boom"):
        engine.wait_for_all()


def test_duplicate_var_rejected():
    engine = NaiveEngine()
    v = engine.new_variable()
    with pytest.raises(ValueError):
        engine.push(lambda: None, const_vars=(v,), mutable_vars=(v,))
