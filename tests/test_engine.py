"""Dependency engine: threaded-vs-serial equivalence under random
read/write workloads (rebuild of tests/cpp/threaded_engine_test.cc)."""

import random
import threading
import time

import pytest

from mxnet_tpu.engine import FnProperty, NaiveEngine, ThreadedEngine


def _random_workload(engine, n_vars=8, n_ops=60, seed=0):
    """Push ops appending to per-var logs; writes must serialize with
    reads/writes on the same var (GenerateWorkload analog)."""
    rng = random.Random(seed)
    history = []
    hist_lock = threading.Lock()
    variables = [engine.new_variable(f"v{i}") for i in range(n_vars)]
    for op_id in range(n_ops):
        n_read = rng.randint(0, 3)
        n_write = rng.randint(1, 2)
        picks = rng.sample(range(n_vars), n_read + n_write)
        reads = [variables[i] for i in picks[:n_read]]
        writes = [variables[i] for i in picks[n_read:]]

        def fn(op_id=op_id, reads=tuple(picks[:n_read]),
               writes=tuple(picks[n_read:])):
            with hist_lock:
                history.append((op_id, reads, writes))

        engine.push(fn, const_vars=reads, mutable_vars=writes)
    engine.wait_for_all()
    return history


def _check_serialization(history, n_ops):
    """All ops ran exactly once, and per-var write ordering respects push
    order: for each var, the op-ids that wrote it appear in increasing
    order (engine guarantees FIFO per var)."""
    assert sorted(h[0] for h in history) == list(range(n_ops))
    last_write = {}
    for op_id, reads, writes in history:
        for v in writes:
            if v in last_write:
                assert last_write[v] < op_id, f"write order violated on var {v}"
            last_write[v] = op_id


@pytest.mark.parametrize("engine_cls", [NaiveEngine, ThreadedEngine])
def test_workload_equivalence(engine_cls):
    engine = engine_cls()
    n_ops = 60
    history = _random_workload(engine, n_ops=n_ops)
    _check_serialization(history, n_ops)


def test_readers_run_concurrently():
    engine = ThreadedEngine(num_workers=4)
    v = engine.new_variable()
    barrier = threading.Barrier(3, timeout=5)

    def reader():
        barrier.wait()  # all three readers must be in flight at once

    for _ in range(3):
        engine.push(reader, const_vars=(v,))
    engine.wait_for_all()


def test_writer_excludes_readers():
    engine = ThreadedEngine(num_workers=4)
    v = engine.new_variable()
    state = {"writer_active": False, "violation": False}
    lock = threading.Lock()

    def writer():
        with lock:
            state["writer_active"] = True
        time.sleep(0.01)
        with lock:
            state["writer_active"] = False

    def reader():
        with lock:
            if state["writer_active"]:
                state["violation"] = True

    for i in range(20):
        if i % 3 == 0:
            engine.push(writer, mutable_vars=(v,))
        else:
            engine.push(reader, const_vars=(v,))
    engine.wait_for_all()
    assert not state["violation"]


def test_wait_for_var():
    engine = ThreadedEngine(num_workers=2)
    v = engine.new_variable()
    done = []
    engine.push(lambda: (time.sleep(0.05), done.append(1)), mutable_vars=(v,))
    engine.wait_for_var(v)
    assert done == [1]
    engine.wait_for_all()


def test_exception_propagates():
    engine = ThreadedEngine(num_workers=2)

    def bad():
        raise RuntimeError("boom")

    engine.push(bad)
    with pytest.raises(RuntimeError, match="boom"):
        engine.wait_for_all()


def test_duplicate_var_rejected():
    engine = NaiveEngine()
    v = engine.new_variable()
    with pytest.raises(ValueError):
        engine.push(lambda: None, const_vars=(v,), mutable_vars=(v,))


def test_priority_dispatch_order():
    """Among READY ops, higher priority dispatches first (reference
    threaded_engine_pooled priority queue; kvstore priority=-key).
    A single-worker engine is saturated with a blocker, then ops of
    shuffled priorities are enqueued; they must run highest-first."""
    import threading

    from mxnet_tpu.engine import ThreadedEngine

    eng = ThreadedEngine(num_workers=1)
    release = threading.Event()
    order = []

    # block the lone normal-lane worker so later pushes queue as READY
    eng.push(lambda: release.wait(10))
    import time
    time.sleep(0.05)  # let the blocker occupy the worker

    for prio in [0, 5, -3, 9, 1, -7, 5]:
        eng.push(lambda p=prio: order.append(p), priority=prio)
    time.sleep(0.05)  # everything queued behind the blocker
    release.set()
    eng.wait_for_all()
    assert order == sorted(order, reverse=True) and len(order) == 7, order


def test_native_priority_dispatch_order():
    """Same contract through the C++ engine (MXTPUEnginePushPriority)."""
    import threading
    import time

    from mxnet_tpu.engine import NativeEngine
    from mxnet_tpu.libinfo import find_lib

    if find_lib() is None:
        pytest.skip("native lib unavailable")
    eng = NativeEngine(num_workers=1, num_io_workers=1)
    order = []
    # block BOTH lanes: native workers steal from the other lane's queue
    # when their own is empty.  Wait for each blocker to REPORT it is
    # running (a fixed sleep races worker startup), and release them one
    # at a time: with both released, TWO workers drain the queue — pops
    # stay priority-ordered but the appends interleave (observed flake).
    # One free worker at a time makes completion order deterministic.
    from mxnet_tpu.engine import FnProperty
    started = [threading.Event(), threading.Event()]
    release = [threading.Event(), threading.Event()]
    eng.push(lambda: (started[0].set(), release[0].wait(10)))
    eng.push(lambda: (started[1].set(), release[1].wait(10)),
             prop=FnProperty.CPU_PRIORITIZED)
    assert started[0].wait(5) and started[1].wait(5)
    for prio in [2, -1, 7, 0, 4]:
        eng.push(lambda p=prio: order.append(p), priority=prio)
    release[0].set()                  # single consumer drains the queue
    deadline = time.monotonic() + 5
    while len(order) < 5 and time.monotonic() < deadline:
        time.sleep(0.005)
    release[1].set()
    eng.wait_for_all()
    assert order == sorted(order, reverse=True) and len(order) == 5, order


def test_priority_overlap_microbenchmark():
    """Low-priority checkpoint-style IO must not delay high-priority
    staging work when both are ready: with one worker, the N staged
    high-priority sends all complete before the big low-priority write
    even though the write was enqueued first."""
    import threading
    import time

    from mxnet_tpu.engine import ThreadedEngine

    eng = ThreadedEngine(num_workers=1)
    release = threading.Event()
    events = []

    eng.push(lambda: release.wait(10))
    time.sleep(0.05)
    # slow low-priority "checkpoint write" enqueued FIRST
    eng.push(lambda: (time.sleep(0.2), events.append("ckpt")),
             priority=-10)
    # then training-critical staged sends at priority=-key
    for key in range(4):
        eng.push(lambda k=key: events.append(f"send{k}"),
                 priority=-key)
    time.sleep(0.05)
    release.set()
    eng.wait_for_all()
    assert events.index("ckpt") == len(events) - 1, events
    assert events[:4] == ["send0", "send1", "send2", "send3"], events
