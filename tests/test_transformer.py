"""Transformer stack: LayerNorm/gelu/FlashAttention symbol ops and the
GPT model-zoo entry (beyond-parity additions; models/transformer.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_layernorm_matches_manual():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6, 8).astype(np.float32) * 3 + 1
    gamma = rng.rand(8).astype(np.float32) + 0.5
    beta = rng.randn(8).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(gamma),
                          mx.nd.array(beta), eps=1e-5).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mu) / np.sqrt(var + 1e-5) * gamma + beta
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_layernorm_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    data = mx.sym.Variable("data")
    net = mx.sym.LayerNorm(data, name="ln")
    rng = np.random.RandomState(1)
    check_numeric_gradient(
        net, {"data": rng.randn(3, 5).astype(np.float32)}, check_eps=5e-2)


def test_gelu_values():
    x = np.array([-3.0, -1.0, 0.0, 1.0, 3.0], np.float32)
    out = mx.nd.gelu(mx.nd.array(x)).asnumpy()
    from scipy.stats import norm  # exact gelu = x * Phi(x)
    want = x * norm.cdf(x)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_flash_attention_op_matches_manual():
    rng = np.random.RandomState(2)
    B, H, S, D = 2, 3, 8, 4
    q, k, v = [rng.randn(B, H, S, D).astype(np.float32) for _ in range(3)]
    for causal in (False, True):
        out = mx.nd.FlashAttention(mx.nd.array(q), mx.nd.array(k),
                                   mx.nd.array(v), causal=causal).asnumpy()
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_gpt_causality():
    """Output at position t must not depend on tokens after t."""
    rng = np.random.RandomState(3)
    V, S = 20, 8
    net = mx.models.gpt(V, S, num_layers=1, d_model=16, num_heads=2)
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(1, S),
                          softmax_label=(1, S))
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rng.randn(*arr.shape).astype(np.float32) * 0.1
    toks = rng.randint(0, V, (1, S)).astype(np.float32)
    exe.arg_dict["data"][:] = toks
    exe.forward(is_train=False)
    base = exe.outputs[0].asnumpy().reshape(S, V)
    # perturb the LAST token: only the last position's output may change
    toks2 = toks.copy()
    toks2[0, -1] = (toks2[0, -1] + 1) % V
    exe.arg_dict["data"][:] = toks2
    exe.forward(is_train=False)
    pert = exe.outputs[0].asnumpy().reshape(S, V)
    np.testing.assert_allclose(base[:-1], pert[:-1], rtol=1e-5, atol=1e-6)
    assert np.abs(base[-1] - pert[-1]).max() > 1e-6


def test_gpt_training_reduces_loss():
    rng = np.random.RandomState(4)
    V, S, B = 12, 16, 16
    # deterministic cycle corpus: fully learnable
    tokens = np.arange(2000) % V
    net = mx.models.gpt(V, S, num_layers=1, d_model=32, num_heads=2)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, S))],
             label_shapes=[("softmax_label", (B, S))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})
    nlls = []
    for step in range(60):
        starts = rng.randint(0, len(tokens) - S - 1, B)
        x = np.stack([tokens[s:s + S] for s in starts]).astype(np.float32)
        y = np.stack([tokens[s + 1:s + S + 1] for s in starts]).astype(np.float32)
        mod.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)]),
                    is_train=True)
        probs = mod.get_outputs()[0].asnumpy()
        nll = -np.log(probs[np.arange(len(probs)),
                            y.reshape(-1).astype(int)] + 1e-9).mean()
        nlls.append(nll)
        mod.backward()
        mod.update()
    assert nlls[-1] < 0.5, nlls[-1]  # cycle is deterministic: near-zero


def test_gpt_sharded_trainer_adam_multichip():
    """Adam opt state (incl. the scalar step count) must place onto the
    mesh (regression: mixed device sets on multi-device jit)."""
    mesh = mx.parallel.make_mesh({"dp": 8})
    V, S, B = 11, 16, 16
    net = mx.models.gpt(V, S, num_layers=1, d_model=16, num_heads=2)
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (B, S), "softmax_label": (B, S)}, mesh=mesh,
        optimizer="adam", optimizer_params={"learning_rate": 1e-3},
        initializer=mx.init.Xavier())
    rng = np.random.RandomState(5)
    x = rng.randint(0, V, (B, S)).astype(np.float32)
    y = rng.randint(0, V, (B, S)).astype(np.float32)
    outs = tr.step({"data": x, "softmax_label": y})
    assert np.isfinite(np.asarray(outs[0])).all()


@pytest.mark.slow
def test_gpt_remat_matches_plain():
    """remat=True (force_mirroring rematerialization) must not change the
    math — same loss trajectory as the plain model."""
    rng = np.random.RandomState(0)
    V, S, B = 50, 16, 4
    X = rng.randint(0, V, (B, S))
    Y = rng.randint(0, V, (B, S))

    losses = {}
    for remat in (False, True):
        net = mx.models.gpt(V, S, num_layers=2, d_model=32, num_heads=2,
                            remat=remat)
        mx.random.seed(0)
        np.random.seed(0)
        tr = mx.parallel.ShardedTrainer(
            net, {"data": (B, S), "softmax_label": (B, S)},
            mesh=mx.parallel.make_mesh({"dp": 1}),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            input_dtypes={"data": np.int32, "softmax_label": np.int32})
        for _ in range(2):
            tr.step({"data": X, "softmax_label": Y})
        losses[remat] = tr.get_params()["gpt_head_bias"]
    np.testing.assert_allclose(losses[False], losses[True],
                               atol=1e-5, rtol=1e-4)


def test_gpt_fused_qkv_matches_plain():
    """fused_qkv=True is the same math: with qkv_weight/bias set to the
    concatenation of the per-projection weights, forward output matches
    the three-matmul model exactly."""
    rng = np.random.RandomState(5)
    V, S, B = 20, 8, 2
    kw = dict(num_layers=2, d_model=16, num_heads=2)
    plain = mx.models.gpt(V, S, **kw)
    fused = mx.models.gpt(V, S, fused_qkv=True, **kw)

    exe_p = plain.simple_bind(mx.cpu(), grad_req="null", data=(B, S),
                              softmax_label=(B, S))
    for name, arr in exe_p.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = rng.randn(*arr.shape).astype(np.float32) * 0.1

    exe_f = fused.simple_bind(mx.cpu(), grad_req="null", data=(B, S),
                              softmax_label=(B, S))
    pd = exe_p.arg_dict
    for name, arr in exe_f.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        if "_qkv_" in name:
            arr[:] = np.concatenate(
                [pd[name.replace("_qkv_", f"_{x}_")].asnumpy()
                 for x in ("q", "k", "v")], axis=0)
        else:
            arr[:] = pd[name].asnumpy()

    toks = rng.randint(0, V, (B, S)).astype(np.float32)
    exe_p.arg_dict["data"][:] = toks
    exe_f.arg_dict["data"][:] = toks
    exe_p.forward(is_train=False)
    exe_f.forward(is_train=False)
    np.testing.assert_allclose(exe_f.outputs[0].asnumpy(),
                               exe_p.outputs[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_gpt_attn_layout_bshd_matches_bhsd():
    """attn_layout='bshd' removes the per-layer activation transposes;
    same params must give the same loss/gradients as the default."""
    vocab, seq_len = 97, 32
    common = dict(num_layers=2, d_model=32, num_heads=4)
    a = mx.models.gpt(vocab, seq_len, **common)
    b = mx.models.gpt(vocab, seq_len, attn_layout="bshd", **common)
    assert a.list_arguments() == b.list_arguments()  # same checkpoint

    rng = np.random.RandomState(0)
    data = rng.randint(0, vocab, (2, seq_len))
    label = rng.randint(0, vocab, (2, seq_len)).astype(np.float32)

    def run(net):
        exe = net.simple_bind(mx.cpu(), data=(2, seq_len),
                              softmax_label=(2, seq_len),
                              type_dict={"data": np.int32})
        for name, arr in exe.arg_dict.items():
            if name == "data":
                arr[:] = data
            elif name == "softmax_label":
                arr[:] = label
            else:
                arr[:] = rng2.uniform(-0.1, 0.1, arr.shape)
        out = exe.forward(is_train=True)[0].asnumpy()
        exe.backward()
        return out, {k: g.asnumpy() for k, g in exe.grad_dict.items()
                     if k not in ("data", "softmax_label")}

    rng2 = np.random.RandomState(1)
    out_a, g_a = run(a)
    rng2 = np.random.RandomState(1)
    out_b, g_b = run(b)
    np.testing.assert_allclose(out_b, out_a, atol=2e-5, rtol=1e-4)
    for k in g_a:
        np.testing.assert_allclose(g_b[k], g_a[k], atol=2e-4, rtol=2e-3,
                                   err_msg=k)


def test_gpt_bshd_removes_activation_transposes():
    """The structural claim: the bshd model's graph has NO SwapAxis
    (BSHD<->BHSD shuffle) nodes — the bhsd model has 4 per layer
    (q/k/v on the way in, attention output on the way out).  (On
    TPU the flash kernel consumes BSHD natively; the HLO-level transpose
    audit lives in BENCH_NOTES.md and the BENCH_ATTN_LAYOUT sweep
    point measures the effect on chip.)"""

    def count_swaps(attn_layout):
        net = mx.models.gpt(211, 32, num_layers=3, d_model=32, num_heads=4,
                            attn_layout=attn_layout)
        return sum(1 for n in net._topo()
                   if not n.is_variable and n.op.name == "SwapAxis")

    assert count_swaps("bhsd") == 12   # 4 per layer (q, k, v in; out)
    assert count_swaps("bshd") == 0
