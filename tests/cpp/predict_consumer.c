/* C consumer of the predict mini-API (MXTPUPred*): load a checkpoint
 * (symbol JSON + param blob) exported by the Python side, run a forward
 * pass from pure C, and print the outputs for the harness to compare.
 *
 * Usage: predict_consumer <symbol.json> <blob.params> <batch> <dim>
 * Reads <batch>*<dim> floats from stdin, prints outputs one per line. */
#include <stdio.h>
#include <stdlib.h>
#include "mxtpu/c_api.h"

static char* read_file(const char* path, long* out_size) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(n + 1);
  if (fread(buf, 1, n, f) != (size_t)n) { fclose(f); free(buf); return NULL; }
  buf[n] = 0;
  fclose(f);
  if (out_size) *out_size = n;
  return buf;
}

int main(int argc, char** argv) {
  if (argc != 5) { fprintf(stderr, "usage: %s json params batch dim\n", argv[0]); return 2; }
  long json_size = 0, blob_size = 0;
  char* json = read_file(argv[1], &json_size);
  char* blob = read_file(argv[2], &blob_size);
  if (!json || !blob) { fprintf(stderr, "read failed\n"); return 2; }
  unsigned batch = (unsigned)atoi(argv[3]), dim = (unsigned)atoi(argv[4]);

  const char* keys[] = {"data"};
  unsigned int indptr[] = {0, 2};
  unsigned int shape[] = {batch, dim};
  PredictorHandle h = NULL;
  if (MXTPUPredCreate(json, blob, (unsigned long)blob_size, 1, 0,
                      1, keys, indptr, shape, &h) != 0) {
    fprintf(stderr, "create failed: %s\n", MXTPUGetLastError());
    return 1;
  }

  unsigned n_in = batch * dim;
  float* in = (float*)malloc(n_in * sizeof(float));
  for (unsigned i = 0; i < n_in; ++i)
    if (scanf("%f", &in[i]) != 1) { fprintf(stderr, "stdin short\n"); return 2; }
  if (MXTPUPredSetInput(h, "data", in, n_in) != 0) {
    fprintf(stderr, "set_input failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  if (MXTPUPredForward(h) != 0) {
    fprintf(stderr, "forward failed: %s\n", MXTPUGetLastError());
    return 1;
  }

  unsigned ndim = 0;
  if (MXTPUPredGetOutputShape(h, 0, NULL, &ndim) != 0 || ndim == 0) {
    fprintf(stderr, "shape failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  unsigned* oshape = (unsigned*)malloc(ndim * sizeof(unsigned));
  MXTPUPredGetOutputShape(h, 0, oshape, &ndim);
  unsigned total = 1;
  for (unsigned i = 0; i < ndim; ++i) total *= oshape[i];

  float* out = (float*)malloc(total * sizeof(float));
  if (MXTPUPredGetOutput(h, 0, out, total) != 0) {
    fprintf(stderr, "get_output failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  for (unsigned i = 0; i < total; ++i) printf("%.6f\n", out[i]);
  MXTPUPredFree(h);
  free(json); free(blob); free(in); free(oshape); free(out);
  return 0;
}
