/* Randomized workload-equivalence stress for the native dependency
 * engine — the pure-C++ analog of the reference's
 * tests/cpp/threaded_engine_test.cc (GenerateWorkload + serial-vs-
 * threaded comparison), driven through include/mxtpu/c_api.h.
 *
 * Each op reads a random set of vars and writes one var; the payload
 * applies a deterministic update to a shared slot array.  Running the
 * same workload serially and through the threaded engine must give
 * identical final state (the engine's read/write ordering guarantee).
 * Prints ENGINE_STRESS_OK on success. */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "mxtpu/c_api.h"

#define N_VARS 24
#define N_OPS 600
#define MAX_READS 4

static double slots[N_VARS];

typedef struct {
  int writes;              /* var index written */
  int reads[MAX_READS];    /* var indices read */
  int n_reads;
  double coef;
} OpSpec;

static OpSpec ops[N_OPS];

/* deterministic xorshift so both runs see the same workload */
static uint64_t rng_state = 0x9e3779b97f4a7c15ull;
static uint64_t xrand(void) {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

static void apply_op(void* payload) {
  OpSpec* op = (OpSpec*)payload;
  double acc = 1.0;
  for (int i = 0; i < op->n_reads; ++i) acc += slots[op->reads[i]];
  slots[op->writes] = slots[op->writes] * 0.5 + acc * op->coef;
}

static void gen_workload(void) {
  for (int i = 0; i < N_OPS; ++i) {
    ops[i].writes = (int)(xrand() % N_VARS);
    ops[i].n_reads = 1 + (int)(xrand() % MAX_READS);
    for (int r = 0; r < ops[i].n_reads; ++r) {
      /* no var may appear twice across the const+mutable sets
       * (engine CheckDuplicate contract): skip the write var and
       * re-draw on collision with an earlier read */
      int v, dup;
      do {
        v = (int)(xrand() % (N_VARS - 1));
        if (v >= ops[i].writes) v += 1;
        dup = 0;
        for (int p = 0; p < r; ++p)
          if (ops[i].reads[p] == v) dup = 1;
      } while (dup);
      ops[i].reads[r] = v;
    }
    ops[i].coef = (double)(xrand() % 1000) / 1000.0 - 0.5;
  }
}

int main(void) {
  gen_workload();

  /* serial reference run */
  double expected[N_VARS];
  for (int i = 0; i < N_VARS; ++i) slots[i] = (double)i;
  for (int i = 0; i < N_OPS; ++i) apply_op(&ops[i]);
  for (int i = 0; i < N_VARS; ++i) expected[i] = slots[i];

  /* threaded engine run over the same workload */
  for (int trial = 0; trial < 3; ++trial) {
    EngineHandle eng = MXTPUEngineCreate(4, 1);
    if (!eng) { fprintf(stderr, "engine create failed\n"); return 1; }
    VarHandle vars[N_VARS];
    for (int i = 0; i < N_VARS; ++i) {
      vars[i] = MXTPUEngineNewVar(eng);
      slots[i] = (double)i;
    }
    for (int i = 0; i < N_OPS; ++i) {
      VarHandle reads[MAX_READS];
      for (int r = 0; r < ops[i].n_reads; ++r)
        reads[r] = vars[ops[i].reads[r]];
      VarHandle write = vars[ops[i].writes];
      MXTPUEnginePush(eng, apply_op, &ops[i], reads, ops[i].n_reads,
                      &write, 1, /*prop=*/(int)(i % 2));
    }
    MXTPUEngineWaitForAll(eng);
    if (MXTPUEnginePending(eng) != 0) {
      fprintf(stderr, "pending != 0 after WaitForAll\n");
      return 1;
    }
    for (int i = 0; i < N_VARS; ++i) {
      double diff = slots[i] - expected[i];
      if (diff < 0) diff = -diff;
      if (diff > 1e-9) {
        fprintf(stderr, "trial %d: slot %d mismatch %f vs %f\n",
                trial, i, slots[i], expected[i]);
        return 1;
      }
    }
    MXTPUEngineFree(eng);
  }
  printf("ENGINE_STRESS_OK\n");
  return 0;
}
