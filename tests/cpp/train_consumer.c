/* Pure-C end-to-end training through the mxtpu C ABI — the proof that
 * a non-Python frontend can build, train and evaluate a model, the role
 * the reference's C API plays for its R/Scala/Matlab frontends
 * (reference src/c_api/c_api.cc:956-1110 executor surface;
 * tests/cpp/ unittest style).
 *
 * Builds LeNet with MXTPUSymbolCreateAtomicSymbol + Compose, reads an
 * MNIST-format idx pair through MXTPUDataIterCreate("MNISTIter"),
 * binds an executor, and trains with a KVStore("local") carrying a
 * server-side SGD optimizer: forward / backward / push(grad) /
 * pull(weight) per batch.  Asserts train accuracy and prints
 * C_TRAIN_OK.
 *
 * Usage: train_consumer <images.idx> <labels.idx> <batch> <epochs>
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHK(call)                                                  \
  do {                                                             \
    if ((call) != 0) {                                             \
      fprintf(stderr, "FAIL %s:%d: %s\n  last_error: %s\n",        \
              __FILE__, __LINE__, #call, MXTPUGetLastError());     \
      exit(1);                                                     \
    }                                                              \
  } while (0)

#define MAX_ARGS 32

/* CreateAtomicSymbol + positional Compose in one step. */
static SymbolHandle make_op(const char* op, const char* name,
                            SymbolHandle* inputs, int n_in,
                            const char** pk, const char** pv, int np) {
  SymbolHandle s;
  CHK(MXTPUSymbolCreateAtomicSymbol(op, np, pk, pv, &s));
  CHK(MXTPUSymbolCompose(s, name, n_in, NULL, inputs));
  return s;
}

static float frand(void) { return (float)rand() / (float)RAND_MAX; }

/* C-side custom optimizer (MXTPUKVStoreSetUpdater): plain SGD computed
 * in this process, updating the store's weight in place. */
static void c_sgd_updater(int key, NDArrayHandle recv, NDArrayHandle local,
                          void* handle) {
  float lr = *(float*)handle;
  uint32_t nd, shape[MXTPU_MAX_NDIM];
  (void)key;
  CHK(MXTPUNDArrayGetShape(local, &nd, shape));
  uint64_t sz = 1;
  for (uint32_t i = 0; i < nd; ++i) sz *= shape[i];
  float* w = (float*)malloc(sz * 4);
  float* g = (float*)malloc(sz * 4);
  CHK(MXTPUNDArraySyncCopyToCPU(local, w, sz * 4));
  CHK(MXTPUNDArraySyncCopyToCPU(recv, g, sz * 4));
  for (uint64_t i = 0; i < sz; ++i) w[i] -= lr * g[i];
  CHK(MXTPUNDArraySyncCopyFromCPU(local, w, sz * 4));
  free(w);
  free(g);
}

/* Exercise the extended surface: views, context, version, C updater. */
static void extended_surface_check(void) {
  const char* version;
  CHK(MXTPUGetVersion(&version));
  uint32_t shp[2] = {4, 2};
  NDArrayHandle a;
  CHK(MXTPUNDArrayCreate(shp, 2, 0, 1, 0, &a));
  float vals[8] = {0, 1, 2, 3, 4, 5, 6, 7};
  CHK(MXTPUNDArraySyncCopyFromCPU(a, vals, sizeof vals));
  NDArrayHandle row, sl, rs;
  CHK(MXTPUNDArrayAt(a, 2, &row));
  float rbuf[2];
  CHK(MXTPUNDArraySyncCopyToCPU(row, rbuf, sizeof rbuf));
  if (rbuf[0] != 4 || rbuf[1] != 5) { fprintf(stderr, "FAIL At\n"); exit(1); }
  CHK(MXTPUNDArraySlice(a, 1, 3, &sl));
  uint32_t nd, sshape[MXTPU_MAX_NDIM];
  CHK(MXTPUNDArrayGetShape(sl, &nd, sshape));
  if (nd != 2 || sshape[0] != 2) { fprintf(stderr, "FAIL Slice\n"); exit(1); }
  uint32_t nshape[1] = {8};
  CHK(MXTPUNDArrayReshape(a, 1, nshape, &rs));
  int devt, devi;
  CHK(MXTPUNDArrayGetContext(a, &devt, &devi));
  if (devt != 1) { fprintf(stderr, "FAIL ctx\n"); exit(1); }

  /* kvstore with a C-implemented SGD updater */
  KVStoreHandle kv;
  CHK(MXTPUKVStoreCreate("local", &kv));
  static float lr = 0.5f;
  CHK(MXTPUKVStoreSetUpdater(kv, c_sgd_updater, &lr));
  uint32_t wshp[1] = {4};
  NDArrayHandle w, grad, out;
  CHK(MXTPUNDArrayCreate(wshp, 1, 0, 1, 0, &w));
  CHK(MXTPUNDArrayCreate(wshp, 1, 0, 1, 0, &grad));
  CHK(MXTPUNDArrayCreate(wshp, 1, 0, 1, 0, &out));
  float winit[4] = {1, 2, 3, 4}, gval[4] = {1, 1, 1, 1};
  CHK(MXTPUNDArraySyncCopyFromCPU(w, winit, sizeof winit));
  CHK(MXTPUNDArraySyncCopyFromCPU(grad, gval, sizeof gval));
  int key0 = 0;
  CHK(MXTPUKVStoreInit(kv, 1, &key0, &w));
  CHK(MXTPUKVStorePush(kv, 1, &key0, &grad, 0));
  CHK(MXTPUKVStorePull(kv, 1, &key0, &out, 0));
  float got[4];
  CHK(MXTPUNDArraySyncCopyToCPU(out, got, sizeof got));
  for (int i = 0; i < 4; ++i)
    if (got[i] != winit[i] - 0.5f) {
      fprintf(stderr, "FAIL C updater: got[%d]=%f\n", i, got[i]);
      exit(1);
    }
  CHK(MXTPUNDArrayFree(a));
  CHK(MXTPUNDArrayFree(row));
  CHK(MXTPUNDArrayFree(sl));
  CHK(MXTPUNDArrayFree(rs));
  CHK(MXTPUNDArrayFree(w));
  CHK(MXTPUNDArrayFree(grad));
  CHK(MXTPUNDArrayFree(out));
  CHK(MXTPUKVStoreFree(kv));

  /* raw-bytes roundtrip */
  uint32_t rshp[2] = {2, 3};
  NDArrayHandle ra, rb;
  CHK(MXTPUNDArrayCreate(rshp, 2, 0, 1, 0, &ra));
  float rv[6] = {1, 2, 3, 4, 5, 6};
  CHK(MXTPUNDArraySyncCopyFromCPU(ra, rv, sizeof rv));
  uint64_t blob_n;
  const char* blob;
  CHK(MXTPUNDArraySaveRawBytes(ra, &blob_n, &blob));
  CHK(MXTPUNDArrayLoadFromRawBytes(blob, blob_n, 1, 0, &rb));
  float rv2[6];
  CHK(MXTPUNDArraySyncCopyToCPU(rb, rv2, sizeof rv2));
  for (int i = 0; i < 6; ++i)
    if (rv2[i] != rv[i]) { fprintf(stderr, "FAIL raw\n"); exit(1); }
  CHK(MXTPUNDArrayWaitToRead(ra));
  CHK(MXTPUNDArrayFree(ra));
  CHK(MXTPUNDArrayFree(rb));

  /* imperative optimizer: one SGD step */
  OptimizerHandle opt;
  {
    const char* k[] = {"learning_rate"};
    const char* v[] = {"0.5"};
    CHK(MXTPUOptimizerCreateOptimizer("sgd", 1, k, v, &opt));
  }
  NDArrayHandle ow, og;
  uint32_t oshp[1] = {3};
  CHK(MXTPUNDArrayCreate(oshp, 1, 0, 1, 0, &ow));
  CHK(MXTPUNDArrayCreate(oshp, 1, 0, 1, 0, &og));
  float wv[3] = {1, 1, 1}, gv[3] = {2, 2, 2};
  CHK(MXTPUNDArraySyncCopyFromCPU(ow, wv, sizeof wv));
  CHK(MXTPUNDArraySyncCopyFromCPU(og, gv, sizeof gv));
  CHK(MXTPUOptimizerUpdate(opt, 0, ow, og));
  float wafter[3];
  CHK(MXTPUNDArraySyncCopyToCPU(ow, wafter, sizeof wafter));
  if (wafter[0] >= 1.0f) { fprintf(stderr, "FAIL opt update\n"); exit(1); }
  CHK(MXTPUOptimizerFree(opt));
  CHK(MXTPUNDArrayFree(ow));
  CHK(MXTPUNDArrayFree(og));

  /* recordio writer/reader roundtrip */
  const char* rec_path = "/tmp/mxtpu_c_rec_test.rec";
  RecordIOHandle wr, rd;
  CHK(MXTPURecordIOWriterCreate(rec_path, &wr));
  CHK(MXTPURecordIOWriterWriteRecord(wr, "hello", 5));
  CHK(MXTPURecordIOWriterWriteRecord(wr, "worlds!", 7));
  uint64_t pos;
  CHK(MXTPURecordIOWriterTell(wr, &pos));
  CHK(MXTPURecordIOClose(wr));
  CHK(MXTPURecordIOReaderCreate(rec_path, &rd));
  uint64_t rn;
  const char* rec_buf;
  CHK(MXTPURecordIOReaderReadRecord(rd, &rn, &rec_buf));
  if (rn != 5 || strncmp(rec_buf, "hello", 5)) {
    fprintf(stderr, "FAIL rec read\n"); exit(1);
  }
  CHK(MXTPURecordIOReaderSeek(rd));
  CHK(MXTPURecordIOReaderReadRecord(rd, &rn, &rec_buf));
  if (rn != 5) { fprintf(stderr, "FAIL rec seek\n"); exit(1); }
  CHK(MXTPURecordIOClose(rd));

  /* symbol group/name/infer-type */
  SymbolHandle va, vb, grp;
  CHK(MXTPUSymbolCreateVariable("a", &va));
  CHK(MXTPUSymbolCreateVariable("b", &vb));
  SymbolHandle pair[2] = {va, vb};
  CHK(MXTPUSymbolCreateGroup(2, pair, &grp));
  int nouts_sz;
  const char** outs_names;
  CHK(MXTPUSymbolListOutputs(grp, &nouts_sz, &outs_names));
  if (nouts_sz != 2) { fprintf(stderr, "FAIL group\n"); exit(1); }
  const char* nm;
  CHK(MXTPUSymbolGetName(va, &nm));
  if (strcmp(nm, "a")) { fprintf(stderr, "FAIL name\n"); exit(1); }
  CHK(MXTPUSymbolFree(va));
  CHK(MXTPUSymbolFree(vb));
  CHK(MXTPUSymbolFree(grp));

  /* roles + lifecycle */
  int is_worker = 0;
  CHK(MXTPUKVStoreIsWorkerNode(&is_worker));
  if (!is_worker) { fprintf(stderr, "FAIL role\n"); exit(1); }
  CHK(MXTPUNotifyShutdown());
  fprintf(stderr, "extended C surface ok (version %s)\n", version);
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s img.idx lab.idx batch epochs\n", argv[0]);
    return 2;
  }
  const char* img_path = argv[1];
  const char* lab_path = argv[2];
  int batch = atoi(argv[3]);
  int epochs = atoi(argv[4]);
  srand(7);
  CHK(MXTPURandomSeed(7));
  extended_surface_check();

  /* ---- LeNet-style symbol ---- */
  SymbolHandle data, net;
  CHK(MXTPUSymbolCreateVariable("data", &data));
  {
    const char* k[] = {"kernel", "num_filter"};
    const char* v[] = {"(3, 3)", "8"};
    net = make_op("Convolution", "conv1", &data, 1, k, v, 2);
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"relu"};
    net = make_op("Activation", "relu1", &net, 1, k, v, 1);
  }
  {
    const char* k[] = {"kernel", "stride", "pool_type"};
    const char* v[] = {"(2, 2)", "(2, 2)", "max"};
    net = make_op("Pooling", "pool1", &net, 1, k, v, 3);
  }
  net = make_op("Flatten", "flat", &net, 1, NULL, NULL, 0);
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"64"};
    net = make_op("FullyConnected", "fc1", &net, 1, k, v, 1);
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"relu"};
    net = make_op("Activation", "relu2", &net, 1, k, v, 1);
  }
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"10"};
    net = make_op("FullyConnected", "fc2", &net, 1, k, v, 1);
  }
  {
    /* batch normalization of the loss grad keeps SGD step size
     * batch-size independent (reference softmax_output-inl.h) */
    const char* k[] = {"normalization"};
    const char* v[] = {"batch"};
    net = make_op("SoftmaxOutput", "softmax", &net, 1, k, v, 1);
  }

  /* round-trip the graph through JSON (MXSymbolCreateFromJSON path) */
  const char* json;
  CHK(MXTPUSymbolSaveToJSON(net, &json));
  SymbolHandle net2;
  CHK(MXTPUSymbolCreateFromJSON(json, &net2));
  CHK(MXTPUSymbolFree(net));
  net = net2;

  int n_args;
  const char** arg_names;
  CHK(MXTPUSymbolListArguments(net, &n_args, &arg_names));
  if (n_args > MAX_ARGS) { fprintf(stderr, "too many args\n"); return 1; }

  /* ---- shapes ---- */
  uint32_t dshape[] = {(uint32_t)batch, 1, 28, 28};
  const char* skeys[] = {"data"};
  uint32_t indptr[] = {0, 4};
  uint32_t in_size, out_size, aux_size;
  const uint32_t *in_ndim, *out_ndim, *aux_ndim;
  const uint32_t **in_data, **out_data, **aux_data;
  int complete;
  CHK(MXTPUSymbolInferShape(net, 1, skeys, indptr, dshape, &in_size,
                            &in_ndim, &in_data, &out_size, &out_ndim,
                            &out_data, &aux_size, &aux_ndim, &aux_data,
                            &complete));
  if (!complete || (int)in_size != n_args) {
    fprintf(stderr, "FAIL infer_shape: complete=%d in_size=%u n_args=%d\n",
            complete, in_size, n_args);
    return 1;
  }

  /* ---- arg + grad arrays; Xavier-ish C-side init ---- */
  NDArrayHandle args[MAX_ARGS], grads[MAX_ARGS];
  uint32_t reqs[MAX_ARGS];
  uint64_t sizes[MAX_ARGS];
  int is_param[MAX_ARGS];
  for (int i = 0; i < n_args; ++i) {
    uint64_t sz = 1;
    for (uint32_t d = 0; d < in_ndim[i]; ++d) sz *= in_data[i][d];
    sizes[i] = sz;
    CHK(MXTPUNDArrayCreate(in_data[i], in_ndim[i], 0, 1, 0, &args[i]));
    is_param[i] = strcmp(arg_names[i], "data") != 0 &&
                  strcmp(arg_names[i], "softmax_label") != 0;
    if (is_param[i]) {
      float* buf = (float*)malloc(sz * 4);
      for (uint64_t j = 0; j < sz; ++j)
        buf[j] = (frand() * 2.f - 1.f) * 0.05f;
      CHK(MXTPUNDArraySyncCopyFromCPU(args[i], buf, sz * 4));
      free(buf);
      CHK(MXTPUNDArrayCreate(in_data[i], in_ndim[i], 0, 1, 0, &grads[i]));
      reqs[i] = 1;
    } else {
      grads[i] = NULL;
      reqs[i] = 0;
    }
  }

  /* ---- executor ---- */
  ExecutorHandle exec;
  CHK(MXTPUExecutorBind(net, 1, 0, (uint32_t)n_args, args, grads, reqs, 0,
                        NULL, &exec));

  /* ---- kvstore with server-side SGD ---- */
  KVStoreHandle kv;
  CHK(MXTPUKVStoreCreate("local", &kv));
  {
    const char* k[] = {"learning_rate", "momentum"};
    const char* v[] = {"0.1", "0.9"};
    CHK(MXTPUKVStoreSetOptimizer(kv, "sgd", 2, k, v));
  }
  for (int i = 0; i < n_args; ++i)
    if (is_param[i]) CHK(MXTPUKVStoreInit(kv, 1, &i, &args[i]));

  /* ---- data ---- */
  DataIterHandle it;
  {
    char bs[16];
    snprintf(bs, sizeof bs, "%d", batch);
    const char* k[] = {"image", "label", "batch_size", "shuffle"};
    const char* v[] = {img_path, lab_path, bs, "True"};
    CHK(MXTPUDataIterCreate("MNISTIter", 4, k, v, &it));
  }

  int data_idx = -1, label_idx = -1;
  for (int i = 0; i < n_args; ++i) {
    if (strcmp(arg_names[i], "data") == 0) data_idx = i;
    if (strcmp(arg_names[i], "softmax_label") == 0) label_idx = i;
  }
  if (data_idx < 0 || label_idx < 0) { fprintf(stderr, "no data arg\n"); return 1; }

  float* dbuf = (float*)malloc(sizes[data_idx] * 4);
  float* lbuf = (float*)malloc(sizes[label_idx] * 4);
  float* obuf = (float*)malloc((uint64_t)batch * 10 * 4);

  /* ---- train ---- */
  for (int e = 0; e < epochs; ++e) {
    CHK(MXTPUDataIterBeforeFirst(it));
    for (;;) {
      int more;
      CHK(MXTPUDataIterNext(it, &more));
      if (!more) break;
      NDArrayHandle bd, bl;
      CHK(MXTPUDataIterGetData(it, &bd));
      CHK(MXTPUDataIterGetLabel(it, &bl));
      CHK(MXTPUNDArraySyncCopyToCPU(bd, dbuf, sizes[data_idx] * 4));
      CHK(MXTPUNDArraySyncCopyToCPU(bl, lbuf, sizes[label_idx] * 4));
      CHK(MXTPUNDArraySyncCopyFromCPU(args[data_idx], dbuf,
                                      sizes[data_idx] * 4));
      CHK(MXTPUNDArraySyncCopyFromCPU(args[label_idx], lbuf,
                                      sizes[label_idx] * 4));
      CHK(MXTPUNDArrayFree(bd));
      CHK(MXTPUNDArrayFree(bl));
      CHK(MXTPUExecutorForward(exec, 1));
      CHK(MXTPUExecutorBackward(exec, 0, NULL));
      for (int i = 0; i < n_args; ++i) {
        if (!is_param[i]) continue;
        CHK(MXTPUKVStorePush(kv, 1, &i, &grads[i], -i));
        CHK(MXTPUKVStorePull(kv, 1, &i, &args[i], -i));
      }
    }
  }

  /* ---- evaluate on the training set ---- */
  long correct = 0, total = 0;
  CHK(MXTPUDataIterBeforeFirst(it));
  for (;;) {
    int more;
    CHK(MXTPUDataIterNext(it, &more));
    if (!more) break;
    NDArrayHandle bd, bl;
    CHK(MXTPUDataIterGetData(it, &bd));
    CHK(MXTPUDataIterGetLabel(it, &bl));
    CHK(MXTPUNDArraySyncCopyToCPU(bd, dbuf, sizes[data_idx] * 4));
    CHK(MXTPUNDArraySyncCopyToCPU(bl, lbuf, sizes[label_idx] * 4));
    CHK(MXTPUNDArraySyncCopyFromCPU(args[data_idx], dbuf,
                                    sizes[data_idx] * 4));
    CHK(MXTPUNDArrayFree(bd));
    CHK(MXTPUNDArrayFree(bl));
    CHK(MXTPUExecutorForward(exec, 0));
    NDArrayHandle outs[4];
    int n_out;
    CHK(MXTPUExecutorOutputs(exec, 4, outs, &n_out));
    uint32_t ondim, oshape[MXTPU_MAX_NDIM];
    CHK(MXTPUNDArrayGetShape(outs[0], &ondim, oshape));
    if (ondim != 2 || (int)oshape[0] != batch || oshape[1] != 10) {
      fprintf(stderr, "bad output shape\n");
      return 1;
    }
    CHK(MXTPUNDArraySyncCopyToCPU(outs[0], obuf,
                                  (uint64_t)batch * 10 * 4));
    for (int n = 0; n < n_out; ++n) CHK(MXTPUNDArrayFree(outs[n]));
    for (int b = 0; b < batch; ++b) {
      int best = 0;
      for (int c = 1; c < 10; ++c)
        if (obuf[b * 10 + c] > obuf[b * 10 + best]) best = c;
      correct += best == (int)lbuf[b];
      total += 1;
    }
  }
  double acc = (double)correct / (double)total;
  fprintf(stderr, "train accuracy: %.3f (%ld/%ld)\n", acc, correct, total);
  if (acc < 0.85) {
    fprintf(stderr, "FAIL accuracy %.3f < 0.85\n", acc);
    return 1;
  }

  free(dbuf);
  free(lbuf);
  free(obuf);
  CHK(MXTPUDataIterFree(it));
  CHK(MXTPUKVStoreFree(kv));
  CHK(MXTPUExecutorFree(exec));
  CHK(MXTPUSymbolFree(net));
  for (int i = 0; i < n_args; ++i) {
    CHK(MXTPUNDArrayFree(args[i]));
    if (grads[i]) CHK(MXTPUNDArrayFree(grads[i]));
  }
  printf("C_TRAIN_OK %.3f\n", acc);
  return 0;
}
