/* Deploy-artifact consumer: compiled against ONLY the amalgamation
 * pair + libm (no libmxtpu, no Python): proves "one file + artifact
 * runs without the Python tree" (reference amalgamation/ contract).
 *
 * Usage: amalgamation_consumer model.mxa input.npy output.npy
 * Reads a float32 C-order .npy batch, runs the graph, writes the
 * output as .npy v1 for the test harness to compare against the
 * Python predictor. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../../amalgamation/mxtpu_predict.h"

static float* read_npy(const char* path, int64_t* dims, int* ndim) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  unsigned char hdr[10];
  if (fread(hdr, 1, 10, f) != 10 || memcmp(hdr, "\x93NUMPY", 6) != 0) {
    fclose(f);
    return NULL;
  }
  unsigned hlen = hdr[8] | (hdr[9] << 8);
  char* h = (char*)malloc(hlen + 1);
  if (fread(h, 1, hlen, f) != hlen) {
    free(h);
    fclose(f);
    return NULL;
  }
  h[hlen] = 0;
  if (!strstr(h, "<f4")) {
    fprintf(stderr, "input must be float32\n");
    free(h);
    fclose(f);
    return NULL;
  }
  char* s = strchr(strstr(h, "'shape'"), '(');
  *ndim = 0;
  int64_t size = 1;
  char* q = s + 1;
  while (*q && *q != ')') {
    while (*q == ' ' || *q == ',') ++q;
    if (*q == ')' || !*q) break;
    if (*ndim >= MXA_MAX_NDIM) {
      fprintf(stderr, "input ndim > %d unsupported\n", MXA_MAX_NDIM);
      free(h);
      fclose(f);
      return NULL;
    }
    char* before = q;
    int64_t v = strtoll(q, &q, 10);
    if (q == before) break; /* malformed header: no spin, no bogus dim */
    dims[(*ndim)++] = v;
    size *= v;
  }
  free(h);
  float* data = (float*)malloc(sizeof(float) * (size_t)size);
  if (fread(data, sizeof(float), (size_t)size, f) != (size_t)size) {
    free(data);
    fclose(f);
    return NULL;
  }
  fclose(f);
  return data;
}

static int write_npy(const char* path, const mxa_tensor* t) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  char shape[256] = "";
  size_t used = 0;
  for (int i = 0; i < t->ndim; ++i) {
    int w = snprintf(shape + used, sizeof(shape) - used, "%lld,",
                     (long long)t->dims[i]);
    if (w < 0 || used + (size_t)w >= sizeof(shape)) {
      fclose(f);
      return -1;
    }
    used += (size_t)w;
  }
  char dict[512];
  snprintf(dict, sizeof(dict),
           "{'descr': '<f4', 'fortran_order': False, 'shape': (%s), }",
           shape);
  size_t dlen = strlen(dict);
  /* header (magic+len+dict+pad) must be 64-aligned and END in \n:
   * at least one pad byte is always needed for the newline */
  size_t pad = 64 - (10 + dlen) % 64;
  if (pad == 0) pad = 64;
  unsigned hlen = (unsigned)(dlen + pad);
  fwrite("\x93NUMPY\x01\x00", 1, 8, f);
  fputc(hlen & 0xff, f);
  fputc((hlen >> 8) & 0xff, f);
  fwrite(dict, 1, dlen, f);
  for (size_t i = 0; i + 1 < pad; ++i) fputc(' ', f);
  fputc('\n', f);
  fwrite(t->data, sizeof(float), (size_t)t->size, f);
  fclose(f);
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s model.mxa in.npy out.npy\n", argv[0]);
    return 2;
  }
  mxa_model* m = mxa_load(argv[1]);
  if (!m) {
    fprintf(stderr, "FAIL load: %s\n", mxa_last_error());
    return 1;
  }
  fprintf(stderr, "model input %s ndim=%d\n", mxa_input_name(m),
          mxa_input_ndim(m));
  int64_t dims[MXA_MAX_NDIM];
  int ndim = 0;
  float* data = read_npy(argv[2], dims, &ndim);
  if (!data) {
    fprintf(stderr, "FAIL reading %s\n", argv[2]);
    mxa_free(m);
    return 1;
  }
  mxa_tensor* out = mxa_forward(m, data, dims, ndim);
  if (!out) {
    fprintf(stderr, "FAIL forward: %s\n", mxa_last_error());
    free(data);
    mxa_free(m);
    return 1;
  }
  if (write_npy(argv[3], out) != 0) {
    fprintf(stderr, "FAIL writing %s\n", argv[3]);
    mxa_free_tensor(out);
    free(data);
    mxa_free(m);
    return 1;
  }
  printf("AMALGAMATION_OK %lld\n", (long long)out->size);
  mxa_free_tensor(out);
  mxa_free(m);
  free(data);
  return 0;
}
