#include <stdio.h>
#include <string.h>
#include "mxtpu/c_api.h"

static int ran = 0;
static void op(void* p) { ran = 1; *(int*)p += 41; }

int main(void) {
  /* engine */
  EngineHandle e = MXTPUEngineCreate(2, 1);
  VarHandle v = MXTPUEngineNewVar(e);
  int x = 1;
  MXTPUEnginePush(e, op, &x, NULL, 0, &v, 1, 0);
  MXTPUEngineWaitForAll(e);
  if (!ran || x != 42) { printf("FAIL engine\n"); return 1; }
  MXTPUEngineFree(e);

  /* registry */
  const char* args[] = {"data"};
  const char* pn[] = {"alpha"};
  const char* pt[] = {"float, optional, default=1.0"};
  const char* pd[] = {"scale"};
  if (MXTPURegisterOp("c_test_op", "doc here", args, 1, pn, pt, pd, 1) != 0)
    { printf("FAIL register: %s\n", MXTPUGetLastError()); return 1; }
  int n; const char** names;
  MXTPUListOps(&n, &names);
  int found = 0;
  for (int i = 0; i < n; ++i) if (!strcmp(names[i], "c_test_op")) found = 1;
  if (!found) { printf("FAIL list\n"); return 1; }
  const char* doc; int na, np2;
  const char **an, **pnn, **ptt, **pdd;
  if (MXTPUGetOpInfo("c_test_op", &doc, &na, &an, &np2, &pnn, &ptt, &pdd) != 0)
    { printf("FAIL info\n"); return 1; }
  if (strcmp(doc, "doc here") || na != 1 || strcmp(an[0], "data") ||
      np2 != 1 || strcmp(ptt[0], pt[0])) { printf("FAIL meta\n"); return 1; }

  /* storage */
  void* p = MXTPUStorageAlloc(1024);
  MXTPUStorageFree(p, 1024);
  void* p2 = MXTPUStorageAlloc(1000);  /* bucket reuse */
  uint64_t a, b, c, h;
  MXTPUStorageStats(&a, &b, &c, &h);
  if (h < 1) { printf("FAIL pool reuse\n"); return 1; }
  MXTPUStorageFree(p2, 1000);
  printf("C_API_OK\n");
  return 0;
}
