// End-to-end training through the header-only C++ frontend
// (include/mxtpu/cpp/mxtpu.hpp) — the second-language-frontend proof:
// builds LeNet, streams MNIST-format idx data through DataIter, trains
// with a KVStore-side SGD optimizer, asserts accuracy.  The program
// never touches Python headers; everything routes through the C ABI
// (reference cpp-package/example/mlp.cpp role).
//
// Usage: cpp_frontend_train <images.idx> <labels.idx> <batch> <epochs>

#include <algorithm>
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include "mxtpu/cpp/mxtpu.hpp"

using namespace mxtpu::cpp;

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr, "usage: %s img.idx lab.idx batch epochs\n",
                 argv[0]);
    return 2;
  }
  const std::string img = argv[1], lab = argv[2];
  const int batch = std::atoi(argv[3]);
  const int epochs = std::atoi(argv[4]);

  try {
    RandomSeed(7);

    // ---- LeNet ----
    Symbol data = Symbol::Variable("data");
    Symbol net = Op("Convolution", {{"kernel", "(3, 3)"},
                                    {"num_filter", "8"}}, {data}, "conv1");
    net = Op("Activation", {{"act_type", "relu"}}, {net}, "relu1");
    net = Op("Pooling", {{"kernel", "(2, 2)"}, {"stride", "(2, 2)"},
                         {"pool_type", "max"}}, {net}, "pool1");
    net = Op("Flatten", {}, {net}, "flat");
    net = Op("FullyConnected", {{"num_hidden", "64"}}, {net}, "fc1");
    net = Op("Activation", {{"act_type", "relu"}}, {net}, "relu2");
    net = Op("FullyConnected", {{"num_hidden", "10"}}, {net}, "fc2");
    net = Op("SoftmaxOutput", {{"normalization", "batch"}}, {net},
             "softmax");

    // JSON round-trip exercises save/load through the frontend
    net = Symbol::FromJSON(net.ToJSON());

    auto arg_names = net.ListArguments();
    auto shapes = net.InferShape(
        {{"data", {static_cast<uint32_t>(batch), 1, 28, 28}}});
    if (!shapes.complete || shapes.arg.size() != arg_names.size())
      throw std::runtime_error("shape inference incomplete");

    // ---- arrays ----
    std::mt19937 rng(7);
    std::uniform_real_distribution<float> dist(-0.05f, 0.05f);
    std::vector<NDArray> args, grads;
    std::vector<GradReq> reqs;
    int data_idx = -1, label_idx = -1;
    for (size_t i = 0; i < arg_names.size(); ++i) {
      args.emplace_back(shapes.arg[i]);
      const bool is_data = arg_names[i] == "data";
      const bool is_label = arg_names[i] == "softmax_label";
      if (is_data) data_idx = static_cast<int>(i);
      if (is_label) label_idx = static_cast<int>(i);
      if (is_data || is_label) {
        grads.emplace_back();  // none
        reqs.push_back(GradReq::kNull);
      } else {
        uint64_t sz = args.back().Size();
        std::vector<float> init(sz);
        for (auto& v : init) v = dist(rng);
        args.back().SyncCopyFromCPU(init);
        grads.emplace_back(shapes.arg[i]);
        reqs.push_back(GradReq::kWrite);
      }
    }

    Executor exec(net, args, grads, reqs);

    KVStore kv("local");
    kv.SetOptimizer("sgd", {{"learning_rate", "0.1"}, {"momentum", "0.9"}});
    for (size_t i = 0; i < args.size(); ++i)
      if (reqs[i] == GradReq::kWrite) kv.Init(static_cast<int>(i), args[i]);

    DataIter it("MNISTIter", {{"image", img}, {"label", lab},
                              {"batch_size", std::to_string(batch)},
                              {"shuffle", "True"}});

    // ---- train ----
    for (int e = 0; e < epochs; ++e) {
      it.Reset();
      while (it.Next()) {
        args[data_idx].SyncCopyFromCPU(it.Data().SyncCopyToCPU());
        args[label_idx].SyncCopyFromCPU(it.Label().SyncCopyToCPU());
        exec.Forward(true);
        exec.Backward();
        for (size_t i = 0; i < args.size(); ++i) {
          if (reqs[i] != GradReq::kWrite) continue;
          kv.Push(static_cast<int>(i), grads[i],
                  -static_cast<int>(i));
          kv.Pull(static_cast<int>(i), &args[i], -static_cast<int>(i));
        }
      }
    }

    // ---- evaluate ----
    long correct = 0, total = 0;
    it.Reset();
    while (it.Next()) {
      args[data_idx].SyncCopyFromCPU(it.Data().SyncCopyToCPU());
      auto labels = it.Label().SyncCopyToCPU();
      exec.Forward(false);
      auto probs = exec.Outputs()[0].SyncCopyToCPU();
      for (int b = 0; b < batch; ++b) {
        int best = static_cast<int>(
            std::max_element(probs.begin() + b * 10,
                             probs.begin() + (b + 1) * 10) -
            (probs.begin() + b * 10));
        correct += best == static_cast<int>(labels[b]);
        ++total;
      }
    }
    double acc = static_cast<double>(correct) / static_cast<double>(total);
    std::fprintf(stderr, "train accuracy: %.3f (%ld/%ld)\n", acc, correct,
                 total);
    if (acc < 0.85) {
      std::fprintf(stderr, "FAIL accuracy %.3f < 0.85\n", acc);
      return 1;
    }
    std::printf("CPP_TRAIN_OK %.3f\n", acc);
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAIL exception: %s\n", e.what());
    return 1;
  }
}
