"""AOT startup subsystem tests (mxnet_tpu/aot/).

CPU-deterministic throughout: the persistent compile cache and export
store both work on the CPU PJRT backend, so the restart story — a
second engine start that loads every bucket program instead of tracing
— is assertable in-process by clearing the shared program cache and
counting compile activity through telemetry.  The cold-vs-warm *wall
time* claim lives in tools/startup_bench.py (contract-tested in
test_bench_contract.py's slow tier); here we pin the *semantics*:
zero fresh traces, zero persistent-cache misses, token-identical
output, and silent fallbacks for missing/stale/corrupt artifacts.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import aot, telemetry
from mxnet_tpu.serve import engine as engine_mod

VOCAB = 89


# -- shared fixtures ---------------------------------------------------------
@pytest.fixture(autouse=True)
def fresh_program_cache():
    """Engines in this module share one model config; the process-wide
    program cache would otherwise leak compiled programs between tests
    and mask the cold paths under test."""
    engine_mod._STEP_CACHE.clear()
    yield


@pytest.fixture
def tel():
    """Recording telemetry for the duration of one test."""
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.disable()
    telemetry.reset()


@pytest.fixture
def compile_cache(tmp_path):
    """Persistent compile cache in a per-test dir; jax config restored
    afterwards so later tests never write into a deleted tmp dir."""
    import jax
    from jax.experimental.compilation_cache import compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    mgr = aot.cache.CompileCacheManager(str(tmp_path / "cc")).enable()
    yield mgr
    jax.config.update("jax_compilation_cache_dir", prev)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      prev_min)
    # drop the memoized cache object: it still points at this test's
    # (deleted) tmp dir and jax would otherwise keep using it
    compilation_cache.reset_cache()


@pytest.fixture(scope="module")
def model():
    """Tiny gpt2-style net + params (same recipe as test_serve)."""
    S = 96
    net = mx.models.gpt(VOCAB, S, num_layers=2, d_model=32, num_heads=4)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(3)
    params = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        scale = 0.35 if name.endswith("weight") else 0.0
        params[name] = (rng.randn(*shp) * scale
                        + (1.0 if name.endswith("gamma") else 0.0)
                        ).astype(np.float32)
    return net, params


def _engine(model, **kw):
    net, params = model
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("max_prefills_per_step", 2)
    return mx.serve.Engine(params, symbol=net, **kw)


def _counts(name):
    snap = telemetry.registry().snapshot().get(name, {"samples": []})
    return {tuple(sorted(s["labels"].items())): s["value"]
            for s in snap["samples"]}


def _total(name, **labels):
    return sum(v for k, v in _counts(name).items()
               if all((lk, lv) in k for lk, lv in labels.items()))


def _serve(eng, prompts, max_new=8):
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run()
    assert all(r.status == "finished" for r in reqs)
    return [r.tokens for r in reqs]


def _prompts(rng=None):
    rng = rng or np.random.RandomState(7)
    return [rng.randint(0, VOCAB, (n,)).astype(np.int32)
            for n in (7, 12, 5)]


# -- compile-cache manager ---------------------------------------------------
def test_cache_manager_wires_jax_and_counts(tel, compile_cache):
    """MXTPU_COMPILE_CACHE wiring: a fresh jit of an already-compiled
    module is served from disk, visible as hit/miss/put counters and
    on-disk entries; the snapshot line is metrics_report-loadable."""
    import jax
    import jax.numpy as jnp

    def build():
        # a FRESH function object per call (same name, same body): the
        # second jit misses every in-process cache but lowers to the
        # identical module, so only the disk cache can satisfy it
        def f(x):
            return jnp.sin(x) @ jnp.cos(x) + jnp.tanh(x)

        return jax.jit(f)

    x = jnp.ones((32, 32), jnp.float32)
    build()(x).block_until_ready()
    misses = _total("mxtpu_compile_cache_misses")
    puts = _total("mxtpu_compile_cache_puts")
    assert misses >= 1 and puts == misses
    st = compile_cache.stats()
    assert st["entries"] >= 1 and st["bytes"] > 0
    build()(x).block_until_ready()
    assert _total("mxtpu_compile_cache_hits") >= 1
    assert _total("mxtpu_compile_cache_misses") == misses

    snap_path = compile_cache.snapshot_to()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import metrics_report

    metrics, _ = metrics_report.load_jsonl(snap_path)
    assert metrics["mxtpu_compile_cache_dir_entries"]["samples"][0][
        "value"] >= 1
    assert "mxtpu_compile_cache_hits" in metrics


def test_cache_manager_eviction_policy(tmp_path):
    """Entry-count eviction drops oldest-access first; a stale jax
    version namespace is pruned wholesale."""
    mgr = aot.cache.CompileCacheManager(str(tmp_path), max_entries=2)
    os.makedirs(mgr.dir, exist_ok=True)
    for i in range(4):
        with open(os.path.join(mgr.dir, f"jit_f{i}-k{i}-cache"), "wb") as f:
            f.write(b"x" * 10)
        with open(os.path.join(mgr.dir, f"jit_f{i}-k{i}-atime"), "wb") as f:
            f.write(int((1000 + i) * 1e9).to_bytes(8, "little"))
    # a sibling version namespace is dropped only once IDLE long enough
    # (a mixed-version fleet mid-rollout keeps both caches warm)
    fresh = os.path.join(str(tmp_path), "jax-9.9.9")
    os.makedirs(fresh)
    with open(os.path.join(fresh, "jit_live-k-cache"), "wb") as f:
        f.write(b"y")
    stale = os.path.join(str(tmp_path), "jax-0.0.1")
    os.makedirs(stale)
    with open(os.path.join(stale, "jit_old-k-cache"), "wb") as f:
        f.write(b"y")
    old = 100.0   # epoch 1970: long past any staleness threshold
    os.utime(os.path.join(stale, "jit_old-k-cache"), (old, old))
    os.utime(stale, (old, old))
    removed = mgr.prune()
    assert removed >= 3              # 2 evictions + the stale namespace
    left = sorted(n for n in os.listdir(mgr.dir) if n.endswith("-cache"))
    assert left == ["jit_f2-k2-cache", "jit_f3-k3-cache"]  # newest kept
    assert not os.path.exists(stale)
    assert os.path.exists(fresh)      # recently-touched namespace kept
    # byte budget: everything over 10 bytes goes, oldest first
    mgr2 = aot.cache.CompileCacheManager(str(tmp_path), max_bytes=10)
    assert mgr2.prune() >= 1
    assert len(mgr2._entries()) == 1


# -- export store ------------------------------------------------------------
def test_export_store_roundtrip_stale_and_corrupt(tel, tmp_path):
    import jax
    import jax.numpy as jnp

    store = aot.ExportStore(str(tmp_path / "aot"))
    fp = aot.fingerprint(subsystem="t", bucket=4)
    assert store.load(fp) is None                      # missing: silent

    def g(x):
        return jnp.tanh(x @ x)

    from mxnet_tpu import jax_compat

    spec = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    exported = jax_compat.export_fn(jax.jit(g), spec)
    path = store.save(fp, exported)
    assert path and os.path.exists(path)
    loaded = store.load(fp)
    assert loaded is not None
    x = np.ones((8, 8), np.float32)
    np.testing.assert_allclose(np.asarray(jax.jit(loaded.call)(x)),
                               np.tanh(x @ x), rtol=1e-6)

    # stale: same file name cannot be produced by a different fp, so
    # simulate a collision by rewriting the header in place
    raw = open(path, "rb").read()
    n = int.from_bytes(raw[8:16], "little")
    other = json.dumps({"fingerprint": dict(fp, bucket=8)},
                       sort_keys=True).encode()
    with open(path, "wb") as f:      # same-length header keeps offsets
        f.write(raw[:8] + len(other).to_bytes(8, "little") + other
                + raw[16 + n:])
    assert store.load(fp) is None
    assert _total("mxtpu_aot_errors_total", kind="stale") == 1

    # corrupt: truncated blob deserializes to None, never raises
    store.save(fp, exported)
    with open(path, "wb") as f:
        f.write(open(path, "rb").read()[:40])
    assert store.load(fp) is None
    assert _total("mxtpu_aot_errors_total", kind="corrupt") == 1


# -- warmup manifests --------------------------------------------------------
def test_manifest_recorder_and_loader(tmp_path, monkeypatch):
    path = str(tmp_path / "m.jsonl")
    rec = aot.ManifestRecorder("spec-a", path)
    assert rec.record("prefill", 16) is True
    assert rec.record("prefill", 16) is False          # deduped
    rec.record("decode", 4)
    assert [e["bucket"] for e in rec.entries()] == [16, 4]

    # a second engine's recorder appends to the same file
    aot.ManifestRecorder("spec-b", path).record("decode", 8)
    with open(path, "a") as f:
        f.write("not json\n")                          # torn tail line
    all_entries = aot.load_manifest(path)
    assert len(all_entries) == 3                       # junk skipped
    mine = aot.load_manifest(path, spec_digest="spec-a")
    assert [(e["kind"], e["bucket"]) for e in mine] \
        == [("prefill", 16), ("decode", 4)]            # foreign spec out

    monkeypatch.setenv(aot.warmup.ENV_MANIFEST, path)
    assert len(aot.load_manifest(None)) == 3           # env resolution
    monkeypatch.delenv(aot.warmup.ENV_MANIFEST)
    assert aot.load_manifest(None) == []
    assert aot.load_manifest(str(tmp_path / "absent.jsonl")) == []


def test_engine_records_manifest_to_env_path(tel, tmp_path, monkeypatch,
                                             model):
    path = str(tmp_path / "traffic.jsonl")
    monkeypatch.setenv(aot.warmup.ENV_MANIFEST, path)
    eng = _engine(model)
    _serve(eng, _prompts())
    eng.shutdown()
    on_disk = aot.load_manifest(path)
    assert sorted((e["kind"], e["bucket"]) for e in on_disk) \
        == sorted((e["kind"], e["bucket"]) for e in eng.manifest())
    assert len(on_disk) >= 3

    # warmup() with no argument replays the env manifest — and replay
    # must not re-append what it just read
    size = os.path.getsize(path)
    engine_mod._STEP_CACHE.clear()
    eng2 = _engine(model)
    assert eng2.warmup() == len(on_disk)
    assert os.path.getsize(path) == size
    eng2.shutdown()


# -- the restart story -------------------------------------------------------
def test_engine_cold_warm_restart_zero_fresh_traces(tel, compile_cache,
                                                    tmp_path, model):
    """The acceptance gate: build an engine, capture its manifest, tear
    everything down (shared program cache included), and assert the
    second construction + warmup() traces NOTHING — every program loads
    from the export store, every XLA compile hits the persistent cache
    — while decoding token-identical output."""
    aot_dir = str(tmp_path / "aot")
    prompts = _prompts()

    cold = _engine(model, aot_dir=aot_dir)
    toks_cold = _serve(cold, prompts)
    manifest = cold.manifest()
    cold.shutdown()
    assert _total("mxtpu_aot_programs_total", source="trace") >= 5
    assert aot.ExportStore(aot_dir).entries()

    engine_mod._STEP_CACHE.clear()                     # simulated restart
    traces = _total("mxtpu_aot_programs_total", source="trace")
    cache_misses = _total("mxtpu_compile_cache_misses")

    warm = _engine(model, aot_dir=aot_dir)
    warmed = warm.warmup(manifest)
    assert warmed == len(manifest)
    # engine ready with ZERO fresh compile work:
    assert _total("mxtpu_aot_programs_total", source="trace") == traces
    assert _total("mxtpu_aot_programs_total", source="artifact") == warmed
    assert _total("mxtpu_compile_cache_misses") == cache_misses
    assert _total("mxtpu_compile_cache_hits") >= warmed

    toks_warm = _serve(warm, prompts)
    assert toks_warm == toks_cold
    # serving after warmup compiled nothing new either
    assert _total("mxtpu_aot_programs_total", source="trace") == traces
    warm.shutdown()


def test_engine_corrupt_and_stale_artifacts_fall_back(tel, tmp_path,
                                                      model):
    """Mangled artifacts must cost a fresh trace, never correctness."""
    aot_dir = str(tmp_path / "aot")
    prompts = _prompts()
    cold = _engine(model, aot_dir=aot_dir)
    toks_cold = _serve(cold, prompts)
    cold.shutdown()

    store = aot.ExportStore(aot_dir)
    for path, _ in store.entries():
        raw = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])              # torn mid-blob

    engine_mod._STEP_CACHE.clear()
    traces = _total("mxtpu_aot_programs_total", source="trace")
    eng = _engine(model, aot_dir=aot_dir)
    toks = _serve(eng, prompts)
    assert toks == toks_cold
    assert _total("mxtpu_aot_programs_total", source="trace") > traces
    assert _total("mxtpu_aot_errors_total", kind="corrupt") >= 1
    eng.shutdown()

    # stale config: a differently-configured engine must ignore the
    # (freshly rewritten) artifacts — fingerprint mismatch, fresh trace
    engine_mod._STEP_CACHE.clear()
    loads = _total("mxtpu_aot_programs_total", source="artifact")
    other = _engine(model, aot_dir=aot_dir, num_blocks=48)
    _serve(other, prompts)
    assert _total("mxtpu_aot_programs_total", source="artifact") == loads
    other.shutdown()


def test_engine_warmup_grid_and_range_checks(tel, model):
    """warmup(None) with no manifest warms the full bucket grid;
    out-of-range or unknown entries are skipped, not compiled."""
    eng = _engine(model, max_batch=2, max_model_len=16)
    n = eng.warmup()
    # decode {1,2} + prefill {1,2,4,8,16} + chunk {1,2,4,8,16} (the
    # suffix/chunk program family prefix-cache hits and chunked
    # prefills run; its cap clamps to max_model_len here)
    assert n == 12
    assert eng.warmup([{"kind": "decode", "bucket": 99},
                       {"kind": "prefill", "bucket": 1000},
                       {"kind": "chunk", "bucket": 1000},
                       {"kind": "mystery", "bucket": 2},
                       {"kind": "decode", "bucket": 2}]) == 1
    eng.shutdown()
    # non-power-of-two caps are real clamp buckets live traffic hits —
    # the grid must include them (decode {1,2,3} + prefill {1..16,24}
    # + chunk {1..16,24})
    engine_mod._STEP_CACHE.clear()
    eng2 = _engine(model, max_batch=3, max_model_len=24)
    assert eng2.warmup() == 15
    eng2.shutdown()


def test_engine_warmup_precompiles_without_aot_store(tel, model):
    """warmup() must mean 'compiled', not 'will compile at the first
    unlucky request' — even with no export store or compile cache
    configured.  After a full-grid warmup, serving triggers zero
    backend compiles."""
    ev = "/jax/core/compile/backend_compile_duration"
    pre = _engine(model, max_batch=2, max_model_len=32)
    _serve(pre, _prompts())            # warm process-level jits
    pre.shutdown()
    engine_mod._STEP_CACHE.clear()

    eng = _engine(model, max_batch=2, max_model_len=32)
    eng.warmup()
    before = _total("mxtpu_jax_events_total", event=ev)
    assert before > 0                  # warmup itself really compiled
    _serve(eng, _prompts())
    assert _total("mxtpu_jax_events_total", event=ev) == before
    eng.shutdown()


# -- fused train step --------------------------------------------------------
def test_fused_step_aot_roundtrip(tel, compile_cache, tmp_path,
                                  monkeypatch):
    """The fused train program exports on first use and a 'restarted'
    module loads it instead of re-tracing — with identical weights."""
    monkeypatch.setenv(aot.export_store.ENV_DIR, str(tmp_path / "aot"))

    def fit_once():
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        y = rng.randint(0, 4, 64).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=16)
        # explicit layer name: the auto-naming counter is process-global
        # and would change the symbol JSON (and so the AOT fingerprint)
        # between the two "processes" this test simulates
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                  name="fc"),
            name="softmax")
        mx.random.seed(0)
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                initializer=mx.initializer.Xavier(), kvstore=None)
        return mod.get_params()[0]

    p1 = fit_once()
    saves = _total("mxtpu_aot_saves_total", kind="fused-step")
    assert saves == 1
    p2 = fit_once()                                    # "restart"
    assert _total("mxtpu_aot_loads_total", kind="fused-step") == 1
    assert _total("mxtpu_aot_saves_total", kind="fused-step") == saves
    for k in p1:
        np.testing.assert_allclose(p1[k].asnumpy(), p2[k].asnumpy(),
                                   rtol=1e-6, atol=1e-7)
