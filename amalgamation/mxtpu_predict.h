/* mxtpu amalgamation: single-file, dependency-free C inference runtime.
 *
 * The deploy analog of the reference's amalgamation/ predict-only build
 * (c_predict_api.h consumed from one compiled file on mobile/JS): this
 * pair (mxtpu_predict.h + mxtpu_predict.c) compiles with any C99
 * compiler against libc + libm ONLY — no Python, no jax, no zlib — and
 * runs the .mxa artifact `mxnet_tpu.predict.export_model` (or
 * `tools/export_model.py`) produces: a STORED zip holding symbol.json
 * + params.npz (+ StableHLO for jax-side consumers, ignored here).
 *
 *   cc -O2 app.c mxtpu_predict.c -lm
 *
 *   mxa_model* m = mxa_load("model.mxa");
 *   mxa_tensor* out = mxa_forward(m, data, dims, 4);
 *   ... out->data[0..out->size) ...
 *   mxa_free_tensor(out); mxa_free(m);
 *
 * Inference-only, float32, NCHW.  Supported ops: Convolution,
 * FullyConnected, BatchNorm (moving stats), Activation, Pooling
 * (max/avg/global), Flatten, Reshape, Concat, Dropout (identity),
 * SoftmaxOutput, elementwise _plus/_minus/_mul — the full ResNet /
 * LeNet / MLP / VGG inference family.  Anything else fails loudly via
 * mxa_last_error().
 */
#ifndef MXTPU_AMALGAMATION_PREDICT_H_
#define MXTPU_AMALGAMATION_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MXA_MAX_NDIM 8

typedef struct {
  int ndim;
  int64_t dims[MXA_MAX_NDIM];
  int64_t size;
  float* data;
} mxa_tensor;

typedef struct mxa_model mxa_model;

/* Load a .mxa artifact; NULL on failure (see mxa_last_error). */
mxa_model* mxa_load(const char* path);

/* Name/shape of the (single) data input recorded at export time. */
const char* mxa_input_name(const mxa_model* m);
int mxa_input_ndim(const mxa_model* m);
const int64_t* mxa_input_dims(const mxa_model* m);

/* Run the graph on one batch (any leading batch size; trailing dims
 * must match the export shape).  Returns a fresh tensor (caller frees
 * with mxa_free_tensor) or NULL on failure. */
mxa_tensor* mxa_forward(mxa_model* m, const float* data,
                        const int64_t* dims, int ndim);

const char* mxa_last_error(void);
void mxa_free_tensor(mxa_tensor* t);
void mxa_free(mxa_model* m);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_AMALGAMATION_PREDICT_H_ */
