/* Single-file inference runtime for .mxa artifacts.  See
 * mxtpu_predict.h for the contract.  C99, libc + libm only.
 *
 * Structure: error buffer -> file slurp -> STORED-zip reader -> .npy
 * reader -> mini JSON parser -> tensor helpers -> ops -> graph
 * interpreter -> public API.  The graph comes from symbol.json (the
 * framework's serialized Symbol: topo-ordered nodes with string
 * params, reference graph JSON shape), the weights from params.npz
 * ("arg:<name>"/"aux:<name>" keys, float32 or tagged-bf16 uint16).
 */
#include "mxtpu_predict.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* strdup is POSIX, not C99 — own copy keeps the file freestanding */
static char* xstrdup(const char* s) {
  size_t n = strlen(s) + 1;
  char* d = (char*)malloc(n);
  if (d) memcpy(d, s, n);
  return d;
}

/* ---- error ---------------------------------------------------------- */

static char mxa_err[512];

const char* mxa_last_error(void) { return mxa_err; }

static void seterr(const char* fmt, const char* a) {
  snprintf(mxa_err, sizeof(mxa_err), fmt, a ? a : "");
}

/* ---- slurp ---------------------------------------------------------- */

static uint8_t* slurp(const char* path, size_t* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    seterr("cannot open %s", path);
    return NULL;
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  uint8_t* buf = (uint8_t*)malloc((size_t)n);
  if (!buf || fread(buf, 1, (size_t)n, f) != (size_t)n) {
    seterr("cannot read %s", path);
    free(buf);
    fclose(f);
    return NULL;
  }
  fclose(f);
  *out_len = (size_t)n;
  return buf;
}

/* ---- STORED zip reader ---------------------------------------------- */

static uint32_t rd32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
static uint16_t rd16(const uint8_t* p) {
  return (uint16_t)((uint32_t)p[0] | ((uint32_t)p[1] << 8));
}

/* Find entry `name`; returns pointer into `zip` and sets *out_len.
 * STORED entries only (the exporter writes no deflate). */
static const uint8_t* zip_find(const uint8_t* zip, size_t len,
                               const char* name, size_t* out_len) {
  if (len < 22) {
    seterr("zip too small%s", NULL);
    return NULL;
  }
  /* EOCD: scan back for PK\5\6 (comment can follow) */
  size_t i = len - 22;
  for (;;) {
    if (zip[i] == 0x50 && zip[i + 1] == 0x4b && zip[i + 2] == 0x05 &&
        zip[i + 3] == 0x06)
      break;
    if (i == 0 || len - i > 22 + 65535) {
      seterr("zip: no end-of-central-directory%s", NULL);
      return NULL;
    }
    --i;
  }
  uint16_t n_entries = rd16(zip + i + 10);
  uint32_t cd_off = rd32(zip + i + 16);
  size_t p = cd_off;
  for (uint16_t e = 0; e < n_entries; ++e) {
    if (p + 46 > len || rd32(zip + p) != 0x02014b50) {
      seterr("zip: bad central directory%s", NULL);
      return NULL;
    }
    uint16_t method = rd16(zip + p + 10);
    uint32_t csize = rd32(zip + p + 20);
    uint16_t nlen = rd16(zip + p + 28);
    uint16_t xlen = rd16(zip + p + 30);
    uint16_t clen = rd16(zip + p + 32);
    uint32_t lho = rd32(zip + p + 42);
    const char* ename = (const char*)(zip + p + 46);
    if ((size_t)nlen == strlen(name) && memcmp(ename, name, nlen) == 0) {
      if (method != 0) {
        seterr("zip entry %s is compressed (runtime reads STORED only)",
               name);
        return NULL;
      }
      /* local header: skip its own (possibly different) name/extra */
      if (lho + 30 > len || rd32(zip + lho) != 0x04034b50) {
        seterr("zip: bad local header for %s", name);
        return NULL;
      }
      uint16_t lnlen = rd16(zip + lho + 26);
      uint16_t lxlen = rd16(zip + lho + 28);
      size_t data = (size_t)lho + 30 + lnlen + lxlen;
      if (data + csize > len) {
        seterr("zip: entry %s truncated", name);
        return NULL;
      }
      *out_len = csize;
      return zip + data;
    }
    p += 46 + (size_t)nlen + xlen + clen;
  }
  seterr("zip: entry %s not found", name);
  return NULL;
}

/* ---- npy ------------------------------------------------------------- */

typedef struct {
  int ndim;
  int64_t dims[MXA_MAX_NDIM];
  int64_t size;
  float* data; /* always converted to f32, owned */
} npy_arr;

static int npy_parse(const uint8_t* buf, size_t len, npy_arr* out,
                     int is_bf16_tagged) {
  if (len < 10 || memcmp(buf, "\x93NUMPY", 6) != 0) {
    seterr("bad npy magic%s", NULL);
    return -1;
  }
  int major = buf[6];
  size_t hlen, hoff;
  if (major == 1) {
    hlen = rd16(buf + 8);
    hoff = 10;
  } else {
    if (len < 12) {
      seterr("npy: truncated header%s", NULL);
      return -1;
    }
    hlen = rd32(buf + 8);
    hoff = 12;
  }
  if (hoff + hlen > len) { /* also guards the avail subtraction below */
    seterr("npy: header exceeds entry%s", NULL);
    return -1;
  }
  /* NUL-terminated copy: the in-zip header is not a C string */
  char hcopy[1024];
  size_t hn = hlen < sizeof(hcopy) - 1 ? hlen : sizeof(hcopy) - 1;
  memcpy(hcopy, buf + hoff, hn);
  hcopy[hn] = 0;
  const char* h = hcopy;
  /* descr */
  const char* d = strstr(h, "'descr'");
  if (!d) {
    seterr("npy: no descr%s", NULL);
    return -1;
  }
  d = strchr(d + 7, '\'');
  if (!d) return -1;
  char descr[16] = {0};
  {
    const char* e = strchr(d + 1, '\'');
    if (!e) {
      seterr("npy: unterminated descr%s", NULL);
      return -1;
    }
    size_t n = (size_t)(e - d - 1);
    if (n >= sizeof(descr)) n = sizeof(descr) - 1;
    memcpy(descr, d + 1, n);
  }
  if (strstr(h, "'fortran_order': True")) {
    seterr("npy: fortran order unsupported%s", NULL);
    return -1;
  }
  /* shape */
  const char* s = strstr(h, "'shape'");
  if (!s || !strchr(s, '(')) {
    seterr("npy: no shape%s", NULL);
    return -1;
  }
  s = strchr(s, '(');
  out->ndim = 0;
  out->size = 1;
  const char* q = s + 1;
  while (*q && *q != ')') {
    while (*q == ' ' || *q == ',') ++q;
    if (*q == ')' || !*q) break;
    const char* before = q;
    int64_t v = strtoll(q, (char**)&q, 10);
    if (q == before) { /* garbage byte in a corrupt header: no spin */
      seterr("npy: malformed shape%s", NULL);
      return -1;
    }
    if (out->ndim >= MXA_MAX_NDIM || v < 0) {
      seterr("npy: bad shape%s", NULL);
      return -1;
    }
    /* overflow-safe: check BEFORE multiplying (a wrapped int64 product
     * is UB and can sneak back under the cap) */
    if (v != 0 && out->size > ((int64_t)1 << 40) / v) {
      seterr("npy: implausible element count%s", NULL);
      return -1;
    }
    out->dims[out->ndim++] = v;
    out->size *= v;
  }
  if (out->ndim == 0) { /* scalar */
    out->ndim = 1;
    out->dims[0] = 1;
  }
  const uint8_t* payload = buf + hoff + hlen;
  size_t avail = len - hoff - hlen;
  out->data = (float*)malloc(sizeof(float) * (size_t)out->size);
  if (!out->data) {
    seterr("oom%s", NULL);
    return -1;
  }
  int64_t n = out->size;
  if (strcmp(descr, "<f4") == 0) {
    if (avail < (size_t)n * 4) goto trunc;
    memcpy(out->data, payload, (size_t)n * 4);
  } else if (strcmp(descr, "<u2") == 0 && is_bf16_tagged) {
    if (avail < (size_t)n * 2) goto trunc;
    for (int64_t i = 0; i < n; ++i) {
      uint32_t bits = ((uint32_t)payload[2 * i] |
                       ((uint32_t)payload[2 * i + 1] << 8))
                      << 16;
      memcpy(&out->data[i], &bits, 4);
    }
  } else if (strcmp(descr, "<f8") == 0) {
    if (avail < (size_t)n * 8) goto trunc;
    for (int64_t i = 0; i < n; ++i) {
      double v;
      memcpy(&v, payload + 8 * i, 8);
      out->data[i] = (float)v;
    }
  } else if (strcmp(descr, "<i4") == 0) {
    if (avail < (size_t)n * 4) goto trunc;
    for (int64_t i = 0; i < n; ++i) {
      int32_t v;
      memcpy(&v, payload + 4 * i, 4);
      out->data[i] = (float)v;
    }
  } else if (strcmp(descr, "<i8") == 0) {
    if (avail < (size_t)n * 8) goto trunc;
    for (int64_t i = 0; i < n; ++i) {
      int64_t v;
      memcpy(&v, payload + 8 * i, 8);
      out->data[i] = (float)v;
    }
  } else {
    seterr("npy: unsupported dtype %s", descr);
    free(out->data);
    return -1;
  }
  return 0;
trunc:
  seterr("npy: truncated payload%s", NULL);
  free(out->data);
  return -1;
}

/* ---- mini JSON ------------------------------------------------------- */

typedef enum { J_NULL, J_BOOL, J_NUM, J_STR, J_ARR, J_OBJ } jtype;

typedef struct jval {
  jtype t;
  double num;
  char* str;                 /* J_STR */
  struct jval** items;       /* J_ARR / J_OBJ values */
  char** keys;               /* J_OBJ keys */
  int n;
} jval;

static void jfree(jval* v) {
  if (!v) return;
  free(v->str);
  for (int i = 0; i < v->n; ++i) {
    jfree(v->items ? v->items[i] : NULL);
    if (v->keys) free(v->keys[i]);
  }
  free(v->items);
  free(v->keys);
  free(v);
}

static void jskip(const char** p) {
  while (**p == ' ' || **p == '\n' || **p == '\t' || **p == '\r') ++*p;
}

static jval* jparse(const char** p);

static char* jstring(const char** p) {
  if (**p != '"') return NULL;
  ++*p;
  size_t cap = 16, n = 0;
  char* s = (char*)malloc(cap);
  while (**p && **p != '"') {
    char c = **p;
    if (c == '\\') {
      ++*p;
      char e = **p;
      if (!e) break;  /* buffer ends in a lone backslash: stop at NUL */
      switch (e) {
        case 'n': c = '\n'; break;
        case 't': c = '\t'; break;
        case 'r': c = '\r'; break;
        case 'b': c = '\b'; break;
        case 'f': c = '\f'; break;
        case 'u': { /* \uXXXX -> UTF-8 (npz keys keep raw UTF-8, so
                     * names must round-trip byte-exactly for
                     * find_param to match) */
          unsigned v = 0;
          for (int k = 0; k < 4 && (*p)[1]; ++k) {
            ++*p;
            char h = **p;
            v = v * 16 + (h <= '9' ? (unsigned)(h - '0')
                                   : (unsigned)((h | 32) - 'a' + 10));
          }
          if (n + 5 > cap) {
            cap = cap * 2 + 8;
            s = (char*)realloc(s, cap);
          }
          if (v < 0x80) {
            s[n++] = (char)v;
          } else if (v < 0x800) {
            s[n++] = (char)(0xC0 | (v >> 6));
            s[n++] = (char)(0x80 | (v & 0x3F));
          } else { /* BMP (surrogate pairs not expected in node names) */
            s[n++] = (char)(0xE0 | (v >> 12));
            s[n++] = (char)(0x80 | ((v >> 6) & 0x3F));
            s[n++] = (char)(0x80 | (v & 0x3F));
          }
          ++*p;
          continue;
        }
        default: c = e;
      }
    }
    if (n + 2 > cap) {
      cap *= 2;
      s = (char*)realloc(s, cap);
    }
    s[n++] = c;
    ++*p;
  }
  if (**p == '"') ++*p;
  s[n] = 0;
  return s;
}

static jval* jnew(jtype t) {
  jval* v = (jval*)calloc(1, sizeof(jval));
  v->t = t;
  return v;
}

static jval* jparse(const char** p) {
  jskip(p);
  char c = **p;
  if (c == '{') {
    jval* v = jnew(J_OBJ);
    ++*p;
    jskip(p);
    while (**p && **p != '}') {
      char* key = jstring(p);
      jskip(p);
      if (**p == ':') ++*p;
      jval* item = jparse(p);
      v->items = (jval**)realloc(v->items, sizeof(jval*) * (size_t)(v->n + 1));
      v->keys = (char**)realloc(v->keys, sizeof(char*) * (size_t)(v->n + 1));
      v->items[v->n] = item;
      v->keys[v->n] = key;
      ++v->n;
      jskip(p);
      if (**p == ',') {
        ++*p;
        jskip(p);
      }
    }
    if (**p == '}') ++*p;
    return v;
  }
  if (c == '[') {
    jval* v = jnew(J_ARR);
    ++*p;
    jskip(p);
    while (**p && **p != ']') {
      jval* item = jparse(p);
      v->items = (jval**)realloc(v->items, sizeof(jval*) * (size_t)(v->n + 1));
      v->items[v->n++] = item;
      jskip(p);
      if (**p == ',') {
        ++*p;
        jskip(p);
      }
    }
    if (**p == ']') ++*p;
    return v;
  }
  if (c == '"') {
    jval* v = jnew(J_STR);
    v->str = jstring(p);
    return v;
  }
  if (strncmp(*p, "true", 4) == 0) {
    *p += 4;
    jval* v = jnew(J_BOOL);
    v->num = 1;
    return v;
  }
  if (strncmp(*p, "false", 5) == 0) {
    *p += 5;
    return jnew(J_BOOL);
  }
  if (strncmp(*p, "null", 4) == 0) {
    *p += 4;
    return jnew(J_NULL);
  }
  jval* v = jnew(J_NUM);
  const char* before = *p;
  v->num = strtod(*p, (char**)p);
  if (*p == before && **p) ++*p; /* unparseable byte: consume it (but
                           * never step past the NUL) — every jparse
                           * call must make progress or corrupt input
                           * spins the object/array loops forever */
  return v;
}

static jval* jget(const jval* obj, const char* key) {
  if (!obj || obj->t != J_OBJ) return NULL;
  for (int i = 0; i < obj->n; ++i)
    if (obj->keys[i] && strcmp(obj->keys[i], key) == 0)
      return obj->items[i];
  return NULL;
}

/* corrupt-input-safe accessors for the graph walk */
static const char* jstr_of(const jval* obj, const char* key) {
  jval* v = jget(obj, key);
  return v && v->t == J_STR && v->str ? v->str : NULL;
}

static int jint_at(const jval* arr, int idx, int* out) {
  if (!arr || arr->t != J_ARR || idx >= arr->n) return 0;
  jval* v = arr->items[idx];
  if (!v || v->t != J_NUM) return 0;
  *out = (int)v->num;
  return 1;
}

/* ---- param-string helpers ("(5, 5)", "True", "relu", "3") ----------- */

static const char* pstr(const jval* params, const char* key,
                        const char* dflt) {
  jval* v = jget(params, key);
  return v && v->t == J_STR ? v->str : dflt;
}

static int pbool(const jval* params, const char* key, int dflt) {
  const char* s = pstr(params, key, NULL);
  if (!s) return dflt;
  return s[0] == 'T' || s[0] == 't' || s[0] == '1';
}

static double pnum(const jval* params, const char* key, double dflt) {
  const char* s = pstr(params, key, NULL);
  return s ? strtod(s, NULL) : dflt;
}

/* parse "(a, b, ...)" or "a" into ints; returns count */
static int ptuple(const jval* params, const char* key, int64_t* out,
                  int cap, int64_t dflt_val, int dflt_n) {
  const char* s = pstr(params, key, NULL);
  if (!s) {
    for (int i = 0; i < dflt_n; ++i) out[i] = dflt_val;
    return dflt_n;
  }
  int n = 0;
  const char* q = s;
  while (*q && n < cap) {
    while (*q && (*q == '(' || *q == ')' || *q == ',' || *q == ' ' ||
                  *q == '[' || *q == ']'))
      ++q;
    if (!*q) break;
    out[n++] = strtoll(q, (char**)&q, 10);
  }
  if (n == 0) {
    for (int i = 0; i < dflt_n; ++i) out[i] = dflt_val;
    return dflt_n;
  }
  return n;
}

/* ---- tensors --------------------------------------------------------- */

static mxa_tensor* tnew(int ndim, const int64_t* dims) {
  mxa_tensor* t = (mxa_tensor*)calloc(1, sizeof(mxa_tensor));
  t->ndim = ndim;
  t->size = 1;
  for (int i = 0; i < ndim; ++i) {
    t->dims[i] = dims[i];
    t->size *= dims[i];
  }
  t->data = (float*)calloc((size_t)t->size, sizeof(float));
  return t;
}

void mxa_free_tensor(mxa_tensor* t) {
  if (t) {
    free(t->data);
    free(t);
  }
}

/* ---- model ----------------------------------------------------------- */

typedef struct {
  char* name;
  npy_arr arr;
} named_param;

struct mxa_model {
  jval* graph;     /* symbol.json */
  jval* manifest;  /* manifest.json */
  named_param* params;
  int n_params;
  char* input_name;
  int input_ndim;
  int64_t input_dims[MXA_MAX_NDIM];
};

static const npy_arr* find_param(const mxa_model* m, const char* prefix,
                                 const char* name) {
  char key[256];
  snprintf(key, sizeof(key), "%s%s", prefix, name);
  for (int i = 0; i < m->n_params; ++i)
    if (strcmp(m->params[i].name, key) == 0) return &m->params[i].arr;
  return NULL;
}

/* ---- ops ------------------------------------------------------------- */

static mxa_tensor* op_convolution(const jval* params, mxa_tensor** in,
                                  int n_in) {
  if (n_in < 2) {
    seterr("Convolution: missing weight%s", NULL);
    return NULL;
  }
  int64_t kernel[2] = {1, 1}, stride[2] = {1, 1}, pad[2] = {0, 0},
          dilate[2] = {1, 1};
  ptuple(params, "kernel", kernel, 2, 1, 2);
  ptuple(params, "stride", stride, 2, 1, 2);
  ptuple(params, "pad", pad, 2, 0, 2);
  ptuple(params, "dilate", dilate, 2, 1, 2);
  if (pnum(params, "num_group", 1) != 1) {
    seterr("Convolution: num_group > 1 unsupported%s", NULL);
    return NULL;
  }
  if (strcmp(pstr(params, "layout", "NCHW"), "NCHW") != 0) {
    seterr("Convolution: only NCHW layout supported%s", NULL);
    return NULL;
  }
  const mxa_tensor* x = in[0];
  const mxa_tensor* w = in[1];
  const mxa_tensor* b = (n_in > 2 && !pbool(params, "no_bias", 0)) ? in[2]
                                                                   : NULL;
  if (x->ndim != 4 || w->ndim != 4) {
    seterr("Convolution: NCHW 2D only%s", NULL);
    return NULL;
  }
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t F = w->dims[0], kh = kernel[0], kw = kernel[1];
  int64_t oh = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) / stride[0] + 1;
  int64_t ow = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) / stride[1] + 1;
  int64_t od[4] = {N, F, oh, ow};
  mxa_tensor* out = tnew(4, od);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t f = 0; f < F; ++f)
      for (int64_t y = 0; y < oh; ++y)
        for (int64_t xo = 0; xo < ow; ++xo) {
          double acc = b ? b->data[f] : 0.0;
          for (int64_t c = 0; c < C; ++c)
            for (int64_t i = 0; i < kh; ++i) {
              int64_t iy = y * stride[0] - pad[0] + i * dilate[0];
              if (iy < 0 || iy >= H) continue;
              const float* xrow = x->data + ((n * C + c) * H + iy) * W;
              const float* wrow = w->data + ((f * C + c) * kh + i) * kw;
              for (int64_t j = 0; j < kw; ++j) {
                int64_t ix = xo * stride[1] - pad[1] + j * dilate[1];
                if (ix < 0 || ix >= W) continue;
                acc += (double)xrow[ix] * wrow[j];
              }
            }
          out->data[((n * F + f) * oh + y) * ow + xo] = (float)acc;
        }
  return out;
}

static mxa_tensor* op_fully_connected(const jval* params, mxa_tensor** in,
                                      int n_in) {
  if (n_in < 2) {
    seterr("FullyConnected: missing weight%s", NULL);
    return NULL;
  }
  const mxa_tensor* x = in[0];
  const mxa_tensor* w = in[1];
  const mxa_tensor* b = (n_in > 2 && !pbool(params, "no_bias", 0)) ? in[2]
                                                                   : NULL;
  int64_t N = x->dims[0];
  int64_t D = x->size / N;
  int64_t Hh = w->dims[0];
  if (w->size != Hh * D) {
    seterr("FullyConnected: weight/input mismatch%s", NULL);
    return NULL;
  }
  int64_t od[2] = {N, Hh};
  mxa_tensor* out = tnew(2, od);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t h = 0; h < Hh; ++h) {
      double acc = b ? b->data[h] : 0.0;
      const float* xr = x->data + n * D;
      const float* wr = w->data + h * D;
      for (int64_t d = 0; d < D; ++d) acc += (double)xr[d] * wr[d];
      out->data[n * Hh + h] = (float)acc;
    }
  return out;
}

static float act_relu(float v) { return v > 0 ? v : 0; }
static float act_sigmoid(float v) { return 1.0f / (1.0f + expf(-v)); }
static float act_softrelu(float v) {
  /* stable softplus: expf overflows past ~88, jax.nn.softplus doesn't */
  return (v > 0 ? v : 0) + log1pf(expf(-fabsf(v)));
}

static mxa_tensor* op_activation(const jval* params, mxa_tensor** in,
                                 int n_in) {
  (void)n_in;
  const char* act = pstr(params, "act_type", "relu");
  /* dispatch ONCE — this is the deploy hot path, and failing before
   * allocation keeps the error path clean */
  float (*fn)(float) = NULL;
  if (strcmp(act, "relu") == 0)
    fn = act_relu;
  else if (strcmp(act, "tanh") == 0)
    fn = tanhf;
  else if (strcmp(act, "sigmoid") == 0)
    fn = act_sigmoid;
  else if (strcmp(act, "softrelu") == 0)
    fn = act_softrelu;
  else {
    seterr("Activation: unsupported act_type %s", act);
    return NULL;
  }
  mxa_tensor* out = tnew(in[0]->ndim, in[0]->dims);
  for (int64_t i = 0; i < in[0]->size; ++i)
    out->data[i] = fn(in[0]->data[i]);
  return out;
}

static mxa_tensor* op_pooling(const jval* params, mxa_tensor** in,
                              int n_in) {
  (void)n_in;
  const mxa_tensor* x = in[0];
  if (x->ndim != 4) {
    seterr("Pooling: NCHW only%s", NULL);
    return NULL;
  }
  const char* type = pstr(params, "pool_type", "max");
  int is_avg = strcmp(type, "avg") == 0;
  if (!is_avg && strcmp(type, "max") != 0) {
    seterr("Pooling: unsupported pool_type %s", type);
    return NULL;
  }
  if (strcmp(pstr(params, "pooling_convention", "valid"), "valid") != 0) {
    seterr("Pooling: only pooling_convention='valid' supported%s", NULL);
    return NULL;
  }
  if (strcmp(pstr(params, "layout", "NCHW"), "NCHW") != 0) {
    seterr("Pooling: only NCHW layout supported%s", NULL);
    return NULL;
  }
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t kernel[2] = {H, W}, stride[2] = {1, 1}, pad[2] = {0, 0};
  if (pbool(params, "global_pool", 0)) {
    kernel[0] = H;
    kernel[1] = W;
    stride[0] = stride[1] = 1;
  } else {
    ptuple(params, "kernel", kernel, 2, 1, 2);
    ptuple(params, "stride", stride, 2, 1, 2);
    ptuple(params, "pad", pad, 2, 0, 2);
  }
  int64_t oh = (H + 2 * pad[0] - kernel[0]) / stride[0] + 1;
  int64_t ow = (W + 2 * pad[1] - kernel[1]) / stride[1] + 1;
  if (oh < 1) oh = 1;
  if (ow < 1) ow = 1;
  int64_t od[4] = {N, C, oh, ow};
  mxa_tensor* out = tnew(4, od);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t y = 0; y < oh; ++y)
        for (int64_t xo = 0; xo < ow; ++xo) {
          double acc = is_avg ? 0.0 : -INFINITY;
          for (int64_t i = 0; i < kernel[0]; ++i) {
            int64_t iy = y * stride[0] - pad[0] + i;
            if (iy < 0 || iy >= H) continue;
            for (int64_t j = 0; j < kernel[1]; ++j) {
              int64_t ix = xo * stride[1] - pad[1] + j;
              if (ix < 0 || ix >= W) continue;
              float v = x->data[((n * C + c) * H + iy) * W + ix];
              if (is_avg)
                acc += v;
              else if (v > acc)
                acc = v;
            }
          }
          /* avg divides by the FULL kernel area, padding included —
           * the mshadow convention the framework reproduces */
          out->data[((n * C + c) * oh + y) * ow + xo] =
              is_avg ? (float)(acc / (double)(kernel[0] * kernel[1]))
                     : (float)acc;
        }
  return out;
}

static mxa_tensor* op_batchnorm(const jval* params, mxa_tensor** in,
                                int n_in) {
  /* inputs: data, gamma, beta + aux moving_mean, moving_var (wired by
   * the interpreter); inference always uses the moving stats */
  if (n_in < 5) {
    seterr("BatchNorm: missing moving stats%s", NULL);
    return NULL;
  }
  const mxa_tensor* x = in[0];
  const float* gamma = in[1]->data;
  const float* beta = in[2]->data;
  const float* mean = in[3]->data;
  const float* var = in[4]->data;
  double eps = pnum(params, "eps", 1e-3);
  int fix_gamma = pbool(params, "fix_gamma", 1);
  if (pnum(params, "axis", 1) != 1) {
    seterr("BatchNorm: only axis=1 (NCHW channel) supported%s", NULL);
    return NULL;
  }
  int64_t C = x->ndim > 1 ? x->dims[1] : x->dims[0];
  int64_t inner = 1;
  for (int i = 2; i < x->ndim; ++i) inner *= x->dims[i];
  int64_t N = x->dims[0];
  mxa_tensor* out = tnew(x->ndim, x->dims);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c) {
      float g = fix_gamma ? 1.0f : gamma[c];
      float scale = (float)((double)g / sqrt((double)var[c] + eps));
      float shift = beta[c] - mean[c] * scale;
      float* dst = out->data + (n * C + c) * inner;
      const float* src = x->data + (n * C + c) * inner;
      for (int64_t i = 0; i < inner; ++i) dst[i] = src[i] * scale + shift;
    }
  return out;
}

static mxa_tensor* op_flatten(mxa_tensor** in) {
  int64_t od[2] = {in[0]->dims[0], in[0]->size / in[0]->dims[0]};
  mxa_tensor* out = tnew(2, od);
  memcpy(out->data, in[0]->data, sizeof(float) * (size_t)out->size);
  return out;
}

static mxa_tensor* op_reshape(const jval* params, mxa_tensor** in) {
  int64_t spec[MXA_MAX_NDIM];
  int n = ptuple(params, "shape", spec, MXA_MAX_NDIM, 0, 0);
  if (n == 0) {
    seterr("Reshape: missing shape%s", NULL);
    return NULL;
  }
  int64_t od[MXA_MAX_NDIM];
  int64_t known = 1;
  int infer = -1;
  for (int i = 0; i < n; ++i) {
    int64_t v = spec[i];
    if (v == 0) v = in[0]->dims[i]; /* mxnet: 0 copies the input dim */
    if (v == -1) {
      infer = i;
      od[i] = 1;
    } else {
      od[i] = v;
      known *= v;
    }
  }
  if (infer >= 0) od[infer] = in[0]->size / known;
  mxa_tensor* out = tnew(n, od);
  if (out->size != in[0]->size) {
    seterr("Reshape: size mismatch%s", NULL);
    mxa_free_tensor(out);
    return NULL;
  }
  memcpy(out->data, in[0]->data, sizeof(float) * (size_t)out->size);
  return out;
}

static mxa_tensor* op_concat(const jval* params, mxa_tensor** in, int n_in) {
  int64_t axis = (int64_t)pnum(params, "dim", 1);
  const mxa_tensor* a = in[0];
  int64_t od[MXA_MAX_NDIM];
  memcpy(od, a->dims, sizeof(od));
  for (int i = 1; i < n_in; ++i) od[axis] += in[i]->dims[axis];
  mxa_tensor* out = tnew(a->ndim, od);
  int64_t outer = 1, inner = 1;
  for (int i = 0; i < (int)axis; ++i) outer *= a->dims[i];
  for (int i = (int)axis + 1; i < a->ndim; ++i) inner *= a->dims[i];
  int64_t off = 0;
  for (int t = 0; t < n_in; ++t) {
    int64_t ax = in[t]->dims[axis];
    for (int64_t o = 0; o < outer; ++o)
      memcpy(out->data + (o * od[axis] + off) * inner,
             in[t]->data + o * ax * inner,
             sizeof(float) * (size_t)(ax * inner));
    off += ax;
  }
  return out;
}

static mxa_tensor* op_softmax_output(mxa_tensor** in) {
  const mxa_tensor* x = in[0];
  int64_t N = x->dims[0], C = x->size / x->dims[0];
  mxa_tensor* out = tnew(x->ndim, x->dims);
  for (int64_t n = 0; n < N; ++n) {
    const float* xr = x->data + n * C;
    float* o = out->data + n * C;
    float mx = xr[0];
    for (int64_t c = 1; c < C; ++c)
      if (xr[c] > mx) mx = xr[c];
    double sum = 0.0;
    for (int64_t c = 0; c < C; ++c) {
      o[c] = expf(xr[c] - mx);
      sum += o[c];
    }
    for (int64_t c = 0; c < C; ++c) o[c] = (float)(o[c] / sum);
  }
  return out;
}

static mxa_tensor* op_elemwise(const char* op, mxa_tensor** in, int n_in) {
  if (n_in != 2 || in[0]->size != in[1]->size) {
    seterr("%s: needs two same-shape inputs", op);
    return NULL;
  }
  mxa_tensor* out = tnew(in[0]->ndim, in[0]->dims);
  const float* a = in[0]->data;
  const float* b = in[1]->data;
  char k = op[1]; /* _plus/_minus/_mul */
  for (int64_t i = 0; i < out->size; ++i)
    out->data[i] = k == 'p' ? a[i] + b[i]
                 : k == 'm' && op[2] == 'i' ? a[i] - b[i]
                                            : a[i] * b[i];
  return out;
}

/* ---- interpreter ----------------------------------------------------- */

const char* mxa_input_name(const mxa_model* m) { return m->input_name; }
int mxa_input_ndim(const mxa_model* m) { return m->input_ndim; }
const int64_t* mxa_input_dims(const mxa_model* m) { return m->input_dims; }

mxa_tensor* mxa_forward(mxa_model* m, const float* data,
                        const int64_t* dims, int ndim) {
  if (ndim < 1 || ndim > MXA_MAX_NDIM) {
    seterr("mxa_forward: ndim out of range [1, 8]%s", NULL);
    return NULL;
  }
  jval* nodes = jget(m->graph, "nodes");
  jval* heads = jget(m->graph, "heads");
  if (!nodes || !heads || heads->n < 1) {
    seterr("graph: missing nodes/heads%s", NULL);
    return NULL;
  }
  if (heads->n > 1) { /* returning head[0] alone would silently drop
                       * outputs of a grouped symbol */
    seterr("graph has multiple outputs; the amalgamation runtime "
           "serves single-output inference graphs%s", NULL);
    return NULL;
  }
  int n_nodes = nodes->n;
  /* per-node single-output values (multi-output ops unsupported) */
  mxa_tensor** vals = (mxa_tensor**)calloc((size_t)n_nodes,
                                           sizeof(mxa_tensor*));
  mxa_tensor* result = NULL;

  for (int i = 0; i < n_nodes; ++i) {
    jval* node = nodes->items[i];
    const char* op = jstr_of(node, "op");
    const char* name = jstr_of(node, "name");
    if (!op || !name) {
      seterr("graph: node missing op/name (corrupt symbol.json)%s", NULL);
      goto fail;
    }
    jval* params = jget(node, "param");
    jval* inputs = jget(node, "inputs");

    if (strcmp(op, "null") == 0) {
      /* variable: data input, weight, or aux state */
      if (strcmp(name, m->input_name) == 0) {
        mxa_tensor* t = tnew(ndim, dims);
        memcpy(t->data, data, sizeof(float) * (size_t)t->size);
        vals[i] = t;
      } else {
        const npy_arr* p = find_param(m, "arg:", name);
        if (!p) p = find_param(m, "aux:", name);
        if (!p) {
          /* unused free input (a label at inference): leave NULL; ops
           * that would consume it (SoftmaxOutput) ignore it */
          vals[i] = NULL;
          continue;
        }
        mxa_tensor* t = tnew(p->ndim, p->dims);
        memcpy(t->data, p->data, sizeof(float) * (size_t)t->size);
        vals[i] = t;
      }
      continue;
    }

    /* gather inputs (fail loudly on overflow — silent truncation would
     * return wrong results for e.g. a 17-branch Concat) */
    mxa_tensor* ins[64];
    int n_in = 0;
    for (int k = 0; inputs && inputs->t == J_ARR && k < inputs->n; ++k) {
      int src = -1;
      if (!jint_at(inputs->items[k], 0, &src) || src < 0 || src >= i) {
        seterr("graph: node %s has a bad input reference", name);
        goto fail; /* topo order: inputs may only reference earlier nodes */
      }
      if (vals[src] == NULL) continue; /* skipped free input (label) */
      if (n_in >= 64) {
        seterr("op %s: more than 64 inputs unsupported", name);
        goto fail;
      }
      ins[n_in++] = vals[src];
    }
    if (n_in < 1) { /* every supported op consumes at least data */
      seterr("graph: op node %s has no live inputs", name);
      goto fail;
    }

    mxa_tensor* out = NULL;
    if (strcmp(op, "Convolution") == 0)
      out = op_convolution(params, ins, n_in);
    else if (strcmp(op, "FullyConnected") == 0)
      out = op_fully_connected(params, ins, n_in);
    else if (strcmp(op, "Activation") == 0)
      out = op_activation(params, ins, n_in);
    else if (strcmp(op, "Pooling") == 0)
      out = op_pooling(params, ins, n_in);
    else if (strcmp(op, "BatchNorm") == 0)
      out = op_batchnorm(params, ins, n_in);
    else if (strcmp(op, "Flatten") == 0)
      out = op_flatten(ins);
    else if (strcmp(op, "Reshape") == 0)
      out = op_reshape(params, ins);
    else if (strcmp(op, "Concat") == 0)
      out = op_concat(params, ins, n_in);
    else if (strcmp(op, "Dropout") == 0) {
      out = tnew(ins[0]->ndim, ins[0]->dims);
      memcpy(out->data, ins[0]->data, sizeof(float) * (size_t)out->size);
    } else if (strcmp(op, "SoftmaxOutput") == 0)
      out = op_softmax_output(ins);
    else if (strcmp(op, "_plus") == 0 || strcmp(op, "_minus") == 0 ||
             strcmp(op, "_mul") == 0 || strcmp(op, "elemwise_add") == 0)
      out = op_elemwise(op[0] == 'e' ? "_plus" : op, ins, n_in);
    else {
      seterr("unsupported op in deploy artifact: %s", op);
      goto fail;
    }
    if (!out) goto fail;
    vals[i] = out;
  }

  {
    int head = -1;
    if (heads->t != J_ARR || !jint_at(heads->items[0], 0, &head)
        || head < 0 || head >= n_nodes || !vals[head]) {
      seterr("graph head has no value%s", NULL);
      goto fail;
    }
    /* detach the head so the cleanup below keeps it alive */
    result = vals[head];
    vals[head] = NULL;
  }

fail:
  for (int i = 0; i < n_nodes; ++i) mxa_free_tensor(vals[i]);
  free(vals);
  return result;
}

/* ---- load / free ----------------------------------------------------- */

mxa_model* mxa_load(const char* path) {
  size_t zlen = 0;
  uint8_t* zip = slurp(path, &zlen);
  if (!zip) return NULL;
  mxa_model* m = (mxa_model*)calloc(1, sizeof(mxa_model));

  size_t slen = 0, mlen = 0, plen = 0;
  const uint8_t* sj = zip_find(zip, zlen, "symbol.json", &slen);
  const uint8_t* mj = zip_find(zip, zlen, "manifest.json", &mlen);
  const uint8_t* pz = zip_find(zip, zlen, "params.npz", &plen);
  if (!sj || !mj || !pz) goto fail;

  {
    char* txt = (char*)malloc(slen + 1);
    memcpy(txt, sj, slen);
    txt[slen] = 0;
    const char* p = txt;
    m->graph = jparse(&p);
    free(txt);
    txt = (char*)malloc(mlen + 1);
    memcpy(txt, mj, mlen);
    txt[mlen] = 0;
    p = txt;
    m->manifest = jparse(&p);
    free(txt);
  }

  /* params.npz: a stored zip of <key>.npy entries */
  {
    size_t p = 0;
    if (plen < 22) {
      seterr("params.npz: too small%s", NULL);
      goto fail;
    }
    /* iterate central directory of the inner zip */
    size_t i = plen - 22;
    for (;;) {
      if (pz[i] == 0x50 && pz[i + 1] == 0x4b && pz[i + 2] == 0x05 &&
          pz[i + 3] == 0x06)
        break;
      if (i == 0) {
        seterr("params.npz: no EOCD%s", NULL);
        goto fail;
      }
      --i;
    }
    uint16_t n_entries = rd16(pz + i + 10);
    p = rd32(pz + i + 16);
    for (uint16_t e = 0; e < n_entries; ++e) {
      /* same bounds discipline as zip_find: a corrupt artifact must
       * seterr, never read past the slurped buffer */
      if (p + 46 > plen || rd32(pz + p) != 0x02014b50) {
        seterr("params.npz: bad central directory%s", NULL);
        goto fail;
      }
      uint16_t method = rd16(pz + p + 10);
      uint32_t csize = rd32(pz + p + 20);
      uint16_t nlen = rd16(pz + p + 28);
      uint16_t xlen = rd16(pz + p + 30);
      uint16_t clen = rd16(pz + p + 32);
      uint32_t lho = rd32(pz + p + 42);
      if (p + 46 + (size_t)nlen > plen) {
        seterr("params.npz: entry name out of bounds%s", NULL);
        goto fail;
      }
      char ename[256] = {0};
      memcpy(ename, pz + p + 46, nlen < 255 ? nlen : 255);
      if (method != 0) {
        seterr("params.npz entry %s compressed", ename);
        goto fail;
      }
      if ((size_t)lho + 30 > plen || rd32(pz + lho) != 0x04034b50) {
        seterr("params.npz: bad local header for %s", ename);
        goto fail;
      }
      uint16_t lnlen = rd16(pz + lho + 26);
      uint16_t lxlen = rd16(pz + lho + 28);
      if ((size_t)lho + 30 + lnlen + lxlen + csize > plen) {
        seterr("params.npz: entry %s truncated", ename);
        goto fail;
      }
      const uint8_t* payload = pz + lho + 30 + lnlen + lxlen;

      /* strip .npy; detect the bf16 tag the framework's savez applies */
      char key[256];
      snprintf(key, sizeof(key), "%s", ename);
      size_t kl = strlen(key);
      if (kl > 4 && strcmp(key + kl - 4, ".npy") == 0) key[kl - 4] = 0;
      int bf16 = strncmp(key, "__bf16__:", 9) == 0;

      npy_arr arr;
      if (npy_parse(payload, csize, &arr, bf16) != 0) goto fail;
      m->params = (named_param*)realloc(
          m->params, sizeof(named_param) * (size_t)(m->n_params + 1));
      m->params[m->n_params].name = xstrdup(bf16 ? key + 9 : key);
      m->params[m->n_params].arr = arr;
      ++m->n_params;
      p += 46 + (size_t)nlen + xlen + clen;
    }
  }

  /* manifest: single data input (v1 contract) */
  {
    jval* names = jget(m->manifest, "data_names");
    if (!names || names->t != J_ARR || names->n != 1
        || !names->items[0] || names->items[0]->t != J_STR
        || !names->items[0]->str) {
      seterr("manifest: exactly one (string) data input supported%s",
             NULL);
      goto fail;
    }
    m->input_name = xstrdup(names->items[0]->str);
    jval* shapes = jget(m->manifest, "input_shapes");
    jval* shp = jget(shapes, m->input_name);
    m->input_ndim = shp ? shp->n : 0;
    for (int i = 0; shp && i < shp->n && i < MXA_MAX_NDIM; ++i)
      m->input_dims[i] = (int64_t)shp->items[i]->num;
  }

  free(zip);
  return m;

fail:
  free(zip);
  mxa_free(m);
  return NULL;
}

void mxa_free(mxa_model* m) {
  if (!m) return;
  jfree(m->graph);
  jfree(m->manifest);
  for (int i = 0; i < m->n_params; ++i) {
    free(m->params[i].name);
    free(m->params[i].arr.data);
  }
  free(m->params);
  free(m->input_name);
  free(m);
}
