#!/usr/bin/env python
"""Stochastic-depth residual network
(rebuild of example/stochastic-depth/{sd_mnist.py,sd_module.py}).

Residual branches are gated per batch by a host-side Bernoulli draw —
implemented as a CustomOp (the reference gates at the module level;
the CustomOp bridge is the TPU-native place for host randomness that
must not be traced into the compiled graph).  At test time branches
are always on, scaled by their survival probability.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


class StochasticGate(mx.operator.CustomOp):
    """Multiplies the branch by 0 or 1 (train) / survival prob (test)."""

    def __init__(self, death_rate):
        self.death_rate = float(death_rate)
        self._gate = 1.0

    def forward(self, is_train, req, in_data, out_data, aux):
        if is_train:
            self._gate = float(np.random.rand() >= self.death_rate)
        else:
            self._gate = 1.0 - self.death_rate
        self.assign(out_data[0], req[0], in_data[0] * self._gate)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0] * self._gate)


@mx.operator.register("stochastic_gate")
class StochasticGateProp(mx.operator.CustomOpProp):
    def __init__(self, death_rate=0.5):
        super().__init__(need_top_grad=True)
        self.death_rate = death_rate

    def list_arguments(self):
        return ["data"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return StochasticGate(self.death_rate)


def residual_unit(data, num_filter, name, death_rate):
    conv1 = mx.sym.Convolution(data, name=f"{name}_conv1", kernel=(3, 3),
                               pad=(1, 1), num_filter=num_filter)
    bn1 = mx.sym.BatchNorm(conv1, name=f"{name}_bn1")
    act1 = mx.sym.Activation(bn1, act_type="relu")
    conv2 = mx.sym.Convolution(act1, name=f"{name}_conv2", kernel=(3, 3),
                               pad=(1, 1), num_filter=num_filter)
    bn2 = mx.sym.BatchNorm(conv2, name=f"{name}_bn2")
    gated = mx.sym.Custom(bn2, name=f"{name}_gate", op_type="stochastic_gate",
                          death_rate=death_rate)
    return mx.sym.Activation(data + gated, act_type="relu")


def build_net(num_units=3, num_filter=16, final_death_rate=0.5):
    data = mx.sym.Variable("data")
    body = mx.sym.Convolution(data, name="conv0", kernel=(3, 3), pad=(1, 1),
                              num_filter=num_filter)
    body = mx.sym.Activation(body, act_type="relu")
    for i in range(num_units):
        # linearly-decayed survival (Huang et al.; reference sd_cifar10.py)
        death_rate = final_death_rate * (i + 1) / num_units
        body = residual_unit(body, num_filter, f"unit{i}", death_rate)
    pool = mx.sym.Pooling(body, global_pool=True, pool_type="avg",
                          kernel=(1, 1))
    flat = mx.sym.Flatten(pool)
    fc = mx.sym.FullyConnected(flat, name="fc", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--num-units", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--n-train", type=int, default=1280)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, args.n_train)
    X = rng.standard_normal((args.n_train, 1, 14, 14)).astype(np.float32) * .3
    X[np.arange(args.n_train), 0, y, y] += 2.5

    net = build_net(num_units=args.num_units)
    mod = mx.mod.Module(net, context=mx.tpu(0))
    mod.fit(mx.io.NDArrayIter(X, y.astype(np.float32), args.batch_size,
                              shuffle=True),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.num_epochs)
    acc = dict(mod.score(mx.io.NDArrayIter(X, y.astype(np.float32),
                                           args.batch_size), "acc"))["accuracy"]
    print(f"stochastic-depth train accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
