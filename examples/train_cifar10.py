#!/usr/bin/env python
"""Train inception-bn-28-small on CIFAR-10 (rebuild of
example/image-classification/train_cifar10.py — the 842/1640/2943
img/sec baseline config from the reference README's results table).

Real data: --data-dir with cifar/train.rec + cifar/test.rec (pack with
tools/im2rec.py from the extracted CIFAR png tree).  Without data, runs
on synthetic batches so the compute path is benchmarkable anywhere.
"""

import os

import numpy as np

import common
import mxnet_tpu as mx


def get_iters(args):
    shape = (3, 28, 28)
    d = args.data_dir
    if d and os.path.exists(os.path.join(d, "train.rec")):
        # reference train_cifar10.py augmentation: pad-to-32 was done at
        # packing time; random 28x28 crop + mirror at train time
        train = mx.ImageRecordIter(
            path_imgrec=os.path.join(d, "train.rec"), data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, mean_img=os.path.join(d, "mean.bin"),
            preprocess_threads=args.data_nthreads,
            part_index=args.part_index, num_parts=args.num_parts)
        test_path = os.path.join(d, "test.rec")
        val = mx.ImageRecordIter(
            path_imgrec=test_path, data_shape=shape,
            batch_size=args.batch_size,
            mean_img=os.path.join(d, "mean.bin"),
            preprocess_threads=args.data_nthreads) \
            if os.path.exists(test_path) else None
        return train, val
    rng = np.random.RandomState(0)
    n = args.batch_size * 8
    X = rng.standard_normal((n,) + shape).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.float32)
    return mx.io.NDArrayIter(X, y, args.batch_size), None


def main():
    parser = common.add_fit_args(__import__("argparse").ArgumentParser(
        description=__doc__))
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--data-nthreads", type=int, default=4)
    parser.add_argument("--part-index", type=int, default=0)
    parser.add_argument("--num-parts", type=int, default=1)
    parser.set_defaults(batch_size=128, lr=0.05, num_epochs=1)
    args = parser.parse_args()

    net = mx.models.inception_bn_small(num_classes=10)
    train, val = get_iters(args)
    common.fit(args, net, train, val)


if __name__ == "__main__":
    main()
