#!/usr/bin/env python
"""Fast-RCNN-style ROI head (compact rebuild of example/rcnn).

The full reference rcnn is a dataset pipeline (Pascal VOC) around this
exact computational core: backbone conv features -> ``ROIPooling`` over
region proposals -> classification head + bbox-regression head trained
jointly (``mx.sym.Group``).  Here the proposals are jittered ground
truth plus random negatives over synthetic box images, so the whole
detection head trains end to end without data downloads.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_head(num_classes):
    data = mx.sym.Variable("data")            # (N, 1, S, S)
    rois = mx.sym.Variable("rois")            # (R, 5) [b, x1, y1, x2, y2]
    conv = mx.sym.Convolution(data, name="conv1", kernel=(3, 3), pad=(1, 1),
                              num_filter=16)
    feat = mx.sym.Activation(conv, act_type="relu")
    pooled = mx.sym.ROIPooling(feat, rois, name="roi_pool",
                               pooled_size=(4, 4), spatial_scale=1.0)
    flat = mx.sym.Flatten(pooled)
    fc = mx.sym.FullyConnected(flat, name="fc", num_hidden=64)
    h = mx.sym.Activation(fc, act_type="relu")
    cls = mx.sym.FullyConnected(h, name="cls", num_hidden=num_classes)
    cls_prob = mx.sym.SoftmaxOutput(cls, name="softmax")
    bbox = mx.sym.FullyConnected(h, name="bbox", num_hidden=4)
    bbox_loss = mx.sym.LinearRegressionOutput(bbox, name="bbox_loss",
                                              grad_scale=0.2)
    return mx.sym.Group([cls_prob, bbox_loss])


def make_batch(rng, n_img, rois_per_img, size):
    """Images with one bright square; proposals = jittered GT + negatives."""
    X = rng.standard_normal((n_img, 1, size, size)).astype(np.float32) * 0.2
    rois, labels, targets = [], [], []
    for b in range(n_img):
        s = rng.randint(size // 4, size // 2)
        x1 = rng.randint(0, size - s)
        y1 = rng.randint(0, size - s)
        X[b, 0, y1:y1 + s, x1:x1 + s] += 1.5
        gt = np.array([x1, y1, x1 + s, y1 + s], np.float32)
        for r in range(rois_per_img):
            if r % 2 == 0:      # positive: jittered ground truth
                jit = rng.randint(-2, 3, 4)
                box = np.clip(gt + jit, 0, size - 1)
                if box[2] <= box[0]: box[2] = box[0] + 1
                if box[3] <= box[1]: box[3] = box[1] + 1
                lab = 1
                # regression target: offset from proposal to gt (normalized)
                tgt = (gt - box) / size
            else:               # negative: random box elsewhere
                w = rng.randint(3, size // 2)
                bx = rng.randint(0, size - w)
                by = rng.randint(0, size - w)
                box = np.array([bx, by, bx + w, by + w], np.float32)
                lab = 0
                tgt = np.zeros(4, np.float32)
            rois.append([b, *box])
            labels.append(lab)
            targets.append(tgt)
    return (X, np.asarray(rois, np.float32), np.asarray(labels, np.float32),
            np.asarray(targets, np.float32))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--images-per-batch", type=int, default=4)
    p.add_argument("--rois-per-image", type=int, default=8)
    p.add_argument("--iterations", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    R = args.images_per_batch * args.rois_per_image

    net = build_head(num_classes=2)
    mod = mx.mod.Module(net, data_names=("data", "rois"),
                        label_names=("softmax_label", "bbox_loss_label"),
                        context=mx.tpu(0))
    # rois and the per-roi labels have no batch ('N') axis: layout ""
    # marks them replicated-whole, not sliced per device (the reference's
    # DataDesc.get_batch_axis == -1 mechanism)
    mod.bind(data_shapes=[("data", (args.images_per_batch, 1, args.size,
                                    args.size)),
                          mx.io.DataDesc("rois", (R, 5), layout="")],
             label_shapes=[mx.io.DataDesc("softmax_label", (R,), layout=""),
                           mx.io.DataDesc("bbox_loss_label", (R, 4),
                                          layout="")])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    metric = mx.metric.Accuracy()
    for it in range(args.iterations):
        X, rois, labels, targets = make_batch(
            rng, args.images_per_batch, args.rois_per_image, args.size)
        batch = mx.io.DataBatch(
            [mx.nd.array(X), mx.nd.array(rois)],
            [mx.nd.array(labels), mx.nd.array(targets)])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        metric.update([mx.nd.array(labels)], [mod.get_outputs()[0]])
        if (it + 1) % 20 == 0:
            logging.info("iter %d roi cls acc %.3f", it + 1,
                         metric.get()[1])
            metric.reset()

    # final eval on a fresh batch
    X, rois, labels, targets = make_batch(
        rng, args.images_per_batch, args.rois_per_image, args.size)
    mod.forward(mx.io.DataBatch([mx.nd.array(X), mx.nd.array(rois)],
                                [mx.nd.array(labels),
                                 mx.nd.array(targets)]), is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
    acc = (pred == labels).mean()
    bbox_err = np.abs(mod.get_outputs()[1].asnumpy()
                      - targets)[labels == 1].mean()
    print(f"rcnn roi-head accuracy {acc:.3f}, bbox l1 {bbox_err:.4f}")


if __name__ == "__main__":
    main()
