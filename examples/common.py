"""Shared training driver for the examples (rebuild of
example/image-classification/train_model.py: kvstore selection,
checkpointing, resume via --load-epoch, Speedometer logging)."""

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def add_fit_args(parser):
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--lr-factor", type=float, default=1.0,
                        help="epoch-wise lr decay factor")
    parser.add_argument("--lr-factor-epoch", type=float, default=1.0)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--kv-store", default="local",
                        help="local / device / dist_sync / dist_async")
    parser.add_argument("--model-prefix", default=None,
                        help="checkpoint prefix")
    parser.add_argument("--load-epoch", type=int, default=None,
                        help="resume from this checkpoint epoch")
    parser.add_argument("--log-interval", type=int, default=50)
    parser.add_argument("--gpus", default=None,
                        help="device indices, e.g. 0,1 (default: all)")
    parser.add_argument("--optimizer", default="sgd",
                        help="sgd / lars / lamb / adam / adamw / ... "
                             "(lars+cosine is the TPU-pod large-batch "
                             "recipe)")
    parser.add_argument("--lr-scheduler", default="factor",
                        choices=["factor", "cosine", "poly"])
    parser.add_argument("--warmup-epochs", type=float, default=0.0,
                        help="linear lr warmup (cosine/poly schedulers)")
    return parser


def contexts(args):
    if args.gpus:
        return [mx.tpu(int(i)) for i in args.gpus.split(",")]
    n = mx.num_devices()
    return [mx.tpu(i) for i in range(n)] if n > 1 else [mx.tpu(0)]


def fit(args, net, train_iter, val_iter=None, eval_metric="acc"):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    kv = mx.kv.create(args.kv_store)

    model_args = {}
    if args.load_epoch is not None:
        assert args.model_prefix is not None
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        model_args = {"arg_params": arg_params, "aux_params": aux_params,
                      "begin_epoch": args.load_epoch}

    lr_scheduler = None
    epoch_size = max(getattr(train_iter, "num_data", 50000)
                     // args.batch_size, 1)
    sched_name = getattr(args, "lr_scheduler", "factor")
    if sched_name in ("cosine", "poly"):
        cls = (mx.lr_scheduler.CosineScheduler if sched_name == "cosine"
               else mx.lr_scheduler.PolyScheduler)
        lr_scheduler = cls(
            max_update=epoch_size * args.num_epochs,
            warmup_steps=int(epoch_size
                             * getattr(args, "warmup_epochs", 0.0)))
    elif args.lr_factor < 1.0:
        lr_scheduler = mx.lr_scheduler.FactorScheduler(
            step=max(int(epoch_size * args.lr_factor_epoch), 1),
            factor=args.lr_factor)

    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix else None)

    opt_name = getattr(args, "optimizer", "sgd")
    opt_kwargs = ({"momentum": 0.9} if opt_name
                  in ("sgd", "ccsgd", "nag", "lars") else {})
    if args.load_epoch is not None:
        # seed the update count so cosine/poly schedules resume from
        # the checkpoint's position instead of replaying the warmup
        opt_kwargs["begin_num_update"] = args.load_epoch * epoch_size
    model = mx.FeedForward(
        net, ctx=contexts(args), num_epoch=args.num_epochs,
        optimizer=opt_name,
        learning_rate=args.lr, wd=1e-4, **opt_kwargs,
        initializer=mx.initializer.Xavier(factor_type="in", magnitude=2.34),
        lr_scheduler=lr_scheduler, **model_args)
    model.fit(X=train_iter, eval_data=val_iter, eval_metric=eval_metric,
              kvstore=kv,
              batch_end_callback=mx.callback.Speedometer(
                  args.batch_size, args.log_interval),
              epoch_end_callback=checkpoint)
    return model
