#!/usr/bin/env python
"""Multi-task training with grouped loss heads
(rebuild of example/multi-task/example_multi_task.py).

One trunk, two SoftmaxOutput heads joined with ``mx.sym.Group``; a
wrapper iterator duplicates the label stream per head and a custom
multi-head accuracy metric tracks each head separately.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_network():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    sm1 = mx.sym.SoftmaxOutput(fc3, name="softmax1")
    # second task: parity of the digit
    fc4 = mx.sym.FullyConnected(act2, name="fc4", num_hidden=2)
    sm2 = mx.sym.SoftmaxOutput(fc4, name="softmax2")
    return mx.sym.Group([sm1, sm2])


class MultiTaskIter(mx.io.DataIter):
    """Wraps a single-label iterator into (digit, parity) label pairs."""

    def __init__(self, data_iter):
        super().__init__()
        self.data_iter = data_iter
        self.batch_size = data_iter.batch_size

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        name, shape = self.data_iter.provide_label[0]
        return [("softmax1_label", shape), ("softmax2_label", shape)]

    def reset(self):
        self.data_iter.reset()

    def next(self):
        batch = self.data_iter.next()
        digits = batch.label[0]
        parity = mx.nd.array(digits.asnumpy() % 2)
        return mx.io.DataBatch(data=batch.data, label=[digits, parity],
                               pad=batch.pad, index=batch.index)


class MultiAccuracy(mx.metric.EvalMetric):
    """Per-head accuracy (reference Multi_Accuracy)."""

    def __init__(self, num):
        super().__init__("multi-accuracy", num=num)

    def update(self, labels, preds):
        for i in range(self.num):
            pred = preds[i].asnumpy().argmax(axis=1)
            label = labels[i].asnumpy().astype("int32")
            self.sum_metric[i] += (pred.flat == label.flat).sum()
            self.num_inst[i] += len(pred.flat)

    def get(self):
        accs = [s / max(n, 1) for s, n in zip(self.sum_metric, self.num_inst)]
        return ([f"task{i}-accuracy" for i in range(self.num)], accs)


def synthetic_mnist(n, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    X = rng.standard_normal((n, 784)).astype(np.float32) * 0.3
    X[np.arange(n), y * 78] += 2.0
    return X, y.astype(np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--n-train", type=int, default=4000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, y = synthetic_mnist(args.n_train)
    Xv, yv = synthetic_mnist(1000, seed=1)
    train = MultiTaskIter(mx.io.NDArrayIter(X, y, args.batch_size,
                                            shuffle=True))
    val = MultiTaskIter(mx.io.NDArrayIter(Xv, yv, args.batch_size))
    net = build_network()
    mod = mx.mod.Module(net, label_names=("softmax1_label", "softmax2_label"),
                        context=mx.tpu(0))
    metric = MultiAccuracy(num=2)
    mod.fit(train, eval_data=val, eval_metric=metric,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.num_epochs)
    names, accs = metric.get()
    for nm, a in zip(names, accs):
        print(f"{nm}: {a:.3f}")


if __name__ == "__main__":
    main()
