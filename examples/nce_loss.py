#!/usr/bin/env python
"""Noise-contrastive estimation (rebuild of example/nce-loss/toy_nce.py).

Instead of a full softmax over the vocabulary, each example is scored
against its true class plus k sampled noise classes; the loss is
logistic over those k+1 dot products.  Built from Embedding lookups +
broadcast arithmetic + LogisticRegressionOutput, mirroring the
reference's nce.py construction.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def nce_loss(data, label_with_noise, label_weight, embed_dim, num_label):
    """Score data against num_label candidate classes.

    label_with_noise: (batch, num_label) class ids, col 0 = true class.
    label_weight: (batch, num_label) 1 for the true class else 0.
    """
    class_embed = mx.sym.Embedding(label_with_noise, name="class_embed",
                                   input_dim=1000, output_dim=embed_dim)
    class_bias = mx.sym.Embedding(label_with_noise, name="class_bias",
                                  input_dim=1000, output_dim=1)
    # (batch, 1, d) * (batch, k, d) -> sum over d -> (batch, k)
    data3 = mx.sym.Reshape(data, target_shape=(0, 1, embed_dim))
    prod = mx.sym.broadcast_mul(data3, class_embed)
    dots = mx.sym.sum(prod, axis=2) + mx.sym.Reshape(class_bias,
                                                     shape=(0, -1))
    return mx.sym.LogisticRegressionOutput(dots, label=label_weight,
                                           name="nce")


def build_net(num_feat, embed_dim, num_label):
    data = mx.sym.Variable("data")
    labels = mx.sym.Variable("label_with_noise")
    weights = mx.sym.Variable("label_weight")
    fc = mx.sym.FullyConnected(data, name="proj", num_hidden=embed_dim)
    h = mx.sym.Activation(fc, act_type="tanh")
    return nce_loss(h, labels, weights, embed_dim, num_label)


class NceIter(mx.io.DataIter):
    """Yields (data, [true + sampled noise classes], weights)."""

    def __init__(self, X, y, batch_size, num_label, vocab, seed=1):
        super().__init__()
        self.X, self.y = X, y
        self.batch_size, self.num_label, self.vocab = (batch_size, num_label,
                                                       vocab)
        self.rng = np.random.RandomState(seed)
        self.cursor = 0
        self.provide_data = [("data", (batch_size, X.shape[1])),
                             ("label_with_noise", (batch_size, num_label)),
                             ("label_weight", (batch_size, num_label))]
        self.provide_label = []

    def reset(self):
        self.cursor = 0

    def next(self):
        if self.cursor + self.batch_size > len(self.X):
            raise StopIteration
        i = self.cursor
        self.cursor += self.batch_size
        yb = self.y[i:i + self.batch_size]
        noise = self.rng.randint(0, self.vocab,
                                 (self.batch_size, self.num_label))
        noise[:, 0] = yb
        w = np.zeros_like(noise, np.float32)
        w[:, 0] = 1.0
        return mx.io.DataBatch(
            [mx.nd.array(self.X[i:i + self.batch_size]),
             mx.nd.array(noise.astype(np.float32)),
             mx.nd.array(w)], [])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--vocab", type=int, default=100)
    p.add_argument("--embed-dim", type=int, default=32)
    p.add_argument("--num-label", type=int, default=6,
                   help="1 true + k noise classes")
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--n-train", type=int, default=3200)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)

    # learnable mapping: class = argmax over feature groups
    y = rng.randint(0, args.vocab, args.n_train)
    X = rng.standard_normal((args.n_train, 64)).astype(np.float32) * 0.3
    X[np.arange(args.n_train), y % 64] += 2.0

    train = NceIter(X, y, args.batch_size, args.num_label, args.vocab)
    net = build_net(64, args.embed_dim, args.num_label)
    mod = mx.mod.Module(net,
                        data_names=("data", "label_with_noise",
                                    "label_weight"),
                        label_names=None, context=mx.tpu(0))
    mod.bind(data_shapes=train.provide_data)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    for epoch in range(args.num_epochs):
        train.reset()
        losses = []
        for batch in train:
            mod.forward(batch, is_train=True)
            out = mod.get_outputs()[0].asnumpy()
            # logistic loss against the weight targets (col 0 = positive)
            w = batch.data[2].asnumpy()
            eps = 1e-7
            losses.append(-np.mean(w * np.log(out + eps)
                                   + (1 - w) * np.log(1 - out + eps)))
            mod.backward()
            mod.update()
        logging.info("epoch %d nce loss %.4f", epoch, np.mean(losses))
    print(f"nce final loss {np.mean(losses):.4f} "
          f"(chance = {-np.log(0.5):.4f} per candidate)")


if __name__ == "__main__":
    main()
