#!/usr/bin/env python
"""Train ResNet-50 on ImageNet RecordIO shards, or benchmark on
synthetic data (rebuild of example/image-classification/train_imagenet.py
+ benchmark.py).

Real data: --data-dir with train.rec/val.rec packed by tools/im2rec.py.
No data: synthetic device-resident batches (the benchmark.py mode).
"""

import os

import numpy as np

import common
import mxnet_tpu as mx


def get_iters(args):
    shape = tuple(int(x) for x in args.image_shape.split(","))
    d = args.data_dir
    if d and os.path.exists(os.path.join(d, "train.rec")):
        train = mx.ImageRecordIter(
            path_imgrec=os.path.join(d, "train.rec"), data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, preprocess_threads=args.data_nthreads,
            part_index=args.part_index, num_parts=args.num_parts)
        val_path = os.path.join(d, "val.rec")
        val = mx.ImageRecordIter(
            path_imgrec=val_path, data_shape=shape,
            batch_size=args.batch_size,
            preprocess_threads=args.data_nthreads) \
            if os.path.exists(val_path) else None
        return train, val
    # synthetic benchmark mode
    rng = np.random.RandomState(0)
    n = args.batch_size * 8
    X = rng.standard_normal((n,) + shape).astype(np.float32)
    y = rng.randint(0, args.num_classes, n).astype(np.float32)
    return mx.io.NDArrayIter(X, y, args.batch_size), None


def main():
    parser = common.add_fit_args(__import__("argparse").ArgumentParser(
        description=__doc__))
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--num-layers", type=int, default=50)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--layout", default="NHWC", choices=["NCHW", "NHWC"],
                        help="NHWC feeds the TPU MXU best")
    parser.add_argument("--data-nthreads", type=int, default=4)
    parser.add_argument("--part-index", type=int, default=0)
    parser.add_argument("--num-parts", type=int, default=1)
    args = parser.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))
    net = mx.models.resnet(num_classes=args.num_classes,
                           num_layers=args.num_layers, image_shape=shape,
                           layout=args.layout)
    train, val = get_iters(args)
    common.fit(args, net, train, val)


if __name__ == "__main__":
    main()
