#!/usr/bin/env python
"""CTC acoustic-model training
(rebuild of example/warpctc/lstm_ocr.py / example/speech-demo shape:
LSTM over frames + CTC loss with unaligned label sequences).

Synthetic task: each "utterance" is a sequence of noisy one-hot frames
stretching a short label string; the model must learn the alignment
itself — exactly what CTC is for.  Uses the fused RNN op and the
WarpCTC-parity ``mx.sym.ctc_loss``.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_net(seq_len, num_feat, num_classes, num_hidden=64):
    data = mx.sym.Variable("data")            # (batch, seq_len, num_feat)
    label = mx.sym.Variable("label")          # (batch, label_len), 0 = blank
    tns = mx.sym.SwapAxis(data, dim1=0, dim2=1)
    rnn = mx.sym.RNN(tns, name="lstm", mode="lstm", state_size=num_hidden,
                     num_layers=1,
                     parameters=mx.sym.Variable("lstm_parameters"),
                     state=mx.sym.Variable("lstm_state"),
                     state_cell=mx.sym.Variable("lstm_state_cell"))
    flat = mx.sym.Reshape(rnn, shape=(-1, num_hidden))
    fc = mx.sym.FullyConnected(flat, name="cls", num_hidden=num_classes + 1)
    pred = mx.sym.Reshape(fc, shape=(seq_len, -1, num_classes + 1))
    return mx.sym.MakeLoss(mx.sym.ctc_loss(pred, label))


def make_data(n, seq_len, label_len, num_classes, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(1, num_classes + 1, (n, label_len))
    num_feat = num_classes + 1
    X = rng.standard_normal((n, seq_len, num_feat)).astype(np.float32) * 0.3
    reps = seq_len // label_len
    for i in range(n):
        for j, c in enumerate(labels[i]):
            X[i, j * reps:(j + 1) * reps, c] += 2.0
    return X, labels.astype(np.float32)


def greedy_decode(probs):
    """Collapse repeats, strip blanks (class 0)."""
    best = probs.argmax(axis=-1)
    out = []
    prev = -1
    for c in best:
        if c != prev and c != 0:
            out.append(int(c))
        prev = c
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=24)
    p.add_argument("--label-len", type=int, default=4)
    p.add_argument("--num-classes", type=int, default=8)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=1600)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, labels = make_data(args.n_train, args.seq_len, args.label_len,
                          args.num_classes)
    train = mx.io.NDArrayIter(X, labels, args.batch_size, shuffle=True,
                              label_name="label")
    net = build_net(args.seq_len, X.shape[2], args.num_classes)
    mod = mx.mod.Module(net, label_names=("label",), context=mx.tpu(0))
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            num_epoch=args.num_epochs, eval_metric=mx.metric.Loss(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))

    # decode a sample with a prediction-only executor
    data = mx.sym.Variable("data")
    tns = mx.sym.SwapAxis(data, dim1=0, dim2=1)
    rnn = mx.sym.RNN(tns, name="lstm", mode="lstm", state_size=64,
                     num_layers=1,
                     parameters=mx.sym.Variable("lstm_parameters"),
                     state=mx.sym.Variable("lstm_state"),
                     state_cell=mx.sym.Variable("lstm_state_cell"))
    flat = mx.sym.Reshape(rnn, shape=(-1, 64))
    fc = mx.sym.FullyConnected(flat, name="cls", num_hidden=args.num_classes + 1)
    pred_sym = mx.sym.SoftmaxActivation(fc)  # (seq_len*1, C+1) rows
    arg_params, _ = mod.get_params()
    exe = pred_sym.simple_bind(ctx=mx.tpu(0), grad_req="null",
                               data=(1,) + X.shape[1:])
    for k, v in arg_params.items():
        # skip batch-shaped RNN initial states (zeros; batch differs here)
        if k in exe.arg_dict and not k.endswith(("state", "state_cell")):
            exe.arg_dict[k][:] = v
    exe.arg_dict["data"][:] = X[:1]
    exe.forward(is_train=False)
    probs = exe.outputs[0].asnumpy()  # (seq_len, C+1), batch of one
    print("target:", labels[0].astype(int).tolist())
    print("decoded:", greedy_decode(probs))


if __name__ == "__main__":
    main()
