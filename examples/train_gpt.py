#!/usr/bin/env python
"""Train a GPT-style language model (beyond-parity model-zoo driver;
the reference era's LM example is examples/lstm_bucketing.py).

Character-level next-token prediction on synthetic Markov text (or a
real text file via --data).  Uses the fused-attention transformer from
``mx.models.gpt`` — on TPU the attention lowers to the Pallas flash
kernel.  ``--trainer sharded`` trains the same symbol with the
data-parallel mesh trainer instead of the Module path.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def synthetic_corpus(n_tokens, vocab, seed=0):
    """Order-1 Markov chain with a sparse transition matrix, so a
    next-token model has learnable structure."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab, 0.1), size=vocab)
    toks = np.zeros(n_tokens, np.int64)
    for i in range(1, n_tokens):
        toks[i] = rng.choice(vocab, p=trans[toks[i - 1]])
    return toks


def _nll(out0, y, ce_loss):
    """Mean next-token NLL from the head's output: per-position losses
    (loss='ce') or softmax probabilities (default head)."""
    if ce_loss:
        return float(np.mean(out0))
    return float(-np.log(out0[np.arange(len(out0)),
                              y.reshape(-1).astype(int)] + 1e-9).mean())


def batches(tokens, batch_size, seq_len, rng):
    starts = rng.randint(0, len(tokens) - seq_len - 1, batch_size)
    x = np.stack([tokens[s:s + seq_len] for s in starts])
    y = np.stack([tokens[s + 1:s + seq_len + 1] for s in starts])
    return x.astype(np.float32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--data", default=None, help="utf-8 text file")
    p.add_argument("--trainer", default="module",
                   choices=["module", "sharded"])
    p.add_argument("--attn-layout", default="bhsd",
                   choices=["bhsd", "bshd"],
                   help="bshd = sequence-major attention (no activation "
                        "transposes; see BENCH_NOTES.md)")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3: store params sharded over dp "
                        "(--trainer sharded only)")
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention: K/V heads "
                        "(0 = num-heads, i.e. standard MHA)")
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings instead of the "
                        "learned table")
    p.add_argument("--window", type=int, default=0,
                   help="sliding-window attention radius (0 = full)")
    p.add_argument("--llama-style", action="store_true",
                   help="rmsnorm + swiglu + rope + tied embeddings "
                        "(the modern decoder recipe) in one flag")
    p.add_argument("--ce-loss", action="store_true",
                   help="fused cross-entropy head (no (B*S, vocab) "
                        "probability tensor)")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, KV-cache-decode N tokens from a "
                        "corpus prompt (models/generate.py)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="sampling temperature for --generate (0 = greedy)")
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")
    if args.generate > 0 and args.seq_len - args.generate < 1:
        p.error("--generate must leave room for a prompt within --seq-len")
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)

    if args.data and os.path.exists(args.data):
        raw = open(args.data, "rb").read()
        chars = sorted(set(raw))
        args.vocab = len(chars)
        lut = {c: i for i, c in enumerate(chars)}
        tokens = np.array([lut[c] for c in raw], np.int64)
        if len(tokens) < args.seq_len + 2:
            p.error(f"--data has {len(tokens)} tokens; need at least "
                    f"seq_len+2 = {args.seq_len + 2}")
    else:
        tokens = synthetic_corpus(50000, args.vocab)

    net = mx.models.gpt(args.vocab, args.seq_len, num_layers=args.num_layers,
                        d_model=args.d_model, num_heads=args.num_heads,
                        attn_layout=args.attn_layout,
                        kv_heads=args.kv_heads or None,
                        pos_embed=("rope" if (args.rope or args.llama_style)
                                   else "learned"),
                        attn_window=args.window,
                        norm="rmsnorm" if args.llama_style else "layernorm",
                        mlp="swiglu" if args.llama_style else "gelu",
                        tie_embeddings=args.llama_style,
                        loss="ce" if args.ce_loss else "softmax")

    if args.trainer == "sharded":
        mesh = mx.parallel.local_mesh("dp")
        tr = mx.parallel.ShardedTrainer(
            net, {"data": (args.batch_size, args.seq_len),
                  "softmax_label": (args.batch_size, args.seq_len)},
            mesh=mesh, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            input_dtypes={"data": np.float32}, fsdp=args.fsdp)
        for step in range(args.steps):
            x, y = batches(tokens, args.batch_size, args.seq_len, rng)
            outs = tr.step({"data": x, "softmax_label": y})
            if step % 20 == 0 or step == args.steps - 1:
                out0 = np.asarray(outs[0])
                nll = _nll(out0, y, args.ce_loss)
                logging.info("step %d nll %.4f (uniform %.4f)", step, nll,
                             np.log(args.vocab))
    else:
        mod = mx.mod.Module(net, context=mx.tpu(0))
        mod.bind(data_shapes=[("data", (args.batch_size, args.seq_len))],
                 label_shapes=[("softmax_label",
                                (args.batch_size, args.seq_len))])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="adam",
                           optimizer_params={"learning_rate": args.lr})
        for step in range(args.steps):
            x, y = batches(tokens, args.batch_size, args.seq_len, rng)
            mod.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)]),
                        is_train=True)
            mod.backward()
            mod.update()
            if step % 20 == 0 or step == args.steps - 1:
                out0 = mod.get_outputs()[0].asnumpy()
                nll = _nll(out0, y, args.ce_loss)
                logging.info("step %d nll %.4f (uniform %.4f)", step, nll,
                             np.log(args.vocab))
    print(f"gpt final nll {nll:.4f} vs uniform {np.log(args.vocab):.4f}")

    if args.generate > 0:
        if args.trainer == "sharded":
            params = tr.get_params()
        else:
            params = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
        prompt_len = min(8, args.seq_len - args.generate)
        prompt = tokens[:prompt_len][None]
        out = mx.models.gpt_generate(params, prompt, args.generate,
                                     temperature=args.temperature,
                                     symbol=net)
        cont = out[0, prompt_len:]
        if args.data and os.path.exists(args.data):
            inv = {i: c for c, i in lut.items()}
            text = bytes(inv[int(t)] for t in out[0]).decode(
                "utf-8", "replace")
            print(f"generated: {text!r}")
        else:
            print(f"prompt {list(map(int, prompt[0]))} -> "
                  f"continuation {list(map(int, cont))}")


if __name__ == "__main__":
    main()
