// LeNet training through the graduated C++ frontend: runtime op-registry
// discovery + Symbol composition + Module-style fit + DataIter + params
// checkpoint + predict — the cpp-package depth proof (reference
// cpp-package/example/lenet.cpp over include/mxnet-cpp, here over the
// flat C ABI in include/mxtpu/c_api.h only; no Python headers).
//
// The accuracy gate matches the Python tier's LeNet convergence test
// (tests/test_train.py::test_lenet_convergence: acc > 0.95).
//
// Usage: train_lenet <images.idx> <labels.idx> <batch> <epochs>

#include <cstdio>
#include <string>
#include <vector>

#include "mxtpu/cpp/mxtpu.hpp"

using namespace mxtpu::cpp;

// LeNet from the RUNTIME-DISCOVERED registry: every op name is checked
// against ListOps() and its required data inputs against GetOpInfo()
// before composing — the frontend hard-codes nothing about the op set.
static Symbol BuildLeNet() {
  auto ops = ListOps();
  auto have = [&](const std::string& n) {
    for (const auto& o : ops)
      if (o == n) return true;
    return false;
  };
  for (const char* need : {"Convolution", "Pooling", "Activation",
                           "BatchNorm", "FullyConnected", "Flatten",
                           "SoftmaxOutput"}) {
    if (!have(need))
      throw std::runtime_error(std::string("registry missing op: ") + need);
    OpInfo info = GetOpInfo(need);
    if (info.arg_names.empty())
      throw std::runtime_error(std::string("op has no inputs: ") + need);
  }
  std::fprintf(stderr, "registry: %zu ops discovered; Convolution(%s...)\n",
               ops.size(), GetOpInfo("Convolution").arg_names[0].c_str());

  Symbol data = Symbol::Variable("data");
  Symbol net = Op("Convolution", {{"kernel", "(5, 5)"},
                                  {"num_filter", "8"}}, {data}, "conv1");
  // BN exercises gamma/beta args AND the aux moving stats through the
  // Module init/save/load path (the reload-score-parity check below
  // fails if aux states are dropped from the checkpoint)
  net = Op("BatchNorm", {{"fix_gamma", "False"}}, {net}, "bn1");
  net = Op("Activation", {{"act_type", "tanh"}}, {net}, "act1");
  net = Op("Pooling", {{"kernel", "(2, 2)"}, {"stride", "(2, 2)"},
                       {"pool_type", "max"}}, {net}, "pool1");
  net = Op("Convolution", {{"kernel", "(5, 5)"},
                           {"num_filter", "16"}}, {net}, "conv2");
  net = Op("Activation", {{"act_type", "tanh"}}, {net}, "act2");
  net = Op("Pooling", {{"kernel", "(2, 2)"}, {"stride", "(2, 2)"},
                       {"pool_type", "max"}}, {net}, "pool2");
  net = Op("Flatten", {}, {net}, "flat");
  net = Op("FullyConnected", {{"num_hidden", "120"}}, {net}, "fc1");
  net = Op("Activation", {{"act_type", "tanh"}}, {net}, "act3");
  net = Op("FullyConnected", {{"num_hidden", "10"}}, {net}, "fc2");
  return Op("SoftmaxOutput", {{"normalization", "batch"}}, {net}, "softmax");
}

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr, "usage: %s img.idx lab.idx batch epochs\n", argv[0]);
    return 2;
  }
  const std::string img = argv[1], lab = argv[2];
  const uint32_t batch = std::atoi(argv[3]);
  const int epochs = std::atoi(argv[4]);

  try {
    RandomSeed(7);

    Module mod(BuildLeNet());
    mod.Bind({{"data", {batch, 1, 28, 28}},
              {"softmax_label", {batch}}});
    Xavier init(3.0, 7);
    mod.InitParams(init);
    mod.InitOptimizer("sgd", {{"learning_rate", "0.1"},
                              {"momentum", "0.9"}});

    DataIter it("MNISTIter", {{"image", img}, {"label", lab},
                              {"batch_size", std::to_string(batch)},
                              {"shuffle", "True"}});

    for (int e = 0; e < epochs; ++e) {
      double acc = mod.FitEpoch(it);
      std::fprintf(stderr, "epoch %d train-accuracy %.3f\n", e, acc);
    }
    double final_acc = mod.Score(it);
    std::fprintf(stderr, "final accuracy %.3f\n", final_acc);

    // checkpoint round trip: save, clobber, reload, same score
    const std::string ckpt = img + ".params";
    mod.SaveParams(ckpt);
    Xavier clobber(3.0, 99);
    mod.InitParams(clobber);
    mod.LoadParams(ckpt);
    double reload_acc = mod.Score(it);
    if (reload_acc != final_acc) {
      std::fprintf(stderr, "FAIL reload score %.5f != %.5f\n", reload_acc,
                   final_acc);
      return 1;
    }

    // single-batch predict surface
    DataIter probe("MNISTIter", {{"image", img}, {"label", lab},
                                 {"batch_size", std::to_string(batch)}});
    probe.Next();
    std::vector<float> p = mod.Predict(probe.Data().SyncCopyToCPU());
    if (p.size() != static_cast<size_t>(batch) * 10) {
      std::fprintf(stderr, "FAIL predict size %zu\n", p.size());
      return 1;
    }

    // same gate as the Python LeNet convergence test
    if (final_acc <= 0.95) {
      std::fprintf(stderr, "FAIL accuracy %.3f <= 0.95\n", final_acc);
      return 1;
    }
    std::printf("CPP_LENET_OK %.3f\n", final_acc);
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAIL exception: %s\n", e.what());
    return 1;
  }
}
