// Train an MLP from C++ through the header-only frontend — the
// user-facing companion of tests/cpp/cpp_frontend_train.cc (reference
// cpp-package/example/mlp.cpp role).  No Python headers: everything
// routes through the flat C ABI.
//
// Build (from the repo root):
//   g++ -std=c++17 examples/cpp/train_mlp.cc -I include \
//       -L mxnet_tpu/lib -lmxtpu -Wl,-rpath,mxnet_tpu/lib -o train_mlp
//   PYTHONPATH=. ./train_mlp
//
// Task: learn y = sign(x0) on random vectors — converges to ~1.0
// train accuracy in a few hundred steps.

#include <cstdio>
#include <random>
#include <vector>

#include "mxtpu/cpp/mxtpu.hpp"

using namespace mxtpu::cpp;

int main() {
  const uint32_t kBatch = 32, kDim = 16, kSteps = 300;
  RandomSeed(0);

  Symbol data = Symbol::Variable("data");
  Symbol net = Op("FullyConnected", {{"num_hidden", "32"}}, {data}, "fc1");
  net = Op("Activation", {{"act_type", "relu"}}, {net}, "relu1");
  net = Op("FullyConnected", {{"num_hidden", "2"}}, {net}, "fc2");
  net = Op("SoftmaxOutput", {{"normalization", "batch"}}, {net}, "softmax");

  auto arg_names = net.ListArguments();
  auto shapes = net.InferShape({{"data", {kBatch, kDim}}});
  if (!shapes.complete || shapes.arg.size() != arg_names.size()) {
    std::fprintf(stderr, "shape inference incomplete\n");
    return 1;
  }

  std::mt19937 rng(0);
  std::normal_distribution<float> gauss(0.f, 1.f);
  std::uniform_real_distribution<float> init(-0.1f, 0.1f);

  std::vector<NDArray> args, grads;
  std::vector<GradReq> reqs;
  int data_idx = -1, label_idx = -1;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    args.emplace_back(shapes.arg[i]);
    if (arg_names[i] == "data") data_idx = static_cast<int>(i);
    if (arg_names[i] == "softmax_label") label_idx = static_cast<int>(i);
    if (arg_names[i] == "data" || arg_names[i] == "softmax_label") {
      grads.emplace_back();
      reqs.push_back(GradReq::kNull);
    } else {
      std::vector<float> w(args.back().Size());
      for (auto& v : w) v = init(rng);
      args.back().SyncCopyFromCPU(w);
      grads.emplace_back(shapes.arg[i]);
      reqs.push_back(GradReq::kWrite);
    }
  }

  if (data_idx < 0 || label_idx < 0) {
    std::fprintf(stderr, "data/softmax_label arguments not found\n");
    return 1;
  }

  Executor exec(net, args, grads, reqs);
  KVStore kv("local");
  kv.SetOptimizer("sgd", {{"learning_rate", "0.2"}, {"momentum", "0.9"}});
  for (size_t i = 0; i < args.size(); ++i)
    if (reqs[i] == GradReq::kWrite) kv.Init(static_cast<int>(i), args[i]);

  std::vector<float> x(kBatch * kDim), y(kBatch);
  double correct = 0, total = 0;
  for (uint32_t step = 0; step < kSteps; ++step) {
    for (uint32_t b = 0; b < kBatch; ++b) {
      for (uint32_t d = 0; d < kDim; ++d) x[b * kDim + d] = gauss(rng);
      y[b] = x[b * kDim] > 0.f ? 1.f : 0.f;
    }
    args[data_idx].SyncCopyFromCPU(x);
    args[label_idx].SyncCopyFromCPU(y);
    exec.Forward(true);
    exec.Backward();
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] != GradReq::kWrite) continue;
      kv.Push(static_cast<int>(i), grads[i], -static_cast<int>(i));
      kv.Pull(static_cast<int>(i), &args[i], -static_cast<int>(i));
    }
    if (step >= kSteps - 50) {  // score the last 50 steps
      auto probs = exec.Outputs()[0].SyncCopyToCPU();
      for (uint32_t b = 0; b < kBatch; ++b) {
        int pred = probs[b * 2 + 1] > probs[b * 2] ? 1 : 0;
        correct += pred == static_cast<int>(y[b]);
        ++total;
      }
    }
  }
  std::printf("cpp train_mlp: accuracy over final steps %.3f\n",
              correct / total);
  return correct / total > 0.9 ? 0 : 1;
}
