#!/usr/bin/env python
"""Kaggle NDSB-2 style volume regression (rebuild of
example/kaggle-ndsb2/Train.py).

The second data-science-bowl recipe: predict a cardiac-volume CDF.
Labels are step-function encoded — ``label[k] = (volume < k)`` over K
bins — a K-way ``LogisticRegressionOutput`` regresses the CDF directly,
and the competition's CRPS metric (mean squared CDF distance) drives
evaluation through ``mx.metric.np``.  Data and encoded labels flow
through ``CSVIter`` with a multi-column ``label_shape``, exactly like
the reference's ``encode_csv`` + ``mx.io.CSVIter`` pipeline.

Synthetic task: the "volume" is the bright-pixel area of a blob image,
so the CDF is learnable from pixels alone.
"""

import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402

K = 60  # CDF bins (reference uses 600 for ml of blood volume)


def get_net(hw):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=16, name="c2")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="f1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=K, name="f2")
    # K-way sigmoid regressing the CDF (Train.py:38)
    return mx.sym.LogisticRegressionOutput(net, name="softmax")


def CRPS(label, pred):
    """Continuous ranked probability score over CDF vectors
    (Train.py:40-50)."""
    # enforce monotone CDF like the reference submission code would
    pred = np.maximum.accumulate(pred, axis=1)
    return float(np.mean(np.square(label - pred)))


def encode_label(volumes):
    """volume scalar -> step-function CDF target (Train.py:52-63)."""
    return np.array([(v < np.arange(K)) for v in volumes], np.float32)


def make_dataset(n, hw, rng):
    imgs = np.zeros((n, 1, hw, hw), np.float32)
    vols = np.zeros(n)
    for i in range(n):
        r = rng.randint(2, hw // 2 - 1)
        cy, cx = rng.randint(r, hw - r, 2)
        yy, xx = np.mgrid[:hw, :hw]
        blob = ((yy - cy) ** 2 + (xx - cx) ** 2) <= r * r
        imgs[i, 0][blob] = 1.0
        imgs[i, 0] += rng.rand(hw, hw) * 0.1
        vols[i] = blob.sum() * K / (hw * hw)  # scale into [0, K)
    return imgs, vols


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hw", type=int, default=24)
    p.add_argument("--n-train", type=int, default=400)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--work-dir", default=None)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(3)
    rng = np.random.RandomState(0)

    work = args.work_dir or tempfile.mkdtemp(prefix="ndsb2_")
    os.makedirs(work, exist_ok=True)
    imgs, vols = make_dataset(args.n_train, args.hw, rng)
    # the reference round-trips everything through CSV files; do the same
    np.savetxt(os.path.join(work, "train-data.csv"),
               imgs.reshape(args.n_train, -1), delimiter=",", fmt="%g")
    np.savetxt(os.path.join(work, "train-systole.csv"),
               encode_label(vols), delimiter=",", fmt="%g")

    data_train = mx.io.CSVIter(
        data_csv=os.path.join(work, "train-data.csv"),
        data_shape=(1, args.hw, args.hw),
        label_csv=os.path.join(work, "train-systole.csv"),
        label_shape=(K,), batch_size=args.batch_size, label_name="softmax_label")

    model = mx.model.FeedForward(
        get_net(args.hw), num_epoch=args.num_epochs,
        learning_rate=args.lr, momentum=0.9, wd=1e-5,
        initializer=mx.initializer.Xavier(rnd_type="gaussian"))
    model.fit(X=data_train, eval_metric=mx.metric.np(CRPS))

    # validation CRPS on fresh volumes
    vimgs, vvols = make_dataset(120, args.hw, rng)
    pred = model.predict(
        X=mx.io.NDArrayIter(vimgs, batch_size=args.batch_size))
    pred = np.asarray(pred)
    crps = CRPS(encode_label(vvols), pred)
    logging.info("validation CRPS %.4f (predict-the-mean would be ~0.1+)",
                 crps)
    assert crps < 0.05, crps
    print(f"NDSB2_OK crps={crps:.4f}")


if __name__ == "__main__":
    main()
