#!/usr/bin/env python
"""Char-level LSTM language model with stepwise sampling.

Rebuild of the reference's char-rnn family
(example/rnn/char-rnn.ipynb + rnn_model.py LSTMInferenceModel): train
an LSTM LM over characters, then generate text one character at a time
through a seq-len-1 inference executor whose hidden/cell state arrays
are carried between steps — the reference's exact inference pattern.

The corpus is synthetic (a repeating alphabet cycle with occasional
noise) so the example is self-contained; a well-trained model samples
the cycle back with near-perfect next-char accuracy.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402

PATTERN = "abcdefgh"


def make_corpus(n_chars, rng):
    """Repeating PATTERN with 5% random substitutions."""
    reps = n_chars // len(PATTERN) + 1
    text = (PATTERN * reps)[:n_chars]
    chars = list(text)
    vocab = sorted(set(PATTERN))
    for i in rng.choice(n_chars, n_chars // 20, replace=False):
        chars[i] = vocab[rng.randint(len(vocab))]
    return "".join(chars), {c: i for i, c in enumerate(vocab)}


def build(vocab, num_hidden, num_embed, for_inference=False):
    """Shared-weight training/inference graphs (shape-agnostic: the bind
    shapes pick T): same argument names, so trained weights copy
    straight into the T=1 inference executor."""
    data = mx.sym.Variable("data")                      # (N, T) ids
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                           name="embed")                # (N, T, E)
    tm = mx.sym.SwapAxis(emb, dim1=0, dim2=1)           # (T, N, E)
    rnn = mx.sym.RNN(tm, state_size=num_hidden, num_layers=1, mode="lstm",
                     state_outputs=for_inference, name="lstm")
    out = rnn[0] if for_inference else rnn
    flat = mx.sym.Reshape(out, shape=(-1, num_hidden))  # (T*N, H)
    logits = mx.sym.FullyConnected(flat, num_hidden=vocab, name="pred")
    sm = mx.sym.SoftmaxOutput(logits, name="softmax",
                              normalization="batch")
    if for_inference:
        return mx.sym.Group([sm, mx.sym.BlockGrad(rnn[1]),
                             mx.sym.BlockGrad(rnn[2])])
    return sm


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=16)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--sample-len", type=int, default=64)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    np.random.seed(0)  # Xavier init draws from global numpy RNG
    mx.random.seed(0)

    text, lut = make_corpus(20000, rng)
    vocab = len(lut)
    ids = np.array([lut[c] for c in text], np.int32)
    T = args.seq_len
    n_seq = (len(ids) - 1) // T
    X = ids[:n_seq * T].reshape(n_seq, T)
    Y = ids[1:n_seq * T + 1].reshape(n_seq, T)

    # -- train --------------------------------------------------------------
    net = build(vocab, args.num_hidden, args.num_embed)
    # labels flattened time-major to match the (T*N,) softmax layout
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",), context=mx.tpu(0))
    mod.bind(data_shapes=[("data", (args.batch_size, T))],
             label_shapes=[mx.io.DataDesc("softmax_label",
                                          (T * args.batch_size,),
                                          layout="T")])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.Accuracy()
    order = np.arange(n_seq - n_seq % args.batch_size)
    for epoch in range(args.epochs):
        rng.shuffle(order)
        metric.reset()
        for s in range(0, len(order), args.batch_size):
            idx = order[s:s + args.batch_size]
            xb = X[idx]
            lab = Y[idx].T.reshape(-1).astype(np.float32)  # time-major
            mod.forward(mx.io.DataBatch([mx.nd.array(xb)],
                                        [mx.nd.array(lab)]),
                        is_train=True)
            mod.backward()
            mod.update()
            metric.update([mx.nd.array(lab)], mod.get_outputs())
        logging.info("epoch %d next-char train acc %.3f", epoch,
                     metric.get()[1])

    # -- stepwise sampling (LSTMInferenceModel pattern) --------------------
    arg_params, aux_params = mod.get_params()
    inf = build(vocab, args.num_hidden, args.num_embed,
                for_inference=True)
    ex = inf.simple_bind(mx.tpu(0), grad_req="null",
                         data=(1, 1),
                         softmax_label=(1,))
    # weights only: the (L*D, N, H) training-state buffers do not fit the
    # batch-1 inference executor; its states start at zero below
    ex.copy_params_from({k: v for k, v in arg_params.items()
                         if not k.startswith("lstm_state")},
                        aux_params, allow_extra_params=True)

    inv = {i: c for c, i in lut.items()}
    cur = lut[PATTERN[0]]
    state = np.zeros((1, 1, args.num_hidden), np.float32)
    cell = np.zeros((1, 1, args.num_hidden), np.float32)
    out_chars = []
    for _ in range(args.sample_len):
        ex.arg_dict["data"][:] = np.array([[cur]], np.float32)
        ex.arg_dict["lstm_state"][:] = state
        ex.arg_dict["lstm_state_cell"][:] = cell
        ex.forward(is_train=False)
        probs = ex.outputs[0].asnumpy()[0]
        state = ex.outputs[1].asnumpy()   # carry LSTM state
        cell = ex.outputs[2].asnumpy()
        cur = int(probs.argmax())         # greedy decode
        out_chars.append(inv[cur])
    sample = "".join(out_chars)
    print("sample:", sample)

    # score the sample against the clean cycle
    want = (PATTERN * (args.sample_len // len(PATTERN) + 2))
    start = want.index(out_chars[0])
    want = want[start:start + args.sample_len]
    acc = np.mean([a == b for a, b in zip(sample, want)])
    print(f"char-rnn sample cycle accuracy {acc:.3f} "
          f"(random = {1.0 / vocab:.3f})")


if __name__ == "__main__":
    main()
