#!/usr/bin/env python
"""Train SSD-VGG16 detection (rebuild of example/ssd/train.py →
train/train_net.py with the native multibox ops).

Real data: --data-dir with train.rec packed by tools/im2rec.py using
detection labels.  Without it, trains briefly on synthetic boxes to
demonstrate the full multibox target/loss path.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def synthetic_det_iter(batch_size, data_shape, num_classes, n=64):
    rng = np.random.RandomState(0)
    X = rng.standard_normal((n,) + data_shape).astype(np.float32)
    labels = np.full((n, 4, 5), -1.0, np.float32)
    for i in range(n):
        for b in range(rng.randint(1, 4)):
            cls = rng.randint(0, num_classes)
            x1, y1 = rng.uniform(0, 0.6, 2)
            w, h = rng.uniform(0.2, 0.4, 2)
            labels[i, b] = [cls, x1, y1, min(x1 + w, 1.0), min(y1 + h, 1.0)]
    return mx.io.NDArrayIter({"data": X}, {"label": labels}, batch_size,
                             shuffle=True)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--data-shape", type=int, default=300)
    p.add_argument("--filter-scale", type=int, default=1,
                   help="channel divisor for quick runs (e.g. 16)")
    p.add_argument("--num-classes", type=int, default=20)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.004)
    p.add_argument("--model-prefix", default=None)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    shape = (3, args.data_shape, args.data_shape)
    net = mx.models.ssd(num_classes=args.num_classes, mode="train",
                        filter_scale=args.filter_scale)
    data = synthetic_det_iter(args.batch_size, shape, args.num_classes)

    mod = mx.mod.Module(net, data_names=["data"], label_names=["label"],
                        context=mx.tpu(0))
    # relu4_3's learned L2-norm scale initializes to 20 (reference
    # train_net.py), everything else Xavier
    initializer = mx.initializer.Mixed(
        ["relu4_3_scale", ".*"],
        [mx.initializer.Constant(20.0), mx.initializer.Xavier()])
    mod.fit(data, num_epoch=args.num_epochs,
            initializer=initializer,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 5),
            eval_metric=mx.metric.Loss() if hasattr(mx.metric, "Loss")
            else "mse",
            epoch_end_callback=(mx.callback.do_checkpoint(args.model_prefix)
                                if args.model_prefix else None))


if __name__ == "__main__":
    main()
