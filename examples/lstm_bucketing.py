#!/usr/bin/env python
"""Bucketed LSTM language model (rebuild of example/rnn/lstm_bucketing.py):
variable-length sentences bucketed into a few padded lengths, one
compiled program per bucket, weights shared across buckets via
BucketingModule.

--data: a tokenized text file (one sentence per line, e.g. PTB
ptb.train.txt).  Without it, trains on synthetic Markov text.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.rnn_io import BucketSentenceIter, build_vocab, \
    encode_sentences  # noqa: E402


def load_sentences(args):
    if args.data and os.path.exists(args.data):
        with open(args.data) as f:
            raw = [line.split() + ["<eos>"] for line in f if line.strip()]
    else:
        # synthetic Markov chains so the example runs without a corpus
        rng = np.random.RandomState(0)
        vocab_size = 200
        trans = rng.dirichlet(np.ones(vocab_size) * 0.05, size=vocab_size)
        raw = []
        for _ in range(2000):
            length = int(rng.randint(5, 60))
            sent, tok = [], int(rng.randint(vocab_size))
            for _ in range(length):
                sent.append(str(tok))
                tok = int(rng.choice(vocab_size, p=trans[tok]))
            raw.append(sent)
    vocab = build_vocab(raw)
    return encode_sentences(raw, vocab), len(vocab) + 1


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", default=None)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-hidden", type=int, default=200)
    p.add_argument("--num-embed", type=int, default=200)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--buckets", default="10,20,30,40,60")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    sentences, vocab_size = load_sentences(args)
    buckets = [int(x) for x in args.buckets.split(",")]
    init_states = [(f"l{i}_init_{k}", (args.batch_size, args.num_hidden))
                   for i in range(args.num_layers) for k in ("c", "h")]
    data = BucketSentenceIter(sentences, args.batch_size, buckets=buckets,
                              init_states=init_states)

    def sym_gen(seq_len):
        sym = mx.models.lstm_unroll(
            args.num_layers, seq_len, vocab_size,
            num_hidden=args.num_hidden, num_embed=args.num_embed,
            num_label=vocab_size)
        data_names = ["data"] + [n for n, _ in init_states]
        return sym, data_names, ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=data.default_bucket_key,
                                 context=mx.tpu(0))
    mod.fit(data, num_epoch=args.num_epochs,
            eval_metric=mx.metric.CrossEntropy(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20),
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5})


if __name__ == "__main__":
    main()
