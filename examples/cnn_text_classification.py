#!/usr/bin/env python
"""CNN sentence classification
(rebuild of example/cnn_text_classification/text_cnn.py — Kim 2014).

Embedding -> parallel Convolutions with filter widths 3/4/5 over the
token axis -> max-over-time Pooling -> Concat -> Dropout -> softmax.
Runs on a synthetic keyword-detection corpus by default.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def text_cnn(seq_len, vocab_size, embed_dim=32, filter_sizes=(3, 4, 5),
             num_filter=32, num_classes=2, dropout=0.5):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, name="embed", input_dim=vocab_size,
                             output_dim=embed_dim)
    # (batch, 1, seq_len, embed_dim) image for the conv layers
    conv_input = mx.sym.Reshape(embed, target_shape=(0, 1, seq_len, embed_dim))
    pooled = []
    for fs in filter_sizes:
        conv = mx.sym.Convolution(conv_input, name=f"conv{fs}",
                                  kernel=(fs, embed_dim),
                                  num_filter=num_filter)
        act = mx.sym.Activation(conv, act_type="relu")
        pool = mx.sym.Pooling(act, pool_type="max",
                              kernel=(seq_len - fs + 1, 1), stride=(1, 1))
        pooled.append(pool)
    concat = mx.sym.Concat(*pooled, num_args=len(pooled), dim=1)
    h = mx.sym.Reshape(concat, target_shape=(0, num_filter * len(filter_sizes)))
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, name="cls", num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def synthetic_corpus(n, seq_len, vocab_size, seed=0):
    """Label 1 iff the 'positive' trigram 7,8,9 appears."""
    rng = np.random.RandomState(seed)
    X = rng.randint(10, vocab_size, (n, seq_len))
    y = rng.randint(0, 2, n)
    pos = y == 1
    starts = rng.randint(0, seq_len - 3, pos.sum())
    for row, s in zip(np.where(pos)[0], starts):
        X[row, s:s + 3] = [7, 8, 9]
    return X.astype(np.float32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--seq-len", type=int, default=24)
    p.add_argument("--vocab-size", type=int, default=200)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.005)
    p.add_argument("--n-train", type=int, default=2000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, y = synthetic_corpus(args.n_train, args.seq_len, args.vocab_size)
    Xv, yv = synthetic_corpus(500, args.seq_len, args.vocab_size, seed=1)
    net = text_cnn(args.seq_len, args.vocab_size)
    mod = mx.mod.Module(net, context=mx.tpu(0))
    mod.fit(mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True),
            eval_data=mx.io.NDArrayIter(Xv, yv, args.batch_size),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    score = mod.score(mx.io.NDArrayIter(Xv, yv, args.batch_size), "acc")
    acc = dict(score)["accuracy"]
    print(f"text-cnn validation accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
