#!/usr/bin/env python
"""SVM-head classifier (rebuild of example/svm_mnist/svm_mnist.py).

Same MLP trunk as the softmax examples, but the head is ``SVMOutput``
— hinge loss (L1 or squared L2 via ``use_linear``), exercising the
margin-loss op on the projected features.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--n-train", type=int, default=4000)
    p.add_argument("--linear", action="store_true",
                   help="L1 hinge instead of squared hinge")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=512)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=10)
    net = mx.sym.SVMOutput(fc2, name="svm", use_linear=args.linear)

    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, args.n_train)
    X = rng.standard_normal((args.n_train, 784)).astype(np.float32) * 0.3
    X[np.arange(args.n_train), y * 78] += 2.0
    yv = rng.randint(0, 10, 1000)
    Xv = rng.standard_normal((1000, 784)).astype(np.float32) * 0.3
    Xv[np.arange(1000), yv * 78] += 2.0

    mod = mx.mod.Module(net, label_names=("svm_label",), context=mx.tpu(0))
    train = mx.io.NDArrayIter(X, y.astype(np.float32), args.batch_size,
                              shuffle=True, label_name="svm_label")
    val = mx.io.NDArrayIter(Xv, yv.astype(np.float32), args.batch_size,
                            label_name="svm_label")
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 0.00001},
            num_epoch=args.num_epochs)
    acc = dict(mod.score(val, "acc"))["accuracy"]
    print(f"svm validation accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
