#!/usr/bin/env python
"""Fast-gradient-sign adversarial examples
(rebuild of example/adversary/adversary_generation.ipynb).

Trains a small MLP, then perturbs test inputs along the sign of the
loss gradient w.r.t. the *data* — exercising executor binding with a
gradient buffer on an input (grad_req on data), the same mechanism the
reference notebook uses via ``simple_bind`` + ``grad_dict['data']``.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_net():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def synthetic_mnist(n, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    X = rng.standard_normal((n, 784)).astype(np.float32) * 0.3
    X[np.arange(n), y * 78] += 2.0
    return X, y.astype(np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--epsilon", type=float, default=0.3)
    p.add_argument("--n-train", type=int, default=4000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.tpu(0)

    X, y = synthetic_mnist(args.n_train)
    Xt, yt = synthetic_mnist(args.batch_size, seed=1)
    net = build_net()
    model = mx.mod.Module(net, context=ctx)
    model.fit(mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True),
              optimizer="sgd",
              optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))

    # bind an executor that also produces d(loss)/d(data)
    exe = net.simple_bind(ctx=ctx, grad_req="write",
                          data=(args.batch_size, 784),
                          softmax_label=(args.batch_size,))
    for name, arr in model.get_params()[0].items():
        exe.arg_dict[name][:] = arr
    exe.arg_dict["data"][:] = Xt
    exe.arg_dict["softmax_label"][:] = yt
    exe.forward(is_train=True)
    clean_pred = exe.outputs[0].asnumpy().argmax(axis=1)
    exe.backward()
    grad_sign = np.sign(exe.grad_dict["data"].asnumpy())

    # FGSM step: x' = x + eps * sign(dL/dx)
    exe.arg_dict["data"][:] = Xt + args.epsilon * grad_sign
    exe.forward(is_train=False)
    adv_pred = exe.outputs[0].asnumpy().argmax(axis=1)

    clean_acc = (clean_pred == yt).mean()
    adv_acc = (adv_pred == yt).mean()
    print(f"clean accuracy {clean_acc:.3f} -> adversarial {adv_acc:.3f} "
          f"(eps={args.epsilon})")
    assert adv_acc <= clean_acc, "FGSM should not improve accuracy"


if __name__ == "__main__":
    main()
