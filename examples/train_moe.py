#!/usr/bin/env python
"""Mixture-of-Experts training demo: expert parallelism over an ``ep``
mesh axis with in-program all-to-all token dispatch/combine and the
Switch-style load-balancing auxiliary loss.

On CPU run with:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_moe.py --ep 8 --experts 8
"""

import argparse

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ep", type=int, default=8, help="expert-parallel ways")
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--d-hidden", type=int, default=128)
    p.add_argument("--tokens", type=int, default=512)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--steps", type=int, default=40)
    args = p.parse_args()

    import jax.numpy as jnp

    import mxnet_tpu as mx

    mesh = mx.parallel.make_mesh({"ep": args.ep})
    layer = mx.parallel.MoELayer(args.d_model, args.d_hidden, args.experts,
                                 mesh, k=args.top_k, capacity_factor=1.5)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((args.tokens, args.d_model))
                    .astype(np.float32))
    tgt = jnp.asarray(np.sin(np.asarray(x)))

    def loss_fn(y):
        return jnp.mean((y - tgt) ** 2)

    for i in range(args.steps):
        loss = layer.grad_step(x, loss_fn, lr=0.1)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.5f} "
                  f"aux {float(getattr(layer, 'last_aux_loss', 0.0)):.4f}")


if __name__ == "__main__":
    main()
