#!/usr/bin/env python
"""Memory-cost comparison with gradient checkpointing
(rebuild of example/memcost — the reference compares inplace/sharing/
mirror memory plans; here the planner is XLA, and the lever is
``MXNET_BACKWARD_DO_MIRROR`` -> ``jax.checkpoint``).

Compiles the train step of a deep MLP chain with and without
mirroring and reports XLA's own memory analysis for each.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def deep_net(depth, hidden):
    h = mx.sym.Variable("data")
    for i in range(depth):
        h = mx.sym.FullyConnected(h, name=f"fc{i}", num_hidden=hidden)
        h = mx.sym.Activation(h, name=f"act{i}", act_type="relu")
    fc = mx.sym.FullyConnected(h, name="out", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def compile_step(batch, hidden, depth):
    import jax

    net = deep_net(depth, hidden)
    mesh = mx.parallel.local_mesh("dp")
    tr = mx.parallel.ShardedTrainer(
        net, {"data": (batch, hidden), "softmax_label": (batch,)},
        mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    placed = tr._place_batch({
        "data": rng.standard_normal((batch, hidden)).astype(np.float32),
        "softmax_label": rng.randint(0, 10, batch).astype(np.float32)})
    comp = tr._train_step.lower(tr.params, tr.opt_state, tr.aux, placed,
                                tr._key, np.float32(1.0)).compile()
    mem = comp.memory_analysis()
    return mem


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--depth", type=int, default=24)
    args = p.parse_args()

    results = {}
    prior = os.environ.get("MXNET_BACKWARD_DO_MIRROR")
    try:
        for mirror in ("0", "1"):
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = mirror
            mem = compile_step(args.batch_size, args.hidden, args.depth)
            temp_mb = mem.temp_size_in_bytes / 1e6
            results[mirror] = temp_mb
            print(f"mirror={mirror}: temp buffers {temp_mb:.1f} MB "
                  f"(args {mem.argument_size_in_bytes / 1e6:.1f} MB, "
                  f"output {mem.output_size_in_bytes / 1e6:.1f} MB)")
    finally:
        # restore: this example runs IN-PROCESS in the test suite
        # (runpy), and a leaked mirror flag changes how every later
        # trace in the process lowers (jax.checkpoint everywhere)
        if prior is None:
            os.environ.pop("MXNET_BACKWARD_DO_MIRROR", None)
        else:
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = prior
    if results["1"] < results["0"]:
        print(f"mirroring saved {results['0'] - results['1']:.1f} MB of "
              "temp memory (recompute in backward)")
    else:
        print("note: XLA already found an equal-or-better schedule here")


if __name__ == "__main__":
    main()
