#!/usr/bin/env python
"""Deep Deterministic Policy Gradient on a continuous-control task.

Rebuild of the reference's DDPG stack
(example/reinforcement-learning/ddpg/: ddpg.py twin actor/critic
training with soft target updates, policies.py deterministic tanh
policy, qfuncs.py Q(s,a) critic, strategies.py Ornstein-Uhlenbeck
exploration, replay_mem.py) on a self-contained 1-D point-mass
environment (drive the mass to the origin; reward = -x^2 - 0.1 a^2),
so the example needs no gym/rllab.

Actor gradients flow through the critic: the policy loss is
``-Q(s, pi(s))``, built symbolically by composing the critic's graph
on top of the actor's output — the same pattern the reference wires
through its ``qfunc.get_qval_sym`` call.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


class PointMass:
    """x' = x + 0.1*a; reward -x^2 - 0.1 a^2; episode of fixed length."""

    def __init__(self, horizon=20):
        self.horizon = horizon
        self.reset()

    def reset(self, rng=None):
        self.x = (rng.uniform(-1.0, 1.0) if rng is not None else 0.8)
        self.t = 0
        return np.array([self.x], np.float32)

    def step(self, action):
        a = float(np.clip(action, -1.0, 1.0))
        self.x = float(np.clip(self.x + 0.1 * a, -2.0, 2.0))
        self.t += 1
        reward = -self.x ** 2 - 0.1 * a ** 2
        return np.array([self.x], np.float32), reward, self.t >= self.horizon


class OUStrategy:
    """Ornstein-Uhlenbeck exploration noise (ddpg/strategies.py)."""

    def __init__(self, rng, theta=0.15, sigma=0.3):
        self.rng, self.theta, self.sigma = rng, theta, sigma
        self.state = 0.0

    def reset(self):
        self.state = 0.0

    def sample(self):
        self.state += (-self.theta * self.state
                       + self.sigma * self.rng.randn())
        return self.state


class ReplayMem:
    def __init__(self, capacity, rng):
        self.capacity, self.rng = capacity, rng
        self.data = []
        self.top = 0

    def append(self, item):
        if len(self.data) < self.capacity:
            self.data.append(item)
        else:
            self.data[self.top] = item
            self.top = (self.top + 1) % self.capacity

    def sample(self, n):
        idx = self.rng.randint(0, len(self.data), n)
        cols = list(zip(*[self.data[i] for i in idx]))
        return [np.asarray(c, np.float32) for c in cols]


def critic_sym(state, action, prefix):
    """Q(s, a): state/action concatenated into a two-layer net
    (ddpg/qfuncs.py ContinuousMLPQ)."""
    h = mx.sym.Concat(state, action, num_args=2, dim=1)
    h = mx.sym.Activation(mx.sym.FullyConnected(
        h, num_hidden=64, name=prefix + "_fc1"), act_type="relu")
    return mx.sym.FullyConnected(h, num_hidden=1, name=prefix + "_q")


def actor_sym(state, n_action, prefix):
    """Deterministic tanh policy (ddpg/policies.py)."""
    h = mx.sym.Activation(mx.sym.FullyConnected(
        state, num_hidden=64, name=prefix + "_fc1"), act_type="relu")
    return mx.sym.Activation(
        mx.sym.FullyConnected(h, num_hidden=n_action, name=prefix + "_out"),
        act_type="tanh")


def make_modules(bs, lr):
    state = mx.sym.Variable("state")
    action = mx.sym.Variable("action")
    target = mx.sym.Variable("target")

    # critic trained on Bellman targets
    qloss = mx.sym.LinearRegressionOutput(
        mx.sym.Flatten(critic_sym(state, action, "critic")), target,
        name="qloss")
    critic = mx.mod.Module(qloss, data_names=("state", "action", "target"),
                           label_names=None, context=mx.tpu(0))
    critic.bind(data_shapes=[("state", (bs, 1)), ("action", (bs, 1)),
                             ("target", (bs,))])
    critic.init_params(initializer=mx.init.Xavier())
    critic.init_optimizer(optimizer="adam",
                          optimizer_params={"learning_rate": lr})

    # actor maximizes Q(s, pi(s)): share the critic weights by name
    pi = actor_sym(state, 1, "actor")
    q_of_pi = critic_sym(state, pi, "critic")
    aloss = mx.sym.MakeLoss(0 - mx.sym.mean(q_of_pi), name="aloss")
    actor_group = mx.sym.Group([mx.sym.BlockGrad(pi, name="piout"), aloss])
    # critic weights inside the actor graph are frozen for the policy
    # step (the reference rebinds with grad_req null on qfunc params)
    frozen = [n for n in actor_group.list_arguments()
              if n.startswith("critic")]
    actor = mx.mod.Module(actor_group, data_names=("state",),
                          label_names=None, context=mx.tpu(0),
                          fixed_param_names=frozen)
    actor.bind(data_shapes=[("state", (bs, 1))])
    actor.init_params(initializer=mx.init.Xavier())
    actor.init_optimizer(optimizer="adam",
                         optimizer_params={"learning_rate": lr * 0.5})
    return critic, actor


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--episodes", type=int, default=120)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--gamma", type=float, default=0.95)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--tau", type=float, default=0.05,
                   help="soft target update rate")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    bs = args.batch_size

    env = PointMass()
    critic, actor = make_modules(bs, args.lr)

    # target copies as plain host-side param dicts + soft updates
    t_critic = {k: v.asnumpy().copy() for k, v in critic.get_params()[0].items()}
    t_actor = {k: v.asnumpy().copy() for k, v in actor.get_params()[0].items()
               if k.startswith("actor")}

    def soft_update(target_dict, params):
        for k in target_dict:
            target_dict[k] = ((1 - args.tau) * target_dict[k]
                              + args.tau * params[k].asnumpy())

    def actor_forward(m, states):
        m.forward(mx.io.DataBatch([mx.nd.array(states)]), is_train=False)
        return m.get_outputs()[0].asnumpy()

    def np_actor(states):
        h = np.maximum(states @ t_actor["actor_fc1_weight"].T
                       + t_actor["actor_fc1_bias"], 0.0)
        return np.tanh(h @ t_actor["actor_out_weight"].T
                       + t_actor["actor_out_bias"])

    def np_critic(states, actions):
        x = np.concatenate([states, actions], axis=1)
        h = np.maximum(x @ t_critic["critic_fc1_weight"].T
                       + t_critic["critic_fc1_bias"], 0.0)
        return h @ t_critic["critic_q_weight"].T + t_critic["critic_q_bias"]

    mem = ReplayMem(10000, rng)
    ou = OUStrategy(rng)
    returns = []
    for ep in range(args.episodes):
        s = env.reset(rng)
        ou.reset()
        total = 0.0
        done = False
        while not done:
            a = float(actor_forward(actor, s[None])[0, 0]) + ou.sample()
            s2, r, done = env.step(a)
            mem.append((s, [np.clip(a, -1, 1)], [r], s2, [float(done)]))
            total += r
            s = s2
            if len(mem.data) >= bs:
                bstate, baction, brew, bnext, bdone = mem.sample(bs)
                # Bellman target through the TARGET actor+critic
                a2 = np_actor(bnext)
                q2 = np_critic(bnext, a2)[:, 0]
                tgt = brew[:, 0] + args.gamma * q2 * (1 - bdone[:, 0])
                critic.forward(mx.io.DataBatch(
                    [mx.nd.array(bstate), mx.nd.array(baction),
                     mx.nd.array(tgt)]), is_train=True)
                critic.backward()
                critic.update()
                # policy step: refresh the critic weights inside the
                # actor graph, then ascend Q(s, pi(s))
                cparams = critic.get_params()[0]
                actor.set_params({**{k: v for k, v in
                                     actor.get_params()[0].items()
                                     if k.startswith("actor")},
                                  **{k: v for k, v in cparams.items()}},
                                 None, allow_missing=True)
                actor.forward(mx.io.DataBatch([mx.nd.array(bstate)]),
                              is_train=True)
                actor.backward()
                actor.update()
                soft_update(t_critic, cparams)
                soft_update(t_actor,
                            {k: v for k, v in actor.get_params()[0].items()
                             if k.startswith("actor")})
        returns.append(total)
        if (ep + 1) % 30 == 0:
            logging.info("episode %d avg return (last 30) %.3f", ep + 1,
                         float(np.mean(returns[-30:])))
    final = float(np.mean(returns[-30:]))
    print(f"ddpg point-mass: final avg return {final:.3f} "
          f"(do-nothing from x=0.8 is ~-12.8, good control > -4)")


if __name__ == "__main__":
    main()
