#!/usr/bin/env python
"""Long-context training demo: ring attention (sequence parallelism)
with the fused Pallas flash-attention kernel on each shard pair.

A toy sequence-classification model whose attention runs sharded over
the ``sp`` mesh axis: each chip holds one sequence shard of Q/K/V and
K/V shards rotate around the ring via ppermute, so peak memory per chip
is O((S/n)^2) instead of O(S^2).  On CPU run with:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_longcontext.py --sp 4 --seq-len 512
"""

import argparse

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sp", type=int, default=4, help="sequence-parallel ways")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--impl", default="auto", choices=["auto", "flash", "xla"])
    p.add_argument("--mode", default="ring", choices=["ring", "ulysses"],
                   help="sequence-parallel scheme: ring (ppermute K/V) or "
                        "ulysses (all-to-all head regrouping)")
    p.add_argument("--layout", default="bhsd", choices=["bhsd", "bshd"],
                   help="bshd = sequence-major shards (no activation "
                        "transposes feeding the flash kernel)")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx

    mesh = mx.parallel.make_mesh({"sp": args.sp})
    B, H, S, D = args.batch, args.heads, args.seq_len, args.dim
    rng = np.random.RandomState(0)

    # toy task: predict the mean of the first token's attended context
    wq, wk, wv, wo = (jnp.asarray(rng.standard_normal((D, D)) * 0.1,
                                  jnp.float32) for _ in range(4))
    params = {"wq": wq, "wk": wk, "wv": wv, "wo": wo}
    x = jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(np.asarray(x).mean(axis=2)))

    def loss_fn(p):
        q, k, v = x @ p["wq"], x @ p["wk"], x @ p["wv"]
        attn = (mx.parallel.ulysses_attention if args.mode == "ulysses"
                else mx.parallel.ring_attention)
        if args.layout == "bshd":
            o = attn(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                     v.transpose(0, 2, 1, 3), mesh, "sp", causal=True,
                     impl=args.impl, layout="bshd").transpose(0, 2, 1, 3)
        else:
            o = attn(q, k, v, mesh, "sp", causal=True, impl=args.impl)
        pooled = o.mean(axis=2) @ p["wo"]
        return jnp.mean((pooled - tgt) ** 2)

    step = jax.jit(jax.value_and_grad(loss_fn))
    lr = 0.05
    for i in range(args.steps):
        loss, grads = step(params)
        params = jax.tree_util.tree_map(lambda a, g: a - lr * g,
                                        params, grads)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.5f}")


if __name__ == "__main__":
    main()
