#!/usr/bin/env python
"""Long-context training demo: ring attention (sequence parallelism)
with the fused Pallas flash-attention kernel on each shard pair.

A toy sequence-classification model whose attention runs sharded over
the ``sp`` mesh axis: each chip holds one sequence shard of Q/K/V and
K/V shards rotate around the ring via ppermute, so peak memory per chip
is O((S/n)^2) instead of O(S^2).  On CPU run with:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_longcontext.py --sp 4 --seq-len 512
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sp", type=int, default=4, help="sequence-parallel ways")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--impl", default="auto", choices=["auto", "flash", "xla"])
    p.add_argument("--mode", default="ring", choices=["ring", "ulysses"],
                   help="sequence-parallel scheme: ring (ppermute K/V) or "
                        "ulysses (all-to-all head regrouping)")
    p.add_argument("--layout", default="bhsd", choices=["bhsd", "bshd"],
                   help="bshd = sequence-major shards (no activation "
                        "transposes feeding the flash kernel)")
    p.add_argument("--trainer", action="store_true",
                   help="use the symbol-level path instead: a "
                        "ShardedTrainer over models.gpt with "
                        "sequence_specs — the FlashAttention ops route "
                        "to ring/Ulysses automatically")
    p.add_argument("--dp", type=int, default=2,
                   help="data-parallel ways for --trainer mode")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx

    if args.trainer:
        return train_symbol_level(args, jax, mx)

    mesh = mx.parallel.make_mesh({"sp": args.sp})
    B, H, S, D = args.batch, args.heads, args.seq_len, args.dim
    rng = np.random.RandomState(0)

    # toy task: predict the mean of the first token's attended context
    wq, wk, wv, wo = (jnp.asarray(rng.standard_normal((D, D)) * 0.1,
                                  jnp.float32) for _ in range(4))
    params = {"wq": wq, "wk": wk, "wv": wv, "wo": wo}
    x = jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(np.asarray(x).mean(axis=2)))

    def loss_fn(p):
        q, k, v = x @ p["wq"], x @ p["wk"], x @ p["wv"]
        attn = (mx.parallel.ulysses_attention if args.mode == "ulysses"
                else mx.parallel.ring_attention)
        if args.layout == "bshd":
            o = attn(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                     v.transpose(0, 2, 1, 3), mesh, "sp", causal=True,
                     impl=args.impl, layout="bshd").transpose(0, 2, 1, 3)
        else:
            o = attn(q, k, v, mesh, "sp", causal=True, impl=args.impl)
        pooled = o.mean(axis=2) @ p["wo"]
        return jnp.mean((pooled - tgt) ** 2)

    step = jax.jit(jax.value_and_grad(loss_fn))
    lr = 0.05
    for i in range(args.steps):
        loss, grads = step(params)
        params = jax.tree_util.tree_map(lambda a, g: a - lr * g,
                                        params, grads)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.5f}")


def train_symbol_level(args, jax, mx):
    """The user-level path: sequence_specs shard the (B, S) token batch
    across a dp x sp mesh and every sym.FlashAttention in models.gpt
    routes to ring (or Ulysses via --mode) attention automatically."""
    from jax.sharding import PartitionSpec as P

    vocab = 97
    B, S = args.batch * args.dp, args.seq_len
    net = mx.models.gpt(vocab, S, num_layers=2, d_model=args.dim,
                        num_heads=args.heads, attn_layout=args.layout,
                        attn_impl=args.impl, attn_sp_impl=args.mode)
    trainer = mx.parallel.ShardedTrainer(
        net, {"data": (B, S), "softmax_label": (B, S)},
        mesh=mx.parallel.make_mesh({"dp": args.dp, "sp": args.sp}),
        batch_axis="dp",
        sequence_specs={"data": P("dp", "sp"),
                        "softmax_label": P("dp", "sp")},
        optimizer="adam", optimizer_params={"learning_rate": 1e-3},
        initializer=mx.initializer.Xavier(),
        input_dtypes={"data": np.int32, "softmax_label": np.float32})

    rng = np.random.RandomState(0)
    X = rng.randint(0, vocab, (B, S))
    Y = np.roll(X, -1, axis=1).astype(np.float32)
    for i in range(args.steps):
        outs = trainer.step({"data": X, "softmax_label": Y})
        if i % 5 == 0 or i == args.steps - 1:
            probs = np.asarray(outs[0])
            nll = -np.mean(np.log(
                probs[np.arange(probs.shape[0]),
                      Y.reshape(-1).astype(int)] + 1e-9))
            print(f"step {i}: nll {nll:.4f}")


if __name__ == "__main__":
    main()
