#!/usr/bin/env python
"""Deep Q-Network on a gridworld.

Rebuild of the reference's DQN stack
(example/reinforcement-learning/dqn/: dqn_demo.py training loop,
replay_memory.py uniform-sampling buffer, base.py target-network
copy) on a self-contained environment — a deterministic 5x5 gridworld
with a goal and a pit — so the example runs without an Atari
emulator.  All the DQN machinery is faithful: epsilon-greedy
exploration with linear decay, experience replay, a frozen target
network synced every N updates, and the Bellman TD(0) regression head
trained with ``LinearRegressionOutput`` on the taken action's Q-value
(the reference masks non-taken actions the same way).
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


class GridWorld:
    """5x5 grid; reach the goal (+1), avoid the pit (-1); step cost."""

    def __init__(self, size=5):
        self.size = size
        self.goal = (size - 1, size - 1)
        self.pit = (size // 2, size // 2)
        self.reset()

    @property
    def n_states(self):
        return self.size * self.size

    def reset(self):
        self.pos = (0, 0)
        return self._obs()

    def _obs(self):
        s = np.zeros(self.n_states, np.float32)
        s[self.pos[0] * self.size + self.pos[1]] = 1.0
        return s

    def step(self, action):
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][action]
        r = min(max(self.pos[0] + dr, 0), self.size - 1)
        c = min(max(self.pos[1] + dc, 0), self.size - 1)
        self.pos = (r, c)
        if self.pos == self.goal:
            return self._obs(), 1.0, True
        if self.pos == self.pit:
            return self._obs(), -1.0, True
        return self._obs(), -0.01, False


class ReplayMemory:
    """Uniform-sampling circular transition buffer
    (dqn/replay_memory.py)."""

    def __init__(self, capacity, state_dim, rng):
        self.capacity = capacity
        self.rng = rng
        self.states = np.zeros((capacity, state_dim), np.float32)
        self.actions = np.zeros(capacity, np.int64)
        self.rewards = np.zeros(capacity, np.float32)
        self.next_states = np.zeros((capacity, state_dim), np.float32)
        self.terminals = np.zeros(capacity, np.float32)
        self.top = 0
        self.size = 0

    def append(self, s, a, r, s2, done):
        i = self.top
        self.states[i], self.actions[i], self.rewards[i] = s, a, r
        self.next_states[i], self.terminals[i] = s2, float(done)
        self.top = (self.top + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, n):
        idx = self.rng.randint(0, self.size, n)
        return (self.states[idx], self.actions[idx], self.rewards[idx],
                self.next_states[idx], self.terminals[idx])


def build_qnet(n_states, n_actions, batch):
    """Q-network with the taken-action regression head: Q(s,.) masked by
    the action one-hot regresses onto the Bellman target (the
    reference's DQNOutput op does exactly this masked-grad trick)."""
    data = mx.sym.Variable("data")
    action = mx.sym.Variable("action")
    target = mx.sym.Variable("target")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=64, name="fc1"),
        act_type="relu")
    q = mx.sym.FullyConnected(h, num_hidden=n_actions, name="qvals")
    onehot = mx.sym.one_hot(action, depth=n_actions)
    q_taken = mx.sym.sum(q * onehot, axis=1)
    loss = mx.sym.LinearRegressionOutput(q_taken, target, name="td")
    return mx.sym.Group([mx.sym.BlockGrad(q, name="qout"), loss])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--episodes", type=int, default=250)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--gamma", type=float, default=0.95)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--target-sync", type=int, default=100)
    p.add_argument("--replay", type=int, default=5000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)

    env = GridWorld()
    n_states, n_actions = env.n_states, 4
    bs = args.batch_size

    net = build_qnet(n_states, n_actions, bs)
    mod = mx.mod.Module(net, data_names=("data", "action", "target"),
                        label_names=None, context=mx.tpu(0))
    mod.bind(data_shapes=[("data", (bs, n_states)), ("action", (bs,)),
                          ("target", (bs,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    # frozen target network (dqn/base.py copy-params sync)
    tmod = mx.mod.Module(net, data_names=("data", "action", "target"),
                         label_names=None, context=mx.tpu(0))
    tmod.bind(data_shapes=[("data", (bs, n_states)), ("action", (bs,)),
                           ("target", (bs,))], for_training=False)
    tmod.init_params(initializer=mx.init.Xavier())

    def sync_target():
        arg_params, aux_params = mod.get_params()
        tmod.set_params(arg_params, aux_params)

    def qvalues(m, states):
        m.forward(mx.io.DataBatch(
            [mx.nd.array(states), mx.nd.zeros((len(states),)),
             mx.nd.zeros((len(states),))]), is_train=False)
        return m.get_outputs()[0].asnumpy()

    sync_target()
    mem = ReplayMemory(args.replay, n_states, rng)
    eps, eps_min, eps_decay = 1.0, 0.05, 1.0 / (args.episodes * 0.6)
    updates = 0
    returns = []
    for ep in range(args.episodes):
        s = env.reset()
        total = 0.0
        for _ in range(40):
            if rng.rand() < eps:
                a = rng.randint(n_actions)
            else:
                a = int(qvalues(mod, s[None])[0].argmax())
            s2, r, done = env.step(a)
            mem.append(s, a, r, s2, done)
            total += r
            s = s2
            if mem.size >= bs:
                bs_, ba, br, bs2, bt = mem.sample(bs)
                qnext = qvalues(tmod, bs2).max(axis=1)
                tgt = br + args.gamma * qnext * (1.0 - bt)
                mod.forward(mx.io.DataBatch(
                    [mx.nd.array(bs_), mx.nd.array(ba.astype(np.float32)),
                     mx.nd.array(tgt)]), is_train=True)
                mod.backward()
                mod.update()
                updates += 1
                if updates % args.target_sync == 0:
                    sync_target()
            if done:
                break
        eps = max(eps_min, eps - eps_decay)
        returns.append(total)
        if (ep + 1) % 50 == 0:
            logging.info("episode %d avg return (last 50) %.3f eps %.2f",
                         ep + 1, float(np.mean(returns[-50:])), eps)
    final = float(np.mean(returns[-50:]))
    print(f"dqn gridworld: final avg return {final:.3f} "
          f"(random walk is ~-0.3, optimal ~0.93)")


if __name__ == "__main__":
    main()
