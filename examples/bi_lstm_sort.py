#!/usr/bin/env python
"""Sorting with a bidirectional LSTM
(rebuild of example/bi-lstm-sort/lstm_sort.py).

The model reads a sequence of tokens and emits the same multiset in
sorted order, one prediction per position — a task only solvable with
context from both directions, exercising the fused bidirectional RNN
op (``mx.sym.RNN`` with ``bidirectional=True``).
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_net(seq_len, vocab_size, num_hidden=64, num_embed=32):
    data = mx.sym.Variable("data")          # (batch, seq_len)
    embed = mx.sym.Embedding(data, name="embed", input_dim=vocab_size,
                             output_dim=num_embed)
    # fused RNN wants (seq_len, batch, feat)
    tns = mx.sym.SwapAxis(embed, dim1=0, dim2=1)
    rnn = mx.sym.RNN(tns, name="lstm", mode="lstm", state_size=num_hidden,
                     num_layers=1, bidirectional=True,
                     parameters=mx.sym.Variable("lstm_parameters"),
                     state=mx.sym.Variable("lstm_state"),
                     state_cell=mx.sym.Variable("lstm_state_cell"))
    back = mx.sym.SwapAxis(rnn, dim1=0, dim2=1)     # (batch, seq, 2*hidden)
    flat = mx.sym.Reshape(back, shape=(-1, 2 * num_hidden))
    fc = mx.sym.FullyConnected(flat, name="cls", num_hidden=vocab_size)
    label = mx.sym.Variable("softmax_label")        # (batch, seq)
    label_flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(fc, label_flat, name="softmax")


def make_data(n, seq_len, vocab_size, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randint(1, vocab_size, (n, seq_len))
    y = np.sort(X, axis=1)
    return X.astype(np.float32), y.astype(np.float32)


class SortIter(mx.io.DataIter):
    """Yields (sequence, flattened sorted labels) batches."""

    def __init__(self, X, y, batch_size, seq_len):
        super().__init__()
        self.X, self.y = X, y
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.cursor = 0
        self.provide_data = [("data", (batch_size, seq_len))]
        self.provide_label = [("softmax_label", (batch_size, seq_len))]

    def reset(self):
        self.cursor = 0

    def next(self):
        if self.cursor + self.batch_size > len(self.X):
            raise StopIteration
        i = self.cursor
        self.cursor += self.batch_size
        xb = self.X[i:i + self.batch_size]
        yb = self.y[i:i + self.batch_size]
        return mx.io.DataBatch([mx.nd.array(xb)], [mx.nd.array(yb)])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=6)
    p.add_argument("--vocab-size", type=int, default=20)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=2000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, y = make_data(args.n_train, args.seq_len, args.vocab_size)
    train = SortIter(X, y, args.batch_size, args.seq_len)
    net = build_net(args.seq_len, args.vocab_size)
    mod = mx.mod.Module(net, context=mx.tpu(0))
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))

    # show one sorted prediction
    train.reset()
    batch = train.next()
    mod.forward(batch, is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
    pred = pred.reshape(args.batch_size, args.seq_len)
    print("input :", batch.data[0].asnumpy()[0].astype(int).tolist())
    print("output:", pred[0].tolist())
    print("target:", np.sort(batch.data[0].asnumpy()[0]).astype(int).tolist())


if __name__ == "__main__":
    main()
