#!/usr/bin/env python
"""DCGAN (rebuild of example/gan/dcgan.py).

Two Modules trained adversarially: the generator G maps noise to
images via Deconvolution stacks; the discriminator D is bound with
``inputs_need_grad=True`` so its input gradients drive G's update —
the same two-module dance as the reference.  Runs on synthetic
gaussian-blob "images" by default so it works without a dataset.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def make_dcgan_sym(ngf, ndf, nc, size, no_bias=True, fix_gamma=True,
                   eps=1e-5 + 1e-12):
    """Generator + discriminator symbols (reference dcgan.py:8-55),
    scaled down: `size` is the output resolution (a power of two >= 8)."""
    rand = mx.sym.Variable("rand")
    # project 1x1 -> 4x4, then upsample by 2 per layer
    n_up = 0
    s = 4
    while s < size:
        s *= 2
        n_up += 1
    filt = ngf * (2 ** n_up)
    g = mx.sym.Deconvolution(rand, name="g0", kernel=(4, 4),
                             num_filter=filt, no_bias=no_bias)
    g = mx.sym.BatchNorm(g, name="gbn0", fix_gamma=fix_gamma, eps=eps)
    g = mx.sym.Activation(g, name="gact0", act_type="relu")
    for i in range(1, n_up + 1):
        filt //= 2
        last = i == n_up
        g = mx.sym.Deconvolution(
            g, name=f"g{i}", kernel=(4, 4), stride=(2, 2), pad=(1, 1),
            num_filter=nc if last else filt, no_bias=no_bias)
        if not last:
            g = mx.sym.BatchNorm(g, name=f"gbn{i}", fix_gamma=fix_gamma,
                                 eps=eps)
            g = mx.sym.Activation(g, name=f"gact{i}", act_type="relu")
    gout = mx.sym.Activation(g, name="gact_out", act_type="tanh")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    d = data
    filt = ndf
    s = size
    i = 0
    while s > 4:
        d = mx.sym.Convolution(d, name=f"d{i}", kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=filt, no_bias=no_bias)
        if i > 0:
            d = mx.sym.BatchNorm(d, name=f"dbn{i}", fix_gamma=fix_gamma,
                                 eps=eps)
        d = mx.sym.LeakyReLU(d, name=f"dact{i}", act_type="leaky", slope=0.2)
        filt *= 2
        s //= 2
        i += 1
    d = mx.sym.Convolution(d, name=f"d{i}", kernel=(4, 4), num_filter=1,
                           no_bias=no_bias)
    d = mx.sym.Flatten(d)
    dloss = mx.sym.LogisticRegressionOutput(data=d, label=label, name="dloss")
    return gout, dloss


class RandIter(mx.io.DataIter):
    """Endless gaussian-noise source (reference dcgan.py RandIter)."""

    def __init__(self, batch_size, ndim):
        super().__init__()
        self.batch_size = batch_size
        self.ndim = ndim
        self.provide_data = [("rand", (batch_size, ndim, 1, 1))]
        self.provide_label = []

    def iter_next(self):
        return True

    def getdata(self):
        return [mx.random.normal(0, 1.0,
                                 shape=(self.batch_size, self.ndim, 1, 1))]


def facc(label, pred):
    return ((pred.ravel() > 0.5) == label.ravel()).mean()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--size", type=int, default=32, help="image resolution")
    p.add_argument("--nc", type=int, default=1, help="image channels")
    p.add_argument("--ngf", type=int, default=32)
    p.add_argument("--ndf", type=int, default=32)
    p.add_argument("--z", type=int, default=64, help="noise dim")
    p.add_argument("--lr", type=float, default=0.0002)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--batches-per-epoch", type=int, default=20)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.tpu(0)
    bs = args.batch_size

    symG, symD = make_dcgan_sym(args.ngf, args.ndf, args.nc, args.size)

    # synthetic "real" data: smooth blobs in [-1, 1]
    rng = np.random.RandomState(0)
    n = bs * args.batches_per_epoch
    grid = np.linspace(-1, 1, args.size)
    yy, xx = np.meshgrid(grid, grid, indexing="ij")
    cx, cy = rng.uniform(-0.5, 0.5, (2, n))
    X = np.exp(-(((xx[None] - cx[:, None, None]) ** 2
                  + (yy[None] - cy[:, None, None]) ** 2) / 0.1))
    X = (X * 2 - 1).astype(np.float32)[:, None].repeat(args.nc, axis=1)
    train_iter = mx.io.NDArrayIter(X, batch_size=bs)
    rand_iter = RandIter(bs, args.z)
    label = mx.nd.zeros((bs,), ctx=ctx)

    modG = mx.mod.Module(symbol=symG, data_names=("rand",), label_names=None,
                         context=ctx)
    modG.bind(data_shapes=rand_iter.provide_data)
    modG.init_params(initializer=mx.init.Normal(0.02))
    modG.init_optimizer(optimizer="adam", optimizer_params={
        "learning_rate": args.lr, "wd": 0., "beta1": args.beta1})

    modD = mx.mod.Module(symbol=symD, data_names=("data",),
                         label_names=("label",), context=ctx)
    modD.bind(data_shapes=train_iter.provide_data,
              label_shapes=[("label", (bs,))], inputs_need_grad=True)
    modD.init_params(initializer=mx.init.Normal(0.02))
    modD.init_optimizer(optimizer="adam", optimizer_params={
        "learning_rate": args.lr, "wd": 0., "beta1": args.beta1})

    metric_acc = mx.metric.CustomMetric(facc)
    for epoch in range(args.num_epochs):
        train_iter.reset()
        metric_acc.reset()
        for t, batch in enumerate(train_iter):
            rbatch = rand_iter.next()
            modG.forward(rbatch, is_train=True)
            out_g = modG.get_outputs()

            # update D: fake batch (label 0) then real batch (label 1)
            label[:] = 0
            modD.forward(mx.io.DataBatch(out_g, [label]), is_train=True)
            modD.backward()
            grads_fake = [[g.copyto(g.context) for g in grad_list]
                          for grad_list in modD._exec_group.grad_arrays]
            metric_acc.update([label], modD.get_outputs())
            label[:] = 1
            modD.forward(mx.io.DataBatch(batch.data, [label]), is_train=True)
            modD.backward()
            for gradsr, gradsf in zip(modD._exec_group.grad_arrays,
                                      grads_fake):
                for gr, gf in zip(gradsr, gradsf):
                    gr += gf
            modD.update()
            metric_acc.update([label], modD.get_outputs())

            # update G: fool D (label 1), grads flow through D's inputs
            label[:] = 1
            modD.forward(mx.io.DataBatch(out_g, [label]), is_train=True)
            modD.backward()
            diff_d = modD.get_input_grads()
            modG.backward(diff_d)
            modG.update()
        name, acc = metric_acc.get()
        logging.info("epoch %d: D %s=%.3f", epoch, name, acc)
    print("dcgan done; final D facc %.3f" % acc)


if __name__ == "__main__":
    main()
