#!/usr/bin/env python
"""Model-parallel LSTM: layers pinned to different devices via ctx_group
(rebuild of example/model-parallel-lstm/lstm.py:48-99 + lstm_ptb.py).

Each LSTM layer is built inside an AttrScope(ctx_group=...) and
group2ctx maps groups to devices at bind time; the graph partitioner
inserts cross-device transfers on group boundaries — on TPU these are
ICI transfers between compiled per-device segments.

Runs on N real devices, or (the canonical test trick) N CPU contexts.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def pipelined_lstm_unroll(num_layers, seq_len, input_size, num_hidden,
                          num_embed, num_label):
    """lstm_unroll with each layer in its own ctx_group (the reference
    pins embed+layer0 to group 'layer0', etc.)."""
    from mxnet_tpu.models.lstm import LSTMParam, LSTMState, lstm_cell

    with mx.AttrScope(ctx_group="embed"):
        data = mx.sym.Variable("data")
        embed = mx.sym.Embedding(
            data, weight=mx.sym.Variable("embed_weight"),
            input_dim=input_size, output_dim=num_embed, name="embed")
        wordvec = mx.sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                      squeeze_axis=True)

    params, states = [], []
    for i in range(num_layers):
        with mx.AttrScope(ctx_group=f"layer{i}"):
            params.append(LSTMParam(
                i2h_weight=mx.sym.Variable(f"l{i}_i2h_weight"),
                i2h_bias=mx.sym.Variable(f"l{i}_i2h_bias"),
                h2h_weight=mx.sym.Variable(f"l{i}_h2h_weight"),
                h2h_bias=mx.sym.Variable(f"l{i}_h2h_bias")))
            states.append(LSTMState(c=mx.sym.Variable(f"l{i}_init_c"),
                                    h=mx.sym.Variable(f"l{i}_init_h")))

    hidden_all = []
    for t in range(seq_len):
        hidden = wordvec[t]
        for i in range(num_layers):
            with mx.AttrScope(ctx_group=f"layer{i}"):
                states[i] = lstm_cell(num_hidden, indata=hidden,
                                      prev_state=states[i], param=params[i],
                                      seqidx=t, layeridx=i)
                hidden = states[i].h
        hidden_all.append(hidden)

    with mx.AttrScope(ctx_group="out"):
        concat = mx.sym.Concat(*hidden_all, dim=0,
                               num_args=len(hidden_all))
        fc = mx.sym.FullyConnected(concat, weight=mx.sym.Variable("cls_weight"),
                                   bias=mx.sym.Variable("cls_bias"),
                                   num_hidden=num_label, name="cls")
        label = mx.sym.transpose(mx.sym.Variable("softmax_label"))
        label_flat = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(fc, label_flat, name="softmax")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=64)
    p.add_argument("--vocab", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--cpu-contexts", action="store_true",
                   help="use N CPU contexts instead of devices "
                        "(the test_model_parallel.py trick)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = pipelined_lstm_unroll(args.num_layers, args.seq_len, args.vocab,
                                args.num_hidden, args.num_embed, args.vocab)

    n_dev = mx.num_devices()
    dev = (lambda i: mx.cpu(i)) if args.cpu_contexts else \
        (lambda i: mx.tpu(i % n_dev))
    group2ctx = {"embed": dev(0), "out": dev(0)}
    for i in range(args.num_layers):
        group2ctx[f"layer{i}"] = dev(i % max(args.num_layers, 1))

    shapes = {"data": (args.batch_size, args.seq_len),
              "softmax_label": (args.batch_size, args.seq_len)}
    for i in range(args.num_layers):
        shapes[f"l{i}_init_c"] = (args.batch_size, args.num_hidden)
        shapes[f"l{i}_init_h"] = (args.batch_size, args.num_hidden)
    exe = net.simple_bind(dev(0), grad_req="write", group2ctx=group2ctx,
                          **shapes)
    init = mx.initializer.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in shapes:
            init(name, arr)

    rng = np.random.RandomState(0)
    opt = mx.opt.SGD(learning_rate=0.05, momentum=0.9)
    updater = mx.opt.get_updater(opt)
    for step in range(args.steps):
        X = rng.randint(0, args.vocab, (args.batch_size, args.seq_len))
        exe.arg_dict["data"][:] = X
        y = np.roll(X, -1, axis=1)
        exe.arg_dict["softmax_label"][:] = y
        exe.forward(is_train=True)
        exe.backward()
        for k, (w, g) in enumerate(zip(exe.arg_arrays, exe.grad_arrays)):
            if g is not None and exe.arg_names[k] not in shapes:
                updater(k, g, w)
        if step % 10 == 0:
            prob = exe.outputs[0].asnumpy()
            ll = -np.log(np.maximum(
                prob[np.arange(prob.shape[0]),
                     y.T.reshape(-1).astype(int)], 1e-9)).mean()
            logging.info("step %d nll %.4f", step, ll)


if __name__ == "__main__":
    main()
