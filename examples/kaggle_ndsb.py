#!/usr/bin/env python
"""Kaggle NDSB-1 style competition recipe (rebuild of
example/kaggle-ndsb1: gen_img_list.py + train_dsb.py + predict_dsb.py +
submission_dsb.py).

End-to-end dataset workflow on top of the im2rec toolchain:
  1. stratified train/val split of a class-per-folder image tree into
     tab-separated ``tr.lst``/``va.lst`` (gen_img_list.py semantics)
  2. pack both lists into RecordIO via tools/im2rec
  3. train a small convnet with ``ImageRecordIter``
  4. predict the validation shard and write a Kaggle-format
     ``submission.csv`` (one probability column per class name)

With no ``--image-folder`` it fabricates a synthetic plankton-like
dataset so the full recipe is runnable (and smoke-testable) anywhere.
"""

import argparse
import csv
import logging
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import mxnet_tpu as mx  # noqa: E402
import im2rec  # noqa: E402


def make_synthetic_tree(root, classes, per_class, hw=24, seed=0):
    """Class-named folders of images whose brightness pattern encodes
    the class — learnable by a small convnet."""
    import cv2

    rng = np.random.RandomState(seed)
    for ci, cls in enumerate(classes):
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = rng.randint(0, 60, (hw, hw, 3), np.uint8)
            band = (hw // len(classes)) or 1
            img[ci * band:(ci + 1) * band, :, :] = 220
            cv2.imwrite(os.path.join(d, f"img_{i}.png"), img)


def gen_img_list(image_folder, out_folder, percent_val=0.25, seed=888):
    """Stratified split (gen_img_list.py --stratified): per class,
    hold out percent_val entries for validation."""
    random.seed(seed)
    entries = list(im2rec.list_images(image_folder, recursive=True))
    per_class = {}
    for path, label in entries:
        per_class.setdefault(label, []).append(path)
    tr, va = [], []
    for label, paths in sorted(per_class.items()):
        random.shuffle(paths)
        n_val = int(len(paths) * percent_val)
        va += [(p, label) for p in paths[:n_val]]
        tr += [(p, label) for p in paths[n_val:]]
    random.shuffle(tr)
    random.shuffle(va)
    os.makedirs(out_folder, exist_ok=True)
    for name, chunk in (("tr", tr), ("va", va)):
        with open(os.path.join(out_folder, f"{name}.lst"), "w") as f:
            for i, (path, label) in enumerate(chunk):
                f.write(f"{i}\t{label}\t{path}\n")
    return (os.path.join(out_folder, "tr.lst"),
            os.path.join(out_folder, "va.lst"))


def pack_list(lst_path, image_folder, prefix):
    """im2rec.pack reads <prefix>.lst, so stage the split list there."""
    import shutil

    if os.path.abspath(lst_path) != os.path.abspath(prefix + ".lst"):
        shutil.copyfile(lst_path, prefix + ".lst")
    args = argparse.Namespace(
        recursive=True, shuffle=0, train_ratio=1.0, test_ratio=0.0,
        resize=0, center_crop=False, quality=95, encoding=".png",
        color=1, pass_through=False, num_thread=2, num_parts=1)
    im2rec.pack(prefix, image_folder, args)
    return prefix + ".rec"


def gen_sub(predictions, va_lst_path, classes, submission_path):
    """submission_dsb.py: header = class names, one row per image."""
    names = []
    with open(va_lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if parts:
                names.append(os.path.basename(parts[-1]))
    with open(submission_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["image"] + list(classes))
        for name, row in zip(names, predictions):
            w.writerow([name] + [f"{p:.6f}" for p in row])


def build_net(num_classes):
    data = mx.sym.Variable("data")
    body = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                              pad=(1, 1), name="conv1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    body = mx.sym.Flatten(body)
    body = mx.sym.FullyConnected(body, num_hidden=64, name="fc1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.FullyConnected(body, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(body, name="softmax")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image-folder", default=None,
                   help="class-per-folder image tree (default: synthesize)")
    p.add_argument("--work-dir", default="ndsb_work")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-hw", type=int, default=24)
    p.add_argument("--per-class", type=int, default=24)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    classes = ["acantharia", "copepod", "detritus", "shrimp"]
    image_folder = args.image_folder
    if image_folder is None:
        image_folder = os.path.join(args.work_dir, "train")
        make_synthetic_tree(image_folder, classes, args.per_class,
                            hw=args.data_hw)
    else:
        classes = sorted(d for d in os.listdir(image_folder)
                         if os.path.isdir(os.path.join(image_folder, d)))

    tr_lst, va_lst = gen_img_list(image_folder, args.work_dir)
    tr_rec = pack_list(tr_lst, image_folder,
                       os.path.join(args.work_dir, "tr"))
    va_rec = pack_list(va_lst, image_folder,
                       os.path.join(args.work_dir, "va"))

    shape = (3, args.data_hw, args.data_hw)
    train_it = mx.io.ImageRecordIter(
        path_imgrec=tr_rec, data_shape=shape, batch_size=args.batch_size,
        shuffle=True, preprocess_threads=2, scale=1.0 / 255)
    val_it = mx.io.ImageRecordIter(
        path_imgrec=va_rec, data_shape=shape, batch_size=args.batch_size,
        preprocess_threads=2, scale=1.0 / 255)

    mod = mx.mod.Module(build_net(len(classes)))
    mod.fit(train_it, eval_data=val_it, num_epoch=args.num_epochs,
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier())

    val_it.reset()
    preds = mod.predict(val_it).asnumpy()
    sub_path = os.path.join(args.work_dir, "submission.csv")
    gen_sub(preds, va_lst, classes, sub_path)

    val_it.reset()
    acc = dict(mod.score(val_it, mx.metric.create("acc")))["accuracy"]
    logging.info("val accuracy %.3f, submission at %s", acc, sub_path)
    assert acc > 0.8, acc
    print(f"NDSB_OK acc={acc:.3f}")


if __name__ == "__main__":
    main()
