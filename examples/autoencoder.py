#!/usr/bin/env python
"""Stacked denoising autoencoder
(rebuild of example/autoencoder/{autoencoder.py,mnist_sae.py}).

Greedy layer-wise pretraining of each encoder/decoder pair followed by
end-to-end fine-tuning, as in the reference's AutoEncoderModel: every
stage is a LinearRegressionOutput symbol trained with the Module API;
pretrained weights carry over via set_params/arg sharing.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_stage_sym(n_in, n_hidden, idx, noise=0.2):
    data = mx.sym.Variable("data")
    if noise > 0:
        corrupted = mx.sym.Dropout(data, name=f"noise_{idx}", p=noise)
    else:
        corrupted = data
    enc = mx.sym.FullyConnected(corrupted, name=f"enc_{idx}",
                                num_hidden=n_hidden)
    act = mx.sym.Activation(enc, name=f"enc_act_{idx}", act_type="relu")
    dec = mx.sym.FullyConnected(act, name=f"dec_{idx}", num_hidden=n_in)
    return mx.sym.LinearRegressionOutput(dec, name=f"rec_{idx}")


def build_finetune_sym(dims):
    """Full encoder->decoder chain over all layers."""
    x = mx.sym.Variable("data")
    h = x
    for i, d in enumerate(dims[1:]):
        h = mx.sym.FullyConnected(h, name=f"enc_{i}", num_hidden=d)
        h = mx.sym.Activation(h, name=f"enc_act_{i}", act_type="relu")
    for i in reversed(range(len(dims) - 1)):
        h = mx.sym.FullyConnected(h, name=f"dec_{i}", num_hidden=dims[i])
        if i > 0:
            h = mx.sym.Activation(h, name=f"dec_act_{i}", act_type="relu")
    return mx.sym.LinearRegressionOutput(h, name="rec")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--dims", default="784,256,64",
                   help="comma-separated layer sizes, input first")
    p.add_argument("--pretrain-epochs", type=int, default=2)
    p.add_argument("--finetune-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=2048)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.tpu(0)
    dims = [int(d) for d in args.dims.split(",")]

    rng = np.random.RandomState(0)
    # low-rank structured data so reconstruction is learnable
    basis = rng.standard_normal((8, dims[0])).astype(np.float32)
    codes = rng.standard_normal((args.n_train, 8)).astype(np.float32)
    X = codes @ basis

    pretrained = {}
    cur = X
    for i in range(len(dims) - 1):
        sym = build_stage_sym(cur.shape[1], dims[i + 1], i)
        mod = mx.mod.Module(sym, label_names=(f"rec_{i}_label",), context=ctx)
        it = mx.io.NDArrayIter(cur, cur, args.batch_size, shuffle=True,
                               label_name=f"rec_{i}_label")
        mod.fit(it, optimizer="adam",
                optimizer_params={"learning_rate": args.lr},
                num_epoch=args.pretrain_epochs, eval_metric="mse")
        arg_params, _ = mod.get_params()
        pretrained.update(arg_params)
        # propagate data through the frozen encoder for the next stage
        w = arg_params[f"enc_{i}_weight"].asnumpy()
        b = arg_params[f"enc_{i}_bias"].asnumpy()
        cur = np.maximum(cur @ w.T + b, 0.0)
        logging.info("pretrained stage %d: %s -> %s", i, w.shape[1], w.shape[0])

    sym = build_finetune_sym(dims)
    mod = mx.mod.Module(sym, label_names=("rec_label",), context=ctx)
    it = mx.io.NDArrayIter(X, X, args.batch_size, shuffle=True,
                           label_name="rec_label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.set_params({k: mx.nd.array(v.asnumpy()) if hasattr(v, "asnumpy")
                    else mx.nd.array(v) for k, v in pretrained.items()},
                   {}, allow_missing=True)
    mod.fit(it, optimizer="adam", optimizer_params={"learning_rate": args.lr},
            num_epoch=args.finetune_epochs, eval_metric="mse")

    # report reconstruction error
    it.reset()
    se, n = 0.0, 0
    for batch in it:
        mod.forward(batch, is_train=False)
        rec = mod.get_outputs()[0].asnumpy()
        ref = batch.data[0].asnumpy()
        se += ((rec - ref) ** 2).sum()
        n += ref.size
    print(f"final reconstruction mse {se / n:.4f}")


if __name__ == "__main__":
    main()
