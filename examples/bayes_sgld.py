#!/usr/bin/env python
"""Bayesian inference with SGLD
(rebuild of example/bayesian-methods — stochastic gradient Langevin
dynamics, Welling & Teh 2011).

Trains a small regression net with the ``sgld`` optimizer: each update
adds gaussian noise scaled to the step size, so the parameter iterates
are posterior samples.  Predictions averaged over the sample chain
beat the single-point estimate on noisy data — the reference's
demonstration, reproduced on a synthetic curve.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_net(num_hidden=32):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=num_hidden)
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=num_hidden)
    act2 = mx.sym.Activation(fc2, act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="out", num_hidden=1)
    return mx.sym.LinearRegressionOutput(fc3, name="lro")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--burn-in-epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=1024)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    X = rng.uniform(-3, 3, (args.n_train, 1)).astype(np.float32)
    y = (np.sin(X[:, 0]) + rng.standard_normal(args.n_train) * 0.2
         ).astype(np.float32)[:, None]
    n_val = (192 // args.batch_size) * args.batch_size or args.batch_size
    Xv = np.linspace(-3, 3, n_val).astype(np.float32)[:, None]
    yv = np.sin(Xv[:, 0]).astype(np.float32)[:, None]

    net = build_net()
    mod = mx.mod.Module(net, label_names=("lro_label",), context=mx.tpu(0))
    train = mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True,
                              label_name="lro_label")
    mod.bind(data_shapes=train.provide_data, label_shapes=train.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params={"learning_rate": args.lr,
                                         "wd": 0.0001})

    val = mx.io.NDArrayIter(Xv, yv, args.batch_size, label_name="lro_label")

    def predict():
        val.reset()
        outs = []
        for batch in val:
            mod.forward(batch, is_train=False)
            outs.append(mod.get_outputs()[0].asnumpy()[:, 0])
        return np.concatenate(outs)[:len(Xv)]

    posterior_sum = np.zeros(len(Xv), np.float64)
    n_samples = 0
    for epoch in range(args.num_epochs):
        train.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
        if epoch >= args.burn_in_epochs:   # collect posterior samples
            posterior_sum += predict()
            n_samples += 1
        logging.info("epoch %d done", epoch)

    point = predict()                       # last iterate alone
    posterior = posterior_sum / max(n_samples, 1)
    target = yv[:, 0]
    mse_point = float(((point - target) ** 2).mean())
    mse_post = float(((posterior - target) ** 2).mean())
    print(f"single-sample mse {mse_point:.4f}; "
          f"posterior-average mse {mse_post:.4f} over {n_samples} samples")


if __name__ == "__main__":
    main()
