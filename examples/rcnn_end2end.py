#!/usr/bin/env python
"""End-to-end Faster R-CNN style training (rebuild of
example/rcnn/train_end2end.py on synthetic data).

The full proposal pipeline in one symbol, like the reference's
get_symbol_train (rcnn/symbol.py): a conv backbone feeds (a) an RPN —
objectness via multi-output SoftmaxOutput with ignore labels, box
deltas via smooth_l1 — and (b) the detection head: the ``proposal``
CustomOp decodes+NMSes RPN outputs into ROIs, ``proposal_target``
samples them against gt boxes in-graph, and ROIPooling + FC heads
classify each ROI.  Anchor targets come from
contrib.rcnn.assign_anchor in the data iterator (AnchorLoader analog).

Synthetic task: one axis-aligned bright rectangle per image, class =
rectangle's fill channel.  After a few epochs the RPN must localize the
rectangle (proposal recall gate) and the head must classify it.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.contrib import rcnn  # noqa: E402

STRIDE = 8
SCALES = (2, 4)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
NUM_CLASSES = 3  # background + 2 object classes
ROI_BATCH = 16


def build_symbol(im_hw, post_nms):
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    gt_boxes = mx.sym.Variable("gt_boxes")
    rpn_label = mx.sym.Variable("rpn_label")
    rpn_bbox_target = mx.sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = mx.sym.Variable("rpn_bbox_weight")

    body = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                              num_filter=16, name="c1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Convolution(body, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                              num_filter=32, name="c2")
    head_feat = mx.sym.Activation(body, act_type="relu", name="head_feat")
    feat = mx.sym.Convolution(head_feat, kernel=(3, 3), pad=(1, 1),
                              stride=(2, 2), num_filter=32, name="c3")
    feat = mx.sym.Activation(feat, act_type="relu", name="feat")

    rpn_conv = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                  num_filter=32, name="rpn_conv")
    rpn_relu = mx.sym.Activation(rpn_conv, act_type="relu")
    rpn_cls_score = mx.sym.Convolution(rpn_relu, kernel=(1, 1),
                                       num_filter=2 * A, name="rpn_cls_score")
    rpn_bbox_pred = mx.sym.Convolution(rpn_relu, kernel=(1, 1),
                                       num_filter=4 * A, name="rpn_bbox_pred")

    # RPN objectness: (1, 2A, H, W) -> (1, 2, A*H*W) softmax with ignore
    score_rs = mx.sym.Reshape(rpn_cls_score, shape=(1, 2, -1),
                              name="rpn_cls_score_reshape")
    rpn_cls_prob = mx.sym.SoftmaxOutput(
        score_rs, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")
    rpn_bbox_loss_ = rpn_bbox_weight * mx.sym.smooth_l1(
        rpn_bbox_pred - rpn_bbox_target, sigma=3.0)
    rpn_bbox_loss = mx.sym.MakeLoss(rpn_bbox_loss_, grad_scale=1.0 / 64,
                                    name="rpn_bbox_loss")

    # proposals -> sampled head batch, all inside the graph
    fh = im_hw // STRIDE
    prob_act = mx.sym.Reshape(rpn_cls_prob, shape=(1, 2 * A, fh, fh),
                              name="rpn_prob_reshape")
    rois = mx.sym.Custom(prob_act, rpn_bbox_pred, im_info,
                         op_type="proposal", feat_stride=STRIDE,
                         scales=str(SCALES), ratios=str(RATIOS),
                         rpn_pre_nms_top_n=200, rpn_post_nms_top_n=post_nms,
                         threshold=0.7, rpn_min_size=4)
    group = mx.sym.Custom(rois, gt_boxes, op_type="proposal_target",
                          num_classes=NUM_CLASSES, batch_rois=ROI_BATCH,
                          fg_fraction=0.5, fg_overlap=0.5,
                          bg_overlap_hi=0.4, name="ptarget")
    sampled_rois = group[0]
    label = group[1]
    bbox_target = group[2]
    bbox_weight = group[3]

    # The head owns a small feature tower from the image.  The shared
    # trunk is shaped purely by class-agnostic objectness here (the
    # reference avoids this with a pretrained VGG trunk); a dedicated
    # stride-4 tower keeps per-channel class identity for ROI pooling.
    ht = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                            num_filter=16, name="h1")
    ht = mx.sym.Activation(ht, act_type="relu")
    ht = mx.sym.Convolution(ht, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                            num_filter=16, name="h2")
    ht = mx.sym.Activation(ht, act_type="relu", name="head_tower")
    pooled = mx.sym.ROIPooling(ht, sampled_rois, pooled_size=(4, 4),
                               spatial_scale=1.0 / 4, name="roi_pool")
    flat = mx.sym.Flatten(pooled)
    fc = mx.sym.Activation(
        mx.sym.FullyConnected(flat, num_hidden=64, name="fc6"),
        act_type="relu")
    cls_score = mx.sym.FullyConnected(fc, num_hidden=NUM_CLASSES,
                                      name="cls_score")
    cls_prob = mx.sym.SoftmaxOutput(cls_score, mx.sym.BlockGrad(label),
                                    normalization="batch", name="cls_prob")
    bbox_pred = mx.sym.FullyConnected(fc, num_hidden=4 * NUM_CLASSES,
                                      name="bbox_pred")
    bbox_loss = mx.sym.MakeLoss(
        mx.sym.BlockGrad(bbox_weight) * mx.sym.smooth_l1(
            bbox_pred - mx.sym.BlockGrad(bbox_target), sigma=1.0),
        grad_scale=1.0 / ROI_BATCH, name="bbox_loss")

    return mx.sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss,
                         mx.sym.BlockGrad(label),
                         mx.sym.BlockGrad(sampled_rois),
                         mx.sym.BlockGrad(rois)])


def make_image(rng, hw):
    """Noise canvas + one bright class-colored rectangle."""
    img = rng.rand(3, hw, hw).astype(np.float32) * 0.2
    cls = rng.randint(1, NUM_CLASSES)
    w, h = rng.randint(hw // 4, hw // 2, 2)
    x1 = rng.randint(0, hw - w)
    y1 = rng.randint(0, hw - h)
    img[cls - 1, y1:y1 + h, x1:x1 + w] = 1.0
    gt = np.array([[x1, y1, x1 + w - 1, y1 + h - 1, cls]], np.float32)
    return img, gt


class RcnnIter(mx.io.DataIter):
    """AnchorLoader analog: images + im_info + gt plus per-image RPN
    targets from assign_anchor."""

    def __init__(self, n, hw, seed=0):
        super().__init__()
        self.hw = hw
        self.n = n
        self.rng = np.random.RandomState(seed)
        self.fh = hw // STRIDE
        self.cursor = 0
        ahw = A * self.fh * self.fh
        self.provide_data = [
            mx.io.DataDesc("data", (1, 3, hw, hw)),
            mx.io.DataDesc("im_info", (1, 3), layout="NC"),
            mx.io.DataDesc("gt_boxes", (1, 5), layout="NC"),
        ]
        self.provide_label = [
            mx.io.DataDesc("rpn_label", (1, ahw), layout="NC"),
            mx.io.DataDesc("rpn_bbox_target", (1, 4 * A, self.fh, self.fh)),
            mx.io.DataDesc("rpn_bbox_weight", (1, 4 * A, self.fh, self.fh)),
        ]

    def reset(self):
        self.cursor = 0

    def next(self):
        if self.cursor >= self.n:
            raise StopIteration
        self.cursor += 1
        img, gt = make_image(self.rng, self.hw)
        tgt = rcnn.assign_anchor(
            (1, 2 * A, self.fh, self.fh), gt[:, :4],
            im_info=(self.hw, self.hw, 1.0), feat_stride=STRIDE,
            scales=SCALES, ratios=RATIOS, batch_rois=64, fg_fraction=0.5,
            fg_overlap=0.6, bg_overlap=0.3, rng=self.rng)
        # (H*W*A,) pos-major -> (A, H, W) channel layout of the heads
        lab = tgt["label"].reshape(self.fh, self.fh, A)
        lab = lab.transpose(2, 0, 1).reshape(1, -1)
        bt = tgt["bbox_target"].reshape(self.fh, self.fh, A, 4)
        bt = bt.transpose(2, 3, 0, 1).reshape(1, 4 * A, self.fh, self.fh)
        bw = tgt["bbox_weight"].reshape(self.fh, self.fh, A, 4)
        bw = bw.transpose(2, 3, 0, 1).reshape(1, 4 * A, self.fh, self.fh)
        return mx.io.DataBatch(
            data=[mx.nd.array(img[None]),
                  mx.nd.array([[self.hw, self.hw, 1.0]]),
                  mx.nd.array(gt[:, :5])],
            label=[mx.nd.array(lab), mx.nd.array(bt), mx.nd.array(bw)],
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hw", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--images-per-epoch", type=int, default=120)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--post-nms", type=int, default=16)
    p.add_argument("--min-recall", type=float, default=0.7)
    p.add_argument("--min-acc", type=float, default=0.6)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(5)

    sym = build_symbol(args.hw, args.post_nms)
    it = RcnnIter(args.images_per_epoch, args.hw)
    mod = mx.mod.Module(sym,
                        data_names=("data", "im_info", "gt_boxes"),
                        label_names=("rpn_label", "rpn_bbox_target",
                                     "rpn_bbox_weight"))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    for epoch in range(args.num_epochs):
        it.reset()
        for nbatch, batch in enumerate(it):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        logging.info("epoch %d done", epoch)

    # evaluate proposal recall + head accuracy on fresh images
    eval_it = RcnnIter(24, args.hw, seed=99)
    recalls, correct, n_fg = [], 0, 0
    for batch in eval_it:
        mod.forward(batch, is_train=True)
        outs = [o.asnumpy() for o in mod.get_outputs()]
        cls_prob, label = outs[2], outs[4]
        proposals = outs[6]          # raw proposal-op rois, pre-sampling:
        gt = batch.data[2].asnumpy()[:, :4]   # gt never joins this set
        iou = rcnn.bbox_overlaps(proposals[:, 1:].astype(np.float64), gt)
        recalls.append(iou.max())
        fg = label > 0
        if fg.any():
            n_fg += int(fg.sum())
            correct += int((cls_prob[fg].argmax(1) == label[fg]).sum())
    recall = float(np.mean([r > 0.5 for r in recalls]))
    acc = correct / max(n_fg, 1)
    logging.info("proposal recall@0.5=%.2f head fg accuracy=%.2f",
                 recall, acc)
    assert recall >= args.min_recall, recall
    assert acc >= args.min_acc, acc
    print(f"RCNN_OK recall={recall:.2f} acc={acc:.2f}")


if __name__ == "__main__":
    main()
