#!/usr/bin/env python
"""Custom operators in numpy
(rebuild of example/numpy-ops/custom_softmax.py + numpy_softmax.py).

Defines softmax twice — once as a ``CustomOp`` (the modern bridge) and
once as a ``NumpyOp`` (the legacy callback op) — and trains the same
MLP with each, verifying the host-side op path end to end.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(e / e.sum(axis=1,
                                                               keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@mx.operator.register("demo_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


class NumpySoftmax(mx.operator.NumpyOp):
    """Same op through the older NumpyOp callback interface
    (reference example/numpy-ops/numpy_softmax.py)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]]

    def forward(self, in_data, out_data):
        x = in_data[0]
        e = np.exp(x - x.max(axis=1, keepdims=True))
        out_data[0][:] = e / e.sum(axis=1, keepdims=True)

    def backward(self, out_grad, in_data, out_data, in_grad):
        l = in_data[1].ravel().astype(np.int64)
        y = out_data[0].copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        in_grad[0][:] = y


def build(kind):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=10)
    label = mx.sym.Variable("softmax_label")
    if kind == "custom":
        return mx.sym.Custom(fc2, label, name="softmax",
                             op_type="demo_softmax")
    return NumpySoftmax()(data=fc2, label=label, name="softmax")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--n-train", type=int, default=2000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, args.n_train)
    X = rng.standard_normal((args.n_train, 784)).astype(np.float32) * 0.3
    X[np.arange(args.n_train), y * 78] += 2.0

    for kind in ("custom", "numpy"):
        net = build(kind)
        mod = mx.mod.Module(net, context=mx.tpu(0))
        mod.fit(mx.io.NDArrayIter(X, y.astype(np.float32), args.batch_size,
                                  shuffle=True),
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                num_epoch=args.num_epochs)
        acc = dict(mod.score(mx.io.NDArrayIter(X, y.astype(np.float32),
                                               args.batch_size),
                             "acc"))["accuracy"]
        print(f"{kind}-op softmax train accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
