#!/usr/bin/env python
"""Time-major RNN language model (rebuild of
example/rnn-time-major/rnn_cell_demo.py).

The point of the original example: feed sequences **time-major** (T, N)
end to end, so the fused RNN op consumes its natural layout with no
SwapAxis transposes in the graph — the reference README measures
1.5-2x over batch-major.  The batch axis is declared via the DataDesc
``layout`` field ('TN': batch axis 1), which the executor group honors
when slicing batches across devices (io.py DataDesc / executor_group
decide_slices).  On TPU the same layout argument keeps XLA from having
to fuse away two transposes around the scan.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_net(seq_len, vocab_size, num_hidden=64, num_embed=32):
    data = mx.sym.Variable("data")          # (seq_len, batch) — time-major
    embed = mx.sym.Embedding(data, name="embed", input_dim=vocab_size,
                             output_dim=num_embed)  # (T, N, E)
    rnn = mx.sym.RNN(embed, name="lstm", mode="lstm", state_size=num_hidden,
                     num_layers=1,
                     parameters=mx.sym.Variable("lstm_parameters"),
                     state=mx.sym.Variable("lstm_state"),
                     state_cell=mx.sym.Variable("lstm_state_cell"))
    flat = mx.sym.Reshape(rnn, shape=(-1, num_hidden))      # (T*N, H)
    fc = mx.sym.FullyConnected(flat, name="cls", num_hidden=vocab_size)
    label = mx.sym.Variable("softmax_label")                # (T, N)
    label_flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(fc, label_flat, name="softmax")


class TimeMajorIter(mx.io.DataIter):
    """Yields (T, N) token batches with next-token labels; DataDescs
    carry layout='TN' so the module slices the batch on axis 1."""

    def __init__(self, corpus, batch_size, seq_len):
        super().__init__()
        self.corpus = corpus
        self.batch_size = batch_size
        self.seq_len = seq_len
        n_seq = (len(corpus) - 1) // seq_len
        self.n_batches = n_seq // batch_size
        self.cursor = 0
        self.provide_data = [mx.io.DataDesc(
            "data", (seq_len, batch_size), layout="TN")]
        self.provide_label = [mx.io.DataDesc(
            "softmax_label", (seq_len, batch_size), layout="TN")]

    def reset(self):
        self.cursor = 0

    def next(self):
        if self.cursor >= self.n_batches:
            raise StopIteration
        i = self.cursor * self.batch_size * self.seq_len
        self.cursor += 1
        span = self.batch_size * self.seq_len
        x = self.corpus[i:i + span].reshape(self.batch_size, self.seq_len).T
        y = self.corpus[i + 1:i + span + 1].reshape(
            self.batch_size, self.seq_len).T
        return mx.io.DataBatch(data=[mx.nd.array(x)],
                               label=[mx.nd.array(y)],
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)


def perplexity(label, pred):
    """Perplexity over flattened (T*N,) labels vs (T*N, V) probs
    (the reference example's metric)."""
    label = label.reshape(-1).astype(int)
    probs = pred[np.arange(len(label)), label]
    return float(np.exp(-np.mean(np.log(np.maximum(probs, 1e-10)))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--corpus-len", type=int, default=20000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    # synthetic markov-ish corpus: token i is usually followed by i+1
    rng = np.random.RandomState(7)
    corpus = np.zeros(args.corpus_len, np.int64)
    for i in range(1, args.corpus_len):
        corpus[i] = ((corpus[i - 1] + 1) % args.vocab
                     if rng.rand() < 0.9 else rng.randint(args.vocab))

    net = build_net(args.seq_len, args.vocab)
    it = TimeMajorIter(corpus.astype(np.float32), args.batch_size,
                       args.seq_len)
    mod = mx.mod.Module(net)
    metric = mx.metric.np(perplexity, name="perplexity")
    mod.fit(it, eval_metric=metric, num_epoch=args.num_epochs,
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier())
    it.reset()
    score = dict(mod.score(it, mx.metric.np(perplexity, name="perplexity")))
    ppl = score["custom(perplexity)"]
    logging.info("final perplexity %.2f (uniform would be %d)", ppl,
                 args.vocab)
    # the 0.9-probability successor structure is learnable: ppl far
    # below uniform proves the time-major path trains
    assert ppl < args.vocab / 4, ppl
    print(f"TIME_MAJOR_OK ppl={ppl:.2f}")


if __name__ == "__main__":
    main()
