"""Int8 post-training quantization workflow on ResNet-50
(contrib/quantization.py; see docs/how_to/quantization.md).

Train-or-load -> calibrate on a few batches -> quantize -> compare
float vs int8 outputs -> save the int8 deployment artifacts.  Runs on
synthetic data by default so it works anywhere; point --data-dir at an
ImageNet rec set for the real thing.

Usage:
  python examples/quantize_resnet.py [--num-layers 18] [--batch 8]
         [--weight-only] [--out /tmp/resnet_int8]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.contrib.quantization import quantize_model  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-layers", type=int, default=18)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image-hw", type=int, default=32,
                   help="synthetic image size (224 for real ImageNet)")
    p.add_argument("--weight-only", action="store_true",
                   help="skip calibration (int8 weights, float compute)")
    p.add_argument("--out", default="/tmp/resnet_int8")
    args = p.parse_args()

    hw = args.image_hw
    net = mx.models.resnet(num_classes=1000, num_layers=args.num_layers,
                           image_shape=(3, hw, hw))
    data_shape = (args.batch, 3, hw, hw)

    # stand-in for a trained checkpoint: random-initialized params
    # (swap for mx.model.load_checkpoint(prefix, epoch) in real use)
    rng = np.random.RandomState(0)
    arg_shapes, _, aux_shapes = net.infer_shape(data=data_shape)
    arg_params = {
        n: mx.nd.array(rng.standard_normal(s).astype(np.float32) * 0.05)
        for n, s in zip(net.list_arguments(), arg_shapes)
        if n not in ("data", "softmax_label")}
    aux_params = {
        n: mx.nd.array(np.ones(s, np.float32) if n.endswith("var")
                       else np.zeros(s, np.float32))
        for n, s in zip(net.list_auxiliary_states(), aux_shapes)}

    calib = None
    if not args.weight_only:
        calib = [rng.uniform(-1, 1, data_shape).astype(np.float32)
                 for _ in range(4)]
    qsym, qargs, qaux = quantize_model(
        net, arg_params, aux_params, calib_data=calib,
        exclude=("conv0",))  # image-space stem stays float

    n_int8 = sum(1 for v in qargs.values() if v.dtype == np.int8)
    f_bytes = sum(int(np.prod(v.shape)) * 4 for v in arg_params.values())
    q_bytes = sum(int(np.prod(v.shape)) * (1 if v.dtype == np.int8 else 4)
                  for v in qargs.values())
    print(f"quantized {n_int8} layers; params {f_bytes / 1e6:.1f} MB -> "
          f"{q_bytes / 1e6:.1f} MB")

    # float vs int8 agreement on a held-out batch
    X = rng.uniform(-1, 1, data_shape).astype(np.float32)

    def forward(sym, params, aux):
        exe = sym.simple_bind(mx.cpu(), grad_req="null", data=data_shape,
                              softmax_label=(args.batch,))
        for k, v in params.items():
            if k in exe.arg_dict:
                exe.arg_dict[k][:] = v
        for k, v in aux.items():
            if k in exe.aux_dict:
                exe.aux_dict[k][:] = v
        exe.arg_dict["data"][:] = X
        return exe.forward(is_train=False)[0].asnumpy()

    p_f = forward(net, arg_params, aux_params)
    p_q = forward(qsym, qargs, qaux)
    agree = (p_f.argmax(1) == p_q.argmax(1)).mean()
    print(f"top-1 agreement float vs int8: {agree:.3f}")

    qsym.save(args.out + "-symbol.json")
    mx.nd.save(args.out + "-0000.params",
               {"arg:" + k: v for k, v in qargs.items()}
               | {"aux:" + k: v for k, v in qaux.items()})
    print(f"saved {args.out}-symbol.json / -0000.params")


if __name__ == "__main__":
    main()
