#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST
(rebuild of example/image-classification/train_mnist.py).

With --data-dir pointing at the idx files, uses MNISTIter; without,
trains on a synthetic stand-in so the example runs anywhere.
"""

import os

import numpy as np

import common
import mxnet_tpu as mx


def get_iters(args):
    flat = args.network == "mlp"
    d = args.data_dir
    if d and os.path.exists(os.path.join(d, "train-images-idx3-ubyte")):
        train = mx.io.MNISTIter(
            image=os.path.join(d, "train-images-idx3-ubyte"),
            label=os.path.join(d, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True, flat=flat)
        val = mx.io.MNISTIter(
            image=os.path.join(d, "t10k-images-idx3-ubyte"),
            label=os.path.join(d, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=False, flat=flat)
        return train, val
    # synthetic fallback: 10 gaussian blobs
    rng = np.random.RandomState(0)
    n = 6400
    y = rng.randint(0, 10, n)
    X = rng.standard_normal((n, 784)).astype(np.float32) * 0.3
    X[np.arange(n), y * 78] += 2.0
    if not flat:
        X = X.reshape(n, 1, 28, 28)
    split = n - 1280
    train = mx.io.NDArrayIter(X[:split], y[:split].astype(np.float32),
                              args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(X[split:], y[split:].astype(np.float32),
                            args.batch_size)
    return train, val


def main():
    parser = common.add_fit_args(__import__("argparse").ArgumentParser(
        description=__doc__))
    parser.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    parser.add_argument("--data-dir", default=None)
    args = parser.parse_args()
    net = (mx.models.mlp() if args.network == "mlp"
           else mx.models.lenet())
    train, val = get_iters(args)
    common.fit(args, net, train, val)


if __name__ == "__main__":
    main()
