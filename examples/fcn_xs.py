#!/usr/bin/env python
"""Fully-convolutional segmentation
(rebuild of example/fcn-xs — FCN-32s/16s-style skip architecture).

Conv trunk -> 1x1 score head -> Deconvolution upsampling, with a skip
connection merged via Crop (the reference's offset-matching mechanism)
and a per-pixel SoftmaxOutput (``multi_output=True``).  Trains on
synthetic blob masks.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_fcn(num_classes):
    data = mx.sym.Variable("data")
    # stage 1 (full res -> /2)
    c1 = mx.sym.Convolution(data, name="conv1", kernel=(3, 3), pad=(1, 1),
                            num_filter=16)
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    # stage 2 (/2 -> /4)
    c2 = mx.sym.Convolution(p1, name="conv2", kernel=(3, 3), pad=(1, 1),
                            num_filter=32)
    a2 = mx.sym.Activation(c2, act_type="relu")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    # deep head at /4
    score4 = mx.sym.Convolution(p2, name="score4", kernel=(1, 1),
                                num_filter=num_classes)
    # upsample /4 -> /2, merge with skip from stage 1 (fcn-16s pattern)
    up2 = mx.sym.Deconvolution(score4, name="up2", kernel=(4, 4),
                               stride=(2, 2), pad=(1, 1),
                               num_filter=num_classes)
    score2 = mx.sym.Convolution(p1, name="score2", kernel=(1, 1),
                                num_filter=num_classes)
    up2c = mx.sym.Crop(up2, score2, name="crop2", num_args=2)
    fused = up2c + score2
    # upsample /2 -> full res
    up1 = mx.sym.Deconvolution(fused, name="up1", kernel=(4, 4),
                               stride=(2, 2), pad=(1, 1),
                               num_filter=num_classes)
    up1c = mx.sym.Crop(up1, data, name="crop1", num_args=2)
    return mx.sym.SoftmaxOutput(up1c, name="softmax", multi_output=True,
                                use_ignore=True, ignore_label=255)


def make_data(n, size, seed=0):
    """Images with a bright disc; mask = disc pixels (2-class)."""
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, 1, size, size)).astype(np.float32) * 0.2
    y = np.zeros((n, size, size), np.float32)
    grid = np.arange(size)
    yy, xx = np.meshgrid(grid, grid, indexing="ij")
    for i in range(n):
        cx, cy = rng.randint(size // 4, 3 * size // 4, 2)
        r = rng.randint(size // 8, size // 4)
        mask = (xx - cx) ** 2 + (yy - cy) ** 2 < r * r
        X[i, 0][mask] += 1.5
        y[i][mask] = 1.0
    return X, y


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--n-train", type=int, default=512)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, y = make_data(args.n_train, args.size)
    train = mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True)
    net = build_fcn(num_classes=2)
    mod = mx.mod.Module(net, context=mx.tpu(0))
    def pixel_acc(label, pred):
        return float((pred.argmax(axis=1) == label).mean())

    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.num_epochs,
            eval_metric=mx.metric.CustomMetric(pixel_acc),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    # pixel accuracy on training data
    train.reset()
    correct = total = 0
    for batch in train:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum()
        total += lab.size
    print(f"fcn pixel accuracy {correct / total:.3f}")


if __name__ == "__main__":
    main()
