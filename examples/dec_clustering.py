#!/usr/bin/env python
"""Deep embedded clustering (rebuild of example/dec/dec.py).

An encoder maps points to an embedding; a ``NumpyOp`` computes the
Student-t soft cluster assignment q against learnable centers (the
reference's DECLoss NumpyOp), and training minimizes KL(p || q) against
the sharpened target distribution p, re-estimated every few epochs.
Runs on synthetic gaussian blobs; reports clustering accuracy by
greedy cluster-to-label matching.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


class DECLoss(mx.operator.NumpyOp):
    """Soft assignment + KL(p||q) gradient (reference dec.py DECLoss)."""

    def __init__(self, num_centers, alpha=1.0):
        super().__init__(need_top_grad=False)
        self.num_centers = num_centers
        self.alpha = alpha

    def list_arguments(self):
        return ["data", "label", "mu"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data = in_shape[0]
        mu = (self.num_centers, data[1])
        label = (data[0], self.num_centers)
        return [data, label, mu], [label]

    def forward(self, in_data, out_data):
        z, _, mu = in_data
        d2 = ((z[:, None, :] - mu[None, :, :]) ** 2).sum(axis=2)
        self.mask = 1.0 / (1.0 + d2 / self.alpha)
        q = self.mask ** ((self.alpha + 1.0) / 2.0)
        out_data[0][:] = (q.T / q.sum(axis=1)).T

    def backward(self, out_grad, in_data, out_data, in_grad):
        z, p, mu = in_data
        q = out_data[0]
        # d KL(p||q) / dz with Student-t kernel
        coeff = (self.alpha + 1.0) / self.alpha * self.mask * (p - q)
        diff = z[:, None, :] - mu[None, :, :]
        in_grad[0][:] = (coeff[:, :, None] * diff).sum(axis=1)
        in_grad[2][:] = -(coeff[:, :, None] * diff).sum(axis=0)
        in_grad[1][:] = 0.0


def target_distribution(q):
    w = q ** 2 / q.sum(axis=0)
    return (w.T / w.sum(axis=1)).T


def cluster_acc(pred, y, k):
    """Greedy cluster->label matching accuracy."""
    total = 0
    for c in range(k):
        members = y[pred == c]
        if len(members):
            total += np.bincount(members).max()
    return total / len(y)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-centers", type=int, default=4)
    p.add_argument("--embed-dim", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--update-interval", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n", type=int, default=1024)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    k = args.num_centers

    # blobs in 16-D
    y = rng.randint(0, k, args.n)
    centers = rng.standard_normal((k, 16)) * 4
    X = (centers[y] + rng.standard_normal((args.n, 16))).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    mu = mx.sym.Variable("mu")
    h = mx.sym.FullyConnected(data, name="enc1", num_hidden=32)
    h = mx.sym.Activation(h, act_type="relu")
    z = mx.sym.FullyConnected(h, name="enc2", num_hidden=args.embed_dim)
    dec = DECLoss(k, alpha=1.0)
    out = mx.sym.MakeLoss(dec(data=z, label=label, mu=mu, name="dec"))

    mod = mx.mod.Module(out, data_names=("data", "label"), label_names=None,
                        context=mx.tpu(0))
    # label (the target distribution p) rides as a second data input so
    # the python loop can feed the re-estimated p; mu is a learnable
    # parameter updated through DECLoss's in_grad[2]
    mod.bind(data_shapes=[("data", (args.batch_size, 16)),
                          ("label", (args.batch_size, k))])
    mod.init_params(initializer=mx.init.Mixed(
        ["mu", ".*"], [mx.init.Zero(), mx.init.Xavier()]))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    # embed helper
    emb = z.simple_bind(mx.tpu(0), grad_req="null", data=(args.n, 16))

    def embed():
        for name, arr in mod.get_params()[0].items():
            if name in emb.arg_dict:
                emb.arg_dict[name][:] = arr
        emb.arg_dict["data"][:] = X
        emb.forward(is_train=False)
        return emb.outputs[0].asnumpy()

    def get_mu():
        return mod.get_params()[0]["mu"].asnumpy()

    # init centers with a few k-means steps on the initial embedding
    zs = embed()
    mu_val = zs[rng.choice(args.n, k, replace=False)]
    for _ in range(10):
        d = ((zs[:, None] - mu_val[None]) ** 2).sum(2)
        assign = d.argmin(1)
        for c in range(k):
            if (assign == c).any():
                mu_val[c] = zs[assign == c].mean(0)
    arg_params, aux_params = mod.get_params()
    arg_params = dict(arg_params)
    arg_params["mu"] = mx.nd.array(mu_val.astype(np.float32))
    mod.set_params(arg_params, aux_params)

    pvals = None
    for epoch in range(args.epochs):
        if epoch % args.update_interval == 0:
            zs = embed()
            mu_val = get_mu()
            d2 = ((zs[:, None] - mu_val[None]) ** 2).sum(2)
            q = 1.0 / (1.0 + d2)
            q = (q.T / q.sum(1)).T
            pvals = target_distribution(q)
            acc = cluster_acc(q.argmax(1), y, k)
            logging.info("epoch %d cluster acc %.3f", epoch, acc)
        perm = rng.permutation(args.n)
        for i in range(0, args.n - args.batch_size + 1, args.batch_size):
            idx = perm[i:i + args.batch_size]
            mod.forward(mx.io.DataBatch(
                [mx.nd.array(X[idx]), mx.nd.array(pvals[idx])]),
                is_train=True)
            mod.backward()
            mod.update()

    zs = embed()
    mu_val = get_mu()
    d2 = ((zs[:, None] - mu_val[None]) ** 2).sum(2)
    acc = cluster_acc(d2.argmin(1), y, k)
    print(f"dec final cluster accuracy {acc:.3f} over {k} centers")


if __name__ == "__main__":
    main()
