#!/usr/bin/env python
"""Neural style transfer (rebuild of example/neural-style/nstyle.py).

Optimizes the *input image* — not network weights — to match the
content features of one image and the gram-matrix style statistics of
another, through a fixed VGG trunk.  Uses an executor bound with a
gradient buffer on the data argument (``grad_req`` on an input), the
same mechanism as the reference's ModelExecutor.

Without ``--params`` (pretrained VGG weights saved via mx.nd.save) it
runs with random filters on synthetic images — the optimization loop
and gradient plumbing are identical, only the aesthetics differ.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def vgg_features(style_layers, content_layer):
    """Truncated VGG trunk returning grouped style + content outputs."""
    data = mx.sym.Variable("data")
    cfg = [(2, 64, "1"), (2, 128, "2"), (3, 256, "3"), (3, 512, "4")]
    h = data
    outs = {}
    for n_convs, filt, stage in cfg:
        for i in range(1, n_convs + 1):
            h = mx.sym.Convolution(h, name=f"conv{stage}_{i}", kernel=(3, 3),
                                   pad=(1, 1), num_filter=filt)
            h = mx.sym.Activation(h, name=f"relu{stage}_{i}",
                                  act_type="relu")
            outs[f"relu{stage}_{i}"] = h
        h = mx.sym.Pooling(h, pool_type="avg", kernel=(2, 2), stride=(2, 2))
    style = [outs[l] for l in style_layers]
    content = outs[content_layer]
    return mx.sym.Group(style + [content]), len(style)


def gram(feat):
    """(C, H*W) gram matrix of a (1, C, H, W) feature map."""
    c = feat.shape[1]
    f = feat.reshape(c, -1)
    return f @ f.T / f.shape[1]


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", type=int, default=64)
    p.add_argument("--max-iter", type=int, default=30)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--style-weight", type=float, default=1.0)
    p.add_argument("--content-weight", type=float, default=10.0)
    p.add_argument("--params", default=None,
                   help="pretrained VGG params (mx.nd.save dict)")
    p.add_argument("--out", default=None, help="save result (npy)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.tpu(0)

    style_layers = ["relu1_2", "relu2_2", "relu3_3", "relu4_3"]
    sym, n_style = vgg_features(style_layers, "relu4_2")
    shape = (1, 3, args.size, args.size)

    exe = sym.simple_bind(ctx=ctx, grad_req="write", data=shape)
    init = mx.init.Xavier()
    for name, arr in exe.arg_dict.items():
        if name != "data":
            init(name, arr)
    if args.params:
        for name, arr in mx.nd.load(args.params).items():
            key = name.split(":", 1)[-1]
            if key in exe.arg_dict and key != "data":
                exe.arg_dict[key][:] = arr

    rng = np.random.RandomState(0)
    grid = np.linspace(-1, 1, args.size)
    yy, xx = np.meshgrid(grid, grid, indexing="ij")
    content_img = np.stack([np.sin(4 * xx), np.cos(4 * yy), xx * yy])[None]
    style_img = np.stack([np.sign(np.sin(8 * xx)), np.sign(np.cos(8 * yy)),
                          np.zeros_like(xx)])[None]

    def extract(img):
        exe.arg_dict["data"][:] = img.astype(np.float32)
        exe.forward(is_train=False)
        feats = [o.asnumpy() for o in exe.outputs]
        return [gram(f) for f in feats[:n_style]], feats[n_style]

    style_grams, _ = extract(style_img)
    _, content_feat = extract(content_img)

    img = rng.standard_normal(shape).astype(np.float32) * 0.1
    # adam state for the image pixels
    m = np.zeros_like(img)
    v = np.zeros_like(img)
    for it in range(1, args.max_iter + 1):
        exe.arg_dict["data"][:] = img
        exe.forward(is_train=True)
        feats = [o.asnumpy() for o in exe.outputs]
        head_grads = []
        loss = 0.0
        for f, g_target in zip(feats[:n_style], style_grams):
            g = gram(f)
            diff = g - g_target
            loss += args.style_weight * float((diff ** 2).sum())
            c = f.shape[1]
            fm = f.reshape(c, -1)
            grad = (2 * args.style_weight / fm.shape[1]) * (diff @ fm)
            head_grads.append(mx.nd.array(grad.reshape(f.shape), ctx=ctx))
        cdiff = feats[n_style] - content_feat
        loss += args.content_weight * float((cdiff ** 2).sum())
        head_grads.append(mx.nd.array(2 * args.content_weight * cdiff,
                                      ctx=ctx))
        exe.backward(head_grads)
        g = exe.grad_dict["data"].asnumpy()
        # adam on pixels
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        img -= args.lr * m / (np.sqrt(v) + 1e-8)
        if it % 10 == 0 or it == 1:
            logging.info("iter %d loss %.3e", it, loss)
    if args.out:
        np.save(args.out, img)
    print(f"style transfer done after {args.max_iter} iters; "
          f"final loss {loss:.3e}")


if __name__ == "__main__":
    main()
