#!/usr/bin/env python
"""Policy-gradient reinforcement learning
(rebuild of example/reinforcement-learning — the reference trains
policy/value nets with hand-rolled loss heads; this is the compact
equivalent on a self-contained environment, no gym dependency).

A contextual bandit: the agent sees a one-hot context and must pick
the matching arm.  The policy net trains with REINFORCE — the loss is
``MakeLoss(-log pi(a|s) * advantage)`` with the advantage fed through
``BlockGrad``, the same symbolic pattern the reference uses for its
actor-critic losses.
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def build_policy(num_actions):
    data = mx.sym.Variable("data")
    adv = mx.sym.Variable("advantage")          # (batch,)
    act = mx.sym.Variable("action")             # (batch,) int
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    h = mx.sym.Activation(fc1, act_type="relu")
    logits = mx.sym.FullyConnected(h, name="logits", num_hidden=num_actions)
    probs = mx.sym.SoftmaxActivation(logits, name="probs")
    # -log pi(a|s) * advantage, advantage treated as a constant
    onehot = mx.sym.one_hot(act, depth=num_actions)
    logp = mx.sym.log(mx.sym.sum(probs * onehot, axis=1) + 1e-8)
    loss = mx.sym.MakeLoss(0 - logp * mx.sym.BlockGrad(adv),
                           name="pg_loss")
    return mx.sym.Group([mx.sym.BlockGrad(probs), loss])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-actions", type=int, default=5)
    p.add_argument("--iterations", type=int, default=150)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    n_act = args.num_actions
    bs = args.batch_size

    net = build_policy(n_act)
    mod = mx.mod.Module(net, data_names=("data", "advantage", "action"),
                        label_names=None, context=mx.tpu(0))
    mod.bind(data_shapes=[("data", (bs, n_act)), ("advantage", (bs,)),
                          ("action", (bs,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    baseline = 0.0
    avg_reward = 0.0
    for it in range(args.iterations):
        ctx_idx = rng.randint(0, n_act, bs)
        states = np.eye(n_act, dtype=np.float32)[ctx_idx]
        # evaluate policy to sample actions
        mod.forward(mx.io.DataBatch(
            [mx.nd.array(states), mx.nd.zeros((bs,)), mx.nd.zeros((bs,))]),
            is_train=False)
        probs = mod.get_outputs()[0].asnumpy()
        actions = np.array([rng.choice(n_act, p=pr / pr.sum())
                            for pr in probs])
        rewards = (actions == ctx_idx).astype(np.float32)
        baseline = 0.9 * baseline + 0.1 * rewards.mean()
        adv = rewards - baseline
        # REINFORCE update
        mod.forward(mx.io.DataBatch(
            [mx.nd.array(states), mx.nd.array(adv),
             mx.nd.array(actions.astype(np.float32))]), is_train=True)
        mod.backward()
        mod.update()
        avg_reward = 0.95 * avg_reward + 0.05 * rewards.mean()
        if (it + 1) % 50 == 0:
            logging.info("iter %d avg reward %.3f", it + 1, avg_reward)
    print(f"policy-gradient bandit: final avg reward {avg_reward:.3f} "
          f"(random = {1.0 / n_act:.3f})")


if __name__ == "__main__":
    main()
