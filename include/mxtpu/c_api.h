/*
 * mxtpu flat C API — the native runtime ABI.
 *
 * Rebuild of the reference's include/mxnet/c_api.h role for the
 * TPU-native stack: opaque handles, int return codes (0 = success,
 * nonzero = failure with MXTPUGetLastError()), per-thread error string.
 *
 * Scope note (deliberate design split, SURVEY.md §7): the *compute*
 * path — arrays, operators, autograd, collectives — compiles through
 * XLA and is driven from the Python layer; this C ABI covers what is
 * native in this framework, mirroring what was native in the
 * reference's runtime:
 *   - the dependency engine (threaded_engine.{h,cc} analog)
 *   - the pooled host storage manager (storage/ analog)
 *   - the RecordIO scanner (io/ analog)
 *   - the runtime-discoverable op registry (MXSymbolListAtomicSymbol-
 *     Creators / MXSymbolGetAtomicSymbolInfo analog), populated by the
 *     host frontend at import so thin in-process language bindings can
 *     generate op wrappers at runtime.
 */

#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- error handling (src/c_api/c_api_error.cc analog) ---- */
/* Message of the last failure on this thread; empty string if none. */
const char* MXTPUGetLastError(void);
void MXTPUSetLastError(const char* msg);

/* ---- dependency engine (include/mxnet/engine.h analog) ---- */
typedef void* EngineHandle;
typedef void* VarHandle;
typedef void (*MXTPUOpCallback)(void* payload);

EngineHandle MXTPUEngineCreate(int num_workers, int num_io_workers);
void MXTPUEngineFree(EngineHandle engine);
VarHandle MXTPUEngineNewVar(EngineHandle engine);
/* Push fn(payload) with read deps const_vars and write deps
 * mutable_vars; prop: 0 = normal worker pool, 1 = prioritized/IO pool
 * (FnProperty analog). Returns immediately; execution is async. */
void MXTPUEnginePush(EngineHandle engine, MXTPUOpCallback fn, void* payload,
                     VarHandle* const_vars, int n_const,
                     VarHandle* mutable_vars, int n_mutable, int prop);
void MXTPUEngineWaitForAll(EngineHandle engine);
void MXTPUEngineWaitForVar(EngineHandle engine, VarHandle var);
int64_t MXTPUEnginePending(EngineHandle engine);

/* ---- pooled host storage (include/mxnet/storage.h analog) ---- */
/* Size-bucketed free-list pool; Alloc may return a recycled buffer. */
void* MXTPUStorageAlloc(uint64_t size);
void MXTPUStorageFree(void* ptr, uint64_t size);
/* Return all pooled buffers to the OS (release-on-pressure). */
void MXTPUStorageReleaseAll(void);
void MXTPUStorageStats(uint64_t* allocated, uint64_t* pooled,
                       uint64_t* allocs, uint64_t* hits);

/* ---- RecordIO scanner (src/io recordio analog) ---- */
/* Build an offset index of a .rec file: returns a handle and writes the
 * record count to *out_count; NULL on failure. */
void* MXTPURecordIOIndex(const char* path, int64_t* out_count);
void MXTPURecordIOIndexGet(void* index, int64_t i, uint64_t* out_offset,
                           uint32_t* out_length);
void MXTPURecordIOIndexFree(void* index);
/* Read records [begin, begin+n) payloads into buf (capacity buf_len);
 * writes each record's length into out_lengths; returns bytes written
 * or -1 on failure. */
int64_t MXTPURecordIOReadBatch(const char* path, void* index, int64_t* order,
                               int64_t n, uint8_t* buf, int64_t buf_len,
                               uint32_t* out_lengths);

/* ---- runtime op registry (c_api.cc op-discovery analog) ---- */
/* Register/replace op metadata. Arrays are parallel; param_types follow
 * the reference's "type, optional, default=..." style strings. */
int MXTPURegisterOp(const char* name, const char* doc,
                    const char** arg_names, int n_args,
                    const char** param_names, const char** param_types,
                    const char** param_docs, int n_params);
/* Enumerate op names; pointers valid until the next MXTPUListOps call. */
int MXTPUListOps(int* out_size, const char*** out_names);
/* Fetch one op's metadata; pointers valid until re-registration. */
/* ---- predict-only mini API (reference include/mxnet/c_predict_api.h:
 * create from symbol JSON + param blob, set named inputs, forward, copy
 * outputs; the binding surface for non-Python frontends).  Implemented
 * over an embedded CPython interpreter driving the JAX predictor. */
typedef void* PredictorHandle;

int MXTPUPredCreate(const char* symbol_json, const void* param_bytes,
                    uint64_t param_size, int dev_type, int dev_id,
                    uint32_t num_input_nodes, const char** input_keys,
                    const uint32_t* input_shape_indptr,
                    const uint32_t* input_shape_data,
                    PredictorHandle* out);
int MXTPUPredSetInput(PredictorHandle handle, const char* key,
                      const float* data, uint32_t size);
int MXTPUPredForward(PredictorHandle handle);
/* Pass shape_data == NULL to query ndim first. */
int MXTPUPredGetOutputShape(PredictorHandle handle, uint32_t index,
                            uint32_t* shape_data, uint32_t* shape_ndim);
int MXTPUPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                       uint32_t size);
int MXTPUPredFree(PredictorHandle handle);

int MXTPUGetOpInfo(const char* name, const char** out_doc, int* out_n_args,
                   const char*** out_arg_names, int* out_n_params,
                   const char*** out_param_names,
                   const char*** out_param_types,
                   const char*** out_param_docs);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_API_H_ */
